"""coast_tpu.obs: campaign telemetry (spans, live metrics, trace export).

The observability layer of the injection pipeline: nested wall-clock
spans and counters (:mod:`coast_tpu.obs.spans`), Chrome/Perfetto
``trace_event`` export (:mod:`coast_tpu.obs.trace_export`), a
rate-limited progress heartbeat (:mod:`coast_tpu.obs.heartbeat`), live
per-batch time-series metrics (:mod:`coast_tpu.obs.metrics`) with a
zero-dependency HTTP endpoint (:mod:`coast_tpu.obs.serve`), statistical
convergence tracking with Wilson-interval early stop
(:mod:`coast_tpu.obs.convergence`), a live TTY dashboard
(:mod:`coast_tpu.obs.console`), per-dispatch device-time attribution
(:mod:`coast_tpu.obs.profiler`) with roofline/MFU accounting
(:mod:`coast_tpu.obs.roofline`), fleet trace federation
(:mod:`coast_tpu.obs.federate`), declarative reliability SLOs with
error-budget burn rates (:mod:`coast_tpu.obs.slo`), and a blackbox
flight recorder with hang forensics (:mod:`coast_tpu.obs.flightrec`).
See docs/observability.md for the workflow.
"""

from coast_tpu.obs.console import Console
from coast_tpu.obs.convergence import (ConvergenceTracker, StopWhen,
                                       StopWhenError, wilson_interval)
from coast_tpu.obs.federate import merge_traces, write_merged_trace
from coast_tpu.obs.flightrec import FlightRecorder
from coast_tpu.obs.slo import SLOError, SLOSet, SLOSpec
from coast_tpu.obs.heartbeat import Heartbeat
from coast_tpu.obs.metrics import (CampaignMetrics, Histogram, Ring,
                                   atomic_write_json)
from coast_tpu.obs.profiler import CampaignProfiler
from coast_tpu.obs.serve import MetricsServer
from coast_tpu.obs.spans import (NULL, Telemetry, count, current, instant,
                                 span)
from coast_tpu.obs.trace_export import (to_trace_doc, to_trace_events,
                                        write_trace)

__all__ = [
    "Telemetry", "NULL", "current", "span", "count", "instant",
    "to_trace_events", "to_trace_doc", "write_trace",
    "Heartbeat", "Console",
    "CampaignMetrics", "Histogram", "Ring", "MetricsServer",
    "atomic_write_json",
    "CampaignProfiler", "merge_traces", "write_merged_trace",
    "ConvergenceTracker", "StopWhen", "StopWhenError", "wilson_interval",
    "FlightRecorder", "SLOSpec", "SLOSet", "SLOError",
]
