"""Roofline / MFU accounting: analytic op counts + measured device time.

docs/perf.md ends on the number that gates the fused-kernel work: TMR
reaches ~0.44% of bf16 peak at flagship sizes, with the *claimed*
culprit per-step scalar bookkeeping between matmul dispatches.  This
module turns that claim into recorded arithmetic:

  * :func:`count_jaxpr_ops` walks a jaxpr and counts arithmetic ops
    (2mnk per ``dot_general``, one per element for elementwise
    primitives, operand size for reductions; pure data movement --
    reshapes, slices, transposes, converts -- counts zero).  Control
    flow recurses: ``scan`` multiplies by its static length, ``while``
    by a caller-supplied trip count (the region's ``nominal_steps`` --
    the fault-free runtime, the honest estimate for the early-exit
    campaign loop), ``cond`` takes the widest branch.
  * :func:`region_ops_per_run` is the USEFUL work of one fault-free run
    (the unprotected step x nominal_steps) -- the MFU numerator;
    :func:`program_ops_per_run` counts the PROTECTED program (lanes,
    voters, CFCSS, guards included), so their ratio
    (:func:`flops_overhead`) generalizes train/'s analytic
    ``flops_overhead`` column to every registry benchmark.
  * :func:`mfu_block` combines those counts with the profiler's
    measured device-busy seconds into the ``summary()["mfu"]`` block:
    achieved ops/s, achieved MFU against a resolved peak, the
    roofline-predicted MFU ceiling from the voter-traffic model of
    docs/perf.md (state x lanes HBM bytes per commit step), the voter
    bytes share, and the dispatch-gap fraction.

Counts are ARITHMETIC ops, not IEEE FLOPs: the integer benchmarks
(crc16, sha256...) do integer work on the same VPU lanes, and a
consistent count is what an A/B needs.  All inputs land in the emitted
block so a reader can audit the model.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["count_jaxpr_ops", "region_ops_per_run", "program_ops_per_run",
           "flops_overhead", "phase_split", "resolve_peak", "mfu_block",
           "region_state_bytes"]

#: One op per output element.
_ELEMENTWISE = frozenset((
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "neg",
    "sign", "abs", "max", "min", "and", "or", "xor", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "clamp",
    "exp", "log", "log1p", "expm1", "tanh", "logistic", "erf",
    "erfc", "erf_inv", "sin", "cos", "tan", "asin", "acos", "atan",
    "atan2", "sinh", "cosh", "sqrt", "rsqrt", "cbrt", "square",
    "floor", "ceil", "round", "nextafter", "is_finite", "population_count",
    "clz",
))

#: One op per INPUT element (the reduction tree).
_REDUCTIONS = frozenset((
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
))


def _size(var) -> int:
    shape = getattr(var.aval, "shape", ())
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _sub_jaxprs(value):
    """Jaxpr-valued params (ClosedJaxpr / Jaxpr / containers thereof),
    the generic recursion for higher-order primitives this counter does
    not special-case."""
    from jax.extend import core as jex_core  # noqa: F401 - jaxpr types
    import jax.core as jcore
    out = []
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, (tuple, list)):
            stack.extend(v)
        elif hasattr(v, "jaxpr") and hasattr(v, "consts"):
            out.append(v.jaxpr)                 # ClosedJaxpr
        elif isinstance(v, jcore.Jaxpr):
            out.append(v)
    return out


def count_jaxpr_ops(jaxpr, while_trip: int = 1) -> float:
    """Arithmetic ops of one jaxpr evaluation (see module docstring).

    ``while_trip`` is the trip-count estimate applied to every ``while``
    encountered -- callers pass the region's ``nominal_steps`` (the
    fault-free runtime the early-exit campaign loop actually executes).
    """
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)     # ClosedJaxpr -> Jaxpr
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
            lhs_shape = eqn.invars[0].aval.shape
            k = 1
            for d in lhs_c:
                k *= int(lhs_shape[d])
            total += 2.0 * k * max(_size(eqn.outvars[0]), 1)
        elif name in _ELEMENTWISE:
            total += _size(eqn.outvars[0])
        elif name in _REDUCTIONS:
            total += _size(eqn.invars[0])
        elif name == "scan":
            inner = count_jaxpr_ops(eqn.params["jaxpr"], while_trip)
            total += inner * int(eqn.params["length"])
        elif name == "while":
            body = count_jaxpr_ops(eqn.params["body_jaxpr"], while_trip)
            cond = count_jaxpr_ops(eqn.params["cond_jaxpr"], while_trip)
            total += max(1, int(while_trip)) * (body + cond)
        elif name == "cond":
            total += max(count_jaxpr_ops(b, while_trip)
                         for b in eqn.params["branches"])
        elif name == "pallas_call":
            # A Pallas kernel runs its jaxpr once PER GRID STEP; the
            # generic recursion below would count the kernel body once
            # and silently undercount a fused program's op budget by the
            # grid size (overstating its MFU).  Grid layout lives in
            # params["grid_mapping"] on current JAX; older layouts carry
            # a bare params["grid"].
            inner = count_jaxpr_ops(eqn.params["jaxpr"], while_trip)
            gm = eqn.params.get("grid_mapping")
            grid = (getattr(gm, "grid", None) if gm is not None
                    else eqn.params.get("grid")) or ()
            trips = 1
            for d in grid:
                trips *= max(int(d), 1)
            total += inner * trips
        else:
            # pjit / closed_call / custom_jvp / remat / checkpoint ...:
            # recurse into any jaxpr-valued param; everything else
            # (reshape, slice, DUS, broadcast, iota, convert, gather) is
            # data movement and counts zero.
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    total += count_jaxpr_ops(sub, while_trip)
    return total


def _state_avals(region):
    import jax
    return jax.eval_shape(region.init)


def region_ops_per_run(region) -> float:
    """Useful arithmetic ops of one fault-free run: the unprotected
    step's jaxpr ops x ``nominal_steps``.  The MFU numerator, and the
    generalization of train/'s per-iteration FLOPs table to regions
    without an analytic ``meta`` block."""
    import jax
    import jax.numpy as jnp
    closed = jax.make_jaxpr(region.bound_step())(
        _state_avals(region), jnp.int32(0))
    return (count_jaxpr_ops(closed, region.nominal_steps)
            * region.nominal_steps)


def program_ops_per_run(prog, steps: Optional[int] = None) -> float:
    """Arithmetic ops of one PROTECTED run (lanes + voters + signatures
    + guards): the jaxpr of ``prog.run`` with its early-exit while loop
    priced at ``steps`` iterations (default the region's
    ``nominal_steps`` -- what a fault-free run executes)."""
    import jax
    import jax.numpy as jnp
    trip = int(steps) if steps is not None else prog.region.nominal_steps
    fault = {k: jax.ShapeDtypeStruct((), jnp.int32)
             for k in ("leaf_id", "lane", "word", "bit", "t")}
    closed = jax.make_jaxpr(lambda f: prog.run(f))(fault)
    return count_jaxpr_ops(closed, trip)


def flops_overhead(prog) -> float:
    """Protected / unprotected op ratio, analytically from the jaxprs --
    the registry-wide generalization of ``coast_tpu.train
    .flops_overhead`` (which stays authoritative for train regions,
    whose ``meta`` carries exact per-phase FLOPs)."""
    useful = region_ops_per_run(prog.region)
    return program_ops_per_run(prog) / useful if useful else float("nan")


def region_state_bytes(region) -> int:
    """Per-lane persistent state footprint from the region's own init
    shapes (the ground truth ``meta["state_bytes"]`` must not
    understate); shared with scripts/flagship_campaign.py's batch
    sizing."""
    import jax
    shapes = jax.eval_shape(region.init)
    return int(sum(int(math.prod(s.shape)) * s.dtype.itemsize
                   for s in jax.tree.leaves(shapes)))


def phase_split(region) -> List[Tuple[str, float]]:
    """The protected-region phases and their analytic work shares, for
    attributing measured device time.  Train regions split fwd/bwd/
    commit by their ``meta`` FLOPs table (the fwd/bwd/commit micro-steps
    of coast_tpu.train); every single-phase region gets one ``step``
    span covering the whole dispatch."""
    flops = (region.meta.get("train") or {}).get("flops")
    if flops:
        total = float(flops["fwd"] + flops["bwd"] + flops["update"]) or 1.0
        return [("fwd", flops["fwd"] / total),
                ("bwd", flops["bwd"] / total),
                ("commit", flops["update"] / total)]
    return [("step", 1.0)]


#: Known per-backend peaks (single chip).  The TPU row is the v5e bf16
#: peak every perf.md MFU number is quoted against; CPU has no honest
#: published peak, so MFU stays None there unless the operator pins one
#: (COAST_PEAK_GFLOPS, or the profile CLI's --peak-gflops for recording
#: a CPU-measured attribution against the TPU target ceiling).
_BACKEND_PEAK_GFLOPS = {"tpu": (197_000.0, "v5e-bf16")}

#: v5e single-chip HBM bandwidth (GB/s), the roofline's byte axis.
DEFAULT_HBM_GBPS = 819.0


def resolve_peak(backend: Optional[str] = None,
                 peak_gflops: Optional[float] = None
                 ) -> Tuple[Optional[float], str]:
    """(peak FLOP/s or None, source tag).  Priority: explicit argument >
    COAST_PEAK_GFLOPS env > the backend table."""
    if peak_gflops:
        return float(peak_gflops) * 1e9, "explicit"
    env = os.environ.get("COAST_PEAK_GFLOPS")
    if env:
        return float(env) * 1e9, "env:COAST_PEAK_GFLOPS"
    if backend is None:
        import jax
        backend = jax.default_backend()
    row = _BACKEND_PEAK_GFLOPS.get(backend)
    if row is None:
        return None, f"unknown backend {backend!r}"
    return row[0] * 1e9, row[1]


def mfu_block(prog, runs: int, device_busy_s: float, wall_s: float,
              dispatch_gap_fraction: float,
              peak_gflops: Optional[float] = None,
              hbm_gbps: float = DEFAULT_HBM_GBPS,
              ops: Optional[Dict[str, float]] = None) -> Dict[str, object]:
    """The ``summary()["mfu"]`` block: analytic ops + measured device
    time -> achieved vs roofline-predicted MFU.

    ``ops`` optionally carries pre-computed ``{"useful", "program"}``
    per-run op counts (the profiler caches them -- the jaxpr trace costs
    a compile-trace, paid once per runner).  ``runs`` is the number of
    physically dispatched injections the measured ``device_busy_s``
    covers.  Every model input is recorded so the block is auditable.
    """
    import jax
    region = prog.region
    if ops is None:
        ops = {"useful": region_ops_per_run(region),
               "program": program_ops_per_run(prog)}
    useful = float(ops["useful"])
    program = float(ops["program"])
    peak, peak_source = resolve_peak(peak_gflops=peak_gflops)
    lanes = int(prog.cfg.num_clones)
    state_bytes = region_state_bytes(region)
    # The docs/perf.md voter-traffic model: per commit step the voter
    # moves O(state x lanes) HBM bytes while the matmul does the useful
    # FLOPs -- one vote per step plus the boundary sync.
    voter_bytes = float(lanes * state_bytes * (region.nominal_steps + 1))
    out: Dict[str, object] = {
        "useful_ops_per_run": round(useful, 1),
        "program_ops_per_run": round(program, 1),
        "flops_overhead": round(program / useful, 4) if useful else None,
        "runs": int(runs),
        "device_busy_s": round(device_busy_s, 6),
        "dispatch_gap_fraction": round(dispatch_gap_fraction, 6),
        "state_bytes": state_bytes,
        "lanes": lanes,
        "voter_bytes_per_run": voter_bytes,
        "hbm_gbps": hbm_gbps,
        "backend": jax.default_backend(),
        "peak_source": peak_source,
    }
    achieved = (useful * runs / device_busy_s) if device_busy_s > 0 else 0.0
    wall_rate = (useful * runs / wall_s) if wall_s > 0 else 0.0
    out["achieved_ops_per_s"] = round(achieved, 1)
    out["achieved_ops_per_s_wall"] = round(wall_rate, 1)
    if peak:
        out["peak_gflops"] = peak / 1e9
        out["achieved_mfu"] = round(achieved / peak, 8)
        out["achieved_mfu_wall"] = round(wall_rate / peak, 8)
        # Roofline ceiling: useful-FLOP time vs voter HBM time.  The
        # protected program cannot beat this no matter how the
        # bookkeeping is fused -- the structural table of docs/perf.md.
        t_flops = useful / peak
        t_bytes = voter_bytes / (hbm_gbps * 1e9)
        denom = t_flops + t_bytes
        out["roofline_mfu"] = round(t_flops / denom, 8) if denom else None
        out["voter_bytes_share"] = (round(t_bytes / denom, 6)
                                    if denom else None)
    else:
        out["peak_gflops"] = None
        out["achieved_mfu"] = None
        out["roofline_mfu"] = None
        out["voter_bytes_share"] = None
    return out
