"""Zero-dependency local metrics endpoint for a running campaign.

``MetricsServer`` wraps stdlib ``http.server`` in a daemon thread and
serves the :class:`coast_tpu.obs.metrics.CampaignMetrics` hub the
campaign loop is feeding:

  * ``GET /metrics``      -- Prometheus text exposition (0.0.4), the
    scrape target a fleet supervisor (ROADMAP item 3) aggregates;
  * ``GET /status``       -- the full JSON status document (rates with
    Wilson CIs, ring-buffer series, stage totals, resilience counters);
  * ``GET /`` or ``/healthz`` -- a one-line liveness body.

Binding is loopback by default -- this is an operator's local
observation port, not a public service; a fleet scraper on another host
tunnels or rebinds explicitly.  ``port=0`` asks the OS for an ephemeral
port (tests, and running several campaigns on one box without port
bookkeeping); ``.port`` reports what was actually bound.

The server never touches the campaign thread: handlers read coherent
snapshots under the hub's lock, so a slow scraper can delay *its own*
response only.
"""

from __future__ import annotations

import errno
import http.server
import json
import sys
import threading
from typing import Optional

from coast_tpu.obs.metrics import CampaignMetrics

__all__ = ["MetricsServer"]


class _Handler(http.server.BaseHTTPRequestHandler):
    # Set per-server via the class factory in MetricsServer.start.
    metrics: CampaignMetrics

    def do_GET(self) -> None:          # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = self.metrics.prometheus().encode("utf-8")
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path in ("/status", "/status.json"):
            body = (json.dumps(self.metrics.snapshot(), sort_keys=True)
                    .encode("utf-8"))
            ctype = "application/json"
        elif path in ("/", "/healthz"):
            body = b"coast_tpu campaign metrics: see /metrics, /status\n"
            ctype = "text/plain; charset=utf-8"
        else:
            self.send_error(404, "unknown path (want /metrics or /status)")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: object) -> None:
        # Scrape traffic must not spam the campaign's terminal.
        pass


class MetricsServer:
    """Threaded HTTP server over one CampaignMetrics hub."""

    def __init__(self, metrics: CampaignMetrics, port: int = 0,
                 host: str = "127.0.0.1", bind: Optional[str] = None):
        """``bind`` is the listen address (default stays the loopback
        ``host``); pass ``bind="0.0.0.0"`` for a fleet aggregator that
        other hosts scrape.  ``metrics`` is duck-typed: anything with
        ``prometheus()``/``snapshot()`` serves (a CampaignMetrics hub,
        or a fleet aggregate, coast_tpu.fleet.telemetry)."""
        self.metrics = metrics
        self.host = bind if bind is not None else host
        self.port = int(port)
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind and serve in a daemon thread; returns the bound port.

        A requested port that is already taken falls back to an
        ephemeral one (with a warning on stderr) instead of dying: on a
        fleet host, per-worker servers and the aggregator coexist, and
        "which port exactly" matters less than "the worker must not
        crash because an operator reused a number"."""
        if self._httpd is not None:
            return self.port
        handler = type("BoundHandler", (_Handler,),
                       {"metrics": self.metrics})
        try:
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), handler)
        except OSError as e:
            if self.port == 0 or e.errno not in (errno.EADDRINUSE,
                                                 errno.EACCES):
                raise
            print(f"# warning: metrics port {self.port} on {self.host} "
                  f"is taken ({e.strerror}); falling back to an "
                  "ephemeral port", file=sys.stderr, flush=True)
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, 0), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="coast-metrics-server", daemon=True)
        self._thread.start()
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "MetricsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
