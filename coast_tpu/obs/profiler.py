"""Per-dispatch device-time attribution for the campaign loop.

The span layer (:mod:`coast_tpu.obs.spans`) times HOST stages: its
``dispatch`` span is the async enqueue and its ``collect`` span is the
blocking fetch, so "where did the device time go" -- the question
ROADMAP #1's fused-kernel work must answer -- is not in the recording.
:class:`CampaignProfiler` closes that gap with the timeline both
accelerator stacks and the Flex-TPU schedule work need:

  * per compiled invocation (one campaign batch), the **device-busy
    duration** and the **host-side gap** the device spent idle waiting
    for the host (journal fsync, stream feeds, padding, Python);
  * the whole-campaign identity ``wall = device_busy + host_gap +
    host_other`` (head before the first enqueue + tail after the last
    ready), exact by construction -- the acceptance check of
    ``artifacts/profile_mm.json``;
  * a per-dispatch device-seconds histogram (the new Prometheus
    *histogram* exporter type in :mod:`coast_tpu.obs.metrics`);
  * per protected-region-phase attribution: train/'s fwd/bwd/commit
    micro-steps split each dispatch's busy window by their analytic
    work shares (:func:`coast_tpu.obs.roofline.phase_split`);
    single-phase regions get one ``device:step`` span.

Measurement is the **blocking-marker** fallback that works on every
backend (CPU included): the collect path blocks on the dispatched batch
(``jax.block_until_ready``) under timing, so

    busy_i = t_ready_i - max(t_enqueue_end_i, t_ready_{i-1})
    gap_i  = max(0, t_enqueue_end_i - t_ready_{i-1})

A ready that lands while the host was busy is observed late, so
``busy`` is an upper bound and ``gap`` a lower bound -- the
conservative direction for the "the gap is host-side bookkeeping"
claim.  Arm ``Telemetry(profiler=True)`` alongside to bracket the same
spans with ``jax.profiler`` annotations for a captured device trace
(where available); the numbers recorded here come from the markers
either way, so CPU CI can pin them.

The DISABLED path (``CampaignRunner(profile=False)``, the default) adds
one ``is not None`` test per batch to the campaign loop -- bounded
under the same <2% budget as the PR 1 telemetry layer
(tests/test_profiler.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from coast_tpu.obs import roofline
from coast_tpu.obs.metrics import Histogram

__all__ = ["CampaignProfiler"]


class CampaignProfiler:
    """Single-writer per-dispatch timeline recorder for one runner.

    The campaign loop calls ``begin`` / ``dispatched`` / ``ready`` /
    ``finish``; ``finish`` returns the JSON-able profile block (and the
    roofline ``mfu`` sub-block) attached to ``CampaignResult.profile``.
    One profiler serves consecutive campaigns of one runner; state
    resets at every ``begin``.
    """

    def __init__(self, prog=None, telemetry=None,
                 peak_gflops: Optional[float] = None,
                 hbm_gbps: float = roofline.DEFAULT_HBM_GBPS):
        self.prog = prog
        self.telemetry = telemetry
        self.peak_gflops = peak_gflops
        self.hbm_gbps = float(hbm_gbps)
        self.phases: List[Tuple[str, float]] = (
            roofline.phase_split(prog.region) if prog is not None
            else [("step", 1.0)])
        self._ops: Optional[Dict[str, float]] = None   # cached jaxpr counts
        # The campaign loop is the single LOGICAL writer, but a watchdog
        # (retry.collect_timeout) runs the blocking fetch -- and thus
        # ``ready`` -- on a worker thread, and an abandoned (timed-out)
        # fetch thread can outlive its flight: the lock keeps a straggler
        # from corrupting the accumulators mid-update.  (The collect
        # wrapper additionally drops a straggler's ready once its flight
        # was re-dispatched -- see campaign.py.)
        self._lock = threading.Lock()
        self.begin(time.perf_counter())

    # -- per-campaign lifecycle ----------------------------------------------
    def begin(self, t0: float) -> None:
        self._t_begin = float(t0)
        self._disp: Dict[int, Tuple[float, float, int]] = {}
        self._prev_ready: Optional[float] = None
        self._first_enq: Optional[float] = None
        self._last_ready: Optional[float] = None
        self._busy_s = 0.0
        self._gap_s = 0.0
        self._rows = 0
        self._dispatches = 0
        self._per_phase = {name: 0.0 for name, _w in self.phases}
        self.hist_device = Histogram()
        self.hist_gap = Histogram()
        self._last_sample: Optional[Dict[str, float]] = None

    def dispatched(self, lo: int, n: int, t0: float, t1: float) -> None:
        """One batch's (re-)enqueue window; keyed by its schedule row so
        a retry's re-dispatch replaces the stale record."""
        with self._lock:
            self._disp[int(lo)] = (float(t0), float(t1), int(n))
            if self._first_enq is None:
                self._first_enq = float(t1)

    def ready(self, lo: int, n: int, t_ready: float) -> None:
        """The blocking marker came back for batch ``lo``: attribute the
        interval since the previous ready into device-busy vs host-gap,
        and emit the per-phase device spans into the telemetry."""
        with self._lock:
            rec = self._disp.pop(int(lo), None)
            if rec is None:       # ready without a dispatch record: skip
                return
            _enq0, enq1, _n_rec = rec
            prev = (self._prev_ready if self._prev_ready is not None
                    else enq1)
            busy_start = max(enq1, prev)
            busy = max(0.0, float(t_ready) - busy_start)
            gap = max(0.0, enq1 - prev)
            self._busy_s += busy
            self._gap_s += gap
            self._rows += int(n)
            self._dispatches += 1
            self._prev_ready = float(t_ready)
            self._last_ready = float(t_ready)
            self.hist_device.observe(busy)
            self.hist_gap.observe(gap)
            self._last_sample = {"device_s": busy, "gap_s": gap}
            tel = self.telemetry
            if busy > 0.0:
                at = busy_start
                for name, w in self.phases:
                    dur = busy * w
                    self._per_phase[name] += dur
                    if tel is not None and tel.enabled:
                        tel.span_at(f"device:{name}", at, at + dur,
                                    device=True, lo=int(lo), n=int(n))
                    at += dur

    def batch_sample(self) -> Optional[Dict[str, float]]:
        """The most recent ready's {device_s, gap_s} -- what the live
        metrics hub observes into its histograms per batch."""
        return self._last_sample

    def finish(self, t_end: float, wall_s: Optional[float] = None
               ) -> Dict[str, object]:
        """Close the campaign window and return the profile block.

        ``host_other_s`` is the loop's head (before the first enqueue)
        plus its tail (after the last ready: final classify, result
        assembly), so ``device_busy + host_gap + host_other == wall``
        exactly -- a journal-replayed prefix (no live dispatches) lands
        in ``host_other`` like any other non-device time."""
        import jax
        wall = float(wall_s) if wall_s is not None \
            else float(t_end) - self._t_begin
        other = max(0.0, wall - self._busy_s - self._gap_s)
        profile: Dict[str, object] = {
            "dispatches": self._dispatches,
            "rows": self._rows,
            "wall_s": round(wall, 6),
            "device_busy_s": round(self._busy_s, 6),
            "host_gap_s": round(self._gap_s, 6),
            "host_other_s": round(other, 6),
            "device_busy_fraction": round(self._busy_s / wall, 6)
            if wall > 0 else 0.0,
            "dispatch_gap_fraction": round(self._gap_s / wall, 6)
            if wall > 0 else 0.0,
            "per_phase_device_s": {name: round(s, 6)
                                   for name, s in self._per_phase.items()},
            "device_seconds_histogram": self.hist_device.snapshot(),
            "host_gap_seconds_histogram": self.hist_gap.snapshot(),
            "backend": jax.default_backend(),
        }
        if self.prog is not None:
            if self._ops is None:
                self._ops = {
                    "useful": roofline.region_ops_per_run(self.prog.region),
                    "program": roofline.program_ops_per_run(self.prog)}
            profile["mfu"] = roofline.mfu_block(
                self.prog, runs=self._rows,
                device_busy_s=self._busy_s, wall_s=wall,
                dispatch_gap_fraction=profile["dispatch_gap_fraction"],
                peak_gflops=self.peak_gflops, hbm_gbps=self.hbm_gbps,
                ops=self._ops)
        return profile
