"""Fleet trace federation: one Perfetto timeline for a whole fleet run.

A fleet campaign's timing evidence is scattered: every worker records
its own spans into its own process, a SIGKILL'd worker's recorder dies
with it, and the queue's claim/complete timestamps live in the item
documents.  :func:`merge_traces` rebuilds ONE coherent timeline from
the **durable** records only -- the per-item campaign journals (whose
batch records carry ``(name, unix_start, duration)`` span triples, PR
8) and the queue item docs -- so the merged trace needs no cooperation
from the workers and survives any of them dying:

  * one Perfetto process per queue item (``item <id>
    benchmark/strategy``), its batch spans on a ``journal`` track;
  * the fleet queue as process 0: enqueue / claim / complete / fail
    instants per item plus one ``item <id>`` lease span from the last
    claim to completion (``lease_expires_unix`` in args);
  * **journal-anchored clock offsets**: span times inside one journal
    come from whichever worker's clock wrote each segment.  The journal
    record ORDER is the ground truth (batch ``lo`` is monotone within a
    campaign), so a resumed segment whose skewed clock would start
    *before* the previous segment's end is shifted forward to abut it
    -- the PR 8 one-coherent-timeline guarantee extended across
    workers.  Forward skew (a gap) is preserved: real requeue waits
    look exactly like that.  Applied offsets are recorded in
    ``otherData.clock_offsets``.
  * **exactly-once**: batch records are deduped by row offset (first
    record wins), so a SIGKILL'd+resumed worker's replayed batches --
    which resume deliberately does NOT re-append -- appear once no
    matter how many claims the item went through.

The output is the same trace_event JSON Object Format as
:mod:`coast_tpu.obs.trace_export`; the fleet supervisor's
``--trace-out`` writes it after the merge.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["merge_traces", "item_timeline", "write_merged_trace"]

#: Spans closer than this to the previous segment's end are treated as
#: in-order (journal fsync granularity), not a clock violation.
_SKEW_EPSILON_S = 1e-4


def item_timeline(journal_path: str
                  ) -> Tuple[List[Tuple[str, float, float, int]],
                             float]:
    """One item's aligned span timeline from its journal.

    Returns ``(spans, max_offset)``: spans as ``(name, unix_t0,
    duration_s, lo)`` with journal-anchored clock correction applied,
    and the largest forward offset any segment needed (0.0 for a
    skew-free journal).  Journals without batch records -- or without
    recorded span triples (telemetry off) -- yield an empty list.
    """
    from coast_tpu.inject.journal import CampaignJournal, JournalError
    try:
        _header, records, _valid = CampaignJournal._load(journal_path)
    except (JournalError, OSError):
        return [], 0.0
    batches: Dict[int, List] = {}
    for rec in records:
        if rec.get("kind") != "batch":
            continue
        lo = int(rec.get("lo", 0))
        if lo in batches:
            continue               # exactly-once: first record wins
        spans = rec.get("spans") or []
        if spans:
            batches[lo] = spans
    out: List[Tuple[str, float, float, int]] = []
    offset = 0.0
    max_offset = 0.0
    prev_end: Optional[float] = None
    for lo in sorted(batches):
        spans = batches[lo]
        start = min(float(t) for _n, t, _d in spans)
        # A batch that begins before the previous batch ended (beyond
        # fsync jitter) was written by a clock behind the previous
        # segment's: re-anchor this segment to abut the journal order.
        if prev_end is not None and start + offset \
                < prev_end - _SKEW_EPSILON_S:
            offset = prev_end - start
            max_offset = max(max_offset, offset)
        end = prev_end if prev_end is not None else float("-inf")
        for name, t, dur in spans:
            t_adj = float(t) + offset
            out.append((str(name), t_adj, float(dur), lo))
            end = max(end, t_adj + float(dur))
        prev_end = end
    return out, max_offset


def merge_traces(queue) -> Dict[str, object]:
    """Merge every queue item's journal timeline plus the queue's own
    claim/lease/complete events into one trace_event document.

    ``queue`` is a :class:`~coast_tpu.fleet.queue.CampaignQueue` or its
    root path.  Items in every state contribute (a claimed item's
    journal shows its progress so far); items without a readable
    journal contribute their queue events only.
    """
    from coast_tpu.fleet.queue import CampaignQueue
    q = queue if not isinstance(queue, str) else CampaignQueue(queue)
    items: List[Dict[str, object]] = []
    for state in ("done", "failed", "claimed", "pending"):
        for rec in q.items(state):
            items.append({"state": state, **rec})
    items.sort(key=lambda r: str(r.get("id")))

    events: List[Dict[str, object]] = [{
        "name": "process_name", "ph": "M", "pid": 0, "tid": 1,
        "args": {"name": "fleet queue"},
    }]
    clock_offsets: Dict[str, float] = {}
    timelines: Dict[str, List[Tuple[str, float, float, int]]] = {}
    t_min = float("inf")
    for rec in items:
        item_id = str(rec.get("id"))
        spans, off = item_timeline(q.journal_path(item_id))
        timelines[item_id] = spans
        if off:
            clock_offsets[item_id] = round(off, 6)
        for _name, t, _dur, _lo in spans:
            t_min = min(t_min, t)
        for key in ("enqueued_unix", "claimed_unix", "completed_unix",
                    "failed_unix"):
            if rec.get(key):
                t_min = min(t_min, float(rec[key]))
    if t_min == float("inf"):
        t_min = 0.0

    def _us(t: float) -> float:
        return round((t - t_min) * 1e6, 3)

    for pid, rec in enumerate(items, start=1):
        item_id = str(rec.get("id"))
        result = rec.get("result") or {}
        spec = rec.get("spec") or {}
        label = (f"item {item_id} "
                 f"{result.get('benchmark') or spec.get('benchmark', '?')}"
                 + (f"/{result['strategy']}"
                    if result.get("strategy") else ""))
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": label}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": "journal"}})
        for name, t, dur, lo in timelines[item_id]:
            events.append({
                "name": name, "cat": "journal", "ph": "X",
                "pid": pid, "tid": 1,
                "ts": _us(t), "dur": round(dur * 1e6, 3),
                "args": {"lo": lo},
            })
        # Queue lifecycle onto the fleet track: the claim/lease/complete
        # vocabulary of fleet/queue.py.
        for key, mark in (("enqueued_unix", "enqueue"),
                          ("claimed_unix", "claim"),
                          ("completed_unix", "complete"),
                          ("failed_unix", "fail")):
            if rec.get(key):
                events.append({
                    "name": f"{mark} {item_id}", "cat": "queue",
                    "ph": "i", "s": "t", "pid": 0, "tid": 1,
                    "ts": _us(float(rec[key])),
                    "args": {"item": item_id,
                             "worker": rec.get("worker")
                             or result.get("worker")},
                })
        if rec.get("claimed_unix") and rec.get("completed_unix"):
            events.append({
                "name": f"item {item_id}", "cat": "lease", "ph": "X",
                "pid": 0, "tid": 1,
                "ts": _us(float(rec["claimed_unix"])),
                "dur": round((float(rec["completed_unix"])
                              - float(rec["claimed_unix"])) * 1e6, 3),
                "args": {"worker": rec.get("worker")
                         or result.get("worker"),
                         "attempts": rec.get("attempts"),
                         "lease_expires_unix":
                             rec.get("lease_expires_unix")},
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"epoch_unix_s": round(t_min, 6),
                      "items": len(items),
                      "clock_offsets": clock_offsets},
    }


def write_merged_trace(queue, path: str) -> str:
    """``merge_traces`` + atomic write (tmp + rename, like every other
    fleet artifact -- a crash mid-dump must not leave a torn trace);
    returns ``path``."""
    from coast_tpu.obs.metrics import atomic_write_json
    atomic_write_json(path, merge_traces(queue))
    return path
