// coast_core: native host-side core of the coast_tpu framework.
//
// The reference's native layer is a family of LLVM-7 C++ ModulePasses
// (projects/); the TPU framework's native layer carries the host-side
// algorithms that are neither XLA's job nor performance-trivial:
//
//   * coast_rand64        - bulk counter-mode splitmix64 for fault
//                           schedules (replaces the per-injection host RNG
//                           of resources/injector.py / threadFunctions.py).
//   * coast_cfcss_assign  - control-flow-signature assignment over a block
//                           graph: unique random signatures, designated-
//                           predecessor XOR diffs, per-edge run-time
//                           adjusters, and an iterate-until-sound check
//                           that re-seeds on aliasing -- the equivalent of
//                           generateSignatures / calcSigDiff /
//                           insertBufferBlock / verifySignatures in
//                           projects/CFCSS/CFCSS.cpp (:187-201, :439-470,
//                           :342-426).  Per-edge adjusters subsume buffer
//                           blocks: a buffer block exists only to give an
//                           edge its own adjuster value.
//
// Exposed with C linkage for ctypes (no pybind11 in this image); the
// Python side (coast_tpu/native/__init__.py) keeps bit-identical numpy
// fallbacks.
//
// Build: make -C coast_tpu/native  ->  libcoast_core.so

#include <cstdint>
#include <cstring>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint64_t kGolden = 0x9E3779B97F4A7C15ULL;

inline uint64_t splitmix_at(uint64_t seed, uint64_t i) {
  // Counter mode: value i = finalizer(seed + (i+1)*golden).  Must stay
  // bit-identical to the numpy fallback in native/__init__.py.
  uint64_t z = seed + (i + 1) * kGolden;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

void coast_rand64(uint64_t seed, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = splitmix_at(seed, (uint64_t)i);
}

// CFCSS signature assignment.
//
// Inputs:  n nodes (node 0 = entry), n_edges directed edges (u,v pairs),
//          seed, sig_bits (reference default 16, CFCSS.h:33-35).
// Outputs: sigs[n]        unique random signatures
//          diffs[n]       d_v = s_{u0(v)} ^ s_v  (entry: d = s_entry,
//                         matching a runtime G initialised to 0)
//          fanin[n]       1 if the node has >1 predecessor
//          dedge[n*n]     run-time adjuster for edge (u,v) into a fan-in
//                         node: D = s_{u0(v)} ^ s_u (0 elsewhere)
//
// Soundness check mirrors verifySignatures' iterate-until-stable loop: for
// every (u,v) pair that is NOT an edge, an illegal jump must not verify:
//   s_u ^ d_v ^ (fanin_v ? dedge[u][v](=0) : 0) != s_v.
// On aliasing we re-seed and retry (the reference regenerates conflicting
// signatures); returns the number of attempts used, or -1 if it could not
// find a sound assignment in 64 tries, -2 on malformed input.
int32_t coast_cfcss_assign(int32_t n, int32_t n_edges, const int32_t* edges,
                           uint64_t seed, int32_t sig_bits, uint32_t* sigs,
                           uint32_t* diffs, uint8_t* fanin, uint32_t* dedge) {
  if (n <= 0 || sig_bits <= 1 || sig_bits > 32) return -2;
  for (int32_t e = 0; e < n_edges; ++e) {
    if (edges[2 * e] < 0 || edges[2 * e] >= n || edges[2 * e + 1] < 0 ||
        edges[2 * e + 1] >= n)
      return -2;
  }
  const uint32_t mask =
      sig_bits == 32 ? 0xFFFFFFFFu : ((1u << sig_bits) - 1u);

  std::vector<int32_t> pred_count(n), u0(n);
  std::vector<char> is_edge((size_t)n * n);
  for (int attempt = 0; attempt < 64; ++attempt) {
    // Unique random signatures (generateSignatures :187-201).  Hash set,
    // not a bitmap: sig_bits=32 would need a 4 GiB bitmap.
    std::unordered_set<uint32_t> used;
    used.reserve((size_t)n * 2);
    uint64_t ctr = 0;
    bool ok = true;
    // Spin bound: identical semantics to the Python fallback (mask + 8,
    // saturated to avoid int32 overflow at sig_bits=32).
    const int64_t max_spins = (int64_t)mask + 8;
    for (int32_t v = 0; v < n; ++v) {
      uint32_t s;
      int64_t spins = 0;
      do {
        s = (uint32_t)splitmix_at(seed + attempt, ctr++) & mask;
        if (++spins > max_spins) { ok = false; break; }
      } while (used.count(s));
      if (!ok) break;
      used.insert(s);
      sigs[v] = s;
    }
    if (!ok) return -1;  // more nodes than signature space

    // Designated predecessor = lowest-numbered predecessor.
    std::fill(pred_count.begin(), pred_count.end(), 0);
    std::fill(u0.begin(), u0.end(), -1);
    std::fill(is_edge.begin(), is_edge.end(), 0);
    for (int32_t e = 0; e < n_edges; ++e) {
      int32_t u = edges[2 * e], v = edges[2 * e + 1];
      if (is_edge[(size_t)u * n + v]) continue;  // duplicate edge
      is_edge[(size_t)u * n + v] = 1;
      pred_count[v]++;
      if (u0[v] < 0 || u < u0[v]) u0[v] = u;
    }

    // Diffs + per-edge adjusters (calcSigDiff :439-457; buffer-block
    // fan-in fixes :342-378 folded into per-edge adjuster values).
    std::memset(dedge, 0, sizeof(uint32_t) * (size_t)n * n);
    for (int32_t v = 0; v < n; ++v) {
      fanin[v] = pred_count[v] > 1 ? 1 : 0;
      diffs[v] = (u0[v] >= 0) ? (sigs[u0[v]] ^ sigs[v]) : sigs[v];
    }
    for (int32_t e = 0; e < n_edges; ++e) {
      int32_t u = edges[2 * e], v = edges[2 * e + 1];
      if (fanin[v]) dedge[(size_t)u * n + v] = sigs[u0[v]] ^ sigs[u];
    }

    // Soundness: no illegal jump may verify (verifySignatures :380-426).
    bool sound = true;
    for (int32_t u = 0; u < n && sound; ++u) {
      for (int32_t v = 0; v < n; ++v) {
        if (is_edge[(size_t)u * n + v]) continue;
        uint32_t g = sigs[u] ^ diffs[v];  // dedge[u][v] == 0 for non-edges
        if (g == sigs[v]) { sound = false; break; }
      }
    }
    if (sound) return attempt + 1;
  }
  return -1;
}

}  // extern "C"
