// coast_core: native host-side core of the coast_tpu framework.
//
// The reference's native layer is a family of LLVM-7 C++ ModulePasses
// (projects/); the TPU framework's native layer carries the host-side
// algorithms that are neither XLA's job nor performance-trivial:
//
//   * coast_rand64        - bulk counter-mode splitmix64 for fault
//                           schedules (replaces the per-injection host RNG
//                           of resources/injector.py / threadFunctions.py).
//   * coast_cfcss_assign  - control-flow-signature assignment over a block
//                           graph: unique random signatures, designated-
//                           predecessor XOR diffs, per-edge run-time
//                           adjusters, and an iterate-until-sound check
//                           that re-seeds on aliasing -- the equivalent of
//                           generateSignatures / calcSigDiff /
//                           insertBufferBlock / verifySignatures in
//                           projects/CFCSS/CFCSS.cpp (:187-201, :439-470,
//                           :342-426).  Per-edge adjusters subsume buffer
//                           blocks: a buffer block exists only to give an
//                           edge its own adjuster value.
//   * coast_ndjson_classify - bulk campaign-log reader: re-classifies the
//                           rows of an InjectionLog-schema ndjson buffer
//                           (the FromDict dispatch of
//                           supportClasses.py:355-389) in one C pass --
//                           the analysis-side mirror of the encoder
//                           below.  A 10^6-row summary drops from ~40s
//                           of per-line json.loads to under a second.
//   * coast_fault_expand  - multi-draw splitmix expansion of a base fault
//                           schedule into per-injection flip GROUPS for the
//                           generalized fault models (multibit / cluster /
//                           burst): one C pass over the base rows derives
//                           every extra site's (leaf, lane, word, bit, t)
//                           from the campaign seed, bit-identical to the
//                           numpy fallback so schedules replay across
//                           hosts with and without the compiled core.
//   * coast_ndjson_encode - bulk campaign-log serialiser: formats a row
//                           range of a campaign's columns into
//                           InjectionLog-schema ndjson lines
//                           (supportClasses.py:338-353) in one C pass.
//                           The reference's logging path is one Python
//                           dict + json.dump per multi-second injection
//                           (threadFunctions.py:184-202); a batched
//                           campaign emits 10^6 rows in seconds, so the
//                           IO-path encoder is native, like the QEMU
//                           fork's C plugin on the reference's high-rate
//                           boundary.
//
// Exposed with C linkage for ctypes (no pybind11 in this image); the
// Python side (coast_tpu/native/__init__.py) keeps bit-identical numpy
// fallbacks.
//
// Build: make -C coast_tpu/native  ->  libcoast_core.so

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint64_t kGolden = 0x9E3779B97F4A7C15ULL;

inline uint64_t splitmix_at(uint64_t seed, uint64_t i) {
  // Counter mode: value i = finalizer(seed + (i+1)*golden).  Must stay
  // bit-identical to the numpy fallback in native/__init__.py.
  uint64_t z = seed + (i + 1) * kGolden;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Append helpers for the ndjson encoder: memcpy/itoa composition is ~5x
// faster than snprintf chains at the 10^6-row scale the encoder exists for.
// Every write is bounds-checked against the buffer end: on overflow the
// writer latches and the encoder returns -1 (the caller retries with a
// smaller row range), so buffer safety never depends on the advisory
// per-line size estimate staying in sync with the templates.
struct Writer {
  char* p;
  char* end;
  bool overflow = false;
};
inline void put_str(Writer& w, const char* s, size_t len) {
  if (w.overflow || (size_t)(w.end - w.p) < len) {
    w.overflow = true;
    return;
  }
  std::memcpy(w.p, s, len);
  w.p += len;
}
inline void put_lit(Writer& w, const char* s) { put_str(w, s, std::strlen(s)); }
inline void put_i64(Writer& w, int64_t v) {
  char tmp[24];
  char* q = tmp + sizeof tmp;
  bool neg = v < 0;
  uint64_t u = neg ? (uint64_t)(-(v + 1)) + 1 : (uint64_t)v;
  do { *--q = (char)('0' + u % 10); u /= 10; } while (u);
  if (neg) *--q = '-';
  put_str(w, q, (size_t)(tmp + sizeof tmp - q));
}

}  // namespace

extern "C" {

// ABI version of the class taxonomy the ndjson entry points speak.
// Version 2 added the DUE sub-bucket classes (DUE_STACK_OVERFLOW=6,
// DUE_ASSERT=7); version 3 adds the training refinements of SDC
// (TRAIN_SELF_HEAL=8, TRAIN_SDC=9): counts arrays are 10 slots and the
// encoder/classifier know the selfHeal/trainSdc result templates.
// Python callers check this BEFORE using the ndjson paths: an older .so
// (rebuild failed on a compiler-less host) must degrade to the Python
// formatter/parser, never silently misclassify the new codes.
int32_t coast_abi_version(void) { return 3; }

void coast_rand64(uint64_t seed, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = splitmix_at(seed, (uint64_t)i);
}

// CFCSS signature assignment.
//
// Inputs:  n nodes (node 0 = entry), n_edges directed edges (u,v pairs),
//          seed, sig_bits (reference default 16, CFCSS.h:33-35).
// Outputs: sigs[n]        unique random signatures
//          diffs[n]       d_v = s_{u0(v)} ^ s_v  (entry: d = s_entry,
//                         matching a runtime G initialised to 0)
//          fanin[n]       1 if the node has >1 predecessor
//          dedge[n*n]     run-time adjuster for edge (u,v) into a fan-in
//                         node: D = s_{u0(v)} ^ s_u (0 elsewhere)
//
// Soundness check mirrors verifySignatures' iterate-until-stable loop: for
// every (u,v) pair that is NOT an edge, an illegal jump must not verify:
//   s_u ^ d_v ^ (fanin_v ? dedge[u][v](=0) : 0) != s_v.
// On aliasing we re-seed and retry (the reference regenerates conflicting
// signatures); returns the number of attempts used, or -1 if it could not
// find a sound assignment in 64 tries, -2 on malformed input.
int32_t coast_cfcss_assign(int32_t n, int32_t n_edges, const int32_t* edges,
                           uint64_t seed, int32_t sig_bits, uint32_t* sigs,
                           uint32_t* diffs, uint8_t* fanin, uint32_t* dedge) {
  if (n <= 0 || sig_bits <= 1 || sig_bits > 32) return -2;
  for (int32_t e = 0; e < n_edges; ++e) {
    if (edges[2 * e] < 0 || edges[2 * e] >= n || edges[2 * e + 1] < 0 ||
        edges[2 * e + 1] >= n)
      return -2;
  }
  const uint32_t mask =
      sig_bits == 32 ? 0xFFFFFFFFu : ((1u << sig_bits) - 1u);

  std::vector<int32_t> pred_count(n), u0(n);
  std::vector<char> is_edge((size_t)n * n);
  for (int attempt = 0; attempt < 64; ++attempt) {
    // Unique random signatures (generateSignatures :187-201).  Hash set,
    // not a bitmap: sig_bits=32 would need a 4 GiB bitmap.
    std::unordered_set<uint32_t> used;
    used.reserve((size_t)n * 2);
    uint64_t ctr = 0;
    bool ok = true;
    // Spin bound: identical semantics to the Python fallback (mask + 8,
    // saturated to avoid int32 overflow at sig_bits=32).
    const int64_t max_spins = (int64_t)mask + 8;
    for (int32_t v = 0; v < n; ++v) {
      uint32_t s;
      int64_t spins = 0;
      do {
        s = (uint32_t)splitmix_at(seed + attempt, ctr++) & mask;
        if (++spins > max_spins) { ok = false; break; }
      } while (used.count(s));
      if (!ok) break;
      used.insert(s);
      sigs[v] = s;
    }
    if (!ok) return -1;  // more nodes than signature space

    // Designated predecessor = lowest-numbered predecessor.
    std::fill(pred_count.begin(), pred_count.end(), 0);
    std::fill(u0.begin(), u0.end(), -1);
    std::fill(is_edge.begin(), is_edge.end(), 0);
    for (int32_t e = 0; e < n_edges; ++e) {
      int32_t u = edges[2 * e], v = edges[2 * e + 1];
      if (is_edge[(size_t)u * n + v]) continue;  // duplicate edge
      is_edge[(size_t)u * n + v] = 1;
      pred_count[v]++;
      if (u0[v] < 0 || u < u0[v]) u0[v] = u;
    }

    // Diffs + per-edge adjusters (calcSigDiff :439-457; buffer-block
    // fan-in fixes :342-378 folded into per-edge adjuster values).
    std::memset(dedge, 0, sizeof(uint32_t) * (size_t)n * n);
    for (int32_t v = 0; v < n; ++v) {
      fanin[v] = pred_count[v] > 1 ? 1 : 0;
      diffs[v] = (u0[v] >= 0) ? (sigs[u0[v]] ^ sigs[v]) : sigs[v];
    }
    for (int32_t e = 0; e < n_edges; ++e) {
      int32_t u = edges[2 * e], v = edges[2 * e + 1];
      if (fanin[v]) dedge[(size_t)u * n + v] = sigs[u0[v]] ^ sigs[u];
    }

    // Soundness: no illegal jump may verify (verifySignatures :380-426).
    bool sound = true;
    for (int32_t u = 0; u < n && sound; ++u) {
      for (int32_t v = 0; v < n; ++v) {
        if (is_edge[(size_t)u * n + v]) continue;
        uint32_t g = sigs[u] ^ diffs[v];  // dedge[u][v] == 0 for non-edges
        if (g == sigs[v]) { sound = false; break; }
      }
    }
    if (sound) return attempt + 1;
  }
  return -1;
}

// Multi-draw fault-model expansion (inject/schedule.FaultModel).
//
// Expands a base single-site schedule (one row per injection) into the
// EXTRA sites of a multi-site fault model -- sites-1 rows per injection,
// site-major within injection (extra row m = i*(sites-1) + (j-1) is
// injection i's site j).  The base row is always site 0 and is not
// rewritten here.  Draws come from a derived counter-mode splitmix64
// stream (exp_seed = splitmix_at(seed, kExpandSalt)), indexed purely by
// (injection, site) so the expansion is order-independent and the numpy
// fallback in native/__init__.py is bit-identical by construction.
//
// Kinds (parameters are validated Python-side):
//   1 multibit(k):      k distinct bits in the base word.  One draw per
//                       injection picks an odd stride in [1,31]; site j
//                       flips bit (bit0 + j*stride) mod 32 -- odd strides
//                       generate Z/32, so all k <= 32 bits are distinct.
//   2 cluster(span,k):  k spatially-correlated flips in ADJACENT words of
//                       the base leaf.  The word space is the lane-major
//                       flattening (lane*words + word), so a cluster that
//                       runs off the end of one replica's words continues
//                       into the next lane -- exactly how the reference's
//                       cloned globals sit at consecutive addresses.  Site
//                       j lands 1 + (u mod span) words past the base
//                       (wrapping mod lanes*words) with its own bit draw.
//   3 burst(window,r):  temporally-bursty independent upsets: each extra
//                       site redraws a uniform location over the WHOLE
//                       map (same decode as MemoryMap.decode) and fires
//                       at t0 + (u mod window), clamped to steps-1.
//
// Outputs are n*(sites-1) int32 rows (group = injection index, then
// leaf/lane/word/bit/t).  Returns 0, or -2 on malformed input.
int32_t coast_fault_expand(
    uint64_t seed, int32_t kind, int32_t sites, int32_t span, int32_t window,
    int32_t steps, int64_t n, const int32_t* leaf0, const int32_t* lane0,
    const int32_t* word0, const int32_t* bit0, const int32_t* t0,
    const int32_t* sec0, int32_t n_sections, const int64_t* sec_bits_end,
    const int32_t* sec_leaf, const int32_t* sec_lanes,
    const int32_t* sec_words, int32_t* group, int32_t* leaf, int32_t* lane,
    int32_t* word, int32_t* bit, int32_t* t) {
  constexpr uint64_t kExpandSalt = 0x5EEDFA11ULL;
  if (n < 0 || sites < 2 || kind < 1 || kind > 3 || n_sections <= 0)
    return -2;
  if ((kind == 2 && span < 1) || (kind == 3 && (window < 1 || steps < 1)))
    return -2;
  const uint64_t exp_seed = splitmix_at(seed, kExpandSalt);
  const int64_t extras = sites - 1;
  const uint64_t total_bits = (uint64_t)sec_bits_end[n_sections - 1];
  for (int64_t i = 0; i < n; ++i) {
    // multibit: one stride draw per injection, shared by its sites.
    const uint64_t stride =
        kind == 1 ? 1 + 2 * (splitmix_at(exp_seed, (uint64_t)i) % 16) : 0;
    for (int64_t j = 1; j <= extras; ++j) {
      const int64_t m = i * extras + (j - 1);
      int32_t* const g = group + m;
      *g = (int32_t)i;
      if (kind == 1) {  // multibit: same word, distinct bits
        leaf[m] = leaf0[i];
        lane[m] = lane0[i];
        word[m] = word0[i];
        bit[m] = (int32_t)(((uint64_t)bit0[i] + (uint64_t)j * stride) % 32);
        t[m] = t0[i];
      } else if (kind == 2) {  // cluster: adjacent words, lane-crossing
        const int32_t s = sec0[i];
        if (s < 0 || s >= n_sections) return -2;
        const uint64_t words = (uint64_t)sec_words[s];
        const uint64_t lw = (uint64_t)sec_lanes[s] * words;
        const uint64_t u_off = splitmix_at(exp_seed, (uint64_t)(2 * m));
        const uint64_t u_bit = splitmix_at(exp_seed, (uint64_t)(2 * m + 1));
        const uint64_t phys = ((uint64_t)lane0[i] * words + (uint64_t)word0[i]
                               + 1 + (u_off % (uint64_t)span)) % lw;
        leaf[m] = leaf0[i];
        lane[m] = (int32_t)(phys / words);
        word[m] = (int32_t)(phys % words);
        bit[m] = (int32_t)(u_bit % 32);
        t[m] = t0[i];
      } else {  // burst: independent location, clustered time
        const uint64_t u_loc = splitmix_at(exp_seed, (uint64_t)(2 * m));
        const uint64_t u_dt = splitmix_at(exp_seed, (uint64_t)(2 * m + 1));
        const uint64_t flat = u_loc % total_bits;
        int32_t s = 0;  // searchsorted(side="right") over the bit edges
        while (s < n_sections - 1 && flat >= (uint64_t)sec_bits_end[s]) ++s;
        const uint64_t start = s == 0 ? 0 : (uint64_t)sec_bits_end[s - 1];
        const uint64_t off = flat - start;
        const uint64_t per_lane = (uint64_t)sec_words[s] * 32;
        leaf[m] = sec_leaf[s];
        lane[m] = (int32_t)(off / per_lane);
        word[m] = (int32_t)((off % per_lane) / 32);
        bit[m] = (int32_t)(off % 32);
        const int64_t tj = (int64_t)t0[i] + (int64_t)(u_dt % (uint64_t)window);
        t[m] = t0[i] < 0 ? t0[i]
                         : (int32_t)(tj < steps ? tj : (int64_t)steps - 1);
      }
    }
  }
  return 0;
}

// Bulk ndjson campaign-log classifier (the analysis read path).
//
// Scans InjectionLog-schema ndjson lines and accumulates the class counts
// of jsonParser-equivalent classify_run (analysis/json_parser.py:44-72):
// the discriminating key of each line's "result" object, in the FromDict
// priority order invalid > timeout > message > core; a core result is
// SDC when errors>0, else CORRECTED when faults>0, else SUCCESS, and
// contributes its runtime to the completed-run step mean.  Keys are
// searched only INSIDE the result object (the "name"/"symbol" fields can
// legitimately contain "<invalid-line>").
//
// counts must hold 8 zeroed int64 (SUCCESS..DUE_ASSERT, classify.py
// order; the DUE sub-bucket classes appended after INVALID).
// Returns the number of lines classified, or -1 if any non-empty line
// lacks the "result" marker (caller falls back to the Python parser).
int64_t coast_ndjson_classify(const char* buf, int64_t len, int64_t* counts,
                              int64_t* step_sum, int64_t* step_n) {
  static const char kResult[] = "\"result\": ";
  static const char kTail[] = ", \"cacheInfo\": null}";
  auto find = [](const char* p, const char* end, const char* needle,
                 size_t nlen) -> const char* {
    if ((size_t)(end - p) < nlen) return nullptr;
    const char* last = end - nlen;
    for (; p <= last; ++p) {
      if (p[0] == needle[0] && std::memcmp(p, needle, nlen) == 0) return p;
    }
    return nullptr;
  };
  // classify_run dispatches on the result DICT's top-level key membership
  // (analysis/json_parser.py:49-72).  Mirror that exactly: scan the result
  // object once, tracking string state and brace depth, and consider only
  // keys at depth 1.  A discriminating word appearing as a string VALUE
  // ({"status": "invalid"}) or inside a NESTED object ({"detail":
  // {"timeout": 5}}) must not reroute classification -- a plain substring
  // search silently diverges from the Python parser on such foreign lines.
  struct ResultKeys {
    bool object = false;   // result is a JSON object; anything else (list,
                           // string, null) gets Python's quirky membership
                           // semantics, so the caller must fall back.
    bool invalid = false, timeout = false, message = false, core = false;
    bool stack_overflow = false, assertion = false;
    bool self_heal = false, train_sdc = false;
    int64_t errors = 0, faults = 0, runtime = 0;
  };
  auto scan_result = [](const char* q, const char* end) -> ResultKeys {
    ResultKeys r;
    while (q < end && (*q == ' ' || *q == '\t')) ++q;
    if (q >= end || *q != '{') return r;   // r.object stays false
    r.object = true;
    int depth = 0;
    bool in_str = false, esc = false, have_key = false;
    const char* str_start = nullptr;    // open depth-1 string, if any
    const char* kb = nullptr;           // last completed depth-1 string
    size_t klen = 0;
    for (; q < end; ++q) {
      const char c = *q;
      if (in_str) {
        if (esc) esc = false;
        else if (c == '\\') esc = true;
        else if (c == '"') {
          in_str = false;
          if (depth == 1 && str_start) {
            kb = str_start;
            klen = (size_t)(q - str_start);
            have_key = true;
            str_start = nullptr;
          }
        }
        continue;
      }
      switch (c) {
        case '"':
          in_str = true;
          if (depth == 1) str_start = q + 1;
          break;
        case '{': case '[': ++depth; break;
        case '}': case ']':
          if (--depth == 0) return r;
          break;
        case ',': have_key = false; break;
        case ':':
          if (depth == 1 && have_key) {
            auto is = [&](const char* w, size_t n) {
              return klen == n && std::memcmp(kb, w, n) == 0;
            };
            if (is("invalid", 7)) r.invalid = true;
            else if (is("stackOverflow", 13)) r.stack_overflow = true;
            else if (is("assertion", 9)) r.assertion = true;
            else if (is("trainSdc", 8)) r.train_sdc = true;
            else if (is("selfHeal", 8)) r.self_heal = true;
            else if (is("timeout", 7)) r.timeout = true;
            else if (is("message", 7)) r.message = true;
            else if (is("core", 4)) r.core = true;
            else if (is("errors", 6) || is("faults", 6)
                     || is("runtime", 7)) {
              const char* v = q + 1;
              while (v < end && (*v == ' ' || *v == '\t')) ++v;
              const bool neg = (v < end && *v == '-');
              if (neg) ++v;
              int64_t x = 0;
              bool any = false;
              while (v < end && *v >= '0' && *v <= '9') {
                x = x * 10 + (*v - '0');
                ++v;
                any = true;
              }
              if (any) {
                x = neg ? -x : x;
                if (kb[0] == 'e') r.errors = x;
                else if (kb[0] == 'f') r.faults = x;
                else r.runtime = x;
              }
            }
            have_key = false;
          }
          break;
        default: break;
      }
    }
    return r;
  };
  auto rfind = [](const char* p, const char* end, const char* needle,
                  size_t nlen) -> const char* {
    if ((size_t)(end - p) < nlen) return nullptr;
    for (const char* q = end - nlen; q >= p; --q) {
      if (q[0] == needle[0] && std::memcmp(q, needle, nlen) == 0) return q;
    }
    return nullptr;
  };
  int64_t lines = 0;
  const char* p = buf;
  const char* const bend = buf + len;
  while (p < bend) {
    const char* nl = (const char*)std::memchr(p, '\n', bend - p);
    const char* lend = nl ? nl : bend;
    if (lend == p) { p = lend + 1; continue; }  // empty line
    // Anchor the result field from the line TAIL: a JSON-escaped leaf
    // name can legitimately contain the bytes "result": (escaping keeps
    // the inner quote characters), but the fixed result templates cannot,
    // so the LAST marker before the ", "cacheInfo": null} suffix is the
    // real field.  Lines without that exact suffix (foreign InjectionLog
    // writers) fall back to the first marker.
    const char* rend = lend;
    const char* res = nullptr;
    const size_t tail_len = sizeof kTail - 1;
    if ((size_t)(lend - p) > tail_len
        && std::memcmp(lend - tail_len, kTail, tail_len) == 0) {
      rend = lend - tail_len;
      res = rfind(p, rend, kResult, sizeof kResult - 1);
    } else {
      res = find(p, lend, kResult, sizeof kResult - 1);
    }
    if (!res) return -1;
    res += sizeof kResult - 1;
    const ResultKeys rk = scan_result(res, rend);
    // Non-object results (a list, a bare string, null): classify_run's
    // `"timeout" in res` membership does substring/element search there,
    // which this scanner deliberately does not model -- punt the whole
    // file to the Python parser rather than silently diverge.
    if (!rk.object) return -1;
    if (rk.invalid) {
      counts[5]++;
    } else if (rk.stack_overflow) {
      counts[6]++;
    } else if (rk.assertion) {
      counts[7]++;
    } else if (rk.train_sdc) {
      // Training refinements of SDC: completed runs (they carry the
      // ordinary core/runtime fields next to the discriminating key),
      // so they feed the mean-runtime statistic like classify_run's
      // "core" accounting does for them.
      counts[9]++;
      *step_sum += rk.runtime;
      (*step_n)++;
    } else if (rk.self_heal) {
      counts[8]++;
      *step_sum += rk.runtime;
      (*step_n)++;
    } else if (rk.timeout) {
      counts[4]++;
    } else if (rk.message) {
      counts[3]++;
    } else if (rk.core) {
      if (rk.errors > 0) counts[2]++;
      else if (rk.faults > 0) counts[1]++;
      else counts[0]++;
      *step_sum += rk.runtime;
      (*step_n)++;
    } else {
      counts[5]++;  // classify_run's final fallback: invalid
    }
    ++lines;
    p = lend + 1;
  }
  return lines;
}

// Bulk ndjson campaign-log encoder.
//
// Formats rows [lo, hi) of the campaign columns as one InjectionLog-schema
// JSON line each, byte-identical to inject/logs.write_ndjson's Python
// formatter.  String fields (section kind/name, timestamp) arrive
// pre-JSON-escaped from Python -- per-campaign work, not per-row.  Class
// codes match inject/classify.py (asserted at the call site):
//   0 SUCCESS, 1 CORRECTED, 2 SDC, 3 DUE_ABORT, 4 DUE_TIMEOUT, 5 INVALID,
//   6 DUE_STACK_OVERFLOW, 7 DUE_ASSERT, 8 TRAIN_SELF_HEAL, 9 TRAIN_SDC.
// Rows with t < 0 are cache draws outside the program footprint (never
// fired) and attribute to the "cache-invalid" pseudo-section.
//
// Returns bytes written into out, or -1 when the rows do not fit out_cap
// (every write is bounds-checked; the caller retries a smaller row range),
// -2 on malformed input.
//
// Two entry points share the body below: coast_ndjson_encode formats rows
// [lo, hi) of full-campaign columns (the one-shot writers), and
// coast_ndjson_encode_rows formats rows [0, n) of a BATCH's columns with
// an explicit "number" base -- the per-batch entry the streaming writer
// feeds as each dispatch batch is collected, so serialization overlaps
// the device work instead of following it.
static int64_t ndjson_encode_body(
    int64_t lo, int64_t hi, int64_t number_base, const int32_t* leaf_id,
    const int32_t* lane, const int32_t* word, const int32_t* bit,
    const int32_t* t, const int32_t* code, const int32_t* errors,
    const int32_t* corrected, const int32_t* steps, int32_t n_leaves,
    const char* const* sec_kind, const char* const* sec_name, const char* ts,
    char* out, int64_t out_cap) {
  if (lo < 0 || hi < lo || n_leaves < 0) return -2;
  const size_t ts_len = std::strlen(ts);
  std::vector<size_t> kind_len(n_leaves), name_len(n_leaves);
  for (int32_t s = 0; s < n_leaves; ++s) {
    kind_len[s] = std::strlen(sec_kind[s]);
    name_len[s] = std::strlen(sec_name[s]);
  }
  Writer w{out, out + out_cap};
  for (int64_t i = lo; i < hi; ++i) {
    put_lit(w, "{\"timestamp\": \"");
    put_str(w, ts, ts_len);
    put_lit(w, "\", \"number\": ");
    put_i64(w, number_base + i);
    put_lit(w, ", \"section\": \"");
    const int32_t lid = leaf_id[i];
    const bool invalid_line = t[i] < 0;
    if (!invalid_line && (lid < 0 || lid >= n_leaves)) return -2;
    if (invalid_line) {
      put_lit(w, "cache-invalid");
    } else {
      put_str(w, sec_kind[lid], kind_len[lid]);
    }
    put_lit(w, "\", \"address\": ");
    put_i64(w, word[i]);
    put_lit(w, ", \"oldValue\": null, \"newValue\": null, "
               "\"sleepTime\": 0, \"cycles\": ");
    put_i64(w, t[i]);
    put_lit(w, ", \"PC\": ");
    put_i64(w, t[i]);
    put_lit(w, ", \"name\": \"");
    if (invalid_line) {
      put_lit(w, "<invalid-line>^bit");
      put_i64(w, bit[i]);
    } else {
      put_str(w, sec_name[lid], name_len[lid]);
      put_lit(w, "[lane ");
      put_i64(w, lane[i]);
      put_lit(w, "]^bit");
      put_i64(w, bit[i]);
    }
    put_lit(w, "\", \"symbol\": \"");
    if (invalid_line) {
      put_lit(w, "<invalid-line>");
    } else {
      put_str(w, sec_name[lid], name_len[lid]);
    }
    put_lit(w, "\", \"result\": ");
    switch (code[i]) {
      case 0:  // SUCCESS
      case 1:  // CORRECTED
      case 2:  // SDC
        put_lit(w, "{\"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\", \"core\": 0, \"runtime\": ");
        put_i64(w, steps[i]);
        put_lit(w, ", \"errors\": ");
        put_i64(w, errors[i]);
        put_lit(w, ", \"faults\": ");
        put_i64(w, corrected[i]);
        put_lit(w, "}");
        break;
      case 3:  // DUE_ABORT
        put_lit(w, "{\"type\": \"DWC/CFCSS\", \"message\": "
                   "\"FAULT_DETECTED abort\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\", \"errors\": 1}");
        break;
      case 4:  // DUE_TIMEOUT
        put_lit(w, "{\"trap\": false, \"timeout\": \"hit step bound at ");
        put_i64(w, steps[i]);
        put_lit(w, "\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\"}");
        break;
      case 5:  // INVALID
        put_lit(w, "{\"invalid\": \"self-check out of domain (E=");
        put_i64(w, errors[i]);
        put_lit(w, ")\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\"}");
        break;
      case 6:  // DUE_STACK_OVERFLOW
        put_lit(w, "{\"stackOverflow\": \"stack check tripped at step ");
        put_i64(w, steps[i]);
        put_lit(w, "\", \"taskName\": \"<kernel>\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\", \"errors\": 1}");
        break;
      case 7:  // DUE_ASSERT
        put_lit(w, "{\"assertion\": \"kernel assert tripped at step ");
        put_i64(w, steps[i]);
        put_lit(w, "\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\", \"errors\": 1}");
        break;
      case 8:  // TRAIN_SELF_HEAL
        put_lit(w, "{\"selfHeal\": \"transient loss perturbation healed "
                   "(E=");
        put_i64(w, errors[i]);
        put_lit(w, ")\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\", \"core\": 0, \"runtime\": ");
        put_i64(w, steps[i]);
        put_lit(w, ", \"errors\": ");
        put_i64(w, errors[i]);
        put_lit(w, ", \"faults\": ");
        put_i64(w, corrected[i]);
        put_lit(w, "}");
        break;
      case 9:  // TRAIN_SDC
        put_lit(w, "{\"trainSdc\": \"persistent weight corruption (E=");
        put_i64(w, errors[i]);
        put_lit(w, ")\", \"timestamp\": \"");
        put_str(w, ts, ts_len);
        put_lit(w, "\", \"core\": 0, \"runtime\": ");
        put_i64(w, steps[i]);
        put_lit(w, ", \"errors\": ");
        put_i64(w, errors[i]);
        put_lit(w, ", \"faults\": ");
        put_i64(w, corrected[i]);
        put_lit(w, "}");
        break;
      default:
        return -2;
    }
    put_lit(w, ", \"cacheInfo\": null}\n");
    if (w.overflow) return -1;
  }
  return w.p - out;
}

int64_t coast_ndjson_encode(
    int64_t lo, int64_t hi, const int32_t* leaf_id, const int32_t* lane,
    const int32_t* word, const int32_t* bit, const int32_t* t,
    const int32_t* code, const int32_t* errors, const int32_t* corrected,
    const int32_t* steps, int32_t n_leaves, const char* const* sec_kind,
    const char* const* sec_name, const char* ts, char* out,
    int64_t out_cap) {
  // Full-campaign columns: row i carries number i.
  return ndjson_encode_body(lo, hi, 0, leaf_id, lane, word, bit, t, code,
                            errors, corrected, steps, n_leaves, sec_kind,
                            sec_name, ts, out, out_cap);
}

// Per-batch entry point: columns hold ONE collected batch (rows [0, n)),
// "number" fields run number_base..number_base+n-1 -- the global row
// indices of the batch within its campaign stream.  Output is
// byte-identical to coast_ndjson_encode over the same rows of the full
// columns (tests/test_stream_logs.py pins it).
int64_t coast_ndjson_encode_rows(
    int64_t n, int64_t number_base, const int32_t* leaf_id,
    const int32_t* lane, const int32_t* word, const int32_t* bit,
    const int32_t* t, const int32_t* code, const int32_t* errors,
    const int32_t* corrected, const int32_t* steps, int32_t n_leaves,
    const char* const* sec_kind, const char* const* sec_name, const char* ts,
    char* out, int64_t out_cap) {
  if (n < 0 || number_base < 0) return -2;
  return ndjson_encode_body(0, n, number_base, leaf_id, lane, word, bit, t,
                            code, errors, corrected, steps, n_leaves,
                            sec_kind, sec_name, ts, out, out_cap);
}

}  // extern "C"
