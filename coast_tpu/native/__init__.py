"""Native C++ core loader (ctypes) with bit-exact numpy fallbacks.

The reference's native components are LLVM C++ passes (projects/); this
framework's native core (coast_core.cpp) carries the host-side work that is
not XLA's job: bulk seeded RNG for fault schedules, CFCSS signature
assignment over block graphs, and the bulk campaign-log ndjson encoder (the
IO path of 10^6-run campaigns).  Built via ``make -C coast_tpu/native``;
every entry point has a Python/numpy fallback that produces *identical*
results so the Python path never blocks on a compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libcoast_core.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SPLITMIX_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _try_build() -> None:
    src = os.path.join(_HERE, "coast_core.cpp")
    if not os.path.exists(src):
        return
    try:
        subprocess.run(["make", "-C", _HERE, "-s"], check=True,
                       capture_output=True, timeout=120)
    except Exception:
        pass


def _stale() -> bool:
    src = os.path.join(_HERE, "coast_core.cpp")
    return (os.path.exists(src) and os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) < os.path.getmtime(src))


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) or _stale():
        _try_build()
    if os.path.exists(_LIB_PATH):
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            try:
                lib.coast_abi_version.argtypes = []
                lib.coast_abi_version.restype = ctypes.c_int32
            except AttributeError:
                pass
            lib.coast_rand64.argtypes = [
                ctypes.c_uint64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")]
            lib.coast_rand64.restype = None
            lib.coast_cfcss_assign.argtypes = [
                ctypes.c_int32, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_uint64, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")]
            lib.coast_cfcss_assign.restype = ctypes.c_int32
            try:
                # Fault-model expansion (own guard: an older .so degrades
                # only the expansion to the numpy fallback, nothing else).
                i32a = np.ctypeslib.ndpointer(np.int32,
                                              flags="C_CONTIGUOUS")
                i64a = np.ctypeslib.ndpointer(np.int64,
                                              flags="C_CONTIGUOUS")
                lib.coast_fault_expand.argtypes = [
                    ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_int64,
                    i32a, i32a, i32a, i32a, i32a, i32a,
                    ctypes.c_int32, i64a, i32a, i32a, i32a,
                    i32a, i32a, i32a, i32a, i32a, i32a]
                lib.coast_fault_expand.restype = ctypes.c_int32
            except AttributeError:
                pass
            try:
                lib.coast_ndjson_classify.argtypes = [
                    ctypes.c_char_p, ctypes.c_int64,
                    np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64)]
                lib.coast_ndjson_classify.restype = ctypes.c_int64
            except AttributeError:
                pass
            try:
                # Newer symbol in its own guard: an older .so (rebuild
                # failed on a compiler-less host) must degrade only the
                # ndjson path, not the whole native core -- callers check
                # hasattr before using it.
                i32arr = np.ctypeslib.ndpointer(np.int32,
                                                flags="C_CONTIGUOUS")
                lib.coast_ndjson_encode.argtypes = [
                    ctypes.c_int64, ctypes.c_int64,
                    i32arr, i32arr, i32arr, i32arr, i32arr,
                    i32arr, i32arr, i32arr, i32arr,
                    ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
                lib.coast_ndjson_encode.restype = ctypes.c_int64
            except AttributeError:
                pass
            try:
                # Per-batch streaming entry (coast_ndjson_encode_rows):
                # formats one collected batch's columns with an explicit
                # "number" base, so the streaming log writer serialises
                # batches as they land instead of after the campaign.
                # Own guard: an older .so degrades only the streaming
                # fast path (Python formatter takes over), nothing else.
                i32arr = np.ctypeslib.ndpointer(np.int32,
                                                flags="C_CONTIGUOUS")
                lib.coast_ndjson_encode_rows.argtypes = [
                    ctypes.c_int64, ctypes.c_int64,
                    i32arr, i32arr, i32arr, i32arr, i32arr,
                    i32arr, i32arr, i32arr, i32arr,
                    ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
                lib.coast_ndjson_encode_rows.restype = ctypes.c_int64
            except AttributeError:
                pass
            _lib = lib
        except (OSError, AttributeError):
            # Unloadable or built from an older source missing a symbol:
            # fall back to numpy rather than crash every native-backed path.
            _lib = None
    return _lib


def native_available() -> bool:
    return get_lib() is not None


# Class-taxonomy ABI this Python layer speaks: must match the NUM_CLASSES
# result codes of inject/classify.py.  The ndjson entry points refuse an
# older .so (missing or lower coast_abi_version): a pre-sub-bucket binary
# would render DUE_STACK_OVERFLOW/DUE_ASSERT (ABI 2) or TRAIN_SELF_HEAL/
# TRAIN_SDC (ABI 3) rows as malformed (-2) or classify their result keys
# into 'invalid' -- silent divergence from the Python paths, which is
# worse than falling back to them.
NDJSON_ABI = 3
NUM_CLASSES = 10


def _ndjson_lib() -> Optional[ctypes.CDLL]:
    lib = get_lib()
    if lib is None or not hasattr(lib, "coast_abi_version"):
        return None
    if lib.coast_abi_version() < NDJSON_ABI:
        return None
    return lib


def splitmix_fill(seed: int, n: int) -> np.ndarray:
    """n counter-mode splitmix64 draws (uint64).  Counter-based (value i =
    finalizer(seed + (i+1)*golden)) so the C++ and numpy paths are trivially
    bit-identical and the numpy path vectorises."""
    seed = seed & 0xFFFFFFFFFFFFFFFF
    lib = get_lib()
    if lib is not None:
        out = np.empty(n, dtype=np.uint64)
        lib.coast_rand64(np.uint64(seed), n, out)
        return out
    with np.errstate(over="ignore"):
        idx = np.arange(1, n + 1, dtype=np.uint64)
        z = np.uint64(seed) + idx * _SPLITMIX_GOLDEN
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


#: Derived-stream salt of the fault-model expansion: the expansion draws
#: come from splitmix_at(seed, FAULT_EXPAND_SALT) so they never collide
#: with the base schedule's own (seed, counter) stream.  Must match
#: kExpandSalt in coast_core.cpp.
FAULT_EXPAND_SALT = 0x5EEDFA11

_FAULT_KINDS = {"multibit": 1, "cluster": 2, "burst": 3}


def fault_expand(seed: int, kind: str, sites: int, span: int, window: int,
                 steps: int, base, sec_tables,
                 force_python: bool = False):
    """Expand a base single-site schedule into its extra flip-group rows.

    ``base`` is a dict of int32 arrays (leaf_id, lane, word, bit, t,
    section_idx), one row per injection; ``sec_tables`` is
    ``(bits_end, leaf, lanes, words)`` -- the MemoryMap's section layout
    (cumulative bit edges int64, then per-section int32 columns).
    Returns ``(group, leaf_id, lane, word, bit, t)`` int32 arrays of
    length ``n * (sites - 1)``, site-major within injection.  Native
    (coast_fault_expand) when available, else a bit-identical numpy
    path; ``force_python`` pins the fallback (the parity tests)."""
    n = len(base["leaf_id"])
    m = n * (sites - 1)
    kind_id = _FAULT_KINDS[kind]
    cols = {k: np.ascontiguousarray(base[k], np.int32)
            for k in ("leaf_id", "lane", "word", "bit", "t", "section_idx")}
    bits_end = np.ascontiguousarray(sec_tables[0], np.int64)
    sec_leaf, sec_lanes, sec_words = (
        np.ascontiguousarray(a, np.int32) for a in sec_tables[1:])
    lib = None if force_python else get_lib()
    if lib is not None and hasattr(lib, "coast_fault_expand"):
        group = np.empty(m, np.int32)
        out = {k: np.empty(m, np.int32)
               for k in ("leaf_id", "lane", "word", "bit", "t")}
        rc = lib.coast_fault_expand(
            np.uint64(seed & 0xFFFFFFFFFFFFFFFF), np.int32(kind_id),
            np.int32(sites), np.int32(span), np.int32(window),
            np.int32(steps), np.int64(n),
            cols["leaf_id"], cols["lane"], cols["word"], cols["bit"],
            cols["t"], cols["section_idx"],
            np.int32(len(sec_leaf)), bits_end, sec_leaf, sec_lanes,
            sec_words, group, out["leaf_id"], out["lane"], out["word"],
            out["bit"], out["t"])
        if rc != 0:
            raise ValueError(f"coast_fault_expand failed (rc={rc})")
        return (group, out["leaf_id"], out["lane"], out["word"],
                out["bit"], out["t"])

    # ---- numpy fallback (bit-identical: same derived stream + indexing) --
    exp_seed = _splitmix_at(seed & 0xFFFFFFFFFFFFFFFF, FAULT_EXPAND_SALT)
    extras = sites - 1
    group = np.repeat(np.arange(n, dtype=np.int32), extras)
    i = group.astype(np.int64)                    # base row per extra row
    j = np.tile(np.arange(1, sites, dtype=np.int64), n)   # site index
    if kind == "multibit":
        u = splitmix_fill(exp_seed, n)
        stride = (1 + 2 * (u % np.uint64(16)))[i]
        bit = ((cols["bit"][i].astype(np.uint64)
                + j.astype(np.uint64) * stride) % np.uint64(32))
        return (group, cols["leaf_id"][i], cols["lane"][i],
                cols["word"][i], bit.astype(np.int32), cols["t"][i])
    # Extra row r (site-major: r = i*extras + (j-1) = 0..m-1 in order)
    # consumes stream draws 2r and 2r+1, matching the C++ loop exactly.
    u = splitmix_fill(exp_seed, 2 * m) if m else np.zeros(0, np.uint64)
    u0, u1 = u[0::2], u[1::2]
    if kind == "cluster":
        s = cols["section_idx"][i]
        words = sec_words[s].astype(np.uint64)
        lw = sec_lanes[s].astype(np.uint64) * words
        phys = (cols["lane"][i].astype(np.uint64) * words
                + cols["word"][i].astype(np.uint64)
                + np.uint64(1) + u0 % np.uint64(span)) % lw
        return (group, cols["leaf_id"][i],
                (phys // words).astype(np.int32),
                (phys % words).astype(np.int32),
                (u1 % np.uint64(32)).astype(np.int32), cols["t"][i])
    # burst: fresh uniform location over the whole map + clustered time
    total_bits = np.uint64(bits_end[-1])
    flat = (u0 % total_bits).astype(np.int64)
    s = np.searchsorted(bits_end, flat, side="right")
    start = np.where(s == 0, 0, bits_end[np.maximum(s - 1, 0)])
    off = flat - start
    per_lane = sec_words[s].astype(np.int64) * 32
    t0 = cols["t"][i].astype(np.int64)
    tj = np.minimum(t0 + (u1 % np.uint64(window)).astype(np.int64),
                    steps - 1)
    return (group, sec_leaf[s].astype(np.int32),
            (off // per_lane).astype(np.int32),
            ((off % per_lane) // 32).astype(np.int32),
            (off % 32).astype(np.int32),
            np.where(t0 < 0, t0, tj).astype(np.int32))


def ndjson_stream_rows(lo: int, hi: int, col, sec_kind_by_leaf,
                       sec_name_by_leaf, ts: str, write,
                       chunk_bytes: int = 32 << 20) -> bool:
    """Native bulk serialisation of campaign rows [lo, hi) to
    InjectionLog-schema ndjson lines (byte-identical to the Python
    formatter in inject/logs.write_ndjson), streamed chunk-by-chunk to
    ``write`` so peak memory stays at one bounded buffer regardless of
    campaign size.  ``col`` is a dict of int32 numpy columns;
    ``sec_kind_by_leaf``/``sec_name_by_leaf`` are lists of
    pre-JSON-escaped strings indexed by leaf_id.  Returns False (before
    writing anything) when the native core is unavailable, so the caller
    can fall back to the Python loop; raises on malformed input, which
    indicates a bug rather than a missing compiler."""
    lib = _ndjson_lib()
    if lib is None or not hasattr(lib, "coast_ndjson_encode"):
        return False
    n_leaves = len(sec_kind_by_leaf)
    kind_arr = (ctypes.c_char_p * n_leaves)(
        *(s.encode() for s in sec_kind_by_leaf))
    name_arr = (ctypes.c_char_p * n_leaves)(
        *(s.encode() for s in sec_name_by_leaf))
    cols = {k: np.ascontiguousarray(col[k], np.int32)
            for k in ("leaf_id", "lane", "word", "bit", "t",
                      "code", "errors", "corrected", "steps")}
    buf = ctypes.create_string_buffer(chunk_bytes)
    ts_b = ts.encode()

    def encode(i, j):
        return lib.coast_ndjson_encode(
            i, j, cols["leaf_id"], cols["lane"], cols["word"], cols["bit"],
            cols["t"], cols["code"], cols["errors"], cols["corrected"],
            cols["steps"], np.int32(n_leaves), kind_arr, name_arr,
            ts_b, buf, chunk_bytes)

    # Advisory chunk sizing: estimate rows per chunk from a conservative
    # per-line bound so long leaf names shrink the chunk up front.  Safety
    # does not depend on the estimate -- the C writer bounds-checks every
    # write and returns -1 on overflow, which the halving loop below
    # retries with fewer rows (discarding that one failed pass).
    max_str = max([len(ts_b)] + [len(s) for s in kind_arr]
                  + [len(s) for s in name_arr])
    line_bound = 320 + 2 * len(ts_b) + 3 * max_str + 9 * 20
    rows_per_chunk = max(1, chunk_bytes // line_bound)
    _drain_encoded(encode, lo, hi, rows_per_chunk, buf, write)
    return True


def _drain_encoded(encode, lo: int, hi: int, rows_per_chunk: int,
                   buf, write) -> None:
    """Shared chunking loop of the native ndjson encoders: encode rows
    [lo, hi) in advisory-sized chunks, halving a chunk that overflowed
    the buffer (the C writer bounds-checks and returns -1), and hand each
    encoded chunk to ``write``."""
    i = lo
    while i < hi:
        j = min(hi, i + rows_per_chunk)
        wrote = encode(i, j)
        while wrote == -1 and j - i > 1:   # belt-and-braces: halve until fit
            j = i + max(1, (j - i) // 2)
            wrote = encode(i, j)
        if wrote < 0:
            raise RuntimeError(
                f"coast_ndjson_encode failed (rc={wrote}) on rows "
                f"[{i}, {j})")
        write(ctypes.string_at(buf, wrote))
        i = j


def ndjson_stream_batch(number_base: int, col, sec_kind_by_leaf,
                        sec_name_by_leaf, ts: str, write,
                        chunk_bytes: int = 32 << 20) -> bool:
    """Native serialisation of ONE collected batch's rows to
    InjectionLog-schema ndjson lines, with ``number`` fields
    number_base..number_base+n-1 -- byte-identical to the same rows of a
    one-shot ``ndjson_stream_rows`` over the full campaign columns.  The
    per-batch entry point of the streaming log writer
    (inject/logs.StreamLogWriter): each batch is encoded as it is
    collected, overlapping the next dispatch.  Returns False (before
    writing anything) when the native core or the
    ``coast_ndjson_encode_rows`` symbol is unavailable (older .so), so
    the caller falls back to the Python formatter."""
    lib = _ndjson_lib()
    if lib is None or not hasattr(lib, "coast_ndjson_encode_rows"):
        return False
    n_leaves = len(sec_kind_by_leaf)
    kind_arr = (ctypes.c_char_p * n_leaves)(
        *(s.encode() for s in sec_kind_by_leaf))
    name_arr = (ctypes.c_char_p * n_leaves)(
        *(s.encode() for s in sec_name_by_leaf))
    cols = {k: np.ascontiguousarray(col[k], np.int32)
            for k in ("leaf_id", "lane", "word", "bit", "t",
                      "code", "errors", "corrected", "steps")}
    n = len(cols["code"])
    ts_b = ts.encode()
    max_str = max([len(ts_b)] + [len(s) for s in kind_arr]
                  + [len(s) for s in name_arr])
    line_bound = 320 + 2 * len(ts_b) + 3 * max_str + 9 * 20
    # This entry runs once PER BATCH, so the buffer is sized to the batch
    # (bounded by chunk_bytes), not allocated at the full chunk budget:
    # ctypes.create_string_buffer zero-fills, and zeroing 32 MB per
    # 2048-row batch would cost more than the encode itself.
    buf_bytes = int(min(chunk_bytes, line_bound * max(n, 1) + 4096))
    buf = ctypes.create_string_buffer(buf_bytes)

    def encode(i, j):
        # Sub-range [i, j) of the batch: shift the column base and the
        # number base together so chunking is invisible in the output.
        sub = {k: cols[k][i:j] for k in cols}
        return lib.coast_ndjson_encode_rows(
            j - i, number_base + i, sub["leaf_id"], sub["lane"],
            sub["word"], sub["bit"], sub["t"], sub["code"], sub["errors"],
            sub["corrected"], sub["steps"], np.int32(n_leaves), kind_arr,
            name_arr, ts_b, buf, buf_bytes)

    rows_per_chunk = max(1, buf_bytes // line_bound)
    _drain_encoded(encode, 0, n, rows_per_chunk, buf, write)
    return True


def ndjson_classify_stream(read_chunk, chunk_bytes: int = 32 << 20):
    """Classify InjectionLog ndjson rows with the native core.

    ``read_chunk(n)`` returns up to n bytes (an open binary file's
    ``read``); partial trailing lines are carried across chunks.  Returns
    ``(counts[NUM_CLASSES], step_sum, step_n, n_lines)`` or None when the
    native core is unavailable (or predates the current class-taxonomy
    ABI); raises ValueError if a line is not InjectionLog-shaped (caller
    falls back to the Python parser)."""
    lib = _ndjson_lib()
    if lib is None or not hasattr(lib, "coast_ndjson_classify"):
        return None
    counts = np.zeros(NUM_CLASSES, np.int64)
    step_sum = ctypes.c_int64(0)
    step_n = ctypes.c_int64(0)
    total = 0
    carry = b""
    while True:
        chunk = read_chunk(chunk_bytes)
        if not chunk:
            buf = carry
            carry = b""
        else:
            data = carry + chunk
            cut = data.rfind(b"\n")
            if cut < 0:
                carry = data
                continue
            buf, carry = data[:cut + 1], data[cut + 1:]
        if buf:
            got = lib.coast_ndjson_classify(
                buf, len(buf), counts,
                ctypes.byref(step_sum), ctypes.byref(step_n))
            if got < 0:
                raise ValueError("not an InjectionLog ndjson stream")
            total += got
        if not chunk:
            break
    return counts, int(step_sum.value), int(step_n.value), total


def _splitmix_at(seed: int, i: int) -> int:
    z = (seed + (i + 1) * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


def cfcss_assign(n: int, edges, seed: int = 0, sig_bits: int = 16):
    """CFCSS signature assignment over a block graph (node 0 = entry).

    Returns dict(sigs, diffs, fanin, dedge, attempts); see coast_core.cpp
    for the algorithm (generateSignatures/calcSigDiff/verifySignatures
    equivalents, CFCSS.cpp:187-201/:439-470/:380-426, with buffer blocks
    folded into per-edge adjusters).  Native path and this fallback are
    bit-identical by construction (same splitmix64 stream + same spin loop).
    """
    seed = seed & 0xFFFFFFFFFFFFFFFF
    edges = np.ascontiguousarray(np.asarray(edges, np.int32).reshape(-1, 2))
    n_edges = len(edges)
    lib = get_lib()
    if lib is not None and hasattr(lib, "coast_cfcss_assign"):
        sigs = np.empty(n, np.uint32)
        diffs = np.empty(n, np.uint32)
        fanin = np.empty(n, np.uint8)
        dedge = np.empty(n * n, np.uint32)
        rc = lib.coast_cfcss_assign(
            np.int32(n), np.int32(n_edges), edges.reshape(-1),
            np.uint64(seed), np.int32(sig_bits), sigs, diffs, fanin, dedge)
        if rc < 0:
            raise ValueError(f"cfcss_assign failed (rc={rc})")
        return {"sigs": sigs, "diffs": diffs, "fanin": fanin.astype(bool),
                "dedge": dedge.reshape(n, n), "attempts": int(rc)}

    # ---- numpy/python fallback (bit-identical) ----
    if n <= 0 or not (1 < sig_bits <= 32):
        raise ValueError("cfcss_assign failed (rc=-2)")
    if np.any(edges < 0) or np.any(edges >= n):
        raise ValueError("cfcss_assign failed (rc=-2)")
    mask = 0xFFFFFFFF if sig_bits == 32 else (1 << sig_bits) - 1
    for attempt in range(64):
        used = set()
        sigs = np.zeros(n, np.uint32)
        ctr = 0
        ok = True
        for v in range(n):
            spins = 0
            while True:
                s = _splitmix_at(seed + attempt, ctr) & mask
                ctr += 1
                spins += 1
                if s not in used:
                    break
                if spins > mask + 8:
                    ok = False
                    break
            if not ok:
                break
            used.add(s)
            sigs[v] = s
        if not ok:
            raise ValueError("cfcss_assign failed (rc=-1)")

        is_edge = np.zeros((n, n), bool)
        u0 = np.full(n, -1, np.int32)
        pred_count = np.zeros(n, np.int32)
        for u, v in edges:
            if is_edge[u, v]:
                continue
            is_edge[u, v] = True
            pred_count[v] += 1
            if u0[v] < 0 or u < u0[v]:
                u0[v] = u
        fanin = pred_count > 1
        diffs = np.where(u0 >= 0, sigs[np.maximum(u0, 0)] ^ sigs, sigs)
        dedge = np.zeros((n, n), np.uint32)
        for u, v in edges:
            if fanin[v]:
                dedge[u, v] = sigs[u0[v]] ^ sigs[u]

        g = sigs[:, None] ^ diffs[None, :]          # illegal jump u -> v
        aliased = np.logical_and(~is_edge, g == sigs[None, :])
        if not aliased.any():
            return {"sigs": sigs, "diffs": diffs.astype(np.uint32),
                    "fanin": fanin, "dedge": dedge, "attempts": attempt + 1}
    raise ValueError("cfcss_assign failed (rc=-1)")
