"""Native C++ core loader (ctypes) with bit-exact numpy fallbacks.

The reference's native components are LLVM C++ passes (projects/); this
framework's native core (coast_core.cpp) carries the host-side compute that
is not XLA's job: bulk seeded RNG for fault schedules, CFCSS signature
assignment over block graphs, and the replica scheduler.  Built via
``make -C coast_tpu/native``; every entry point has a numpy fallback that
produces *identical* results so the Python path never blocks on a compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libcoast_core.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SPLITMIX_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _try_build() -> None:
    src = os.path.join(_HERE, "coast_core.cpp")
    if not os.path.exists(src):
        return
    try:
        subprocess.run(["make", "-C", _HERE, "-s"], check=True,
                       capture_output=True, timeout=120)
    except Exception:
        pass


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH):
        _try_build()
    if os.path.exists(_LIB_PATH):
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.coast_rand64.argtypes = [
                ctypes.c_uint64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")]
            lib.coast_rand64.restype = None
            _lib = lib
        except OSError:
            _lib = None
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def splitmix_fill(seed: int, n: int) -> np.ndarray:
    """n counter-mode splitmix64 draws (uint64).  Counter-based (value i =
    finalizer(seed + (i+1)*golden)) so the C++ and numpy paths are trivially
    bit-identical and the numpy path vectorises."""
    seed = seed & 0xFFFFFFFFFFFFFFFF
    lib = get_lib()
    if lib is not None:
        out = np.empty(n, dtype=np.uint64)
        lib.coast_rand64(np.uint64(seed), n, out)
        return out
    with np.errstate(over="ignore"):
        idx = np.arange(1, n + 1, dtype=np.uint64)
        z = np.uint64(seed) + idx * _SPLITMIX_GOLDEN
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))
