"""Sphere-of-Replication verification: the verifyOptions equivalent.

The reference statically checks that the user's replication-scope choices
are self-consistent before cloning anything, and refuses to compile
otherwise (projects/dataflowProtection/verification.cpp:719-1077, rules
table in the comment at :686-718; ``std::exit(-1)`` at :1055-1065).  Its
rules, translated to the region model:

  Protected -> NotProtected (a replicated value stored to an unreplicated
  leaf): OK, but *vote first* -- the engine already forces a boundary vote
  on every such store (the ``syncGlobalStores`` set, verification.cpp
  :587,676); the verifier reports these as forced sync points.

  NotProtected -> Protected (an unreplicated, *mutable* leaf feeding a
  replicated leaf): NOT OK -- corrupted unprotected state would be imported
  into every replica identically, silently defeating replication.  Reading
  never-written (read-only) unprotected data is OK.

TPU-native analysis: where the reference walks LLVM use-def chains, we
trace the region's ``step`` to a **jaxpr** and propagate leaf provenance
through its equations -- the use-def chain of the XLA program itself.  A
leaf is *written* if its output is not the identity passthrough of its
input var; leaf-level dependencies are the transitive closure over eqn
operands.

Like the reference, violations raise (the exit(-1) analogue) with an error
listing every offending leaf, and the expected-rejection unit tests
(globalPointers.c / linkedList.c / verifyOptions.c, unitTestDriver.py
``cf=True``) assert that bad configs *fail to compile*.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Set

import jax
import jax.numpy as jnp
from jax.extend.core import Literal

from coast_tpu.ir.region import KIND_CTRL, KIND_LINK, KIND_RO, Region

# Mirror of the reference's colored error prefix (dataflowProtection.h:84-96).
_ERR = "ERROR (SoR verification): "


class SoRViolation(Exception):
    """Raised instead of the reference's std::exit(-1); carries all
    violations found (the reference also reports all before exiting)."""

    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("\n".join(_ERR + e for e in errors))


@dataclasses.dataclass(frozen=True)
class RegionDataflow:
    """Static dataflow facts about a region's step function."""

    written: FrozenSet[str]                 # leaves not passed through identity
    deps: Dict[str, FrozenSet[str]]         # out leaf -> source leaves
    # Address-forming roles: leaves whose values flow into the index
    # operands of load-like (gather/dynamic_slice) or store-like
    # (scatter/dynamic_update_slice) primitives.  These are the TPU
    # analogues of the GEP operands the reference's syncGEP votes
    # (synchronization.cpp:413-474): a "load address" is a gather index, a
    # "store address" is a scatter/dynamic-update index.
    load_addr: FrozenSet[str] = frozenset()
    store_addr: FrozenSet[str] = frozenset()
    # Leaves used as the *target* of a store-like partial update (operand 0
    # of dynamic_update_slice / scatter): the memory the program stores
    # into, i.e. the leaves the reference's syncStoreInst guards
    # (synchronization.cpp:476-561).  Used by the region lifter to classify
    # KIND_MEM automatically.
    stored_into: FrozenSet[str] = frozenset()
    # Leaves whose values feed branch predicates (cond/while predicates,
    # select_n selectors): the terminator-sync state the reference votes
    # before every branch (syncTerminator :741-1113).  Used by the lifter
    # to classify KIND_CTRL.
    branch_pred: FrozenSet[str] = frozenset()


# Primitives that read memory at a data-dependent address (their trailing
# operands are indices) vs write at one.  jnp indexing lowers to these.
_LOAD_PRIMS = ("gather", "dynamic_slice")
_STORE_UPDATE_PRIM = "dynamic_update_slice"

# Sentinel for "this var has been seen with conflicting alias roots" in
# _trace_provenance's passthrough tracking (distinct from "never seen").
_NO_ALIAS = object()


def _trace_provenance(jaxpr, names):
    """Propagate leaf provenance through a jaxpr whose first ``len(names)``
    invars are the named state leaves (any remaining invars -- e.g. the step
    counter ``t`` -- carry no provenance).

    Returns ``(out_sets, in_var_of, facts)`` where ``out_sets`` is the leaf
    dep set of every jaxpr outvar, ``in_var_of`` maps leaf name -> invar,
    and ``facts`` holds the role sets (load/store address, store target,
    branch predicate)."""
    src: Dict[object, Set[str]] = {}
    in_var_of: Dict[str, object] = {}
    for name, var in zip(names, jaxpr.invars):
        src[var] = {name}
        in_var_of[name] = var

    load_addr: Set[str] = set()
    store_addr: Set[str] = set()
    stored_into: Set[str] = set()
    branch_pred: Set[str] = set()

    # Var-level aliasing: alias[v] is the top-level invar v is provably
    # identical to on EVERY control path (cond/switch branches that all
    # return the same operand unchanged, pjit/call passthrough).  Needed
    # because ``lax.cond``/``lax.switch`` outputs are fresh jaxpr vars even
    # when every branch is an identity -- without it, a leaf routed through
    # a phase switch looks written and loses its unwritten-global (ro)
    # classification.  ``_NO_ALIAS`` marks a var seen with conflicting
    # roots (alias knowledge only ever narrows, keeping fixpoints sound).
    alias: Dict[object, object] = {v: v for v in jaxpr.invars}

    def aroot(v):
        if isinstance(v, Literal):
            return None
        r = alias.get(v)
        return None if r is _NO_ALIAS else r

    def aseed(inner_vars, outer_vars) -> None:
        for iv, ov in zip(inner_vars, outer_vars):
            r = None if isinstance(ov, Literal) else aroot(ov)
            cur = alias.get(iv)
            if cur is None:
                alias[iv] = r if r is not None else _NO_ALIAS
            elif cur is not r:
                alias[iv] = _NO_ALIAS

    def var_deps(v) -> Set[str]:
        if isinstance(v, Literal):
            return set()
        return src.get(v, set())

    def seed(inner_vars, dep_sets) -> None:
        for iv, d in zip(inner_vars, dep_sets):
            src[iv] = src.get(iv, set()) | d

    def walk(jpr) -> List[Set[str]]:
        """Propagate through one (sub-)jaxpr; returns outvar dep sets.
        Monotone over ``src``, so fixpoint iteration is safe."""
        for eqn in jpr.eqns:
            prim = eqn.primitive.name
            ins = [var_deps(v) for v in eqn.invars]
            if prim in _LOAD_PRIMS:
                for d in ins[1:]:
                    load_addr.update(d)
            elif prim == _STORE_UPDATE_PRIM:
                for d in ins[2:]:
                    store_addr.update(d)
                stored_into.update(ins[0])
            elif prim.startswith("scatter"):
                if len(ins) > 1:
                    store_addr.update(ins[1])
                stored_into.update(ins[0])
            elif prim == "name":
                # ops/indexing.py tags its index (and store target) with
                # checkpoint_name so address roles survive the dense
                # lowering, which deliberately contains no gather/slice
                # primitive for this walk to find.  Both lowerings carry
                # the tag, so a region's sync structure is identical
                # whichever one the backend resolves.
                tag = str(eqn.params.get("name", ""))
                if tag == "coast:load_addr":
                    load_addr.update(ins[0])
                elif tag == "coast:store_addr":
                    store_addr.update(ins[0])
                elif tag == "coast:stored_into":
                    stored_into.update(ins[0])
            elif prim == "select_n":
                branch_pred.update(ins[0])

            out_sets: List[Set[str]] = []
            params = eqn.params
            if prim == "cond" and "branches" in params:
                per_branch = []
                for br in params["branches"]:
                    seed(br.jaxpr.invars, ins[1:])
                    aseed(br.jaxpr.invars, eqn.invars[1:])
                    per_branch.append(walk(br.jaxpr))
                # Control dependence: which branch ran (the predicate)
                # influences every output -- exactly why the reference
                # votes branch predicates (syncTerminator).
                pred = ins[0]
                branch_pred.update(pred)
                out_sets = [set().union(pred, *(b[i] for b in per_branch))
                            for i in range(len(eqn.outvars))]
                # A cond/switch output every branch returns as the SAME
                # unchanged invar IS that invar, whichever branch ran:
                # identity passthrough survives the fresh outvars.
                for i, ov in enumerate(eqn.outvars):
                    roots = {aroot(br.jaxpr.outvars[i])
                             for br in params["branches"]}
                    if len(roots) == 1 and None not in roots:
                        alias.setdefault(ov, roots.pop())
            elif prim == "while":
                cn = params["cond_nconsts"]
                bn = params["body_nconsts"]
                cj = params["cond_jaxpr"].jaxpr
                bj = params["body_jaxpr"].jaxpr
                carry = [set(d) for d in ins[cn + bn:]]
                # Fixpoint bound: a dependency can advance one carry slot
                # per pass, so |carry| passes suffice (+2 slack).
                cond_deps: Set[str] = set()
                for _ in range(len(carry) + 2):
                    seed(cj.invars, ins[:cn] + carry)
                    cond_out = walk(cj)
                    cond_deps |= set().union(*cond_out) if cond_out else set()
                    seed(bj.invars, ins[cn:cn + bn] + carry)
                    new_carry = walk(bj)
                    grew = any(not n <= c for n, c in zip(new_carry, carry))
                    carry = [c | n for c, n in zip(carry, new_carry)]
                    if not grew:
                        break
                # Control dependence: the loop predicate decides how many
                # iterations ran, so it taints every carried output.
                branch_pred.update(cond_deps)
                out_sets = [c | cond_deps for c in carry]
            elif prim == "scan":
                sub = params["jaxpr"].jaxpr
                cur = list(ins)
                n_carry = params["num_carry"]
                n_consts = params["num_consts"]
                for _ in range(max(n_carry, 1) + 2):   # loop-carry fixpoint
                    seed(sub.invars, cur)
                    outs = walk(sub)
                    carry_out = outs[:n_carry]
                    old = cur[n_consts:n_consts + n_carry]
                    grew = any(not n <= c for n, c in zip(carry_out, old))
                    cur = (cur[:n_consts]
                           + [c | n for c, n in zip(old, carry_out)]
                           + cur[n_consts + n_carry:])
                    if not grew:
                        break
                out_sets = outs
            elif "jaxpr" in params:               # pjit / closed_call / remat
                sub = params["jaxpr"]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                seed(sub.invars, ins)
                aseed(sub.invars, eqn.invars)
                out_sets = walk(sub)
                for ov, sv in zip(eqn.outvars, sub.outvars):
                    r = aroot(sv)
                    if r is not None:
                        alias.setdefault(ov, r)
            elif "call_jaxpr" in params:
                sub = params["call_jaxpr"]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                seed(sub.invars, ins)
                aseed(sub.invars, eqn.invars)
                out_sets = walk(sub)
                for ov, sv in zip(eqn.outvars, sub.outvars):
                    r = aroot(sv)
                    if r is not None:
                        alias.setdefault(ov, r)

            if len(out_sets) != len(eqn.outvars):
                acc: Set[str] = set()
                for d in ins:
                    acc |= d
                out_sets = [acc] * len(eqn.outvars)
            for v, s in zip(eqn.outvars, out_sets):
                src[v] = src.get(v, set()) | s
        return [var_deps(v) for v in jpr.outvars]

    out_sets = walk(jaxpr)
    facts = {"load_addr": frozenset(load_addr),
             "store_addr": frozenset(store_addr),
             "stored_into": frozenset(stored_into),
             "branch_pred": frozenset(branch_pred)}
    return out_sets, in_var_of, facts, aroot


def analyze_step(step, state) -> RegionDataflow:
    """Trace a step function over ``state`` shapes and propagate leaf
    provenance through the jaxpr.

    Provenance recurses into sub-jaxprs (pjit/scan/cond/while) so address
    roles inside control-flow bodies are found; loop carries run to a
    fixpoint.  The reference is likewise transitive at calls
    (verification.cpp getCallArgIndex :383-441)."""
    state = jax.eval_shape(lambda: state)  # accept arrays or ShapeDtypeStructs
    closed = jax.make_jaxpr(step)(state, jnp.int32(0))
    jaxpr = closed.jaxpr

    names = sorted(state)
    # jax.make_jaxpr flattens (state, t): state leaves in dict-key order
    # (dicts flatten sorted), then t.
    assert len(jaxpr.invars) == len(names) + 1, (
        len(jaxpr.invars), len(names))
    out_sets, in_var_of, facts, aroot = _trace_provenance(jaxpr, names)

    assert len(jaxpr.outvars) == len(names), (
        f"step() must return exactly the state leaves; got "
        f"{len(jaxpr.outvars)} outputs for {len(names)} leaves")
    out_deps: Dict[str, FrozenSet[str]] = {}
    written: Set[str] = set()
    for name, var, deps in zip(names, jaxpr.outvars, out_sets):
        if isinstance(var, Literal):
            out_deps[name] = frozenset()
            written.add(name)
        elif (var is in_var_of.get(name)
              or aroot(var) is in_var_of.get(name)):
            out_deps[name] = frozenset({name})      # identity passthrough
        else:
            out_deps[name] = frozenset(deps)
            written.add(name)
    return RegionDataflow(written=frozenset(written), deps=out_deps, **facts)


def analyze(region: Region) -> RegionDataflow:
    """Provenance analysis of a region's step (see analyze_step).  Sub-
    functions are bound unwrapped: the analysis sees the module as written,
    before any scope-class rewrapping (the reference likewise verifies
    before cloning, dataflowProtection.cpp:63-164)."""
    return analyze_step(region.bound_step(), jax.eval_shape(region.init))


def reads_of(fn, state, *extra_args) -> FrozenSet[str]:
    """The set of state leaves the output of ``fn(state, *extra)`` depends
    on -- e.g. which leaves a region's done() predicate reads.  Used by the
    lifter to classify termination-steering leaves as KIND_CTRL."""
    state = jax.eval_shape(lambda: state)
    closed = jax.make_jaxpr(fn)(state, *extra_args)
    names = sorted(state)
    out_sets, _, _, _ = _trace_provenance(closed.jaxpr, names)
    acc: Set[str] = set()
    for s in out_sets:
        acc |= s
    return frozenset(acc)


def _scope_excluded(region: Region, cfg, name: str) -> bool:
    """Excluded from the SoR by an explicit user choice (CL list,
    annotation, or region default), as opposed to by kind or mode."""
    if name in cfg.ignore_globals:
        return True
    if name in cfg.xmr_globals:
        return False
    spec = region.spec[name]
    if spec.xmr is False:
        return True
    return region.default_xmr is False and spec.xmr is not True


def verify_options(region: Region, cfg) -> FrozenSet[str]:
    """The verifyOptions pipeline step.  Raises SoRViolation on any rule
    break; returns the forced-boundary-sync leaf set otherwise.

    Per-leaf opt-out: LeafSpec.no_verify mirrors the parameterized
    ``no-verify-<glbl>`` annotation (interface.cpp:364-532).
    """
    flow = analyze(region)
    errors: List[str] = []
    forced_sync: Set[str] = set()

    # -- unknown names in scope lists (processCommandLine :244-362 reports
    #    missing names and exits) --
    for opt, val in (("ignore_globals", cfg.ignore_globals),
                     ("xmr_globals", cfg.xmr_globals)):
        for name in val:
            if name not in region.spec:
                errors.append(
                    f"-{opt}: no leaf named '{name}' in region "
                    f"'{region.name}' (have: {', '.join(sorted(region.spec))})")
    both = set(cfg.ignore_globals) & set(cfg.xmr_globals)
    for name in sorted(both):
        errors.append(f"leaf '{name}' listed in both -ignore_globals and "
                      "-xmr_globals")

    # -- function-scope lists: every named function must exist (the
    #    missing-name error of processCommandLine, interface.cpp:244-362);
    #    flags with no tpu semantics are refused, never silently inert --
    fns = getattr(region, "functions", {}) or {}
    for flag, names in getattr(cfg, "fn_lists", dict)().items():
        for name in names:
            if name not in fns:
                have = ", ".join(sorted(fns)) or "<none>"
                errors.append(
                    f"-{flag}: no function named '{name}' in region "
                    f"'{region.name}' (have: {have})")
    for name in getattr(cfg, "isr_functions", ()):
        errors.append(
            f"-isrFunctions: '{name}': interrupt service routines do not "
            "exist in a stepped TPU region; remove the flag (the reference "
            "excludes ISRs from cloning, inspection.cpp:183-186 -- here "
            "there is nothing to exclude)")
    for name in getattr(cfg, "runtime_init_globals", ()):
        if name not in region.spec:
            errors.append(
                f"-runtimeInitGlobals: no leaf named '{name}' in region "
                f"'{region.name}' (every replicated leaf is already "
                "runtime-initialised from the init() image by "
                "init_pstate, the addGlobalRuntimeInit analogue)")

    if errors:
        raise SoRViolation(errors)

    replicated = {name: cfg.resolve_xmr(region, name) for name in region.spec}
    any_replicated = any(replicated.values())

    for name, spec in region.spec.items():
        no_verify = getattr(spec, "no_verify", False)

        # -- read-only leaves must not be written (const-ness; the closest
        #    LLVM analogue is storing through a pointer to const) --
        if spec.kind == KIND_RO and name in flow.written and not no_verify:
            errors.append(
                f"read-only leaf '{name}' is written by step(); "
                "declare it KIND_MEM or stop writing it")

        # -- conflicting annotations: explicitly replicating a leaf the
        #    engine will never clone (the verifyOptions.c expected-fail
        #    class: scope options that contradict each other) --
        if spec.kind == KIND_RO and (spec.xmr is True
                                     or name in cfg.xmr_globals):
            errors.append(
                f"leaf '{name}' is KIND_RO (never cloned, "
                "cloning.cpp:62-288 rule) but annotated __xMR; "
                "conflicting replication scope")

        if not any_replicated or no_verify:
            continue

        # -- unvoted control: a ctrl leaf excluded from the SoR by scope
        #    choice steers every replica from one corruptible copy --
        if (spec.kind == KIND_CTRL and not replicated[name]
                and cfg.num_clones > 1 and _scope_excluded(region, cfg, name)):
            errors.append(
                f"control leaf '{name}' excluded from replication: "
                "branch predicates must be voted before the branch "
                "(syncTerminator, synchronization.cpp:741-1113); "
                "an unprotected loop variable defeats every replica")

    # -- NotProtected -> Protected writes (rules table :686-718) --
    if cfg.num_clones > 1:
        # A hole needs *scope choice* exclusion: kind-based exclusion by
        # -noMemReplication is the load-sync design, not a hole (the
        # pervasive noMemReplicationFlag branches sync reads instead).
        # KIND_LINK leaves are sanctioned crossings, not holes: the
        # engine forces a SoR-crossing vote on their commit (vote-then-
        # exchange), or the region declares unvoted_crossing and carries
        # its own receive-side voter over the in-flight copies
        # (exchange-then-vote).  Reads from them are the halo-integrate
        # of a sharded region -- the surface the 'link' fault model
        # measures, not a scope mistake to refuse.
        mutable_unprot = {
            n for n in region.spec
            if not replicated[n] and n in flow.written
            and region.spec[n].kind not in (KIND_RO, KIND_LINK)
            and _scope_excluded(region, cfg, n)}
        for name in sorted(region.spec):
            if not replicated[name] or getattr(region.spec[name],
                                               "no_verify", False):
                continue
            bad = (flow.deps.get(name, frozenset()) & mutable_unprot) - {name}
            for srcname in sorted(bad):
                errors.append(
                    f"replicated leaf '{name}' reads mutable unprotected "
                    f"leaf '{srcname}': NotProtected->Protected writes are "
                    "not OK (verification.cpp rules table :686-718); "
                    "replicate the source or mark it no_verify")

        # -- Protected -> NotProtected: forced boundary votes (OK) --
        for name in sorted(region.spec):
            if replicated[name] or region.spec[name].kind == KIND_RO:
                continue
            if name in flow.written and any(
                    replicated.get(s, False)
                    for s in flow.deps.get(name, frozenset())):
                forced_sync.add(name)

    if errors:
        raise SoRViolation(errors)
    return frozenset(forced_sync)
