"""CFCSS: control-flow checking by software signatures, stackable with TMR/DWC.

The reference pass (projects/CFCSS/, 904 LoC) instruments every basic block
with a signature store + XOR compare against runtime globals
``BasicBlockSignatureTracker`` / ``RunTimeSignatureAdjuster``
(CFCSS.cpp:726-731), branching to ``FAULT_DETECTED_CFC`` on mismatch
(:87-122).  TPU-native re-expression:

  * signature assignment (unique random sigs, designated-predecessor diffs,
    fan-in adjusters, soundness iteration) runs in the native C++ core
    (coast_tpu/native/coast_core.cpp `coast_cfcss_assign`); buffer blocks
    (insertBufferBlock :342-378) are folded into per-edge adjusters.
  * the runtime tracker G and the previous-block register are *injectable
    replicated state leaves* -- per lane, exactly as stacking CFCSS after
    TMR replicates its globals in the reference -- updated each step with
    an XOR gather and compared against the expected signature.
  * a mismatch in any lane latches ``cfc_fault``: the batched analogue of
    branching to the CFC error handler and aborting (DUE classification).

The signature transition, per step, with v_lane = block_of(that lane's own
control state) -- classified **per lane**, exactly as each replica's
instruction stream carries its own signature tracker in the reference
(stacking CFCSS after TMR clones the runtime globals):

    G'_lane = G_lane ^ diffs[v_lane] ^ (fanin[v_lane] ? dedge[prev_lane, v_lane] : 0)
    fault  |= any_lane(G'_lane != sigs[v_lane]);   prev'_lane = v_lane

A lane whose corrupted control state steers it onto an illegal edge
(u_prev, v) mismatches by the assignment's soundness guarantee
(coast_core.cpp verify loop) even when every other lane is clean -- so
CFCSS catches lane-local control corruption that disabled ctrl voting
(-noStoreAddrSync/-noLoadSync) would otherwise let slip to the output.
Classifying from the voted view instead would absorb exactly those
corruptions before CFCSS could see them (VERDICT round 1 weakness #5).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.native import cfcss_assign
from coast_tpu.passes.dataflow_protection import ProtectedProgram

# Synthetic leaf names for the CFCSS runtime state (the reference's
# BasicBlockSignatureTracker / previous-block analogue).  They are part of
# the injectable memory map, like the reference's globals.
G_LEAF = "__cfcss_sig_tracker"
PREV_LEAF = "__cfcss_prev_block"

SIG_BITS = 16  # reference default signature width (CFCSS.h:33-35)


def apply_cfcss(prog: ProtectedProgram, seed: int = 0) -> ProtectedProgram:
    """Stack CFCSS onto a protected program (mutates and returns it).

    Mirrors pass stacking in the reference build system: `opt -TMR -CFCSS`
    runs both ModulePasses over the same module (BASELINE.json config 5).
    """
    region = prog.region
    graph: BlockGraph = region.graph
    if graph is None:
        raise ValueError(
            f"region {region.name} has no block graph; CFCSS needs one "
            "(the reference requires basic blocks to instrument)")
    graph.validate()

    tables = cfcss_assign(graph.n, graph.edges, seed=seed, sig_bits=SIG_BITS)
    sigs = jnp.asarray(tables["sigs"], jnp.uint32)
    diffs = jnp.asarray(tables["diffs"], jnp.uint32)
    fanin = jnp.asarray(tables["fanin"])
    dedge = jnp.asarray(tables["dedge"], jnp.uint32)

    n_lanes = prog.cfg.num_clones

    def cfcss_init() -> Dict[str, jax.Array]:
        return {
            # G starts at the entry signature (runtime globals initialised
            # before main in the reference, CFCSS.cpp:726-731).
            G_LEAF: jnp.broadcast_to(sigs[0], (n_lanes,)).astype(jnp.uint32),
            PREV_LEAF: jnp.zeros((n_lanes,), jnp.int32),
        }

    def lane_blocks(state) -> jax.Array:
        """block_of evaluated on each lane's OWN control state -> (n_lanes,)
        int32.  The voted view is deliberately not used here: voting would
        repair the very control corruption CFCSS exists to detect."""
        region_state = {k: state[k] for k in region.spec}
        if n_lanes == 1 or not prog._any_replicated:
            v = graph.block_of(region_state)
            return jnp.broadcast_to(jnp.asarray(v, jnp.int32), (n_lanes,))
        in_axes = ({k: (0 if prog.replicated[k] else None)
                    for k in region_state},)
        return jax.vmap(graph.block_of, in_axes=in_axes)(
            region_state).astype(jnp.int32)

    def cfcss_step(new_state, flags, t, halted):
        from coast_tpu.ops import voters
        v = lane_blocks(new_state)                       # (n_lanes,)
        g = new_state[G_LEAF]
        prev = new_state[PREV_LEAF]
        adj = jnp.where(fanin[v], dedge[prev, v], jnp.uint32(0))
        g_new = g ^ diffs[v] ^ adj
        # The any() collapses the lane axis by design (a mismatch in ANY
        # lane aborts); tag it as the CFCSS sync point so the replication
        # linter does not read the reduction as a lost replica.
        mismatch = jnp.any(
            voters.sync_tag(g_new != sigs[v], "cfcss", G_LEAF))
        flags = {**flags,
                 "cfc_fault": jnp.logical_or(
                     flags["cfc_fault"],
                     jnp.logical_and(~halted, mismatch))}
        new_state = {**new_state,
                     G_LEAF: jnp.where(halted, g, g_new),
                     PREV_LEAF: jnp.where(halted, prev, v)}
        return new_state, flags

    prog.install_cfcss(cfcss_init, cfcss_step, tables)
    return prog
