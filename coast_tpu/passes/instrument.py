"""Instrumentation passes: tracing, profiling, and the exit-marker hook.

The reference ships three utility ModulePasses next to the protection engine
(SURVEY.md §2.1 #6-#8); each gets a TPU-native equivalent here:

  * ``debugStatements`` (projects/debugStatements/debugStatements.cpp) prints
    ``fn-->bb`` at every basic-block entry via an inserted printf.  Printing
    from inside a jitted scan would serialise the program on host callbacks,
    so the TPU form records the per-step (block, live) trace as scan outputs
    -- one device->host transfer -- and formats the same ``fn-->bb`` lines
    host-side (:func:`trace_run` / :func:`format_trace`).
  * ``smallProfile`` (projects/smallProfile/smallProfile.cpp) keeps a global
    call counter per function and prints ``<name>: <count>`` from a generated
    ``PRINT_PROFILE_STATS`` before main returns (:103-253).  The region
    analogue counts executed steps per block -- a histogram of the same
    trace -- plus a whole-region counter (:func:`profile_run` /
    :func:`format_profile_stats`).
  * ``exitMarker`` (projects/exitMarker/exitMarker.cpp:96-140) calls a dummy
    ``EXIT_MARKER(ret)`` before every return in main so the fault-injection
    platform can breakpoint the final state.  The campaign analogue is a
    stable final-memory-image hook: :func:`run_to_exit_marker` returns the
    voted final state pytree (what GDB would read at that breakpoint)
    alongside the run record.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from coast_tpu.passes.dataflow_protection import ProtectedProgram


def _block_names(prog: ProtectedProgram) -> List[str]:
    graph = prog.region.graph
    if graph is None:
        # Regions without a declared CFG are a single logical block, like a
        # straight-line function body.
        return [prog.region.name]
    return list(graph.names)


def trace_run(prog: ProtectedProgram,
              fault: Optional[Dict[str, jax.Array]] = None
              ) -> Tuple[Dict[str, np.ndarray], List[str]]:
    """Run with tracing and return (record, ``fn-->bb`` lines).

    The lines are exactly the debugStatements output shape: one
    ``<region>--><block>`` per executed step, in execution order
    (debugStatements.cpp prints name + "-->" + block at each entry).
    """
    rec = jax.device_get(jax.jit(
        lambda f: prog.run(f, trace=True))(fault)
        if fault is not None else
        jax.jit(lambda: prog.run(trace=True))())
    return rec, format_trace(prog, rec)


def format_trace(prog: ProtectedProgram, rec: Dict[str, np.ndarray],
                 fn_print_list: Sequence[str] = ()) -> List[str]:
    """Trace tensors -> printf lines; ``fn_print_list`` filters by block
    name, the -fnPrintList CL list (debugStatements.cpp:22)."""
    names = _block_names(prog)
    blocks = np.asarray(rec["trace_block"])
    live = np.asarray(rec["trace_live"])
    lines = []
    for blk, ok in zip(blocks, live):
        if not ok:
            continue
        name = names[int(blk)] if 0 <= int(blk) < len(names) else f"bb{blk}"
        if fn_print_list and name not in fn_print_list:
            continue
        lines.append(f"{prog.region.name}-->{name}")
    return lines


def profile_run(prog: ProtectedProgram,
                fault: Optional[Dict[str, jax.Array]] = None
                ) -> Tuple[Dict[str, np.ndarray], Dict[str, int]]:
    """Run with profiling and return (record, counters).

    Counters mirror smallProfile's ``__<fn>_profCnt`` globals
    (createGlobalCounter, smallProfile.cpp:278-304): one per block (steps
    executed in that block) plus the whole region under its own name (the
    'calls to main' counter -- a region is entered once per run, so the
    value is total live steps, its dynamic instruction count analogue).
    """
    rec = jax.device_get(jax.jit(
        lambda f: prog.run(f, trace=True))(fault)
        if fault is not None else
        jax.jit(lambda: prog.run(trace=True))())
    return rec, profile_counts(prog, rec)


def profile_counts(prog: ProtectedProgram,
                   rec: Dict[str, np.ndarray]) -> Dict[str, int]:
    names = _block_names(prog)
    blocks = np.asarray(rec["trace_block"])
    live = np.asarray(rec["trace_live"])
    hist = np.bincount(blocks[live], minlength=len(names))
    counts = {name: int(hist[i]) for i, name in enumerate(names)}
    counts[prog.region.name] = int(live.sum())
    return counts


def format_profile_stats(counts: Dict[str, int]) -> List[str]:
    """``PRINT_PROFILE_STATS`` output: ``<name>: <count>`` per counter
    (insertProfilePrintFunction, smallProfile.cpp:184-253)."""
    return [f"{name}: {cnt}" for name, cnt in counts.items()]


def run_to_exit_marker(prog: ProtectedProgram,
                       fault: Optional[Dict[str, jax.Array]] = None
                       ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Run to the EXIT_MARKER breakpoint and return (final_state, record).

    ``final_state`` is the voted view of the final memory image -- per leaf,
    what the reference's GDB client reads when it hits the EXIT_MARKER
    breakpoint before main returns (exitMarker.cpp:120-140;
    resources/benchmarks.py breakpoint table).  One jitted run.
    """
    rec = jax.device_get(
        jax.jit(lambda f: prog.run(f, return_state=True))(fault)
        if fault is not None else
        jax.jit(lambda: prog.run(return_state=True))())
    final_state = rec.pop("final_state")
    return final_state, rec


def state_digest(final_state: Dict[str, np.ndarray]) -> Dict[str, int]:
    """Per-leaf XOR digest of the final image -- the compact form the opt
    CLI prints under -ExitMarker (stable across runs for a given program,
    like the mm benchmark's golden XOR convention, tests/mm_common/mm.c:31)."""
    out = {}
    for name in sorted(final_state):
        arr = np.asarray(final_state[name]).astype(np.uint32, copy=False)
        out[name] = int(np.bitwise_xor.reduce(arr.reshape(-1) & 0xFFFFFFFF))
    return out
