"""Strategy front-ends: TMR / DWC / EDDI over the replication engine.

Mirrors the thin-wrapper passes of the reference: projects/TMR/TMR.cpp:26-36
(``dataflowProtection::run(M, 3)``), projects/DWC/DWC.cpp:26-36 (``run(M, 2)``)
and the deprecated projects/EDDI/EDDI.cpp:29-43 which refuses to run and tells
the user to switch to DWC.

Every ProtectionConfig knob flows through ``**overrides`` unchanged --
including ``fuse_step=True`` (the fused protected-step engine of
ops/fused_step.py; ``-fuseStep`` on the opt CLI), which is pinned
bit-identical to the unfused loop and therefore composes with any
strategy here.
"""

from __future__ import annotations

import dataclasses

from coast_tpu.ir.region import Region
from coast_tpu.passes.dataflow_protection import (ProtectedProgram,
                                                  ProtectionConfig, protect)


def TMR(region: Region, **overrides) -> ProtectedProgram:
    """Triple modular redundancy (SWIFT-R/Trikaya lineage,
    docs/source/passes.rst:16): 3 lanes, majority voters, fault masking."""
    cfg = dataclasses.replace(ProtectionConfig(num_clones=3), **overrides)
    if cfg.num_clones != 3:
        raise ValueError("TMR is fixed at 3 replicas (TMR.cpp:26-36)")
    return protect(region, cfg)


def DWC(region: Region, **overrides) -> ProtectedProgram:
    """Duplication with compare: 2 lanes, compare + abort (detect-only)."""
    cfg = dataclasses.replace(ProtectionConfig(num_clones=2), **overrides)
    if cfg.num_clones != 2:
        raise ValueError("DWC is fixed at 2 replicas (DWC.cpp:26-36)")
    return protect(region, cfg)


def EDDI(region: Region, **overrides) -> ProtectedProgram:
    """Deprecated; kept for name recognition exactly like the reference
    (EDDI.cpp:29-43 asserts with this instruction)."""
    raise NotImplementedError(
        "EDDI is deprecated. Switch to DWC (duplication with compare).")


def unprotected(region: Region, **overrides) -> ProtectedProgram:
    """Passthrough (the 'no OPT_PASSES' baseline build of the test harness,
    unittest/cfg/full.yml first column)."""
    cfg = dataclasses.replace(ProtectionConfig(num_clones=1), **overrides)
    return protect(region, cfg)
