"""dataflowProtection: the core replication engine, TPU-native.

The reference's engine (projects/dataflowProtection/, 7,899 LoC C++) clones
LLVM instructions/globals N-1 times, rewires operands, and inserts voters at
sync points (pipeline dataflowProtection.cpp:63-164).  The TPU-native engine
does the equivalent transform on a :class:`~coast_tpu.ir.region.Region`:

  * *cloning*  -> replicated state leaves get a leading lane axis of size N
    (the replica set lives as one HBM tensor per leaf; cloned globals at
    distinct addresses become lanes, cloning.cpp:2417-2462).
  * *instruction replication* -> the region ``step`` runs once per lane:
    ``vmap`` over the lane axis (interleaved scheduling) or an unrolled
    per-lane loop (segmented scheduling) -- the -i / -s knob of
    utils.cpp:370-550 becomes a lowering choice, not an instruction mover.
  * *insertVoters* -> jnp reductions over the lane axis (coast_tpu.ops.voters)
    at the same sync-point classes the reference uses
    (populateSyncPoints, synchronization.cpp:95-259):
       - store sync   : writes to ``mem`` leaves (syncStoreInst :476-561)
       - terminator   : ``ctrl`` leaves (loop counters/predicates) are voted
         every step *before* the done-predicate branch, so lanes cannot
         structurally diverge (syncTerminator :741-1113)
       - SoR crossing : writes to *shared* (non-xMR) leaves are voted before
         the single store, which is also how -noMemReplication syncs
         (the pervasive noMemReplicationFlag branches of 1b/1c)
       - call/return  : the region boundary -- check()/output() read a voted
         view of the final state (processCallSync :563-738).
  * *error handling* -> DWC's ``FAULT_DETECTED_DWC -> abort()``
    (synchronization.cpp:1198-1267) cannot abort a batched campaign; it
    becomes a latched poison flag that freezes the run's state and classifies
    it DUE.  TMR's ``TMR_ERROR_CNT`` correction counter
    (insertTMRCorrectionCount :1354-1465) becomes an int32 accumulator; the
    ``-countSyncs`` ``__SYNC_COUNT`` global (:103-121) likewise.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_OPT_STATE,
                                 KIND_PARAM, KIND_RO, KIND_STACK, Region,
                                 State)
from coast_tpu.ops import voters
from coast_tpu.ops.bitflip import make_flipper

_INT_DTYPES = (jnp.int32, jnp.uint32, jnp.float32)


@dataclasses.dataclass(frozen=True)
class ProtectionConfig:
    """Mirror of the reference CLI surface (dataflowProtection.cpp:14-47,
    full flag table docs/source/passes.rst:30-140).

    num_clones: 3 = TMR, 2 = DWC, 1 = unprotected passthrough.
    """

    num_clones: int = 3
    # -noMemReplication: keep one copy of memory, replicate compute only;
    # sync (vote) every value as it is stored (registers-only replication).
    no_mem_replication: bool = False
    # -noStoreDataSync: skip voting the data of stores to replicated memory.
    no_store_data_sync: bool = False
    # -noLoadSync: skip voting address-forming control state *before* the
    # lanes consume it (the reference votes GEP operands feeding loads,
    # syncGEP synchronization.cpp:413-474).  Pre-step vote: repairs a flip
    # before any load in the step dereferences it.
    no_load_sync: bool = False
    # -noStoreAddrSync: skip voting address-forming control state at the
    # commit boundary (GEP operands feeding stores, :413-474).  Post-step
    # vote: repairs control state before the next step's stores use it.
    no_store_addr_sync: bool = False
    # -countErrors -> TMR_ERROR_CNT analogue.
    count_errors: bool = True
    # -countSyncs -> __SYNC_COUNT analogue.
    count_syncs: bool = False
    # -i (interleave, default) vs -s (segmented) replica scheduling.
    segmented: bool = False
    # -protectStack: vote the region's call-stack leaves (LeafSpec.stack)
    # every step, the analogue of saving llvm.returnaddress copies at entry
    # and voting them before returns (insertStackProtection,
    # synchronization.cpp:1579-1812).
    protect_stack: bool = False
    # Scope overrides, the -ignoreGlbls / -cloneGlbls CL lists
    # (interface.cpp:82-164); highest priority, above region annotations.
    ignore_globals: Tuple[str, ...] = ()
    xmr_globals: Tuple[str, ...] = ()
    # Function-scope lists (interface.cpp:82-164), applied to the region's
    # named sub-functions (Region.functions) by rewrapping each call per
    # its scope class (interface/wrappers.py lane_* combinators).
    # Precedence mirrors the reference's merge rules (clone lists override
    # ignore lists): cloneAfterCall > protectedLibFn > cloneReturn >
    # cloneFns > ignoreFns > replicateFnCalls > skipLibCalls > default
    # (replicated).
    ignore_fns: Tuple[str, ...] = ()
    skip_lib_calls: Tuple[str, ...] = ()
    replicate_fn_calls: Tuple[str, ...] = ()
    clone_fns: Tuple[str, ...] = ()
    clone_return_fns: Tuple[str, ...] = ()        # -cloneReturn (.RR)
    clone_after_call_fns: Tuple[str, ...] = ()    # -cloneAfterCall
    protected_lib_fns: Tuple[str, ...] = ()       # -protectedLibFn
    # -pallasVoters: lower eligible large-leaf votes through the fused
    # Pallas TPU kernel (ops/pallas_voters.py) instead of the jnp voter
    # XLA fuses; bit-identical, ~1.4x the bandwidth on flagship-sized
    # leaves, falls back automatically off-TPU / for small leaves.
    # None = auto: ON whenever the default backend is the TPU (the kernel
    # the README advertises should be what default campaigns run), OFF
    # elsewhere.  The CLI flag forces it on; pass False to force it off.
    pallas_voters: "bool | None" = None
    # -fuseStep: the fused protected-step path (ops/fused_step.py).  The
    # engine derives a static FusePlan and prunes the per-step work that
    # is provably identity -- done-cone-only terminator votes, freeze
    # wheres on leaves whose commit equals their pre-step image, a
    # sparse one-word flip off-TPU, the five bool latches packed into
    # one uint32 word, and while_loop -> bounded scan where max_steps ==
    # nominal_steps.  Outputs are bit-identical to the unfused engine
    # (the dense-ndjson differential pin, tests/test_fused.py); fuse
    # mode is campaign identity in the journal header (absent = off).
    fuse_step: bool = False
    # -isrFunctions: interrupt handlers excluded from cloning.  There is no
    # interrupt concept in a stepped TPU region; a non-empty list is a hard
    # configuration error (refused, not silently inert).
    isr_functions: Tuple[str, ...] = ()
    # -runtimeInitGlobals: cloned globals re-initialised by a runtime
    # memcpy at program start (addGlobalRuntimeInit, cloning.cpp:2543-2588).
    # The engine broadcast-initialises *every* replicated leaf from the
    # single init() image (init_pstate), so the semantics hold for all
    # leaves by construction; listed names are validated to exist.
    runtime_init_globals: Tuple[str, ...] = ()
    # CFCSS stacking (projects/CFCSS); filled by passes.cfcss.
    cfcss: bool = False

    def fn_scope_of(self, name: str) -> str:
        """Resolve a sub-function's scope class.  Precedence encodes the
        reference's CL merge rules (getFunctionsFromCL, interface.cpp
        :88-164: cloneAfterCall implies skipLibCalls+ignoreFns, clone
        lists override ignore lists)."""
        if name in self.clone_after_call_fns:
            return "clone_after_call"
        if name in self.protected_lib_fns:
            return "protected_lib"
        if name in self.clone_return_fns:
            return "replicated_return"
        if name in self.clone_fns:
            return "replicated"
        if name in self.ignore_fns:
            return "ignored"
        if name in self.replicate_fn_calls:
            return "replicated"
        if name in self.skip_lib_calls:
            return "skip_lib"
        return "replicated"

    def fn_lists(self) -> Dict[str, Tuple[str, ...]]:
        return {"ignoreFns": self.ignore_fns,
                "skipLibCalls": self.skip_lib_calls,
                "replicateFnCalls": self.replicate_fn_calls,
                "cloneFns": self.clone_fns,
                "cloneReturn": self.clone_return_fns,
                "cloneAfterCall": self.clone_after_call_fns,
                "protectedLibFn": self.protected_lib_fns}

    def resolve_xmr(self, region: Region, name: str) -> bool:
        if self.num_clones == 1:
            return False
        if name in self.ignore_globals:
            return False
        if name in self.xmr_globals:
            return True
        if self.no_mem_replication and region.spec[name].kind in (
                KIND_MEM, KIND_RO, KIND_STACK, KIND_PARAM, KIND_OPT_STATE):
            return False
        if region.spec[name].kind == KIND_RO:
            # Read-only inputs are never cloned: same rule as constants /
            # unwritten globals staying single-copy in the reference unless
            # explicitly listed (populateValuesToClone, cloning.cpp:62-288).
            return False
        return region.leaf_is_xmr(name)


def _flags_init(cfg: ProtectionConfig) -> Dict[str, jax.Array]:
    return {
        "dwc_fault": jnp.bool_(False),      # DWC miscompare latched -> DUE
        "cfc_fault": jnp.bool_(False),      # CFCSS signature fault -> DUE
        # RTOS kernel guard latches (Region.stack_guard/assert_guard):
        # stack check / configASSERT trip -> their own DUE sub-buckets.
        "stack_fault": jnp.bool_(False),
        "assert_fault": jnp.bool_(False),
        "tmr_cnt": jnp.int32(0),            # TMR_ERROR_CNT
        "sync_cnt": jnp.int32(0),           # __SYNC_COUNT
        "steps": jnp.int32(0),              # guest runtime T in steps
        "done": jnp.bool_(False),
    }


def _halted(flags: Dict[str, jax.Array]) -> jax.Array:
    """A run stops evolving once ANY terminal latch is set: completion,
    DWC/CFCSS abort, or a tripped kernel guard.  Fused builds carry the
    five latches packed in one uint32 word (ops/fused_step.py), so the
    four-OR chain collapses to a single compare."""
    if "latch" in flags:
        return flags["latch"] != 0
    return (flags["done"] | flags["dwc_fault"] | flags["cfc_fault"]
            | flags["stack_fault"] | flags["assert_fault"])


class ProtectedProgram:
    """A region after dataflowProtection: N-lane stepped program + flags.

    The compiled artifact the strategies (TMR/DWC) and the fault-injection
    campaign runner consume.  All methods are jit-traceable.
    """

    def __init__(self, region: Region, cfg: ProtectionConfig):
        region.validate()
        # verifyOptions runs before any cloning, and refuses to build on a
        # rule violation (pipeline order, dataflowProtection.cpp:63-164).
        from coast_tpu.passes.verification import verify_options
        self.forced_sync = verify_options(region, cfg)
        self.region = region
        self.cfg = cfg
        self.replicated: Dict[str, bool] = {
            name: cfg.resolve_xmr(region, name) for name in region.spec
        }
        for name, spec in region.spec.items():
            if spec.unvoted_crossing and self.replicated[name]:
                raise ValueError(
                    f"leaf {name!r} declares unvoted_crossing but resolves "
                    "to a replicated scope: the declaration is only "
                    "meaningful on shared (non-xMR) leaves whose SoR-"
                    "crossing vote the region replaces with its own "
                    "receive-side voter")
        # Spec-leaf view: CFCSS later registers synthetic replicated
        # runtime leaves, but the lane axis only exists if some PROGRAM
        # leaf is replicated.
        self._any_replicated = any(self.replicated[k] for k in region.spec)
        # Address-forming roles from the provenance pass: which ctrl leaves
        # feed load indices vs store indices (the GEP-operand classification
        # of syncGEP, synchronization.cpp:413-474).
        from coast_tpu.passes.verification import analyze
        flow = analyze(region)
        # Kept for the fused-step planner (ops/fused_step.build_plan)
        # and anyone else needing the provenance roles post-build.
        self.flow = flow
        # Sync-point table: which replicated leaves get voted at the commit
        # boundary each step (post-step), and which get a pre-step vote.
        self.step_sync: Dict[str, bool] = {}
        self.pre_sync: Dict[str, bool] = {}
        for name, spec in region.spec.items():
            if not self.replicated[name]:
                continue
            self.pre_sync[name] = False
            if spec.kind == KIND_CTRL:
                in_load = name in flow.load_addr
                in_store = name in flow.store_addr
                # Pure predicates (neither address role) are terminator-sync
                # state: syncTerminator voting is not flag-gated in the
                # reference (synchronization.cpp:741-1113), so they are
                # always voted at the commit boundary.
                self.step_sync[name] = ((in_store and not cfg.no_store_addr_sync)
                                        or not (in_load or in_store))
                self.pre_sync[name] = in_load and not cfg.no_load_sync
            elif spec.kind in (KIND_MEM, KIND_STACK):
                # Store-data sync exists where STORES exist: the reference
                # inserts its voter at each store site (syncStoreInst,
                # synchronization.cpp:476-561), so a leaf the step never
                # writes has no sync point and is NOT voted per step -- a
                # flip there propagates through compute and is repaired at
                # the written leaves' votes, exactly as in the reference.
                # This is also the flagship HBM win: mm1024's never-written
                # operand matrices are 2/3 of the per-step voter traffic.
                # KIND_STACK (per-task kernel stacks) follows the same
                # store rule; its votes carry the 'stack' sync class tag.
                self.step_sync[name] = (not cfg.no_store_data_sync
                                        and name in flow.written)
            elif spec.kind in (KIND_PARAM, KIND_OPT_STATE):
                # Training regions: parameters and optimizer state follow
                # the store rule (written leaves get a commit-boundary
                # vote) under their own sync classes.  The train regions
                # additionally gate these votes to the optimizer-commit
                # phase via a 3-tuple store_slice hint -- the selective
                # "vote the applied update, not every micro-step" shape.
                self.step_sync[name] = (not cfg.no_store_data_sync
                                        and name in flow.written)
            else:  # reg: registers are voted only where used by a sync point
                self.step_sync[name] = False
            if cfg.protect_stack and spec.stack:
                # Stack protection is an independent mechanism stacked on
                # top of the normal sync taxonomy: the saved return-address
                # copies are voted even when store/ctrl syncs are disabled.
                self.step_sync[name] = True
        # Store-slice hints: the reference's store sync votes the stored
        # VALUE, not the whole array (syncStoreInst selects over the store
        # operand, synchronization.cpp:476-561).  A region that knows which
        # slice its step stores (meta["store_slice"]: leaf -> fn(view, t)
        # -> (starts, sizes)) gets exactly that: the vote reads/writes only
        # the stored rows, and divergence elsewhere is caught by the
        # region-boundary sync -- the flagship's voter HBM traffic becomes
        # O(stored block), not O(leaf).
        self._store_slice = dict(region.meta.get("store_slice") or {})
        if cfg.num_clones > 1:
            for name in self._store_slice:
                if name not in region.spec:
                    raise ValueError(
                        f"store_slice hint for unknown leaf {name!r}")
                if not self.replicated.get(name):
                    raise ValueError(
                        f"store_slice hint for {name!r}: not a replicated "
                        "leaf")
                if not self.step_sync.get(name):
                    raise ValueError(
                        f"store_slice hint for {name!r}: leaf has no step "
                        "store sync (register-class, never written, or "
                        "store-data sync disabled) -- the hint would be "
                        "dead code")
        else:
            self._store_slice = {}       # no votes exist to slice
        # Voter lowering: -pallasVoters (or auto-on when the backend IS the
        # TPU) routes eligible large leaves through the fused Pallas kernel
        # (which itself falls back to the jnp voter when not applicable);
        # off-TPU defaults stay on the jnp reductions XLA fuses.
        use_pallas = (cfg.pallas_voters if cfg.pallas_voters is not None
                      else jax.default_backend() == "tpu")
        if use_pallas:
            from coast_tpu.ops import pallas_voters
            self._vote = pallas_voters.vote
        else:
            self._vote = voters.vote
        # Function-scope resolution (the populateFnWorklist closure,
        # cloning.cpp:294-431): each named sub-function gets a scope class
        # and is rewrapped accordingly inside the lane trace.
        self.fn_scope: Dict[str, str] = {
            name: cfg.fn_scope_of(name) for name in region.functions}
        cross_lane = [n for n, c in self.fn_scope.items()
                      if c in ("ignored", "skip_lib", "protected_lib",
                               "clone_after_call")]
        if cfg.segmented and cross_lane and cfg.num_clones > 1:
            raise ValueError(
                "segmented (-s) replica scheduling cannot express the "
                "cross-lane call-boundary sync of function scope classes "
                f"for {sorted(cross_lane)}; use interleaved (-i) scheduling")
        # Injectable memory map order (stable): used by the flipper and by
        # inject.mem.MemoryMap.
        self.leaf_order = [n for n in region.spec if region.spec[n].inject]
        self._flip = make_flipper(self.leaf_order)
        # CFCSS runtime hooks, installed by passes.cfcss.apply_cfcss.
        self._cfcss_init = None
        self._cfcss_step = None
        self.cfcss_tables = None
        if cfg.cfcss:
            # -CFCSS stacking requested in the config itself (opt -TMR
            # -CFCSS runs both passes over one module); lazy import breaks
            # the passes.cfcss -> dataflow_protection import cycle.
            from coast_tpu.passes.cfcss import apply_cfcss
            apply_cfcss(self)
        # Fused-step plan (-fuseStep): derived LAST so the planner sees
        # the final leaf_order/sync tables (CFCSS leaves included).
        # The plan's exact_dataflow gate decides whether the fused
        # schedule ACTIVATES: float regions re-round under any program
        # restructuring (XLA fusion/FMA lowering is context dependent),
        # so they keep the legacy program bit-for-bit while cfg.fuse_step
        # still marks campaign identity (ops/fused_step.py docstring).
        self._fuse_plan = None
        self._sparse_flip = None
        self.fuse_plan_info = None
        if cfg.fuse_step:
            from coast_tpu.ops import fused_step
            plan = fused_step.build_plan(self)
            self.fuse_plan_info = plan
            if plan.exact_dataflow:
                self._fuse_plan = plan
                if plan.sparse_flip:
                    self._sparse_flip = fused_step.make_sparse_flipper(
                        self.leaf_order)

    def unfused_twin(self) -> "ProtectedProgram":
        """The identical build with ``fuse_step`` off.  The fused step is
        differentially pinned bit-identical to this twin, so the static
        analyses (equiv partition, vulnerability map, isolation prover)
        walk the twin's jaxpr: every partition fingerprint, merge mode,
        and proof is unchanged by fusion -- which is what keeps fused
        campaigns journal/equiv-compatible artifacts apart from their
        own ``fuse`` header key."""
        if not self.cfg.fuse_step:
            return self
        return ProtectedProgram(
            self.region, dataclasses.replace(self.cfg, fuse_step=False))

    # -- CFCSS stacking (passes.cfcss) --------------------------------------
    def install_cfcss(self, init_fn, step_fn, tables) -> None:
        """Register the CFCSS runtime: extra injectable replicated leaves
        (signature tracker + previous block, the reference's runtime globals
        CFCSS.cpp:726-731) and the per-step signature update/check."""
        self._cfcss_init = init_fn
        self._cfcss_step = step_fn
        self.cfcss_tables = tables
        for name in jax.eval_shape(init_fn):
            self.replicated[name] = True
            if name not in self.leaf_order:
                self.leaf_order.append(name)
        self._flip = make_flipper(self.leaf_order)

    def injectable_sections(self):
        """(name, kind, lanes, words_per_lane) rows for the memory map.
        Synthetic (CFCSS) leaves report kind 'cfcss'."""
        state = jax.eval_shape(self.region.init)
        if self._cfcss_init is not None:
            cfcss_shapes = jax.eval_shape(self._cfcss_init)
        rows = []
        for name in self.leaf_order:
            if name in self.region.spec:
                shape = state[name].shape
                kind = self.region.spec[name].kind
                lanes = self.cfg.num_clones if self.replicated[name] else 1
            else:
                # CFCSS leaves are built already laned: (num_clones, ...).
                shape = cfcss_shapes[name].shape[1:]
                kind = "cfcss"
                lanes = self.cfg.num_clones
            words = 1
            for d in shape:
                words *= int(d)
            rows.append((name, kind, lanes, words))
        return rows

    # -- state construction -------------------------------------------------
    def init_pstate(self) -> Tuple[State, Dict[str, jax.Array]]:
        state = self.region.init()
        for name, arr in state.items():
            if arr.dtype not in _INT_DTYPES:
                raise TypeError(
                    f"leaf {name!r} has dtype {arr.dtype}; injectable state "
                    "must be 32-bit (word-addressed memory map)")
        pstate = {
            name: (jnp.broadcast_to(arr, (self.cfg.num_clones,) + arr.shape)
                   if self.replicated[name] else arr)
            for name, arr in state.items()
        }
        if self._cfcss_init is not None:
            pstate.update(self._cfcss_init())
        if self._fuse_plan is not None:
            from coast_tpu.ops import fused_step
            return pstate, fused_step.flags_init()
        return pstate, _flags_init(self.cfg)

    def _sync_class_of(self, name: str) -> str:
        """The sync-point class a commit-boundary vote on ``name`` belongs
        to -- the label baked into the vote's ``coast:sync:`` tag and
        independently re-derived by the replication-integrity linter
        (analysis/lint/provenance.py expected-coverage table)."""
        spec = self.region.spec[name]
        if self.cfg.protect_stack and spec.stack:
            return "stack"
        if spec.kind == KIND_MEM:
            return "store_data"
        if spec.kind == KIND_CTRL:
            return "ctrl"
        if spec.kind in (KIND_PARAM, KIND_OPT_STATE):
            # Training leaves vote under their own classes so the lint's
            # independently re-derived coverage expectation can require
            # the weight-update commit votes by name.
            return spec.kind
        # KIND_STACK kernel stacks and -protectStack register copies both
        # vote under the 'stack' class.
        return "stack"

    # -- lane execution -----------------------------------------------------
    def _fn_env(self):
        """Build the per-trace function namespace: each named sub-function
        rewrapped per its scope class (the call-boundary contracts of
        interface/wrappers.py); boundary miscompares accumulate in the
        namespace log and are latched by step()."""
        from coast_tpu.interface import wrappers as W
        from coast_tpu.ir.region import FnNamespace
        env = FnNamespace({})
        n = self.cfg.num_clones
        wrapped = {}
        for name, fn in self.region.functions.items():
            cls = self.fn_scope[name]
            if n == 1 or cls in ("replicated", "replicated_return"):
                # Replicated bodies/calls are the natural per-lane call
                # under vmap; .RR additionally skips boundary sync, which
                # is also the per-lane default here.
                wrapped[name] = fn
            elif cls == "ignored":
                wrapped[name] = W.lane_ignored(fn, n, env.miscompares,
                                               name=name)
            elif cls == "skip_lib":
                wrapped[name] = W.lane_skip_lib(fn, n, name=name)
            elif cls == "protected_lib":
                wrapped[name] = W.lane_protected_lib(fn, n, env.miscompares,
                                                     name=name)
            else:  # clone_after_call
                wrapped[name] = W.lane_clone_after_call(fn, n, name=name)
        env._fns = wrapped
        return env

    def _run_lanes(self, pstate: State, t: jax.Array):
        """Execute step() once per lane; returns ``(laned, call_mis)`` where
        every leaf of ``laned`` carries a lane axis and ``call_mis`` is the
        vector of call-boundary miscompares from function-scope wrappers
        (empty when the region has no such calls).

        Interleaved (-i): one vmap -- XLA vectorises the N replicas through
        each op, the closest analogue of interleaving replica instructions.
        Segmented (-s): an unrolled per-lane loop -- each replica's whole
        step is scheduled as a unit before the next (utils.cpp:370-550).
        """
        n = self.cfg.num_clones
        no_mis = jnp.zeros((0,), jnp.bool_)
        if n == 1 or not self._any_replicated:
            # Single lane, or an all-shared scope (e.g. __DEFAULT_NO_xMR
            # with no __xMR marks): the reference's opt likewise compiles
            # a -TMR build that replicates nothing (scopeLists empty, so
            # zero sync points are inserted); there is no lane axis to
            # vmap over and no votes downstream.
            out = self.region.bound_step()(pstate, t)
            return {k: v[None] for k, v in out.items()}, no_mis

        if self.cfg.segmented:
            step = self.region.bound_step()
            lane_outs = []
            for lane in range(n):
                lane_state = {
                    k: (v[lane] if self.replicated[k] else v)
                    for k, v in pstate.items()
                }
                lane_outs.append(step(lane_state, t))
            return ({k: jnp.stack([o[k] for o in lane_outs])
                     for k in lane_outs[0]}, no_mis)

        in_axes = ({k: (0 if self.replicated[k] else None) for k in pstate},
                   None)

        if not self.region.wants_fns():
            laned = jax.vmap(self.region.step, in_axes=in_axes,
                             out_axes=0)(pstate, t)
            return laned, no_mis

        from coast_tpu.interface.wrappers import LANE_AXIS

        def step_plus(state, t):
            env = self._fn_env()
            out = self.region.step(state, t, env)
            mis = (jnp.stack(env.miscompares) if env.miscompares
                   else jnp.zeros((0,), jnp.bool_))
            return out, mis

        laned, mis = jax.vmap(step_plus, in_axes=in_axes, out_axes=0,
                              axis_name=LANE_AXIS)(pstate, t)
        # The wrappers compute each miscompare from an all_gather, so every
        # lane carries the identical value; one lane's copy is the record.
        return laned, mis[0]

    # -- one protected step -------------------------------------------------
    def step(self, pstate: State, flags: Dict[str, jax.Array],
             t: jax.Array) -> Tuple[State, Dict[str, jax.Array]]:
        cfg = self.cfg
        halted = _halted(flags)

        region_state = {k: pstate[k] for k in self.region.spec}
        miscompares = []
        syncs = jnp.int32(0)

        # Pre-step load sync: vote address-forming ctrl state before any
        # load in this step dereferences it -- the syncGEP-before-the-load
        # insertion point (synchronization.cpp:413-474).  TMR repairs the
        # lanes in place; DWC latches the miscompare below and the step
        # does not commit (check before use).
        if cfg.num_clones > 1:
            for name in region_state:
                if self.pre_sync.get(name, False):
                    lanes = voters.sync_tag(region_state[name],
                                            "load_addr", name)
                    voted, mis = self._vote(lanes, cfg.num_clones)
                    miscompares.append(mis)
                    syncs = syncs + 1
                    if cfg.num_clones == 3:
                        region_state[name] = jnp.broadcast_to(
                            voted, region_state[name].shape)

        # CFCSS check at block entry: v = the block this step executes,
        # classified per lane from the state the step actually runs with --
        # after the pre-step repairs, exactly as the reference's block-entry
        # compare sits after syncTerminator voted the predicates that
        # steered here (CFCSS.cpp:504-550).  A mismatch aborts before the
        # block body commits.
        if self._cfcss_step is not None:
            merged = {**pstate, **region_state}
            if self._fuse_plan is not None:
                # Packed-latch marshal: the hook's contract is the bool
                # flag dict; only the cfc bit crosses it, so unpack and
                # re-OR exactly that bit around the call.
                from coast_tpu.ops import fused_step as _fs
                shim = {"cfc_fault": _fs.latch_get(flags["latch"],
                                                   _fs.LATCH_CFC)}
                merged, shim = self._cfcss_step(merged, shim, t, halted)
                flags = {**flags,
                         "latch": _fs.latch_or(flags["latch"], _fs.LATCH_CFC,
                                               shim["cfc_fault"])}
                halted = jnp.logical_or(halted, shim["cfc_fault"])
            else:
                merged, flags = self._cfcss_step(merged, flags, t, halted)
                halted = jnp.logical_or(halted, flags["cfc_fault"])
            # Only the CFCSS runtime leaves (signature tracker, previous
            # block) carry the hook's updates back; the pre-step vote
            # repairs stay local to this step's execution so the frozen
            # image of a halted run keeps its true pre-step state.
            pstate = {**pstate,
                      **{k: merged[k] for k in merged
                         if k not in self.region.spec}}

        laned, call_mis = self._run_lanes(region_state, t)

        # Kernel guards: the RTOS stack check / configASSERT of
        # coast_tpu.rtos regions, evaluated PER LANE on the stepped,
        # PRE-VOTE state -- the replicated kernel's own check code runs
        # inside each replica in the reference rtos build, so a blown
        # canary in one clone's stack trips the hook even though the
        # store-sync vote would have repaired that lane at commit
        # (detection is not maskable by TMR; the reference's TMR FreeRTOS
        # campaigns record stack-overflow DUEs for exactly this reason).
        # The lane collapse of the any() reduction is sanctioned for the
        # replication linter by tagging every guard input with the
        # 'guard' sync class (a detector, like a voter compare).
        trip_stack = jnp.bool_(False)
        trip_assert = jnp.bool_(False)
        if (self.region.stack_guard is not None
                or self.region.assert_guard is not None):
            gview = {name: voters.sync_tag(laned[name], "guard", name)
                     for name in laned}
            if self.region.stack_guard is not None:
                trip_stack = jnp.any(jax.vmap(self.region.stack_guard)(gview))
            if self.region.assert_guard is not None:
                trip_assert = jnp.any(
                    jax.vmap(self.region.assert_guard)(gview))
            trip_stack = jnp.logical_and(~halted, trip_stack)
            trip_assert = jnp.logical_and(~halted, trip_assert)
            if self._fuse_plan is not None:
                from coast_tpu.ops import fused_step as _fs
                latch = _fs.latch_or(flags["latch"], _fs.LATCH_STACK,
                                     trip_stack)
                latch = _fs.latch_or(latch, _fs.LATCH_ASSERT, trip_assert)
                flags = {**flags, "latch": latch}
            else:
                flags = {**flags,
                         "stack_fault": jnp.logical_or(flags["stack_fault"],
                                                       trip_stack),
                         "assert_fault": jnp.logical_or(flags["assert_fault"],
                                                        trip_assert)}
        trip_now = jnp.logical_or(trip_stack, trip_assert)

        # Call-boundary syncs executed by function-scope wrappers inside the
        # lane trace (processCallSync, synchronization.cpp:563-738): each
        # entry is one vote/compare at a sub-function call site.
        n_call_sync = int(call_mis.shape[0])
        if n_call_sync and cfg.num_clones > 1:
            for j in range(n_call_sync):
                miscompares.append(call_mis[j])
            syncs = syncs + n_call_sync

        # Pre-step view for store-slice hints: ctrl scalars voted (a single
        # corrupted lane must not redirect the vote window), everything
        # else lane 0 -- the hint only reads control state, and voting the
        # large leaves here would re-create the traffic the hint removes.
        slice_view = None
        if self._store_slice and cfg.num_clones > 1:
            slice_view = {}
            for name, arr in region_state.items():
                if not self.replicated[name]:
                    slice_view[name] = arr
                elif self.region.spec[name].kind == KIND_CTRL:
                    # TMR: majority -- one corrupted lane cannot redirect
                    # the vote window.  DWC has no majority; lane 0 is
                    # read through the tagged boundary view: a diverged
                    # ctrl lane latches dwc_fault at this step's own
                    # ctrl commit compare, so a wrong window can only
                    # accompany an already-detected fault.
                    slice_view[name] = (voters.tmr_vote(arr)[0]
                                        if cfg.num_clones == 3
                                        else voters.lane_view(arr))
                else:
                    slice_view[name] = arr[0]

        new_state: State = {}
        for name in region_state:
            out = laned[name]
            if self.replicated[name]:
                if self.step_sync[name] and cfg.num_clones > 1:
                    hint = self._store_slice.get(name)
                    if hint is not None:
                        # Vote only the slice this step stored: the store
                        # sync covers the store OPERAND (syncStoreInst);
                        # rows committed earlier are re-checked once at the
                        # region boundary, not every step.  Slice indices
                        # come from the pre-step view with voted ctrl state
                        # so a single corrupted lane cannot redirect the
                        # vote window.  A 3-tuple hint adds a traced
                        # ``active`` flag: steps that store nothing (e.g.
                        # compute micro-steps) skip the vote entirely via
                        # lax.cond, halving the slice traffic.
                        hint_out = hint(slice_view, t)
                        if len(hint_out) == 3:
                            starts, sizes, active = hint_out
                        else:
                            starts, sizes = hint_out
                            active = None
                        starts = tuple(jnp.asarray(s, jnp.int32)
                                       for s in starts)

                        def vote_slice(lanes, _starts=starts,
                                       _sizes=sizes, _name=name):
                            sl = jax.vmap(
                                lambda lane: jax.lax.dynamic_slice(
                                    lane, _starts, _sizes))(lanes)
                            # The hinted vote carries the leaf's own sync
                            # class (store_data for KIND_MEM, param/
                            # opt_state for training leaves) so coverage
                            # expectations hold under slice hints too.
                            sl = voters.sync_tag(
                                sl, self._sync_class_of(_name), _name)
                            voted, m = self._vote(sl, cfg.num_clones)
                            if cfg.num_clones == 3:
                                rep = jnp.broadcast_to(voted, sl.shape)
                                lanes = jax.vmap(
                                    lambda lane, r:
                                    jax.lax.dynamic_update_slice(
                                        lane, r, _starts))(lanes, rep)
                            return lanes, m

                        if active is None:
                            out, mis = vote_slice(out)
                            syncs = syncs + 1
                        else:
                            out, mis = jax.lax.cond(
                                active, vote_slice,
                                lambda lanes: (lanes, jnp.bool_(False)),
                                out)
                            syncs = syncs + active.astype(jnp.int32)
                        miscompares.append(mis)
                    else:
                        lanes = voters.sync_tag(
                            out, self._sync_class_of(name), name)
                        voted, mis = self._vote(lanes, cfg.num_clones)
                        miscompares.append(mis)
                        syncs = syncs + 1
                        if cfg.num_clones == 3:
                            # Voted value repairs every replica (the
                            # reference stores the select output through
                            # original + cloned stores, syncStoreInst
                            # :476-561).
                            out = jnp.broadcast_to(voted, out.shape)
                new_state[name] = out
            else:
                if self.region.spec[name].kind == KIND_RO:
                    new_state[name] = out[0]
                elif self.region.spec[name].unvoted_crossing:
                    # Declared unvoted SoR crossing (LeafSpec): the region
                    # carries replica-resolved data through this shared
                    # leaf itself (e.g. the exchange-then-vote halo buffer
                    # voted on the RECEIVE side, after the collective);
                    # inserting the engine's vote here would collapse the
                    # redundancy the region deliberately ships across the
                    # link.  Lane 0's value commits raw -- an honest
                    # single point of failure the provenance lint and the
                    # isolation prover both surface.
                    new_state[name] = out[0]
                elif cfg.num_clones > 1 and self._any_replicated:
                    # Store crossing the sphere of replication: vote before
                    # the single store (verification.cpp forces these into
                    # syncGlobalStores :587,676).
                    lanes = voters.sync_tag(out, "sor_crossing", name)
                    voted, mis = self._vote(lanes, cfg.num_clones)
                    miscompares.append(mis)
                    syncs = syncs + 1
                    new_state[name] = voted
                else:
                    new_state[name] = out[0]

        # Latch fault/correction accounting.  DWC checks *before* the store
        # commits: a miscompare this step freezes the state at its pre-step
        # image, the analogue of branching to the error block before the
        # store instruction (syncStoreInst, synchronization.cpp:476-561).
        fault_now = jnp.bool_(False)
        if miscompares and cfg.num_clones == 2:
            mis_any = jnp.any(jnp.stack(miscompares))
            fault_now = jnp.logical_and(~halted, mis_any)
            if self._fuse_plan is not None:
                from coast_tpu.ops import fused_step as _fs
                flags = {**flags,
                         "latch": _fs.latch_or(flags["latch"], _fs.LATCH_DWC,
                                               fault_now)}
            else:
                flags = {**flags,
                         "dwc_fault": jnp.logical_or(flags["dwc_fault"],
                                                     fault_now)}
        elif miscompares and cfg.num_clones == 3 and cfg.count_errors:
            mis_cnt = jnp.sum(jnp.stack(miscompares).astype(jnp.int32))
            flags = {**flags,
                     "tmr_cnt": flags["tmr_cnt"] + jnp.where(halted, 0, mis_cnt)}
        if cfg.count_syncs:
            flags = {**flags,
                     "sync_cnt": flags["sync_cnt"] + jnp.where(halted, 0, syncs)}

        # Carry CFCSS runtime leaves through (updated by the entry hook).
        for name in pstate:
            if name not in new_state:
                new_state[name] = pstate[name]

        # Terminator: evaluate done() on the voted view, *before* committing,
        # so a single corrupted lane cannot steer control flow
        # (syncTerminator votes branch predicates, :741-1113).  Fused
        # builds vote only the predicate's dataflow cone (FusePlan
        # .done_leaves): a vote on a leaf done() never reads is pure and
        # cannot change done_now -- the pruning the profiler attributed
        # ~1/4 of the whole per-step op budget to.
        commit_halt = jnp.logical_or(halted, fault_now)
        done_only = (self._fuse_plan.done_leaves
                     if self._fuse_plan is not None else None)
        done_now = self.region.done(self._voted_view(new_state,
                                                     only=done_only))
        # A step whose kernel guard tripped still commits (the blown-stack
        # image is the memory a debugger reads at the hook) but cannot
        # reach completion: the hook preempts the guest before any success
        # line, exactly like the reference's overflow/assert hooks.
        done_gate = jnp.logical_and(~commit_halt, ~trip_now)
        if self._fuse_plan is not None:
            from coast_tpu.ops import fused_step as _fs
            flags = {**flags,
                     "latch": _fs.latch_or(flags["latch"], _fs.LATCH_DONE,
                                           jnp.logical_and(done_gate,
                                                           done_now)),
                     "steps": flags["steps"] + jnp.where(commit_halt, 0, 1)}
        else:
            flags = {**flags,
                     "done": jnp.logical_or(flags["done"],
                                            jnp.logical_and(done_gate,
                                                            done_now)),
                     "steps": flags["steps"] + jnp.where(commit_halt, 0, 1)}

        # Freeze state once halted (DWC abort semantics in a batch: the run's
        # memory image stops evolving the step the fault latches -- and the
        # fault step itself never commits, check-before-store).  Fused
        # builds keep the where only on leaves whose stepped value can
        # actually differ from the pre-step image (FusePlan.frozen_leaves:
        # written, commit-voted, or pre-step repaired); everything else
        # commits pstate directly -- bit-equal even mid-flip, since the
        # flip lands on pstate before the step and the lane passthrough
        # preserves it.
        if self._fuse_plan is not None:
            frozen = self._fuse_plan.frozen_leaves
            new_state = {
                name: (jnp.where(commit_halt, pstate[name], val)
                       if name in frozen else pstate[name])
                for name, val in new_state.items()}
        else:
            new_state = jax.tree.map(
                lambda old, new: jnp.where(commit_halt, old, new),
                pstate, new_state)
        return new_state, flags

    # -- whole-program runners ---------------------------------------------
    def _voted_view(self, pstate: State, only=None) -> State:
        """Collapse lanes for the unprotected consumer of the result -- the
        analogue of checkGolden() being __NO_xMR and reading voted stores
        (tests/matrixMultiply/matrixMultiply.c checkGolden).

        ``only`` (fused builds): vote just the named leaves; the rest read
        a sanctioned lane-0 view.  Sound exactly when the consumer's
        dataflow cone is contained in ``only`` (FusePlan.done_leaves for
        the terminator view) -- a vote is pure, so skipping one on a leaf
        the consumer never reads cannot change its value."""
        view: State = {}
        for name, arr in pstate.items():
            if not self.replicated[name]:
                view[name] = arr
            elif only is not None and name not in only:
                view[name] = voters.lane_view(arr)
            elif self.cfg.num_clones == 3:
                view[name] = voters.tmr_vote(arr)[0]
            else:
                # DWC has no majority; the boundary read is lane 0, tagged
                # as a sanctioned view for the replication linter (the
                # final compare in run() latches any divergence first).
                view[name] = voters.lane_view(arr)
        return view

    def run(self, fault: Optional[Dict[str, jax.Array]] = None,
            trace: bool = False,
            return_state: bool = False,
            unroll: int = 1) -> Dict[str, jax.Array]:
        """Run to completion; optionally XOR one bit at step ``fault['t']``.

        ``fault`` keys: leaf_id, lane, word, bit, t (int32 scalars).  Returns
        the run record mirroring the guest UART line ``C: E: F: T:``
        (resources/decoder.py:66) plus the DUE flags.

        Multi-site fault models (inject/schedule.FaultModel) pass each key
        as an int32 vector of shape [sites] instead -- one flip GROUP per
        run.  Site g fires its own one-hot XOR when ``t == fault['t'][g]``
        (sites may share a step -- a multibit word -- or spread over a
        burst window), through the same hoisted per-site masks; the
        scalar path is byte-for-byte the historical single-site program,
        so FaultModel.single campaigns compile and classify identically.

        ``trace=True`` additionally records, per scan step, the block about
        to execute and whether the run was still live -- the raw material of
        the debugStatements/smallProfile instrumentation passes
        (coast_tpu.passes.instrument).  The trace rides out of the scan as
        two stacked tensors (one host transfer), not per-step host prints.

        ``unroll`` sets how many steps the early-exit loop executes per
        iteration; any value yields the identical run record (overshooting
        sub-steps are masked to no-ops).  The default stays 1 pending an
        on-chip sweep with the hoisted flip masks (the pre-hoist balance
        no longer holds; see artifacts/unroll_sweep.json once captured) --
        unrolling trades per-iteration loop overhead against masked no-op
        sub-steps after the early exit.  The traced path is a fixed-length
        scan, so ``unroll`` does not apply there.
        """
        n_sites = 0
        if fault is not None:
            # Accept plain Python ints (the CLI / README ergonomics).
            fault = {k: jnp.asarray(v, jnp.int32) for k, v in fault.items()}
            # Vector entries are a flip group: sites is static (a shape),
            # so the site loop unrolls into the traced program.
            n_sites = (int(fault["t"].shape[0]) if fault["t"].ndim else 0)
        pstate, flags = self.init_pstate()

        # The flip's one-hot masks are step-invariant: build them ONCE
        # outside the loop (the in-loop iota-compare rebuild measured ~2/3
        # of small-benchmark campaign runtime), leaving one select+XOR per
        # leaf per step -- per SITE for a flip group, each with its own
        # fire step.  Fused builds off-TPU lower the flip sparsely
        # instead (FusePlan.sparse_flip: one-word dynamic slice + scalar
        # XOR per leaf, ops/fused_step.make_sparse_flipper -- identical
        # semantics, ~words-per-leaf fewer ops per step).
        if self._sparse_flip is not None:
            build_fn, apply_fn = self._sparse_flip
        else:
            build_fn = self._flip.build_masks
            apply_fn = self._flip.apply_masks
        if fault is None:
            masks = None
        elif n_sites:
            masks = [build_fn(
                         pstate, self.replicated, fault["leaf_id"][g],
                         fault["lane"][g], fault["word"][g], fault["bit"][g])
                     for g in range(n_sites)]
        else:
            masks = build_fn(pstate, self.replicated,
                             fault["leaf_id"], fault["lane"],
                             fault["word"], fault["bit"])

        def body(carry, t):
            pstate, flags = carry
            halted = _halted(flags)
            if fault is not None:
                # No injection once halted: the reference's sleep window is
                # bounded by the measured runtime, so flips always land in a
                # live guest (threadFunctions.py:451-520); a flip into a
                # finished/aborted run's frozen image would mis-classify it.
                if n_sites:
                    for g in range(n_sites):
                        fire = jnp.logical_and(t == fault["t"][g],
                                               jnp.logical_not(halted))
                        pstate = apply_fn(pstate, masks[g], fire)
                else:
                    fire = jnp.logical_and(t == fault["t"],
                                           jnp.logical_not(halted))
                    pstate = apply_fn(pstate, masks, fire)
            ys = None
            if trace:
                if self.region.graph is not None:
                    blk = self.region.graph.block_of(self._voted_view(
                        {k: pstate[k] for k in self.region.spec}))
                else:
                    blk = jnp.int32(0)
                ys = (jnp.asarray(blk, jnp.int32),
                      jnp.logical_not(halted))
            return self.step(pstate, flags, t), ys

        if trace:
            # The per-step trace needs fixed-length stacked outputs.
            (pstate, flags), ys = jax.lax.scan(
                body, (pstate, flags),
                jnp.arange(self.region.max_steps, dtype=jnp.int32))
        elif (self._fuse_plan is not None
              and self._fuse_plan.bounded_scan):
            # while_loop -> bounded scan (FusePlan.bounded_scan): when
            # max_steps == nominal_steps the early exit buys nothing (a
            # batched while pays the bound anyway) and scan drops the
            # per-trip cond evaluation.  Post-halt trips are frozen
            # no-ops, so the record is bit-identical; ``unroll`` does
            # not apply to the fixed-trip form.
            def sbody(carry, t):
                out, _ = body(carry, t)
                return out, None

            (pstate, flags), _ = jax.lax.scan(
                sbody, (pstate, flags),
                jnp.arange(self.region.max_steps, dtype=jnp.int32))
        else:
            # Early exit: stop as soon as the run halts instead of always
            # paying the full max_steps watchdog window (3x the nominal
            # runtime).  Under a vmapped campaign the batching rule keeps
            # iterating while ANY run is live and masks the finished ones
            # -- which our freeze-once-halted step already guarantees is
            # value-preserving -- so a batch costs roughly its slowest
            # member, not the watchdog bound (the reference likewise waits
            # on the breakpoint, not the watchdog, threadFunctions.py
            # :754-842).  A single loop keeps the batched-while iteration
            # count at max(total steps) across the batch -- a
            # flip-then-continue two-phase split would serialise to
            # max(fault.t) + max(remaining), nearly doubling it.
            def wstep(pstate, flags, t):
                out, _ = body((pstate, flags), t)
                return out

            unroll_n = max(1, int(unroll))
            limit = jnp.int32(self.region.max_steps)

            def cond(carry):
                (pstate, flags), t = carry
                return jnp.logical_and(t < limit, ~_halted(flags))

            def guarded(carry, t):
                """One sub-step, masked to a no-op past the watchdog bound
                so an unrolled iteration that overshoots cannot let a hung
                run keep executing -- the record matches the unroll=1
                program exactly."""
                new_state, new_flags = wstep(*carry, t)
                ok = t < limit
                return jax.tree.map(
                    lambda o, n: jnp.where(ok, n, o),
                    carry, (new_state, new_flags))

            def wbody(carry):
                st, t = carry
                if unroll_n == 1:
                    return wstep(*st, t), t + 1
                for k in range(unroll_n):
                    st = guarded(st, t + k)
                return st, t + unroll_n

            (pstate, flags), _ = jax.lax.while_loop(
                cond, wbody, ((pstate, flags), jnp.int32(0)))

        # Region-boundary sync: when the result escapes the SoR (the
        # external call at the end -- printf of the result / the golden
        # check), every replicated leaf is compared/voted once, exactly the
        # reference's call sync point (processCallSync,
        # synchronization.cpp:563-738).  This is what catches divergence in
        # register leaves that never pass through a store sync (e.g. a CRC
        # accumulator flipped mid-loop).  Only a normally-completed run
        # reaches the call; an aborted/hung guest never prints.
        if self.cfg.num_clones > 1:
            mis = jnp.bool_(False)
            mis_cnt = jnp.int32(0)
            for name, arr in pstate.items():
                if not self.replicated[name]:
                    continue
                lanes = voters.sync_tag(arr, "boundary", name)
                _, m = self._vote(lanes, self.cfg.num_clones)
                mis = jnp.logical_or(mis, m)
                mis_cnt = mis_cnt + m.astype(jnp.int32)
            # Only a run that completed without ANY detected fault (abort
            # or kernel-guard trip) reaches the external call.  Packed
            # latches make the four-AND gate one equality: done set,
            # every fault bit clear <=> latch == LATCH_DONE_ONLY.
            if self._fuse_plan is not None:
                from coast_tpu.ops import fused_step as _fs
                reached_call = flags["latch"] == jnp.uint32(
                    _fs.LATCH_DONE_ONLY)
                if self.cfg.num_clones == 2:
                    flags = {**flags,
                             "latch": _fs.latch_or(
                                 flags["latch"], _fs.LATCH_DWC,
                                 jnp.logical_and(reached_call, mis))}
                elif self.cfg.count_errors:
                    flags = {**flags,
                             "tmr_cnt": flags["tmr_cnt"]
                             + jnp.where(reached_call, mis_cnt, 0)}
            else:
                reached_call = jnp.logical_and(
                    flags["done"], jnp.logical_not(flags["dwc_fault"]))
                reached_call = jnp.logical_and(
                    reached_call, jnp.logical_not(flags["cfc_fault"]))
                reached_call = jnp.logical_and(
                    reached_call, jnp.logical_not(flags["stack_fault"]))
                reached_call = jnp.logical_and(
                    reached_call, jnp.logical_not(flags["assert_fault"]))
                if self.cfg.num_clones == 2:
                    flags = {**flags,
                             "dwc_fault": jnp.logical_or(
                                 flags["dwc_fault"],
                                 jnp.logical_and(reached_call, mis))}
                elif self.cfg.count_errors:
                    flags = {**flags,
                             "tmr_cnt": flags["tmr_cnt"]
                             + jnp.where(reached_call, mis_cnt, 0)}

        if self._fuse_plan is not None:
            # Expand the packed latch word back to the historical flag
            # dict once, at record-extraction time.
            from coast_tpu.ops import fused_step as _fs
            flags = _fs.unpack_latch(flags)

        view = self._voted_view(pstate)
        rec = {
            "errors": self.region.check(view),          # E: SDC count
            "corrected": flags["tmr_cnt"],              # F: TMR corrections
            "steps": flags["steps"],                    # T: runtime
            "sync_count": flags["sync_cnt"],
            "done": flags["done"],
            "dwc_fault": flags["dwc_fault"],
            "cfc_fault": flags["cfc_fault"],
            "stack_fault": flags["stack_fault"],
            "assert_fault": flags["assert_fault"],
            "output": self.region.output(view),
        }
        if self.region.train_probe is not None:
            # Training-outcome verdict over the voted final view (0 =
            # loss trajectory clean, 1 = deviated but re-converged, 2 =
            # still diverged); classify() splits the SDC bucket on it.
            # Only train records carry the key, so every other region's
            # classification program is unchanged.
            rec["train_probe"] = jnp.asarray(
                self.region.train_probe(view), jnp.int32)
        if trace:
            rec["trace_block"], rec["trace_live"] = ys
        if return_state:
            # The voted final memory image -- what a debugger reads at the
            # EXIT_MARKER breakpoint before main returns (exitMarker.cpp
            # :120-140); consumed by passes.instrument.run_to_exit_marker.
            rec["final_state"] = view
        return rec


def protect(region: Region, cfg: ProtectionConfig) -> ProtectedProgram:
    """`opt -load DataflowProtection.so` equivalent: apply the engine."""
    return ProtectedProgram(region, cfg)
