"""``python -m coast_tpu <verb>``: the package's top-level entry point.

One stable spelling for the user-facing verbs, so operators (and the
repo's own Makefile) do not need to know the module layout:

    python -m coast_tpu ci ...        # protection-regression CI
    python -m coast_tpu profile ...   # campaign attribution report
    python -m coast_tpu slo ...       # reliability SLO check/report
    python -m coast_tpu serve ...     # protected inference service
    python -m coast_tpu fleet ...     # campaign fleet (alias)
    python -m coast_tpu analysis ...  # log analysis (alias)
    python -m coast_tpu opt ...       # protect + run one program (alias)

``ci`` is the canonical home of the CI subcommand (ROADMAP item 3) and
``profile`` of the device-time attribution report (obs/profile_cli);
the others forward to their module CLIs unchanged.
"""

from __future__ import annotations

import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip(), file=sys.stderr)
        return 0 if argv else 2
    verb, rest = argv[0], argv[1:]
    if verb == "ci":
        from coast_tpu.ci.__main__ import main as ci_main
        return ci_main(rest)
    if verb == "profile":
        from coast_tpu.obs.profile_cli import main as profile_main
        return profile_main(rest)
    if verb == "slo":
        from coast_tpu.obs.slo_cli import main as slo_main
        return slo_main(rest)
    if verb == "serve":
        from coast_tpu.serve.front import main as serve_main
        return serve_main(rest)
    if verb == "fleet":
        from coast_tpu.fleet.supervisor import main as fleet_main
        return fleet_main(rest)
    if verb == "analysis":
        from coast_tpu.analysis.json_parser import main as an_main
        return an_main(rest)
    if verb == "opt":
        from coast_tpu.opt import main as opt_main
        return opt_main(rest)
    print(f"Error, unknown verb {verb!r}; want one of: ci, profile, "
          "slo, serve, fleet, analysis, opt "
          "(see python -m coast_tpu --help)",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
