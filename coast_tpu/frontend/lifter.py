"""Region lifter: derive a protected Region from user code automatically.

The reference never asks the user for a dataflow spec: ``opt -TMR`` walks
the LLVM module and discovers every instruction, global, and argument that
needs cloning (populateValuesToClone, cloning.cpp:62-288; function closure
populateFnWorklist :294-431), guided only by scope annotations.  Round 1 of
this framework required each benchmark to hand-author its Region (``spec``,
``step``, ``done``, ``block_of``).  This module closes that gap with two
entry points:

``lift_step(name, step, init, done=...)``
    The user writes a plain jittable step function over a dict state and a
    termination predicate; the lifter *derives* everything else:

      * **LeafSpec kinds** from jaxpr provenance (passes.verification
        ``analyze_step``): an unwritten leaf is read-only (the unwritten-
        global rule of cloning.cpp:62-288); a leaf that is the target of a
        store-like partial update (dynamic_update_slice / scatter) is
        ``mem`` (the store-sync class, synchronization.cpp:476-561); a
        written leaf feeding the done() predicate, a branch predicate, or a
        load/store address is ``ctrl`` (terminator/GEP sync,
        :741-1113 / :413-474); any other written leaf is a data register.
      * **nominal_steps** by measuring a fault-free run to termination (the
        reference's timing-calibration runs, threadFunctions.py:387-449).
      * **check()** as a golden compare against the fault-free output (the
        role of the benchmark self-checks, tests/mm_common/mm.c:31).
      * a coarse **block graph** for CFCSS when none is supplied.

``lift_fn(name, fn, *example_args)``
    The user hands over a whole jittable function.  The lifter traces it to
    a jaxpr, finds the dominant top-level loop (``lax.scan`` / ``lax.
    while_loop`` -- the analogue of the main loop COAST's injection window
    brackets), and slices the program into prologue / loop body / epilogue:
    the prologue is evaluated at lift time into initial state, each loop
    iteration becomes one region step, and the epilogue becomes the output
    projection.  Loop carries become register/ctrl leaves, scanned inputs
    and loop-invariant captures become read-only leaves, stacked scan
    outputs become memory leaves written through dynamic updates.

Annotations (a dict name -> LeafSpec) override any derived classification,
playing the role of the COAST.h ``__xMR`` / ``__NO_xMR`` source macros
(tests/COAST.h:11-64): scope is the user's choice; discovery is the
compiler's job.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.extend.core import Literal

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region, State)
from coast_tpu.passes.verification import analyze_step, reads_of

_32BIT = (jnp.int32, jnp.uint32, jnp.float32)


class LiftError(Exception):
    """The lifter could not derive a Region; the message says why and what
    to supply (mirrors the reference's refusal style for unsupported
    constructs, e.g. the hard-unsupported function list cloning.cpp:50)."""


# ---------------------------------------------------------------------------
# lift_step: stepped user function -> Region
# ---------------------------------------------------------------------------

def _classify(state, step, done) -> Dict[str, LeafSpec]:
    flow = analyze_step(step, state)
    done_deps = reads_of(done, state)
    ctrl = done_deps | flow.load_addr | flow.store_addr | flow.branch_pred
    spec: Dict[str, LeafSpec] = {}
    for name in state:
        if name not in flow.written:
            kind = KIND_RO
        elif name in flow.stored_into:
            # Store-target beats ctrl: a memory leaf whose contents feed an
            # address or predicate (e.g. an interpreter's memory) is still
            # memory -- its writes go through the store-sync voter.
            kind = KIND_MEM
        elif name in ctrl:
            kind = KIND_CTRL
        else:
            kind = KIND_REG
        spec[name] = LeafSpec(kind)
    return spec


def _measure_steps(init_fn, step, done, cap: int) -> int:
    """Fault-free run to termination; the timing-calibration analogue."""

    def cond(carry):
        s, t = carry
        return jnp.logical_and(t < cap, jnp.logical_not(done(s)))

    def body(carry):
        s, t = carry
        return step(s, t), t + 1

    _, t = jax.jit(lambda s: jax.lax.while_loop(cond, body, (s, jnp.int32(0))))(
        init_fn())
    steps = int(t)
    if steps >= cap:
        raise LiftError(
            f"program did not terminate within {cap} steps; pass "
            "nominal_steps= explicitly (or fix the done() predicate)")
    return steps


def _final_state(init_fn, step, done, max_steps: int) -> State:
    def cond(carry):
        s, t = carry
        return jnp.logical_and(t < max_steps, jnp.logical_not(done(s)))

    def body(carry):
        s, t = carry
        return step(s, t), t + 1

    s, _ = jax.jit(lambda s: jax.lax.while_loop(cond, body, (s, jnp.int32(0))))(
        init_fn())
    return s


def _flat_u32(leaves: Sequence[jax.Array]) -> jax.Array:
    """Flatten arrays of any 32-bit dtype into one uint32 word vector (the
    word-addressed memory-image view the injector and SDC attribution use,
    resources/mem.py:56-85)."""
    if not leaves:
        return jnp.zeros((0,), jnp.uint32)
    parts = [jax.lax.bitcast_convert_type(jnp.asarray(x), jnp.uint32).reshape(-1)
             for x in leaves]
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def lift_step(name: str,
              step: Callable[[State, jax.Array], State],
              init,
              *,
              done: Callable[[State], jax.Array],
              check: Optional[Callable[[State], jax.Array]] = None,
              output: Optional[Callable[[State], jax.Array]] = None,
              nominal_steps: Optional[int] = None,
              max_steps: Optional[int] = None,
              annotations: Optional[Dict[str, LeafSpec]] = None,
              default_xmr: bool = True,
              graph: Optional[BlockGraph] = None,
              step_cap: int = 1 << 16,
              meta: Optional[dict] = None) -> Region:
    """Derive a Region from a stepped user function.  Only ``step``,
    ``init`` (dict of arrays, or a callable) and ``done`` are required."""
    init_fn = init if callable(init) else (lambda: dict(init))
    state = jax.eval_shape(init_fn)
    if not isinstance(state, dict):
        raise LiftError("init must produce a flat dict of arrays "
                        f"(got {type(state).__name__})")
    bad = {k: str(v.dtype) for k, v in state.items() if v.dtype not in _32BIT}
    if bad:
        raise LiftError(
            "injectable state must be 32-bit (word-addressed memory map); "
            f"non-32-bit leaves: {bad}; cast them or restructure")

    spec = _classify(state, step, done)
    for leaf, override in (annotations or {}).items():
        if leaf not in spec:
            raise LiftError(f"annotation for unknown leaf {leaf!r} "
                            f"(state has: {', '.join(sorted(spec))})")
        spec[leaf] = override

    if nominal_steps is None:
        nominal_steps = _measure_steps(init_fn, step, done, step_cap)
    if max_steps is None:
        # Watchdog bound: 3x fault-free runtime, matching the slack the
        # reference gives its sleep window over measured runtime
        # (threadFunctions.py:451-520) and mm's hand-written region.
        max_steps = max(3 * nominal_steps, nominal_steps + 4)

    if output is None:
        # The observable result: written memory if any (what the program
        # stored), else the surviving data registers.
        mem = [n for n in sorted(state) if spec[n].kind == KIND_MEM]
        obs = mem or [n for n in sorted(state) if spec[n].kind == KIND_REG]
        if not obs:
            raise LiftError("no written leaves to observe; pass output=")

        def output(s, _obs=tuple(obs)):
            return _flat_u32([s[n] for n in _obs])

    if check is None:
        golden = jax.device_get(output(
            _final_state(init_fn, step, done, max_steps)))
        golden = jnp.asarray(golden)

        def check(s, _golden=golden):
            return jnp.sum(output(s) != _golden).astype(jnp.int32)

    if graph is None:
        # Coarse 3-block graph: enough structure for CFCSS to catch control
        # teleportation across the loop boundary; regions wanting per-phase
        # fidelity pass their own (models/chstone_mips.py style).
        graph = BlockGraph(
            names=["entry", "body", "exit"],
            edges=[(0, 1), (1, 1), (1, 2)],
            block_of=lambda s: jnp.where(done(s), jnp.int32(2),
                                         jnp.int32(1)).astype(jnp.int32),
        )

    region = Region(
        name=name,
        init=init_fn,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=int(nominal_steps),
        max_steps=int(max_steps),
        spec=spec,
        default_xmr=default_xmr,
        graph=graph,
        meta={"lifted": True, **(meta or {})},
    )
    region.validate()
    return region


# ---------------------------------------------------------------------------
# lift_fn: whole jittable function -> Region (auto-stepped at the main loop)
# ---------------------------------------------------------------------------

def _read(env, v):
    return v.val if isinstance(v, Literal) else env[v]


def _eval_eqns(eqns, env) -> None:
    """Interpret a run of jaxpr equations in ``env`` (concrete at lift time,
    traced inside step/output)."""
    for eqn in eqns:
        # get_bind_params splits trace-level params (e.g. pjit's jaxpr) into
        # bindable sub-functions, exactly as jax.core.eval_jaxpr does.
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        outs = eqn.primitive.bind(*subfuns,
                                  *[_read(env, v) for v in eqn.invars],
                                  **bind_params)
        if not eqn.primitive.multiple_results:
            outs = [outs]
        for v, o in zip(eqn.outvars, outs):
            env[v] = o


def _loop_score(eqn) -> int:
    """Rank candidate main loops by estimated dynamic work."""
    if eqn.primitive.name == "scan":
        body = eqn.params["jaxpr"].jaxpr
        return int(eqn.params["length"]) * max(len(body.eqns), 1)
    body = eqn.params["body_jaxpr"].jaxpr
    return 64 * max(len(body.eqns), 1)   # trip count unknown; assume modest


def lift_fn(name: str,
            fn: Callable,
            *example_args,
            annotations: Optional[Dict[str, LeafSpec]] = None,
            default_xmr: bool = True,
            max_steps: Optional[int] = None,
            step_cap: int = 1 << 16,
            meta: Optional[dict] = None) -> Region:
    """Derive a Region from a whole jittable function.

    The dominant top-level ``lax.scan`` / ``lax.while_loop`` becomes the
    step boundary; everything before it is evaluated once into the initial
    state, everything after it becomes the output projection.  State leaf
    names: ``c<i>`` loop carries, ``k<i>`` loop-invariant captures (read-
    only), ``x<i>`` scanned inputs, ``y<i>`` stacked scan outputs, ``_t``
    the step counter.
    """
    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr

    loops = [(i, e) for i, e in enumerate(jaxpr.eqns)
             if e.primitive.name in ("scan", "while")]
    if not loops:
        raise LiftError(
            "no top-level lax.scan/lax.while_loop found to step the program "
            "at; write the main loop with lax.scan/while_loop, or author a "
            "stepped region via lift_step()")
    k, loop = max(loops, key=lambda ie: _loop_score(ie[1]))

    # -- prologue: evaluate to concrete values at lift time ----------------
    env: Dict[object, object] = {}
    flat_args = jax.tree.leaves(example_args)
    if len(flat_args) != len(jaxpr.invars):
        raise LiftError(
            f"example args flatten to {len(flat_args)} leaves but the "
            f"traced function has {len(jaxpr.invars)} inputs")
    for v, val in zip(jaxpr.invars, flat_args):
        env[v] = jnp.asarray(val)
    for v, val in zip(jaxpr.constvars, closed.consts):
        env[v] = jnp.asarray(val)
    _eval_eqns(jaxpr.eqns[:k], env)

    prim = loop.primitive.name
    if prim == "scan":
        if loop.params.get("reverse", False):
            raise LiftError("reverse scan is not supported; re-express the "
                            "loop forward or use lift_step")
        n_consts = loop.params["num_consts"]
        n_carry = loop.params["num_carry"]
        length = int(loop.params["length"])
        body = loop.params["jaxpr"]          # ClosedJaxpr
        in_vals = [_read(env, v) for v in loop.invars]
        consts, carry0 = in_vals[:n_consts], in_vals[n_consts:n_consts + n_carry]
        xs = in_vals[n_consts + n_carry:]
        ys_avals = [ov.aval for ov in loop.outvars[n_carry:]]

        def init_fn():
            st = {"_t": jnp.int32(0)}
            for j, v in enumerate(consts):
                st[f"k{j}"] = v
            for j, v in enumerate(carry0):
                st[f"c{j}"] = v
            for j, v in enumerate(xs):
                st[f"x{j}"] = v
            for j, av in enumerate(ys_avals):
                st[f"y{j}"] = jnp.zeros(av.shape, av.dtype)
            return st

        def step(st, t):
            i = st["_t"]
            args = ([st[f"k{j}"] for j in range(n_consts)]
                    + [st[f"c{j}"] for j in range(n_carry)]
                    + [jax.lax.dynamic_index_in_dim(st[f"x{j}"], i, axis=0,
                                                    keepdims=False)
                       for j in range(len(xs))])
            outs = jax.core.eval_jaxpr(body.jaxpr, body.consts, *args)
            new = dict(st)
            for j in range(n_carry):
                new[f"c{j}"] = outs[j]
            for j, y in enumerate(outs[n_carry:]):
                new[f"y{j}"] = jax.lax.dynamic_update_index_in_dim(
                    st[f"y{j}"], y, i, axis=0)
            new["_t"] = i + 1
            return new

        def done(st):
            return st["_t"] >= length

        def loop_outs_from_state(st):
            return ([st[f"c{j}"] for j in range(n_carry)]
                    + [st[f"y{j}"] for j in range(len(ys_avals))])

        nominal = length
    else:  # while
        cn = loop.params["cond_nconsts"]
        bn = loop.params["body_nconsts"]
        cond_j = loop.params["cond_jaxpr"]
        body_j = loop.params["body_jaxpr"]
        in_vals = [_read(env, v) for v in loop.invars]
        cconsts, bconsts = in_vals[:cn], in_vals[cn:cn + bn]
        carry0 = in_vals[cn + bn:]

        def init_fn():
            st = {}
            for j, v in enumerate(cconsts):
                st[f"kc{j}"] = v
            for j, v in enumerate(bconsts):
                st[f"k{j}"] = v
            for j, v in enumerate(carry0):
                st[f"c{j}"] = v
            return st

        def step(st, t):
            args = ([st[f"k{j}"] for j in range(bn)]
                    + [st[f"c{j}"] for j in range(len(carry0))])
            outs = jax.core.eval_jaxpr(body_j.jaxpr, body_j.consts, *args)
            new = dict(st)
            for j, o in enumerate(outs):
                new[f"c{j}"] = o
            return new

        def done(st):
            args = ([st[f"kc{j}"] for j in range(cn)]
                    + [st[f"c{j}"] for j in range(len(carry0))])
            (alive,) = jax.core.eval_jaxpr(cond_j.jaxpr, cond_j.consts, *args)
            return jnp.logical_not(alive)

        def loop_outs_from_state(st):
            return [st[f"c{j}"] for j in range(len(carry0))]

        nominal = None  # measured by lift_step

    # -- epilogue: output projection over the final state ------------------
    epi_eqns = jaxpr.eqns[k + 1:]
    # Values the epilogue / function outputs need from before the loop are
    # baked in as constants (they are loop-invariant by construction).
    frozen_env = dict(env)

    def output(st):
        e = dict(frozen_env)
        for v, val in zip(loop.outvars, loop_outs_from_state(st)):
            e[v] = val
        _eval_eqns(epi_eqns, e)
        return _flat_u32([_read(e, v) for v in jaxpr.outvars])

    return lift_step(
        name, step, init_fn, done=done, output=output,
        nominal_steps=nominal, max_steps=max_steps,
        annotations=annotations, default_xmr=default_xmr,
        step_cap=step_cap,
        meta={"lifted_from": "fn", "loop": prim, **(meta or {})})
