"""Region lifter: derive a protected Region from user code automatically.

The reference never asks the user for a dataflow spec: ``opt -TMR`` walks
the LLVM module and discovers every instruction, global, and argument that
needs cloning (populateValuesToClone, cloning.cpp:62-288; function closure
populateFnWorklist :294-431), guided only by scope annotations.  Round 1 of
this framework required each benchmark to hand-author its Region (``spec``,
``step``, ``done``, ``block_of``).  This module closes that gap with two
entry points:

``lift_step(name, step, init, done=...)``
    The user writes a plain jittable step function over a dict state and a
    termination predicate; the lifter *derives* everything else:

      * **LeafSpec kinds** from jaxpr provenance (passes.verification
        ``analyze_step``): an unwritten leaf is read-only (the unwritten-
        global rule of cloning.cpp:62-288); a leaf that is the target of a
        store-like partial update (dynamic_update_slice / scatter) is
        ``mem`` (the store-sync class, synchronization.cpp:476-561); a
        written leaf feeding the done() predicate, a branch predicate, or a
        load/store address is ``ctrl`` (terminator/GEP sync,
        :741-1113 / :413-474); any other written leaf is a data register.
      * **nominal_steps** by measuring a fault-free run to termination (the
        reference's timing-calibration runs, threadFunctions.py:387-449).
      * **check()** as a golden compare against the fault-free output (the
        role of the benchmark self-checks, tests/mm_common/mm.c:31).
      * a coarse **block graph** for CFCSS when none is supplied.

``lift_fn(name, fn, *example_args)``
    The user hands over a whole jittable function.  The lifter traces it to
    a jaxpr, finds the dominant top-level loop (``lax.scan`` / ``lax.
    while_loop`` -- the analogue of the main loop COAST's injection window
    brackets), and slices the program into prologue / loop body / epilogue:
    the prologue is evaluated at lift time into initial state, each loop
    iteration becomes one region step, and the epilogue becomes the output
    projection.  Loop carries become register/ctrl leaves, scanned inputs
    and loop-invariant captures become read-only leaves, stacked scan
    outputs become memory leaves written through dynamic updates.

Annotations (a dict name -> LeafSpec) override any derived classification,
playing the role of the COAST.h ``__xMR`` / ``__NO_xMR`` source macros
(tests/COAST.h:11-64): scope is the user's choice; discovery is the
compiler's job.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend.core import Literal

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ops.indexing import row_select, row_update
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region, State)
from coast_tpu.passes.verification import analyze_step, reads_of

_32BIT = (jnp.int32, jnp.uint32, jnp.float32)


class LiftError(Exception):
    """The lifter could not derive a Region; the message says why and what
    to supply (mirrors the reference's refusal style for unsupported
    constructs, e.g. the hard-unsupported function list cloning.cpp:50)."""


# ---------------------------------------------------------------------------
# lift_step: stepped user function -> Region
# ---------------------------------------------------------------------------

def _classify(state, step, done) -> Dict[str, LeafSpec]:
    flow = analyze_step(step, state)
    done_deps = reads_of(done, state)
    ctrl = done_deps | flow.load_addr | flow.store_addr | flow.branch_pred
    spec: Dict[str, LeafSpec] = {}
    for name in state:
        if name not in flow.written:
            kind = KIND_RO
        elif name in flow.stored_into:
            # Store-target beats ctrl: a memory leaf whose contents feed an
            # address or predicate (e.g. an interpreter's memory) is still
            # memory -- its writes go through the store-sync voter.
            kind = KIND_MEM
        elif name in ctrl:
            kind = KIND_CTRL
        else:
            kind = KIND_REG
        spec[name] = LeafSpec(kind)
    return spec


def _measure_steps(init_fn, step, done, cap: int) -> int:
    """Fault-free run to termination; the timing-calibration analogue."""

    def cond(carry):
        s, t = carry
        return jnp.logical_and(t < cap, jnp.logical_not(done(s)))

    def body(carry):
        s, t = carry
        return step(s, t), t + 1

    _, t = jax.jit(lambda s: jax.lax.while_loop(cond, body, (s, jnp.int32(0))))(
        init_fn())
    steps = int(t)
    if steps >= cap:
        raise LiftError(
            f"program did not terminate within {cap} steps; pass "
            "nominal_steps= explicitly (or fix the done() predicate)")
    return steps


def _final_state(init_fn, step, done, max_steps: int) -> State:
    def cond(carry):
        s, t = carry
        return jnp.logical_and(t < max_steps, jnp.logical_not(done(s)))

    def body(carry):
        s, t = carry
        return step(s, t), t + 1

    s, _ = jax.jit(lambda s: jax.lax.while_loop(cond, body, (s, jnp.int32(0))))(
        init_fn())
    return s


def _flat_u32(leaves: Sequence[jax.Array]) -> jax.Array:
    """Flatten arrays of any 32-bit dtype into one uint32 word vector (the
    word-addressed memory-image view the injector and SDC attribution use,
    resources/mem.py:56-85)."""
    if not leaves:
        return jnp.zeros((0,), jnp.uint32)
    parts = [jax.lax.bitcast_convert_type(jnp.asarray(x), jnp.uint32).reshape(-1)
             for x in leaves]
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def lift_step(name: str,
              step: Callable[[State, jax.Array], State],
              init,
              *,
              done: Callable[[State], jax.Array],
              check: Optional[Callable[[State], jax.Array]] = None,
              output: Optional[Callable[[State], jax.Array]] = None,
              nominal_steps: Optional[int] = None,
              max_steps: Optional[int] = None,
              annotations: Optional[Dict[str, LeafSpec]] = None,
              default_xmr: bool = True,
              graph: Optional[BlockGraph] = None,
              step_cap: int = 1 << 16,
              functions: Optional[Dict[str, Callable]] = None,
              meta: Optional[dict] = None) -> Region:
    """Derive a Region from a stepped user function.  Only ``step``,
    ``init`` (dict of arrays, or a callable) and ``done`` are required.

    ``functions`` enables the MULTI-FUNCTION form: ``step(state, t,
    fns)`` calling named sub-functions through the ``fns`` namespace
    (the function-scope unit of the reference's -ignoreFns/-cloneFns
    lists).  Classification and step measurement run with the raw
    functions bound, exactly as Region.bound_step() does for analysis
    passes; the derived Region keeps the 3-arg step + namespace so the
    protection engine can wrap each function per its scope class."""
    init_fn = init if callable(init) else (lambda: dict(init))
    user_step = step
    if functions:
        from coast_tpu.ir.region import FnNamespace
        _raw_ns = FnNamespace(dict(functions))
        step = lambda s, t: user_step(s, t, _raw_ns)  # noqa: E731
    state = jax.eval_shape(init_fn)
    if not isinstance(state, dict):
        raise LiftError("init must produce a flat dict of arrays "
                        f"(got {type(state).__name__})")
    bad = {k: str(v.dtype) for k, v in state.items() if v.dtype not in _32BIT}
    if bad:
        raise LiftError(
            "injectable state must be 32-bit (word-addressed memory map); "
            f"non-32-bit leaves: {bad}; cast them or restructure")

    spec = _classify(state, step, done)
    for leaf, override in (annotations or {}).items():
        if leaf not in spec:
            raise LiftError(f"annotation for unknown leaf {leaf!r} "
                            f"(state has: {', '.join(sorted(spec))})")
        spec[leaf] = override

    if nominal_steps is None:
        nominal_steps = _measure_steps(init_fn, step, done, step_cap)
    if max_steps is None:
        # Watchdog bound: 3x fault-free runtime, matching the slack the
        # reference gives its sleep window over measured runtime
        # (threadFunctions.py:451-520) and mm's hand-written region.
        max_steps = max(3 * nominal_steps, nominal_steps + 4)

    if output is None:
        # The observable result: written memory if any (what the program
        # stored), else the surviving data registers.
        mem = [n for n in sorted(state) if spec[n].kind == KIND_MEM]
        obs = mem or [n for n in sorted(state) if spec[n].kind == KIND_REG]
        if not obs:
            raise LiftError("no written leaves to observe; pass output=")

        def output(s, _obs=tuple(obs)):
            return _flat_u32([s[n] for n in _obs])

    if check is None:
        golden = jax.device_get(output(
            _final_state(init_fn, step, done, max_steps)))
        golden = jnp.asarray(golden)

        def check(s, _golden=golden):
            return jnp.sum(output(s) != _golden).astype(jnp.int32)

    if graph is None:
        # Coarse 3-block graph: enough structure for CFCSS to catch control
        # teleportation across the loop boundary; regions wanting per-phase
        # fidelity pass their own (models/chstone_mips.py style).
        graph = BlockGraph(
            names=["entry", "body", "exit"],
            edges=[(0, 1), (1, 1), (1, 2)],
            block_of=lambda s: jnp.where(done(s), jnp.int32(2),
                                         jnp.int32(1)).astype(jnp.int32),
        )

    region = Region(
        name=name,
        init=init_fn,
        step=user_step if functions else step,
        done=done,
        check=check,
        output=output,
        nominal_steps=int(nominal_steps),
        max_steps=int(max_steps),
        spec=spec,
        default_xmr=default_xmr,
        graph=graph,
        functions=dict(functions or {}),
        meta={"lifted": True, **(meta or {})},
    )
    region.validate()
    return region


# ---------------------------------------------------------------------------
# lift_fn: whole jittable function -> Region (auto-stepped at its loops)
# ---------------------------------------------------------------------------

def _read(env, v):
    return v.val if isinstance(v, Literal) else env[v]


def _eval_eqns(eqns, env) -> None:
    """Interpret a run of jaxpr equations in ``env`` (concrete at lift time,
    traced inside step/output)."""
    for eqn in eqns:
        # get_bind_params splits trace-level params (e.g. pjit's jaxpr) into
        # bindable sub-functions, exactly as jax.core.eval_jaxpr does.
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        outs = eqn.primitive.bind(*subfuns,
                                  *[_read(env, v) for v in eqn.invars],
                                  **bind_params)
        if not eqn.primitive.multiple_results:
            outs = [outs]
        for v, o in zip(eqn.outvars, outs):
            env[v] = o


_HEAVY_PRIMS = ("dot_general", "conv_general_dilated", "scan", "while",
                "sort", "fft")


def _all_prims(eqns):
    """Primitive names in ``eqns``, recursing into sub-jaxprs (pjit/jit
    wrap whole calls like jnp.sort in one opaque equation)."""
    for e in eqns:
        yield e.primitive.name
        for param in e.params.values():
            objs = param if isinstance(param, (list, tuple)) else [param]
            for obj in objs:
                if hasattr(obj, "jaxpr"):          # ClosedJaxpr -> Jaxpr
                    obj = obj.jaxpr
                if hasattr(obj, "eqns"):
                    yield from _all_prims(obj.eqns)


def _epilogue_is_heavy(eqns) -> bool:
    """Epilogues with real work (heavy primitives, or enough equations
    to carry a meaningful cross-section) are lowered into a final
    stepped transition so they execute INSIDE the injection window --
    the reference engine protects the whole module (cloning.cpp:62-288).
    Trivial epilogues (output projections, a handful of reshapes) stay
    in output(): stepping them would churn every region's leaf layout
    for no injectable surface."""
    if len(eqns) > 24:
        return True
    return any(p in _HEAVY_PRIMS for p in _all_prims(eqns))


def _out_words(outvars) -> int:
    """Word count of the flattened u32 output image (_flat_u32)."""
    total = 0
    for v in outvars:
        shape = (np.shape(v.val) if isinstance(v, Literal)
                 else v.aval.shape)
        total += int(np.prod(shape, dtype=np.int64))
    return total


class _Phase:
    """One top-level loop as a stepped phase: leaf layout, per-iteration
    step, completion predicate, and the mapping from state back to the
    loop equation's outvars.  ``prefix`` namespaces the leaves (empty for
    the single-loop layout, ``p<N>_`` for multi-phase regions); scans use
    ``idx_name`` as their iteration counter leaf."""

    def __init__(self, eqn, prefix: str, idx_name: str):
        self.eqn = eqn
        self.prefix = prefix
        self.prim = eqn.primitive.name
        self.idx_name = idx_name
        if self.prim == "scan":
            # A reverse scan steps the same carries with flipped indexing:
            # iteration i reads x[L-1-i] and writes y[L-1-i].
            self.reverse = bool(eqn.params.get("reverse", False))
            self.n_consts = eqn.params["num_consts"]
            self.n_carry = eqn.params["num_carry"]
            self.length = int(eqn.params["length"])
            self.body = eqn.params["jaxpr"]              # ClosedJaxpr
            self.n_xs = len(eqn.invars) - self.n_consts - self.n_carry
            self.ys_avals = [ov.aval for ov in eqn.outvars[self.n_carry:]]
        else:  # while
            self.cn = eqn.params["cond_nconsts"]
            self.bn = eqn.params["body_nconsts"]
            self.cond_j = eqn.params["cond_jaxpr"]
            self.body_j = eqn.params["body_jaxpr"]
            self.n_carry = len(eqn.invars) - self.cn - self.bn

    # -- leaf layout -------------------------------------------------------
    def leaves_from_invals(self, in_vals) -> Dict[str, jax.Array]:
        """Leaf dict for this phase given concrete/traced loop inputs."""
        p = self.prefix
        st: Dict[str, jax.Array] = {}
        if self.prim == "scan":
            st[self.idx_name] = jnp.int32(0)
            consts = in_vals[:self.n_consts]
            carry = in_vals[self.n_consts:self.n_consts + self.n_carry]
            xs = in_vals[self.n_consts + self.n_carry:]
            for j, v in enumerate(consts):
                st[f"{p}k{j}"] = v
            for j, v in enumerate(carry):
                st[f"{p}c{j}"] = v
            for j, v in enumerate(xs):
                st[f"{p}x{j}"] = v
            for j, av in enumerate(self.ys_avals):
                st[f"{p}y{j}"] = jnp.zeros(av.shape, av.dtype)
        else:
            cconsts = in_vals[:self.cn]
            bconsts = in_vals[self.cn:self.cn + self.bn]
            carry = in_vals[self.cn + self.bn:]
            for j, v in enumerate(cconsts):
                st[f"{p}kc{j}"] = v
            for j, v in enumerate(bconsts):
                st[f"{p}k{j}"] = v
            for j, v in enumerate(carry):
                st[f"{p}c{j}"] = v
        return st

    def leaf_names_by_position(self) -> List[str]:
        """Leaf name for each loop input, aligned with eqn.invars order
        (the inverse of leaves_from_invals' layout)."""
        p = self.prefix
        if self.prim == "scan":
            return ([f"{p}k{j}" for j in range(self.n_consts)]
                    + [f"{p}c{j}" for j in range(self.n_carry)]
                    + [f"{p}x{j}" for j in range(self.n_xs)])
        return ([f"{p}kc{j}" for j in range(self.cn)]
                + [f"{p}k{j}" for j in range(self.bn)]
                + [f"{p}c{j}" for j in range(self.n_carry)])

    def zero_leaves(self) -> Dict[str, jax.Array]:
        """Placeholder leaves for a phase whose inputs arrive at runtime
        (written by the preceding interlude transition)."""
        zeros = [jnp.zeros(v.aval.shape, v.aval.dtype) for v in
                 self.eqn.invars]
        return self.leaves_from_invals(zeros)

    # -- runtime behavior --------------------------------------------------
    def iter_step(self, st):
        p = self.prefix
        new = dict(st)
        if self.prim == "scan":
            if self.length == 0:
                # Zero-trip phase: done at entry; the iteration branch is
                # still traced by lax.cond, so it must not index 0-length
                # xs -- a no-op keeps the trace valid.
                return new
            i = st[self.idx_name]
            pos = (self.length - 1 - i) if self.reverse else i
            args = ([st[f"{p}k{j}"] for j in range(self.n_consts)]
                    + [st[f"{p}c{j}"] for j in range(self.n_carry)]
                    + [row_select(st[f"{p}x{j}"], pos)
                       for j in range(self.n_xs)])
            outs = jax.core.eval_jaxpr(self.body.jaxpr, self.body.consts,
                                       *args)
            for j in range(self.n_carry):
                new[f"{p}c{j}"] = outs[j]
            for j, y in enumerate(outs[self.n_carry:]):
                new[f"{p}y{j}"] = row_update(st[f"{p}y{j}"], y, pos)
            new[self.idx_name] = i + 1
        else:
            args = ([st[f"{p}k{j}"] for j in range(self.bn)]
                    + [st[f"{p}c{j}"] for j in range(self.n_carry)])
            outs = jax.core.eval_jaxpr(self.body_j.jaxpr,
                                       self.body_j.consts, *args)
            for j, o in enumerate(outs):
                new[f"{p}c{j}"] = o
        return new

    def phase_done(self, st):
        p = self.prefix
        if self.prim == "scan":
            return st[self.idx_name] >= self.length
        args = ([st[f"{p}kc{j}"] for j in range(self.cn)]
                + [st[f"{p}c{j}"] for j in range(self.n_carry)])
        (alive,) = jax.core.eval_jaxpr(self.cond_j.jaxpr,
                                       self.cond_j.consts, *args)
        return jnp.logical_not(alive)

    def outs_from_state(self, st):
        p = self.prefix
        outs = [st[f"{p}c{j}"] for j in range(self.n_carry)]
        if self.prim == "scan":
            outs += [st[f"{p}y{j}"] for j in range(len(self.ys_avals))]
        return outs


def _free_prologue_vars(segments, loops, env, outvars) -> List[object]:
    """Prologue-computed vars consumed after the loop boundary: by any
    interlude/epilogue equation, any later loop's inputs, or the function
    outputs.  These must stay injectable (ro leaves), not vanish into a
    baked closure -- the reference engine protects them as globals."""
    produced_after = set()
    for seg in segments:
        for eqn in seg:
            produced_after.update(eqn.outvars)
    for loop in loops:
        produced_after.update(loop.outvars)
    needed: List[object] = []
    seen = set()

    def visit(v):
        if isinstance(v, Literal) or v in seen:
            return
        seen.add(v)
        if v in env and v not in produced_after:
            needed.append(v)

    for seg in segments:
        for eqn in seg:
            for v in eqn.invars:
                visit(v)
    for loop in loops[1:]:
        for v in loop.invars:
            visit(v)
    for v in outvars:              # fn may return a prologue value directly
        visit(v)
    return needed


def lift_fn(name: str,
            fn: Callable,
            *example_args,
            annotations: Optional[Dict[str, LeafSpec]] = None,
            default_xmr: bool = True,
            max_steps: Optional[int] = None,
            step_cap: int = 1 << 16,
            meta: Optional[dict] = None) -> Region:
    """Derive a Region from a whole jittable function.

    EVERY top-level ``lax.scan`` / ``lax.while_loop`` becomes a stepped
    phase (the reference protects the whole module, cloning.cpp:62-288,
    not just its hottest loop).  The prologue is evaluated once into the
    initial state; code between loops (interludes) runs as stepped phase
    transitions; an epilogue with real work (heavy primitives or many
    equations) runs as a FINAL stepped transition writing the flattened
    output image into an ``_outbuf`` memory leaf -- inside the injection
    window -- while a trivial epilogue stays in output() as a pure
    projection.

    Single-loop leaf names: ``c<i>`` loop carries, ``k<i>`` loop-invariant
    captures (read-only), ``x<i>`` scanned inputs, ``y<i>`` stacked scan
    outputs, ``_t`` the step counter, ``g<i>`` prologue values the
    epilogue reads (read-only, injectable).  Multi-loop regions prefix
    per-phase leaves ``p<N>_`` and add ``_phase`` plus ``m<i>`` leaves for
    interlude values consumed by later phases.
    """
    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr

    loop_idx = [i for i, e in enumerate(jaxpr.eqns)
                if e.primitive.name in ("scan", "while")]
    if not loop_idx:
        raise LiftError(
            "no top-level lax.scan/lax.while_loop found to step the program "
            "at; write the main loop with lax.scan/while_loop, or author a "
            "stepped region via lift_step()")

    # -- prologue: evaluate to concrete values at lift time ----------------
    env: Dict[object, object] = {}
    flat_args = jax.tree.leaves(example_args)
    if len(flat_args) != len(jaxpr.invars):
        raise LiftError(
            f"example args flatten to {len(flat_args)} leaves but the "
            f"traced function has {len(jaxpr.invars)} inputs")
    for v, val in zip(jaxpr.invars, flat_args):
        env[v] = jnp.asarray(val)
    for v, val in zip(jaxpr.constvars, closed.consts):
        env[v] = jnp.asarray(val)
    _eval_eqns(jaxpr.eqns[:loop_idx[0]], env)

    loops = [jaxpr.eqns[i] for i in loop_idx]
    segments = [jaxpr.eqns[loop_idx[p] + 1:
                           (loop_idx[p + 1] if p + 1 < len(loop_idx)
                            else len(jaxpr.eqns))]
                for p in range(len(loop_idx))]

    # Prologue values consumed past the loop boundary become ro leaves
    # (g<j>); non-32-bit ones cannot enter the word-addressed memory map
    # and stay baked (same as the reference's non-word data).
    g_vars = _free_prologue_vars(segments, loops, env, jaxpr.outvars)
    g_map = {}                       # var -> leaf name (injectable)
    baked = {}                       # var -> concrete value (not injectable)
    for v in g_vars:
        val = jnp.asarray(env[v])
        if val.dtype in _32BIT:
            g_map[v] = f"g{len(g_map)}"
        else:
            baked[v] = val

    # One set of phase adapters, shared by the builders below and by the
    # arg->leaf map, so the leaf-naming scheme lives in exactly one place.
    if len(loops) == 1:
        phases = [_Phase(loops[0], prefix="", idx_name="_t")]
    else:
        phases = [_Phase(loops[p], prefix=f"p{p}_", idx_name=f"p{p}_i")
                  for p in range(len(loops))]

    # Which state leaf each PROGRAM ARGUMENT became (by flat arg index):
    # g leaves, or a loop input leaf when the arg feeds a loop unchanged.
    # Args transformed before use (data * scale) have no single leaf and
    # are absent.  Consumers (lift_c's per-global __xMR annotations) use
    # this to map source-level names onto derived leaves.
    arg_leaves: Dict[int, str] = {}
    invar_index = {v: i for i, v in enumerate(jaxpr.invars)}
    for v, leaf in g_map.items():
        if v in invar_index:
            arg_leaves[invar_index[v]] = leaf
    for loop, phase in zip(loops, phases):
        for v, leaf in zip(loop.invars, phase.leaf_names_by_position()):
            if not isinstance(v, Literal) and v in invar_index:
                arg_leaves.setdefault(invar_index[v], leaf)
    meta = {"arg_leaves": arg_leaves, **(meta or {})}

    if len(loops) == 1:
        region = _lift_fn_single(name, jaxpr, loops[0], segments[0], env,
                                 g_map, baked, annotations, default_xmr,
                                 max_steps, step_cap, meta, phases[0])
    else:
        region = _lift_fn_multi(name, jaxpr, loops, segments, env,
                                g_map, baked, annotations, default_xmr,
                                max_steps, step_cap, meta, phases)
    return region


def _seed_env(st, g_map, baked):
    e = {v: st[leaf] for v, leaf in g_map.items()}
    e.update(baked)
    return e


def _force_ro_g_leaves(g_leaves, annotations):
    """Pin prologue g<j> leaves to read-only unless the user says otherwise.

    No transition ever writes a g leaf (they are prologue values, only read
    past the loop boundary), but the epilogue/interlude transitions route
    state through ``lax.cond``/``lax.switch``, whose outputs are fresh jaxpr
    vars -- provenance identity detection (analyze_step) cannot see the
    passthrough and would classify them as written registers.  A g leaf
    misread as ``reg`` gets replicated per-lane and voted, so a single-lane
    flip is silently outvoted and the leaf stops being injectable -- the
    opposite of the unwritten-global rule (cloning.cpp:62-288) these leaves
    exist to mirror.  Explicit user annotations still win."""
    if not g_leaves:
        return annotations
    return {**{leaf: LeafSpec(kind=KIND_RO) for leaf in g_leaves},
            **(annotations or {})}


def _lift_fn_single(name, jaxpr, loop, epi_eqns, env, g_map, baked,
                    annotations, default_xmr, max_steps, step_cap, meta,
                    phase):
    in_vals = [_read(env, v) for v in loop.invars]
    base_leaves = phase.leaves_from_invals(in_vals)
    g_leaves = {leaf: jnp.asarray(env[v]) for v, leaf in g_map.items()}
    annotations = _force_ro_g_leaves(g_leaves, annotations)

    def eval_epilogue(st):
        e = _seed_env(st, g_map, baked)
        for v, val in zip(loop.outvars, phase.outs_from_state(st)):
            e[v] = val
        _eval_eqns(epi_eqns, e)
        return _flat_u32([_read(e, v) for v in jaxpr.outvars])

    if _epilogue_is_heavy(epi_eqns):
        # The epilogue runs as ONE extra stepped transition that writes
        # the flattened output image into an ``_outbuf`` memory leaf --
        # inside the injection window, like everything else the program
        # computes (the reference's exitMarker breakpoints on exactly
        # this final memory image, exitMarker.cpp:96-140).  output()
        # then just reads the leaf.
        #
        # Cost note: under a vmapped campaign lax.cond lowers to select
        # (both branches execute per lane per step), so the epilogue is
        # re-evaluated every step -- the same shape multi-phase
        # transitions already have.  That prices fidelity over
        # throughput deliberately: outside the window the work was
        # invisible to injection, which under-reports the program's
        # cross-section (the reference protects the whole module).
        def init_fn():
            return {**base_leaves, **g_leaves,
                    "_phase": jnp.int32(0),
                    "_outbuf": jnp.zeros((_out_words(jaxpr.outvars),),
                                         jnp.uint32)}

        def epi_transition(st):
            new = dict(st)
            new["_outbuf"] = eval_epilogue(st)
            new["_phase"] = jnp.int32(1)
            return new

        def step(st, t):
            return jax.lax.cond(
                jnp.logical_and(phase.phase_done(st), st["_phase"] == 0),
                epi_transition, phase.iter_step, st)

        def done(st):
            return st["_phase"] >= 1

        def output(st):
            return st["_outbuf"]

        nominal = (phase.length + 1 if phase.prim == "scan" else None)
        return lift_step(
            name, step, init_fn, done=done, output=output,
            nominal_steps=nominal, max_steps=max_steps,
            annotations=annotations, default_xmr=default_xmr,
            step_cap=step_cap,
            meta={"lifted_from": "fn", "loop": phase.prim,
                  "stepped_epilogue": True, **(meta or {})})

    def init_fn():
        return {**base_leaves, **g_leaves}

    def step(st, t):
        return phase.iter_step(st)

    def done(st):
        return phase.phase_done(st)

    def output(st):
        return eval_epilogue(st)

    nominal = phase.length if phase.prim == "scan" else None
    return lift_step(
        name, step, init_fn, done=done, output=output,
        nominal_steps=nominal, max_steps=max_steps,
        annotations=annotations, default_xmr=default_xmr,
        step_cap=step_cap,
        meta={"lifted_from": "fn", "loop": phase.prim, **(meta or {})})


def _lift_fn_multi(name, jaxpr, loops, segments, env, g_map, baked,
                   annotations, default_xmr, max_steps, step_cap, meta,
                   phases):
    """Multi-phase region: phase p executes loop p one iteration per step;
    when loop p completes, ONE transition step evaluates the interlude
    (code between loop p and loop p+1), seeds phase p+1's leaves, and
    advances ``_phase``.  A heavy epilogue runs in the final transition
    (into ``_outbuf``); a trivial one stays in output()."""
    m = len(loops)

    # Interlude values consumed by LATER segments (beyond the transition
    # that computes them) must live in state: m<j> leaves.
    produced_by_seg = [set(ov for eqn in segments[p] for ov in eqn.outvars)
                       for p in range(m)]
    mm_map: Dict[object, str] = {}       # var -> m<j> leaf name
    m_producer: Dict[object, int] = {}   # var -> producing segment index
    for p in range(m - 1):               # the epilogue's outputs go nowhere
        consumed_later = set()
        for q in range(p + 1, m):
            for eqn in segments[q]:
                consumed_later.update(v for v in eqn.invars
                                      if not isinstance(v, Literal))
            consumed_later.update(v for v in loops[q].invars
                                  if not isinstance(v, Literal))
        consumed_later.update(v for v in jaxpr.outvars
                              if not isinstance(v, Literal))
        for v in produced_by_seg[p]:
            if v in consumed_later and v not in mm_map:
                aval = v.aval
                if aval.dtype not in _32BIT:
                    raise LiftError(
                        f"interlude value of dtype {aval.dtype} is "
                        "consumed by a later phase; only 32-bit values "
                        "can cross phases (word-addressed memory map)")
                mm_map[v] = f"m{len(mm_map)}"
                m_producer[v] = p

    g_leaves = {leaf: jnp.asarray(env[v]) for v, leaf in g_map.items()}
    annotations = _force_ro_g_leaves(g_leaves, annotations)
    in_vals0 = [_read(env, v) for v in loops[0].invars]
    # A heavy epilogue executes inside the FINAL transition step (the
    # last inter-phase), writing the flattened output image into an
    # ``_outbuf`` memory leaf -- inside the injection window; output()
    # reads the leaf (the exitMarker final-memory-image discipline).
    stepped_epi = _epilogue_is_heavy(segments[m - 1])

    def init_fn():
        st = {"_phase": jnp.int32(0), **g_leaves}
        st.update(phases[0].leaves_from_invals(in_vals0))
        for p in range(1, m):
            st.update(phases[p].zero_leaves())
        for v, leaf in mm_map.items():
            st[leaf] = jnp.zeros(v.aval.shape, v.aval.dtype)
        if stepped_epi:
            st["_outbuf"] = jnp.zeros((_out_words(jaxpr.outvars),),
                                      jnp.uint32)
        return st

    def full_env(st, upto: int):
        """Env with g/m leaves and the outvars of loops 0..upto."""
        e = _seed_env(st, g_map, baked)
        for v, leaf in mm_map.items():
            e[v] = st[leaf]
        for q in range(upto + 1):
            for v, val in zip(loops[q].outvars,
                              phases[q].outs_from_state(st)):
                e[v] = val
        return e

    def transition(p):
        """Loop p finished: evaluate interlude p, seed phase p+1, advance.
        The final transition (p == m-1) evaluates a heavy epilogue into
        ``_outbuf`` so its work is stepped."""
        def tr(st):
            new = dict(st)
            if p < m - 1:
                e = full_env(st, p)
                _eval_eqns(segments[p], e)
                in_vals = [_read(e, v) for v in loops[p + 1].invars]
                new.update(phases[p + 1].leaves_from_invals(in_vals))
                for v, leaf in mm_map.items():
                    if m_producer[v] == p:
                        new[leaf] = e[v]
            elif stepped_epi:
                e = full_env(st, m - 1)
                _eval_eqns(segments[m - 1], e)
                new["_outbuf"] = _flat_u32(
                    [_read(e, v) for v in jaxpr.outvars])
            new["_phase"] = st["_phase"] + 1
            return new
        return tr

    def phase_branch(p):
        def br(st):
            return jax.lax.cond(phases[p].phase_done(st), transition(p),
                                phases[p].iter_step, st)
        return br

    branches = [phase_branch(p) for p in range(m)]

    def step(st, t):
        ph = jnp.clip(st["_phase"], 0, m - 1)
        return jax.lax.switch(ph, branches, st)

    def done(st):
        return st["_phase"] >= m

    def output(st):
        if stepped_epi:
            return st["_outbuf"]
        e = full_env(st, m - 1)
        _eval_eqns(segments[m - 1], e)
        return _flat_u32([_read(e, v) for v in jaxpr.outvars])

    # Explicit prologue/loop/interlude/epilogue structure for CFCSS:
    # entry=0, loop<p>=2p+1, inter<p>=2p+2, exit=2m+1.  inter<m-1> is the
    # final transition into exit (and runs a heavy epilogue; a trivial
    # one stays in output()).
    names = ["entry"]
    for p in range(m):
        names += [f"loop{p}", f"inter{p}"]
    names.append("exit")
    edges = [(0, 1), (0, 2)]
    for p in range(m):
        lp, ip = 2 * p + 1, 2 * p + 2
        edges += [(lp, lp), (lp, ip)]
        nxt = 2 * (p + 1) + 1 if p + 1 < m else 2 * m + 1
        edges.append((ip, nxt))
        if p + 1 < m:
            edges.append((ip, 2 * (p + 1) + 2))   # zero-trip next loop
    exit_b = 2 * m + 1

    def block_of(st):
        def blk(p):
            def b(s):
                return jnp.where(phases[p].phase_done(s),
                                 jnp.int32(2 * p + 2), jnp.int32(2 * p + 1))
            return b
        ph = jnp.clip(st["_phase"], 0, m - 1)
        inner = jax.lax.switch(ph, [blk(p) for p in range(m)], st)
        return jnp.where(st["_phase"] >= m, jnp.int32(exit_b),
                         inner).astype(jnp.int32)

    graph = BlockGraph(names=names, edges=edges, block_of=block_of)

    return lift_step(
        name, step, init_fn, done=done, output=output,
        nominal_steps=None, max_steps=max_steps,
        annotations=annotations, default_xmr=default_xmr,
        step_cap=step_cap, graph=graph,
        meta={"lifted_from": "fn", "loops": [ph.prim for ph in phases],
              "phases": m, **(meta or {})})
