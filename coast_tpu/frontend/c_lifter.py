"""Restricted-C frontend: ingest reference benchmark sources directly.

The reference protects arbitrary programs handed to ``opt`` as LLVM IR
(cloning.cpp:62-288); its benchmarks are C files under tests/.  This
module closes the ingestion boundary at demo scale (SURVEY §7's
``-replicateTarget=tpu`` fallback, "a source-level frontend for the
benchmarks"): it parses a restricted C subset with pycparser, compiles
the AST to a jittable JAX function (globals become function inputs,
``printf`` arguments become observed outputs), and hands that function
to ``lift_fn`` -- so every top-level C loop becomes a stepped phase of
the derived Region and the whole existing protection/injection stack
applies unchanged.

Supported subset (enough for tests/mm_common/mm.c and friends; refusals
are loud and name the construct):

  * global scalars/arrays of 32-bit integer types, with initializers;
  * ``typedef`` of integer types; ``#define NAME literal``;
  * functions with int parameters/locals, ``for`` loops (any bounds --
    statically-counted loops lower to ``lax.scan``, general ones to
    ``lax.while_loop``), ``if``/``else``, ternaries, assignments
    (including ``+=`` family, ``++``/``--``), array subscripts,
    integer arithmetic/bitwise/comparison ops, calls to other functions
    defined in the same translation unit, and ``printf`` (its arguments
    become program outputs -- the reference's QEMU loop greps stdout, so
    stdout IS the observable; prints must sit OUTSIDE loops/branches,
    where the printed value is a well-defined program output);
  * narrow integer types (char/short/uint8_t/uint16_t): modeled with
    exact C value semantics -- values live promoted in int32 lanes and
    every store/cast re-normalizes (mask + sign-extend), so byte/short
    wraparound (CRC state machines) is bit-exact; memory LAYOUT stays
    one lane word per element (the word-addressed injection model;
    bits above the declared width are masked at read, since they do
    not exist in real byte memory);
  * pointer parameters walked over a global array (``*p++``, ``p[i]``
    after ``p++``, ``p + k``, ``p = p + 1``), char-pointer globals
    initialized with a string literal (crc16.c's message), LOCAL
    pointer variables bound to arrays (``char *p = s;`` incl. through
    pointer casts), and deref stores (``*p++ = c``) -- a pointer is an
    int32 walk cursor over its aliased array;
  * caller-LOCAL arrays passed by reference (sha256.c's
    ``sha256_hash(data, bitlen, state, ...)``): modeled as
    copy-in/copy-out through a transient slot, sound because the
    subset has no overlapping aliases;
  * local array declarations (``uint32_t m[64]``), function-like
    macros with continuation lines (ROTRIGHT, DBL_INT_ADD), comma
    expressions in ``for`` init/next, character constants;
  * ``while``/``for`` conditions with side effects (``while
    (length--)``) via a rotated loop lowering; the run-once
    ``while (1) { ...; break; }`` idiom; mid-loop conditional breaks
    (``if (c) break;`` -- lowered to a carried flag with exact C
    semantics: the broken-out iteration skips the rest of the body AND
    the for-next); structured early ``return``s anywhere in a function
    (carried flag pair, same masking discipline; a printf AFTER an
    early-return point refuses loudly -- whether it prints would be
    data-dependent, so it cannot be a fixed program output) -- other
    break/goto placements refuse loudly;
  * COAST.h annotation macros are stripped and recorded
    (``__DEFAULT_NO_xMR``, ``__xMR``, ``__NO_xMR``).

Integer model: ILP32, matching the reference's Cortex-A9/MSP430 targets
-- ``int``/``long``/pointers-free code where ``unsigned long`` is 32
bits.  All arithmetic is mod-2^32 (uint32) or int32.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.frontend.lifter import LiftError, lift_fn
from coast_tpu.ir.region import LeafSpec, Region

try:
    from pycparser import c_ast, c_parser
    _HAVE_PYCPARSER = True
except Exception:  # pragma: no cover - pycparser ships with cffi
    _HAVE_PYCPARSER = False


class CLiftError(LiftError):
    """Unsupported C construct; the message names it and the location."""


# ---------------------------------------------------------------------------
# Minimal preprocessing: the subset needs no system headers.
# ---------------------------------------------------------------------------

_COAST_MACROS = ("__DEFAULT_NO_xMR", "__DEFAULT_xMR", "__xMR", "__NO_xMR",
                 "__xMR_FN", "__NO_xMR_FN")

# Further COAST.h attribute macros: recorded and stripped so annotated
# sources PARSE (the annotations expand to __attribute__ in the real
# header, COAST.h:11-67); behaviors already designed away (ISRs,
# malloc/printf wrappers) surface later as loud refusals on the
# construct itself, not as parse errors on the macro token.
_COAST_STRIP_TOKENS = ("__xMR_FN_CALL", "__SKIP_FN_CALL",
                       "__COAST_VOLATILE", "__ISR_FUNC", "__xMR_RET_VAL",
                       "__xMR_PROT_LIB", "__xMR_ALL_AFTER_CALL",
                       "__COAST_NO_INLINE")
# Function-like COAST macros whose whole invocation line is a no-op
# declaration in the real header (wrapper registration).
_COAST_STRIP_CALLS = ("PRINTF_WRAPPER_REGISTER", "MALLOC_WRAPPER_REGISTER",
                      "__COAST_IGNORE_GLOBAL")

_PRELUDE = """
typedef unsigned int uint32_t;
typedef int int32_t;
typedef unsigned short uint16_t;
typedef short int16_t;
typedef unsigned char uint8_t;
typedef signed char int8_t;
"""


def _strip_comments(text: str) -> str:
    """Remove //... and /*...*/ outside string literals (pycparser wants
    preprocessed input)."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"':
            j = i + 1
            while j < n and text[j] != '"':
                j += 2 if text[j] == "\\" else 1
            out.append(text[i:j + 1])
            i = j + 1
        elif text.startswith("//", i):
            i = text.find("\n", i)
            i = n if i < 0 else i
        elif text.startswith("/*", i):
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("\n" * text.count("\n", i, j))   # keep line numbers
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def preprocess(text: str, include_dirs: Sequence[str] = (),
               defines: Optional[Dict[str, str]] = None,
               name_flags: Optional[Dict[str, bool]] = None,
               fdefines: Optional[Dict[str, Tuple[List[str], str]]] = None,
               ) -> Tuple[str, Dict[str, str], List[str], Dict[str, bool]]:
    """Strip/resolve the tiny preprocessor surface the benchmarks use.

    Returns (source, defines, coast_macros, name_flags).  ``#include
    "local.c"`` is inlined from ``include_dirs`` (the mm_common.c
    pattern) and SHARES the including file's ``#define`` table, exactly
    like cpp textual inclusion; ``#include <...>`` system headers are
    dropped (the prelude supplies the stdint names); object-like AND
    function-like ``#define``s substitute (continuation lines joined;
    arguments are paren-wrapped on substitution, which the benchmark
    macros -- ROTRIGHT, DBL_INT_ADD -- are written to tolerate).
    ``name_flags`` collects per-declaration scope annotations:
    ``uint32_t __xMR results[..]`` records ``{"results": True}`` (and
    ``__NO_xMR`` False) -- the identifier FOLLOWING the macro, matching
    the reference's declaration style (tests/mm_common/mm_tmr.c).
    """
    text = _strip_comments(text).replace("\\\n", " ")
    defines = {} if defines is None else defines
    fdefines = {} if fdefines is None else fdefines
    name_flags = {} if name_flags is None else name_flags
    annotations: List[str] = []
    out: List[str] = []

    def expand_fn(line: str) -> str:
        """Expand function-like macro calls with balanced-paren args."""
        for _ in range(8):                       # bounded nesting
            changed = False
            for name, (params, body) in fdefines.items():
                m = re.search(rf"\b{re.escape(name)}\s*\(", line)
                if not m:
                    continue
                start, i = m.start(), m.end()
                depth, args, cur = 1, [], ""
                while i < len(line) and depth:
                    ch = line[i]
                    if ch == "(":
                        depth += 1
                    elif ch == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    if depth == 1 and ch == ",":
                        args.append(cur)
                        cur = ""
                    else:
                        cur += ch
                    i += 1
                if depth:
                    raise CLiftError(
                        f"unbalanced macro call {name}(... in: {line!r}")
                args.append(cur)
                if not params:
                    args = [a for a in args if a.strip()]
                if len(args) != len(params):
                    raise CLiftError(
                        f"macro {name} expects {len(params)} args, "
                        f"got {len(args)} in: {line!r}")
                # Token paste FIRST (cpp order): a parameter adjacent to
                # ## substitutes its RAW argument (no parens, no prior
                # expansion), then the operator splices the tokens --
                # CHStone sha's `f##n(B,C,D)` / `CONST##n`.
                raw = {p: a.strip() for p, a in zip(params, args)}

                def paste(m):
                    l, r2 = m.group(1), m.group(2)
                    return raw.get(l, l) + raw.get(r2, r2)

                while re.search(r"\w+\s*##\s*\w+", body):
                    body = re.sub(r"(\w+)\s*##\s*(\w+)", paste, body,
                                  count=1)
                # SIMULTANEOUS parameter substitution with a function
                # replacement: sequential re.sub would re-substitute an
                # argument that mentions a later parameter's name, and a
                # string template would reinterpret backslashes in the
                # argument ('\n' in a char constant).  An argument that
                # is already one parenthesized unit is not re-wrapped
                # (_ANSI_ARGS_((void)) must yield (void), not ((void))).
                def wrap_arg(s: str) -> str:
                    s = s.strip()
                    if s.startswith("(") and s.endswith(")"):
                        depth = 0
                        for k, ch in enumerate(s):
                            if ch == "(":
                                depth += 1
                            elif ch == ")":
                                depth -= 1
                                if depth == 0 and k != len(s) - 1:
                                    break
                        else:
                            return s
                    return f"({s})"

                amap = {p: wrap_arg(a) for p, a in zip(params, args)}
                if amap:
                    pat = "|".join(rf"\b{re.escape(p)}\b" for p in amap)
                    sub = re.sub(pat, lambda m: amap[m.group(0)], body)
                else:
                    sub = body
                line = line[:start] + sub + line[i + 1:]
                changed = True
            if not changed:
                return line
        return line

    _LIT_RE = re.compile(r'"(?:[^"\\]|\\.)*"|\'(?:[^\'\\]|\\.)*\'')

    def expand(line: str) -> str:
        # String/char literals are masked out before substitution (cpp
        # never substitutes inside them -- a macro name appearing in a
        # printf format must survive) and restored after; literals
        # introduced BY an expansion are masked on the next pass.
        lits: List[str] = []

        def mask(m):
            lits.append(m.group(0))
            return f"\x01{len(lits) - 1}\x02"

        for _ in range(8):                       # rescan until stable
            line = _LIT_RE.sub(mask, line)
            before = line
            for name, val in defines.items():
                # Function replacement: a value containing backslashes
                # must not be reinterpreted as a regex template.
                line = re.sub(rf"\b{re.escape(name)}\b", lambda m: val,
                              line)
            line = expand_fn(line)
            if line == before:
                break
        return re.sub(r"\x01(\d+)\x02", lambda m: lits[int(m.group(1))],
                      line)

    def _paren_balance(s: str) -> int:
        s = _LIT_RE.sub("", s)
        return s.count("(") - s.count(")")

    # Conditional-inclusion stack: [taking, evaluable, satisfied].
    # #ifdef/#ifndef evaluate against the defines tables (motion's
    # global.h selects the _ANSI_ARGS_ variant this way); other #if
    # forms keep the legacy include-everything behavior
    # (evaluable=False), their #else/#elif branches included too.
    cond_stack: List[List[bool]] = []

    lines_in = text.splitlines()
    li = 0
    while li < len(lines_in):
        raw = lines_in[li]
        li += 1
        # A function-like macro call spanning lines (motion's
        # _ANSI_ARGS_((int *PMV, ...) prototypes): join until balanced.
        if (any(re.search(rf"\b{re.escape(n)}\s*\(", raw)
                for n in fdefines)
                and not raw.lstrip().startswith("#")):
            guard = 0
            while (_paren_balance(raw) > 0 and li < len(lines_in)
                   and guard < 100):
                raw += " " + lines_in[li]
                li += 1
                guard += 1
        line = raw
        stripped = line.strip()
        if stripped.startswith("#"):
            # cpp allows whitespace between # and the directive name
            # (global.h's `#   define _ANSI_ARGS_(x) x`).
            stripped = re.sub(r"^#\s+", "#", stripped)
        if stripped.startswith("#ifdef") or stripped.startswith("#ifndef"):
            m = re.match(r"#ifn?def\s+(\w+)", stripped)
            if m:
                known = (m.group(1) in defines or m.group(1) in fdefines)
                taking = (known if stripped.startswith("#ifdef")
                          else not known)
                cond_stack.append([taking, True, taking])
            else:
                cond_stack.append([True, False, True])
            continue
        if stripped.startswith("#if"):
            cond_stack.append([True, False, True])
            continue
        if stripped.startswith("#elif"):
            if cond_stack and cond_stack[-1][1]:
                if cond_stack[-1][2]:        # a branch was taken: skip rest
                    cond_stack[-1][0] = False
                else:                        # unknown #elif: legacy include
                    cond_stack[-1] = [True, False, True]
            continue
        if stripped.startswith("#else"):
            if cond_stack and cond_stack[-1][1]:
                cond_stack[-1][0] = not cond_stack[-1][2]
            continue
        if stripped.startswith("#endif"):
            if cond_stack:
                cond_stack.pop()
            continue
        if not all(e[0] for e in cond_stack):
            continue                          # skipped conditional branch
        if stripped.startswith("#include"):
            m = re.match(r'#include\s+"([^"]+)"', stripped)
            if m:
                fname = m.group(1)
                for d in include_dirs:
                    path = os.path.join(d, fname)
                    if os.path.exists(path):
                        if fname.endswith("COAST.h") or fname == "COAST.h":
                            break
                        with open(path) as f:
                            sub, _, subann, _ = preprocess(
                                f.read(), include_dirs, defines,
                                name_flags, fdefines)
                        annotations.extend(subann)
                        out.append(sub)
                        break
                else:
                    if not fname.endswith("COAST.h"):
                        raise CLiftError(
                            f'#include "{fname}" not found in '
                            f"{list(include_dirs)}")
            continue
        if stripped.startswith("#define"):
            fm = re.match(r"#define\s+(\w+)\(([^)]*)\)\s+(.+?)\s*$",
                          stripped)
            if fm:
                params = [p.strip() for p in fm.group(2).split(",")
                          if p.strip()]
                fdefines[fm.group(1)] = (params, fm.group(3))
                continue
            m = re.match(r"#define\s+(\w+)\s+(.+?)\s*$", stripped)
            if m:
                defines[m.group(1)] = expand(m.group(2))
                continue
            m = re.match(r"#define\s+(\w+)\s*$", stripped)
            if m:
                # Valueless define (SPARC-GCC.h's `#define INLINE`):
                # substitutes to nothing, and flips #ifdef decisions.
                defines[m.group(1)] = ""
            continue
        if stripped.startswith("#"):
            continue                      # #ifdef guards etc.: benign here
        # Expand BEFORE the annotation passes: a source-local alias like
        # `#define FUNCTION_TAG __xMR` must be recorded and stripped the
        # same as a literal __xMR (load_store.c's style).
        line = expand(line)
        # Per-declaration scope annotations.  Styles the reference corpus
        # uses: mid-declaration ``uint32_t __xMR name[..]`` (the token
        # after the macro is the name), prefix ``__xMR uint32_t name``
        # (the SECOND token is; the first is a type and resolves to
        # nothing), and trailing ``int foo() __xMR``.
        for m in re.finditer(r"\b(__NO_xMR|__xMR)\s+(\w+)(?:\s+(\w+))?",
                             line):
            flag = m.group(1) == "__xMR"
            name_flags.setdefault(m.group(2), flag)
            if m.group(3):
                name_flags.setdefault(m.group(3), flag)
        for m in re.finditer(r"\b(\w+)\s*\([^()]*\)\s*(__NO_xMR|__xMR)\b",
                             line):
            name_flags.setdefault(m.group(1), m.group(2) == "__xMR")
        # Record + strip COAST annotation macros and GCC attributes.
        for mac in _COAST_MACROS + _COAST_STRIP_TOKENS:
            if re.search(rf"\b{mac}\b", line):
                annotations.append(mac)
                line = re.sub(rf"\b{mac}\b", "", line)
        for mac in _COAST_STRIP_CALLS:
            if re.search(rf"\b{mac}\s*\(", line):
                annotations.append(mac)
                line = re.sub(rf"\b{mac}\s*\([^)]*\)\s*;?", "", line)
        line = re.sub(r"__attribute__\s*\(\(.*?\)\)", "", line)
        out.append(line)
    return "\n".join(out), defines, annotations, name_flags


# ---------------------------------------------------------------------------
# Types
# ---------------------------------------------------------------------------

_UNSIGNED = {"unsigned", "uint32_t", "_Bool"}
_NARROW = {"char": 8, "short": 16, "uint8_t": 8, "int8_t": 8,
           "uint16_t": 16, "int16_t": 16}


class _CType:
    """A C integer type on the 32-bit lane model.

    Narrow (8/16-bit) values live in int32 lanes holding their PROMOTED
    value (C's integer promotions take unsigned char/short to int, which
    int32 represents exactly), and every STORE to a narrow lvalue
    re-normalizes: mask to the declared width, sign-extend if signed --
    the mod-2^8/2^16 wraparound semantics the reference's byte/short
    benchmarks rely on (crc16.c's ``unsigned char x``/``unsigned short
    crc``).  Memory LAYOUT stays one lane word per element (the
    injection model is word-addressed; byte packing is out of scope and
    documented in docs/lifter.md)."""

    __slots__ = ("dtype", "bits", "unsigned")

    def __init__(self, dtype, bits: int = 32, unsigned: bool = False):
        self.dtype = dtype
        self.bits = bits
        self.unsigned = unsigned

    def store(self, v):
        """Normalize a value being stored into this type's lane."""
        if isinstance(v, _C64):
            v = v.lo                    # C conversion 64 -> 32: mod 2^32
        v = jnp.asarray(v)
        if self.bits == 32:
            return v.astype(self.dtype)
        mask = (1 << self.bits) - 1
        v = v.astype(jnp.int32) & mask
        if not self.unsigned:
            sign = 1 << (self.bits - 1)
            v = (v ^ sign) - sign
        return v

    def zero(self):
        return jnp.zeros((), self.dtype)


@jax.tree_util.register_pytree_node_class
class _C64:
    """A 64-bit C integer as a uint32 limb pair (lo, hi).

    JAX's x64 mode stays off (the whole lane/memory model is 32-bit
    words, matching the reference's ILP32 targets); ``long long``
    values instead live as two 32-bit lanes with explicit carry
    arithmetic -- the same limb model the df64 softfloat re-expression
    uses (models/chstone/df64.py).  Registered as a pytree so 64-bit
    locals carry through lax.scan/cond like any other value."""

    def __init__(self, lo, hi, unsigned: bool = False):
        self.lo = jnp.asarray(lo, jnp.uint32)
        self.hi = jnp.asarray(hi, jnp.uint32)
        self.unsigned = bool(unsigned)

    def tree_flatten(self):
        return (self.lo, self.hi), self.unsigned

    @classmethod
    def tree_unflatten(cls, aux, children):
        # Bypass __init__: jax's tree-structure checks unflatten with
        # sentinel (non-array) leaves, and the strict constructor must
        # keep raising on real misuse.
        obj = object.__new__(cls)
        obj.lo, obj.hi = children
        obj.unsigned = aux
        return obj

    def with_sign(self, unsigned: bool) -> "_C64":
        return _C64(self.lo, self.hi, unsigned)


def _to64(v, unsigned_hint: bool = False) -> _C64:
    """C conversion of a value to a 64-bit integer."""
    if isinstance(v, _C64):
        return v
    v = jnp.asarray(v)
    if v.dtype == jnp.uint32 or unsigned_hint:
        return _C64(v, jnp.uint32(0), True)
    v32 = v.astype(jnp.int32)
    hi = jnp.where(v32 < 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    return _C64(v32, hi, False)


def _mulhi_u32(x, y):
    """High 32 bits of the exact 64-bit product of two uint32 (16-bit
    limb decomposition; every partial product fits uint32)."""
    x = jnp.asarray(x, jnp.uint32)
    y = jnp.asarray(y, jnp.uint32)
    xl, xh = x & 0xFFFF, x >> 16
    yl, yh = y & 0xFFFF, y >> 16
    ll = xl * yl
    lh = xl * yh
    hl = xh * yl
    hh = xh * yh
    cross = (ll >> 16) + (lh & 0xFFFF) + (hl & 0xFFFF)
    return hh + (lh >> 16) + (hl >> 16) + (cross >> 16)


def _c64_add(a: _C64, b: _C64, unsigned: bool) -> _C64:
    lo = a.lo + b.lo
    carry = (lo < a.lo).astype(jnp.uint32)
    return _C64(lo, a.hi + b.hi + carry, unsigned)


def _c64_neg(a: _C64) -> _C64:
    return _c64_add(_C64(~a.lo, ~a.hi, a.unsigned),
                    _C64(1, 0, a.unsigned), a.unsigned)


def _c64_mul(a: _C64, b: _C64, unsigned: bool) -> _C64:
    # Product mod 2^64: lo-lo full product + cross terms into hi.
    lo = a.lo * b.lo
    hi = _mulhi_u32(a.lo, b.lo) + a.lo * b.hi + a.hi * b.lo
    return _C64(lo, hi, unsigned)


def _c64_shl(a: _C64, s) -> _C64:
    s = jnp.asarray(s, jnp.uint32) & 63
    sl = jnp.clip(s, 0, 31)
    sr = jnp.clip(32 - s.astype(jnp.int32), 0, 31).astype(jnp.uint32)
    lo_small = a.lo << sl
    hi_small = (a.hi << sl) | jnp.where(s > 0, a.lo >> sr, jnp.uint32(0))
    big = jnp.clip(s - 32, 0, 31)
    lo = jnp.where(s < 32, lo_small, jnp.uint32(0))
    hi = jnp.where(s < 32, hi_small, a.lo << big)
    return _C64(lo, hi, a.unsigned)


def _c64_shr(a: _C64, s) -> _C64:
    """C >> on the 64-bit value: logical for unsigned, arithmetic for
    signed (the left operand's type governs, C11 6.5.7)."""
    s = jnp.asarray(s, jnp.uint32) & 63
    sl = jnp.clip(s, 0, 31)
    sr = jnp.clip(32 - s.astype(jnp.int32), 0, 31).astype(jnp.uint32)
    fill = (jnp.uint32(0) if a.unsigned else
            jnp.where(a.hi.astype(jnp.int32) < 0,
                      jnp.uint32(0xFFFFFFFF), jnp.uint32(0)))
    hi_sh = ((a.hi >> sl) if a.unsigned
             else (a.hi.astype(jnp.int32) >> sl.astype(jnp.int32)
                   ).astype(jnp.uint32))
    lo_small = (a.lo >> sl) | jnp.where(s > 0, a.hi << sr, jnp.uint32(0))
    big = jnp.clip(s - 32, 0, 31)
    lo_big = ((a.hi >> big) if a.unsigned
              else (a.hi.astype(jnp.int32) >> big.astype(jnp.int32)
                    ).astype(jnp.uint32))
    lo = jnp.where(s < 32, lo_small, lo_big)
    hi = jnp.where(s < 32, hi_sh, fill)
    return _C64(lo, hi, a.unsigned)


def _c64_divmod(a: _C64, b: _C64) -> Tuple[_C64, _C64]:
    """Unsigned 64/64 division: 64-step restoring shift-subtract on
    limb pairs (softfloat's estimateDiv128To64 path).  The classic
    overflow trick keeps the remainder in 64 bits: when the shifted
    remainder wraps past 2^64 its true value exceeds the divisor, so
    the subtraction is taken and the mod-2^64 result is exact."""

    def step(i, st):
        qlo, qhi, rlo, rhi = st
        bit = 63 - i
        nbit = jnp.where(
            bit >= 32,
            (a.hi >> jnp.uint32(jnp.clip(bit - 32, 0, 31))) & 1,
            (a.lo >> jnp.uint32(jnp.clip(bit, 0, 31))) & 1)
        ov = rhi >> 31
        r2 = _c64_shl(_C64(rlo, rhi, True), 1)
        r2 = _C64(r2.lo | nbit, r2.hi, True)
        ge = jnp.logical_or(
            ov.astype(bool),
            jnp.logical_not(_c64_lt(r2, b, True)))
        r3 = _c64_add(r2, _c64_neg(b), True)
        rlo2 = jnp.where(ge, r3.lo, r2.lo)
        rhi2 = jnp.where(ge, r3.hi, r2.hi)
        q2 = _c64_shl(_C64(qlo, qhi, True), 1)
        qlo2 = q2.lo | ge.astype(jnp.uint32)
        return (qlo2, q2.hi, rlo2, rhi2)

    z = jnp.uint32(0)
    qlo, qhi, rlo, rhi = jax.lax.fori_loop(0, 64, step, (z, z, z, z))
    # b == 0 is C UB; pin it to q=~0, r=a (softfloat never divides by 0).
    bz = jnp.equal(b.lo | b.hi, 0)
    q = _C64(jnp.where(bz, jnp.uint32(0xFFFFFFFF), qlo),
             jnp.where(bz, jnp.uint32(0xFFFFFFFF), qhi), True)
    r = _C64(jnp.where(bz, a.lo, rlo), jnp.where(bz, a.hi, rhi), True)
    return q, r


def _c64_lt(a: _C64, b: _C64, unsigned: bool):
    if unsigned:
        hi_lt = jnp.less(a.hi, b.hi)
        hi_eq = jnp.equal(a.hi, b.hi)
    else:
        hi_lt = jnp.less(a.hi.astype(jnp.int32), b.hi.astype(jnp.int32))
        hi_eq = jnp.equal(a.hi, b.hi)
    return jnp.logical_or(hi_lt, jnp.logical_and(hi_eq,
                                                 jnp.less(a.lo, b.lo)))


class _CType64(_CType):
    """``long long`` on the limb-pair model (no memory layout: 64-bit
    GLOBALS/arrays are outside the word-addressed injection map and
    refuse at declaration; 64-bit LOCALS are register values)."""

    def __init__(self, unsigned: bool = False):
        super().__init__(jnp.uint32, 64, unsigned)

    def store(self, v):
        # Extension is governed by the SOURCE's signedness (in _to64);
        # the declared type only sets the result's signedness.
        v64 = _to64(v)
        return _C64(v64.lo, v64.hi, self.unsigned)

    def zero(self):
        return _C64(0, 0, self.unsigned)


def _ctype_of(names: List[str], typedefs: Dict[str, object]) -> _CType:
    """ILP32 _CType for a declared type-name list (``long long`` -> the
    64-bit limb-pair type)."""
    for n in names:
        if n in typedefs:
            return typedefs[n]
    uns = any(n in _UNSIGNED for n in names) or "unsigned" in names
    # Plain char is UNSIGNED on the reference's ARM targets (AAPCS).
    if "char" in names and "signed" not in names:
        uns = True
    if names.count("long") >= 2:
        return _CType64(uns)
    bits = 32
    for n in names:
        if n in _NARROW:
            bits = _NARROW[n]
    if bits == 32:
        return _CType(jnp.uint32 if uns else jnp.int32, 32, uns)
    return _CType(jnp.int32, bits, uns)


# ---------------------------------------------------------------------------
# AST -> JAX compiler
# ---------------------------------------------------------------------------

class _NoPrintList(list):
    """printf sentinel for traced sub-regions (loops, branches)."""

    def __init__(self, coord, reason=None):
        super().__init__()
        self.coord = coord
        self.reason = reason

    def _refuse(self):
        if self.reason:
            raise CLiftError(
                f"printf {self.reason} at {self.coord}: whether the "
                "print happens would depend on traced values, so it "
                "cannot be a fixed program output; print before the "
                "early exit or restructure")
        raise CLiftError(
            f"printf inside a loop or branch at {self.coord}: per-"
            "iteration prints would be traced values that cannot escape "
            "the loop; move the printf after the loop (print the final "
            "value) or restructure")

    def append(self, _):
        self._refuse()

    def extend(self, _):
        self._refuse()


class _Scope:
    """Name -> traced value, with global-write tracking.

    ``aliases`` implements C's array-argument pointer semantics at the
    only granularity the subset needs: an array parameter whose call
    argument names a GLOBAL array reads/writes that global directly
    (matrix_multiply(first_matrix, ..., results_matrix) mutates
    results_matrix, exactly as the pointer would)."""

    def __init__(self, globals_: Dict[str, jax.Array],
                 ctypes: Optional[Dict[str, "_CType"]] = None):
        self.g = globals_          # shared, mutated in place
        self.locals: Dict[str, jax.Array] = {}
        self.aliases: Dict[str, str] = {}       # param name -> global name
        self.ptrs: set = set()                  # declared pointer locals
        self.ctypes: Dict[str, _CType] = dict(ctypes or {})
        self.printed: List[jax.Array] = []
        # Constant shadow environment: scalar names whose CURRENT value
        # is a compile-time-known int.  Inside jax.make_jaxpr every jnp
        # value -- literals included -- is an abstract tracer, so
        # trace-time control decisions (statically-taken branches,
        # print-loop bounds) need classic constant propagation on the
        # side.  Absent = unknown; every traced write invalidates.
        self.consts: Dict[str, int] = {}

    def fork(self, no_print_at=None, no_print_reason=None):
        """Child scope for a traced sub-region (loop body/cond, branch).
        ``no_print_at`` arms the printf guard: values printed inside a
        traced sub-region are scan/cond tracers that cannot escape to the
        program output, so the guard refuses loudly instead of letting
        an opaque tracer-leak KeyError surface at lift time."""
        sub = _Scope(dict(self.g), self.ctypes)
        sub.locals = dict(self.locals)
        sub.aliases = dict(self.aliases)
        sub.ptrs = set(self.ptrs)
        sub.consts = dict(self.consts)
        sub.printed = (self.printed if no_print_at is None
                       else _NoPrintList(no_print_at, no_print_reason))
        return sub

    def read(self, name: str):
        # Locals FIRST: a pointer parameter holds its walk cursor as a
        # local under its own name while aliasing the pointed-to global
        # (``*p++`` support; _Compiler._ptr_parts).
        if name in self.locals:
            return self.locals[name]
        name = self.aliases.get(name, name)
        if name in self.locals:
            return self.locals[name]
        if name in self.g:
            return self.g[name]
        raise CLiftError(f"undeclared identifier {name!r}")

    def write(self, name: str, val):
        if name in self.locals:
            self.locals[name] = val
            return
        name = self.aliases.get(name, name)
        if name in self.locals:
            self.locals[name] = val
        elif name in self.g:
            self.g[name] = val
        else:
            self.locals[name] = val

    def read_binding(self, name: str):
        """Read an already-RESOLVED binding (a local name or a global/
        transient-slot name) with NO alias resolution.  Loop/branch
        carries hold resolved names; re-resolving them through this
        scope's alias map would mis-route when a parameter shadows a
        global of the same name (sha256_hash's ``data`` param vs the
        global ``data``)."""
        if name in self.locals:
            return self.locals[name]
        if name in self.g:
            return self.g[name]
        raise CLiftError(f"unbound carry name {name!r}")

    def write_binding(self, name: str, val):
        if name in self.locals:
            self.locals[name] = val
        else:
            self.g[name] = val

    def ctype(self, name: str) -> Optional["_CType"]:
        if name in self.locals:
            # The local's own declared type.  A pointer parameter's walk
            # cursor deliberately has none: it is a plain int32 offset,
            # NOT the narrow pointee type the alias would resolve to.
            return self.ctypes.get(name)
        return self.ctypes.get(self.aliases.get(name, name))


def _const_int(node) -> Optional[int]:
    # pycparser types suffixed literals "unsigned int"/"long int"/etc.
    if isinstance(node, c_ast.Constant) and "int" in node.type:
        return int(node.value.rstrip("uUlL"), 0)
    if isinstance(node, c_ast.UnaryOp) and node.op in ("-", "+", "~"):
        v = _const_int(node.expr)
        if v is None:
            return None
        return {"-": -v, "+": v, "~": ~v}[node.op]
    if isinstance(node, c_ast.BinaryOp):
        # Constant folding for dimension/label expressions (blowfish's
        # `BF_ROUNDS + 2`); division is C truncation toward zero.
        a, b = _const_int(node.left), _const_int(node.right)
        if a is None or b is None:
            return None
        try:
            return {
                "+": lambda: a + b, "-": lambda: a - b,
                "*": lambda: a * b,
                "/": lambda: int(a / b) if b else None,
                "%": lambda: a - int(a / b) * b if b else None,
                "<<": lambda: a << b, ">>": lambda: a >> b,
                "&": lambda: a & b, "|": lambda: a | b,
                "^": lambda: a ^ b,
            }[node.op]()
        except KeyError:
            return None
    return None


class _Compiler:
    def __init__(self, tu, typedefs, funcs, name: str,
                 g_ctypes: Optional[Dict[str, _CType]] = None,
                 g_ptrs: Optional[set] = None):
        self.tu = tu
        self.typedefs = typedefs
        self.funcs = funcs
        self.name = name
        self.g_ctypes = dict(g_ctypes or {})
        # Global pointer variables: their int32 CURSOR lives in the
        # globals dict (runtime, injectable state); the aliased base
        # array is static, resolved at the first seating and required
        # to stay the same (motion's ld_Rdptr over ld_Rdbfr).
        self.g_ptrs: set = set(g_ptrs or ())
        self.g_ptr_base: Dict[str, str] = {}
        self._tmp = 0          # transient copy-in/out slot counter
        # id(node) -> reason, for synthesized guard Ifs whose printf
        # refusal should name the REAL construct (pycparser nodes have
        # __slots__, so no attribute can be set on them).
        self._synth_reason = {}
        # Desugar pre-pass state (switch / do-while / while(1)-unroll /
        # branch print slots), memoized per function definition.
        self._desugared: set = set()
        self._print_slots: Dict[int, List[Tuple[str, int]]] = {}
        self._sw_temps: Dict[int, List[str]] = {}
        self._assigned_globals_cache: Dict[int, List[str]] = {}
        self.print_strings: List[str] = []     # slot id -> format string

    # -- trace-time constant propagation -----------------------------------
    @staticmethod
    def _wrap32(v: int) -> int:
        """Canonical signed-32 representation of a mod-2^32 value."""
        v &= 0xFFFFFFFF
        return v - (1 << 32) if v >= 0x80000000 else v

    @staticmethod
    def _has_effects(node) -> bool:
        """Does evaluating ``node`` have side effects (writes/calls)?"""
        found: List[object] = []

        class V(c_ast.NodeVisitor):
            def visit_Assignment(v, n):
                found.append(n)

            def visit_FuncCall(v, n):
                found.append(n)

            def visit_UnaryOp(v, n):
                if n.op in ("++", "p++", "--", "p--"):
                    found.append(n)
                v.generic_visit(n)

        if node is not None:
            V().visit(node)
        return bool(found)

    def _const_eval(self, node, sc: _Scope) -> Optional[int]:
        """Compile-time value of a PURE expression, or None if unknown.

        Conservative by construction: every fold either matches the C
        (ILP32) result exactly or returns None -- ordered comparisons
        and ``>>`` bail out when a sign-domain ambiguity could flip the
        result.  Values are kept in canonical signed-32 form."""
        if isinstance(node, c_ast.Constant):
            if "char" in node.type and node.value.startswith("'"):
                body = node.value[1:-1].encode().decode("unicode_escape")
                return ord(body)
            if "int" in node.type:
                v = int(node.value.rstrip("uUlL"), 0)
                return self._wrap32(v) if v <= 0xFFFFFFFF else None
            return None
        if isinstance(node, c_ast.ID):
            return sc.consts.get(node.name)
        if isinstance(node, c_ast.Cast):
            if isinstance(node.to_type.type, c_ast.PtrDecl):
                return None
            v = self._const_eval(node.expr, sc)
            if v is None:
                return None
            ct = _ctype_of(node.to_type.type.type.names, self.typedefs)
            if isinstance(ct, _CType64):
                return None
            return self._norm_const(ct, v)
        if isinstance(node, c_ast.UnaryOp):
            if node.op not in ("-", "+", "~", "!"):
                return None
            v = self._const_eval(node.expr, sc)
            if v is None:
                return None
            if node.op == "!":
                return int(v == 0)
            return self._wrap32({"-": -v, "+": v, "~": ~v}[node.op])
        if isinstance(node, c_ast.TernaryOp):
            c = self._const_eval(node.cond, sc)
            if c is None:
                return None
            return self._const_eval(node.iftrue if c else node.iffalse, sc)
        if isinstance(node, c_ast.BinaryOp):
            a = self._const_eval(node.left, sc)
            if a is None:
                return None
            if node.op in ("&&", "||"):
                if node.op == "&&" and a == 0:
                    return 0
                if node.op == "||" and a != 0:
                    return 1
                b = self._const_eval(node.right, sc)
                return None if b is None else int(b != 0)
            b = self._const_eval(node.right, sc)
            if b is None:
                return None
            op = node.op
            if op in ("==", "!="):
                eq = (a & 0xFFFFFFFF) == (b & 0xFFFFFFFF)
                return int(eq if op == "==" else not eq)
            if op in ("<", ">", "<=", ">="):
                # int vs unsigned compare agree only when both
                # operands are non-negative in the signed view.
                if a < 0 or b < 0:
                    return None
                return int({"<": a < b, ">": a > b,
                            "<=": a <= b, ">=": a >= b}[op])
            if op == ">>":
                if a < 0:
                    return None          # arithmetic-vs-logical ambiguity
                return a >> (b & 31)
            if op == "<<":
                return self._wrap32(a << (b & 31))
            if op in ("+", "-", "*", "&", "|", "^"):
                return self._wrap32({"+": a + b, "-": a - b, "*": a * b,
                                     "&": a & b, "|": a | b,
                                     "^": a ^ b}[op])
            if op in ("/", "%"):
                # C truncates toward zero; Python floors -- fold only
                # the unambiguous non-negative case.
                if a < 0 or b <= 0:
                    return None
                return a // b if op == "/" else a % b
            return None
        return None

    @staticmethod
    def _norm_const(ct: _CType, v: int) -> int:
        """C conversion of a known value into the declared type."""
        mask = (1 << ct.bits) - 1
        v &= mask
        if not ct.unsigned and v >= (1 << (ct.bits - 1)):
            v -= 1 << ct.bits
        return v

    def _const_set(self, sc: _Scope, name: str, v: Optional[int],
                   ct: Optional[_CType] = None) -> None:
        if v is None:
            sc.consts.pop(name, None)
        else:
            if ct is not None and not isinstance(ct, _CType64):
                v = self._norm_const(ct, v)
            sc.consts[name] = v

    # -- expressions -------------------------------------------------------
    def eval(self, node, sc: _Scope):
        if isinstance(node, c_ast.Constant):
            if "char" in node.type and node.value.startswith("'"):
                # Character constant: type int in C.
                body = node.value[1:-1].encode().decode("unicode_escape")
                return jnp.int32(ord(body))
            if "int" in node.type:
                v = node.value.rstrip("uUlL")
                base = int(v, 0)
                # C type of the literal: explicit u suffix, or a hex/octal
                # literal too big for int (0xffffffff is unsigned int in
                # ILP32; decimal literals never become unsigned).
                uns = ("u" in node.value.lower()
                       or (base > 0x7FFFFFFF
                           and v.lower().startswith("0")))
                if base > 0xFFFFFFFF:
                    # Literal outside 32 bits: a long long constant.
                    return _C64(base & 0xFFFFFFFF,
                                (base >> 32) & 0xFFFFFFFF, uns)
                return (jnp.uint32(base & 0xFFFFFFFF) if uns
                        else jnp.int32(np.int32(base & 0xFFFFFFFF)))
            raise CLiftError(f"unsupported constant type {node.type!r}")
        if isinstance(node, c_ast.ExprList):
            # C comma expression: evaluate left to right, value is last.
            v = jnp.int32(0)
            for e in node.exprs:
                v = self.eval(e, sc)
            return v
        if isinstance(node, c_ast.ID):
            v = sc.read(node.name)
            ct = sc.ctype(node.name)
            # Narrow SCALAR reads re-normalize: an injected bit above the
            # declared width does not exist in real byte/short memory, so
            # the promoted value masks it (docs/lifter.md, layout
            # envelope).  Arrays pass through untouched -- an ID naming an
            # array is C pointer decay, not a value read.
            if ct is not None and ct.bits < 32 and jnp.ndim(v) == 0:
                return ct.store(v)
            return v
        if isinstance(node, c_ast.ArrayRef):
            arr, idx, base = self._array_path(node, sc)
            ct = (sc.ctypes.get(base[0]) if isinstance(base, tuple)
                  else sc.ctype(base))
            if isinstance(ct, _CType64):
                row = arr[idx]                  # (..., 2) limb pair
                return _C64(row[..., 0], row[..., 1], ct.unsigned)
            v = arr[idx]
            return (ct.store(v) if ct is not None and ct.bits < 32
                    else v)
        if isinstance(node, c_ast.BinaryOp):
            return self._binop(node, sc)
        if isinstance(node, c_ast.UnaryOp):
            return self._unop(node, sc)
        if isinstance(node, c_ast.TernaryOp):
            c = self.eval(node.cond, sc)
            a = self.eval(node.iftrue, sc)
            b = self.eval(node.iffalse, sc)
            if isinstance(a, _C64) or isinstance(b, _C64):
                a64, b64 = _to64(a), _to64(b)
                t_ = self._truth(c)
                return _C64(jnp.where(t_, a64.lo, b64.lo),
                            jnp.where(t_, a64.hi, b64.hi),
                            a64.unsigned or b64.unsigned)
            a, b = self._usual_conv(a, b)
            return jnp.where(jnp.not_equal(c, 0), a, b)
        if isinstance(node, c_ast.FuncCall):
            return self._call(node, sc)
        if isinstance(node, c_ast.Cast):
            if isinstance(node.to_type.type, c_ast.PtrDecl):
                raise CLiftError(
                    f"pointer cast in value position at {node.coord}; "
                    "pointer casts are modeled only where a pointer "
                    "flows (seatings, call arguments, derefs)")
            ct = _ctype_of(node.to_type.type.type.names, self.typedefs)
            # C cast semantics: value converted to the target type --
            # truncate + re-sign for narrow targets, plain dtype change
            # for 32-bit ones.
            return ct.store(self.eval(node.expr, sc))
        if isinstance(node, c_ast.Assignment):
            # expression-position assignment (e.g. in for-next)
            return self._assign(node, sc)
        raise CLiftError(
            f"unsupported expression {type(node).__name__} at {node.coord}")

    def _usual_conv(self, a, b):
        """C usual arithmetic conversions, ILP32 32-bit lane: if either
        side is unsigned, both are."""
        a = jnp.asarray(a)
        b = jnp.asarray(b)
        if a.dtype == jnp.uint32 or b.dtype == jnp.uint32:
            return a.astype(jnp.uint32), b.astype(jnp.uint32)
        return a.astype(jnp.int32), b.astype(jnp.int32)

    @staticmethod
    def _truth(v):
        """C truth value of a scalar or limb-pair value."""
        if isinstance(v, _C64):
            return jnp.not_equal(v.lo | v.hi, 0)
        return jnp.not_equal(jnp.asarray(v), 0)

    def _ptrish(self, node, sc) -> bool:
        """Is this expression a pointer value (decayed array, walked or
        global pointer, &-expr, pointer +/- offset)?"""
        if isinstance(node, c_ast.ID):
            if node.name in sc.aliases:
                return True
            if (node.name in self.g_ptrs
                    and node.name not in sc.locals):
                return True
            tgt = node.name
            return tgt in sc.g and jnp.ndim(sc.g[tgt]) >= 1
        if isinstance(node, c_ast.Cast):
            return (isinstance(node.to_type.type, c_ast.PtrDecl)
                    and self._ptrish(node.expr, sc))
        if isinstance(node, c_ast.UnaryOp) and node.op == "&":
            return True
        if isinstance(node, c_ast.BinaryOp) and node.op in ("+", "-"):
            return (self._ptrish(node.left, sc)
                    or self._ptrish(node.right, sc))
        return False

    def _binop(self, node, sc):
        if (node.op in ("==", "!=", "<", ">", "<=", ">=", "-")
                and (self._ptrish(node.left, sc)
                     or self._ptrish(node.right, sc))):
            # Pointer comparison / difference: both sides resolve to
            # (base, offset); same base -> compare/subtract offsets
            # (element-indexed cursors, matching C's element units).
            ba, oa = self._ptr_parts(node.left, sc)
            bb, ob = self._ptr_parts(node.right, sc)
            if ba != bb:
                raise CLiftError(
                    f"pointer {node.op} across different arrays "
                    f"({ba!r} vs {bb!r}) at {node.coord}")
            return self._apply_binop(node.op, jnp.asarray(oa, jnp.int32),
                                     jnp.asarray(ob, jnp.int32), node)
        a = self.eval(node.left, sc)
        b = self.eval(node.right, sc)
        return self._apply_binop(node.op, a, b, node)

    def _apply_binop(self, op, a, b, node):
        if op in ("&&", "||"):
            az = self._truth(a)
            bz = self._truth(b)
            r = jnp.logical_and(az, bz) if op == "&&" else jnp.logical_or(az, bz)
            return r.astype(jnp.int32)
        if isinstance(a, _C64) or isinstance(b, _C64):
            return self._binop64(op, a, b, node)
        a, b = self._usual_conv(a, b)
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return jax.lax.div(a, b) if a.dtype == jnp.int32 else a // b
        if op == "%":
            return jax.lax.rem(a, b) if a.dtype == jnp.int32 else a % b
        if op == "^":
            return a ^ b
        if op == "&":
            return a & b
        if op == "|":
            return a | b
        if op == "<<":
            return a << b
        if op == ">>":
            return a >> b
        cmp = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
               ">": jnp.greater, "<=": jnp.less_equal,
               ">=": jnp.greater_equal}.get(op)
        if cmp is not None:
            return cmp(a, b).astype(jnp.int32)
        raise CLiftError(f"unsupported binary op {op!r} at {node.coord}")

    def _binop64(self, op, a, b, node):
        """Binary ops with a 64-bit (limb-pair) operand."""
        if op in ("<<", ">>"):
            # The SHIFT COUNT is not subject to the usual conversions:
            # a << amount keeps a's type; the amount reduces to int.
            a64 = _to64(a)
            s = b.lo if isinstance(b, _C64) else jnp.asarray(b, jnp.uint32)
            return _c64_shl(a64, s) if op == "<<" else _c64_shr(a64, s)
        a64, b64 = _to64(a), _to64(b)
        unsigned = a64.unsigned or b64.unsigned
        if op == "+":
            return _c64_add(a64, b64, unsigned)
        if op == "-":
            return _c64_add(a64, _c64_neg(b64), unsigned)
        if op == "*":
            return _c64_mul(a64, b64, unsigned)
        if op in ("/", "%"):
            if not unsigned:
                raise CLiftError(
                    f"signed 64-bit {op} at {node.coord} is outside the "
                    "modeled envelope (softfloat divides unsigned)")
            q, r = _c64_divmod(a64, b64)
            return q if op == "/" else r
        if op == "&":
            return _C64(a64.lo & b64.lo, a64.hi & b64.hi, unsigned)
        if op == "|":
            return _C64(a64.lo | b64.lo, a64.hi | b64.hi, unsigned)
        if op == "^":
            return _C64(a64.lo ^ b64.lo, a64.hi ^ b64.hi, unsigned)
        if op == "==":
            return jnp.logical_and(jnp.equal(a64.lo, b64.lo),
                                   jnp.equal(a64.hi, b64.hi)
                                   ).astype(jnp.int32)
        if op == "!=":
            return jnp.logical_or(jnp.not_equal(a64.lo, b64.lo),
                                  jnp.not_equal(a64.hi, b64.hi)
                                  ).astype(jnp.int32)
        if op == "<":
            return _c64_lt(a64, b64, unsigned).astype(jnp.int32)
        if op == ">":
            return _c64_lt(b64, a64, unsigned).astype(jnp.int32)
        if op == "<=":
            return jnp.logical_not(_c64_lt(b64, a64, unsigned)
                                   ).astype(jnp.int32)
        if op == ">=":
            return jnp.logical_not(_c64_lt(a64, b64, unsigned)
                                   ).astype(jnp.int32)
        raise CLiftError(
            f"unsupported 64-bit binary op {op!r} at {node.coord} "
            "(long long supports + - * & | ^ << >> and comparisons)")

    def _unop(self, node, sc):
        op = node.op
        if op in ("++", "p++", "--", "p--"):
            name = node.expr
            old = self.eval(name, sc)
            if isinstance(old, _C64):
                one = _C64(1, 0, old.unsigned)
                new = (_c64_add(old, one, old.unsigned) if "++" in op
                       else _c64_add(old, _c64_neg(one), old.unsigned))
            else:
                delta = jnp.asarray(1, old.dtype)
                new = old + delta if "++" in op else old - delta
            self._store(name, new, sc)
            if isinstance(name, c_ast.ID):
                prev = sc.consts.get(name.name)
                self._const_set(
                    sc, name.name,
                    None if prev is None else
                    self._wrap32(prev + (1 if "++" in op else -1)),
                    sc.ctype(name.name))
            return old if op.startswith("p") else new
        if op == "*":
            base, off = self._ptr_parts(node.expr, sc)
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                v = self._union_read(sc, base)[off]
                return (ct.store(v) if ct is not None and ct.bits < 32
                        else v)
            arr = sc.g[base]
            ct = sc.ctypes.get(base)
            if isinstance(ct, _CType64):
                row = arr.reshape(-1, 2)[off]   # limb-pair element
                return _C64(row[0], row[1], ct.unsigned)
            if jnp.ndim(arr) > 1:
                arr = arr.reshape(-1)       # cursors walk row-major memory
            v = arr[off]
            return (ct.store(v) if ct is not None and ct.bits < 32
                    else v)
        if op == "sizeof":
            return jnp.int32(self._sizeof(node.expr, sc))
        v = self.eval(node.expr, sc)
        if isinstance(v, _C64):
            if op == "-":
                return _c64_neg(v)
            if op == "+":
                return v
            if op == "~":
                return _C64(~v.lo, ~v.hi, v.unsigned)
            if op == "!":
                return jnp.equal(v.lo | v.hi, 0).astype(jnp.int32)
            raise CLiftError(
                f"unsupported unary op {op!r} on long long at {node.coord}")
        if op == "-":
            return -v
        if op == "+":
            return v
        if op == "~":
            return ~v
        if op == "!":
            return jnp.equal(v, 0).astype(jnp.int32)
        raise CLiftError(f"unsupported unary op {op!r} at {node.coord}")

    def _sizeof(self, expr, sc) -> int:
        """C sizeof in the REAL C layout (not the lane layout): element
        count times the declared element width in bytes.  The benchmarks
        use it for byte-array lengths (aes.c's sizeof(input))."""
        if isinstance(expr, c_ast.Typename):
            ct = _ctype_of(getattr(expr.type.type, "names", ["int"]),
                           self.typedefs)
            return ct.bits // 8
        if isinstance(expr, c_ast.ID):
            name = expr.name
            if name in sc.aliases:
                # Array/pointer PARAMETERS and local pointer variables
                # decay: C's sizeof is the pointer size (ILP32: 4), the
                # classic sizeof-of-parameter trap included.
                return 4
            arr = sc.read(name)
            ct = sc.ctype(name)
            width = (ct.bits // 8) if ct is not None else 4
            n = int(np.prod(arr.shape)) if jnp.ndim(arr) else 1
            return n * width
        raise CLiftError(
            f"unsupported sizeof operand at {getattr(expr, 'coord', '?')}")

    def _ptr_parts(self, expr, sc) -> Tuple[str, jax.Array]:
        """Resolve a pointer-valued expression to (global name, offset).

        The subset's pointers are walked array parameters: ``p`` (cursor
        or start), ``p++``/``++p``/``p--``/``--p`` (cursor effect applies,
        value is the C-correct old/new pointer), and ``p + e``.  This is
        the shape the reference's byte-stream benchmarks use
        (crc16.c:26 ``*data_p++``)."""
        if isinstance(expr, c_ast.ID) and expr.name in sc.aliases:
            return (sc.aliases[expr.name],
                    jnp.asarray(sc.locals.get(expr.name, 0), jnp.int32))
        if (isinstance(expr, c_ast.ID) and expr.name in self.g_ptrs
                and expr.name not in sc.locals):
            base = self.g_ptr_base.get(expr.name)
            if base is None:
                raise CLiftError(
                    f"global pointer {expr.name!r} used before any "
                    "seating; seat it (p = arr) first")
            return base, jnp.asarray(sc.read(expr.name), jnp.int32)
        if isinstance(expr, c_ast.ID) and expr.name in sc.locals:
            # A LOCAL array (possibly shadowing a same-name global)
            # cannot be a pointer target -- aliases only bind into the
            # globals dict.  Refuse loudly instead of silently binding
            # the shadowed global.
            raise CLiftError(
                f"pointer to local array {expr.name!r} at "
                f"{getattr(expr, 'coord', '?')} is not supported; make "
                "the array a global or pass it as a call argument")
        if (isinstance(expr, c_ast.ID) and expr.name in sc.g
                and jnp.ndim(sc.g[expr.name]) >= 1):
            # A global array name decays to a pointer to its start.
            return expr.name, jnp.int32(0)
        if (isinstance(expr, c_ast.UnaryOp)
                and expr.op in ("++", "p++", "--", "p--")
                and isinstance(expr.expr, c_ast.ID)):
            nm = expr.expr.name
            if nm in sc.aliases:
                if nm not in sc.locals:
                    raise CLiftError(
                        f"pointer arithmetic on unwalked parameter "
                        f"{nm!r} at {expr.coord}")
                off = self._unop(expr, sc)      # applies the cursor effect
                return sc.aliases[nm], jnp.asarray(off, jnp.int32)
            if nm in self.g_ptrs and nm not in sc.locals:
                base = self.g_ptr_base.get(nm)
                if base is None:
                    raise CLiftError(
                        f"global pointer {nm!r} walked before any "
                        f"seating at {expr.coord}")
                off = self._unop(expr, sc)      # global cursor effect
                return base, jnp.asarray(off, jnp.int32)
        if isinstance(expr, c_ast.Cast):
            # Pointer casts ((void*)buf, (char*)p) change the static type,
            # not the address: pass through.  The pointee's ctype stays
            # the ALIASED array's -- reinterpreting an int array as bytes
            # would need sub-word addressing, outside the lane model.
            return self._ptr_parts(expr.expr, sc)
        if isinstance(expr, c_ast.UnaryOp) and expr.op == "&":
            # Address-of: &arr -> (arr, 0); &arr[k] -> (arr, k); multi-dim
            # &arr[j][k] -> (arr, j*cols + k) -- the cursor indexes the
            # row-major FLATTENED array (sha_stream's &indata[j][0]).
            inner = expr.expr
            if isinstance(inner, c_ast.ArrayRef):
                idxs, node2 = [], inner
                while isinstance(node2, c_ast.ArrayRef):
                    idxs.append(node2.subscript)
                    node2 = node2.name
                if isinstance(node2, c_ast.ID):
                    base, off = self._ptr_parts(node2, sc)
                    shape = jnp.shape(sc.g[base])
                    idxs = list(reversed(idxs))
                    if len(idxs) > len(shape):
                        raise CLiftError(
                            f"too many subscripts under & at {expr.coord}")
                    flat = jnp.int32(0)
                    for d, ix in enumerate(idxs):
                        stride = int(np.prod(shape[d + 1:], dtype=np.int64))
                        flat = flat + jnp.asarray(
                            self.eval(ix, sc), jnp.int32) * stride
                    return base, off + flat
            if (isinstance(inner, c_ast.ID) and inner.name in sc.locals
                    and inner.name not in sc.aliases
                    and jnp.ndim(sc.locals[inner.name]) == 0):
                raise CLiftError(
                    f"address-of scalar {inner.name!r} at "
                    f"{getattr(expr, 'coord', '?')} is not supported "
                    "(no out-parameter model; return the value instead)")
            return self._ptr_parts(inner, sc)
        if isinstance(expr, c_ast.BinaryOp) and expr.op in ("+", "-"):
            base, off = self._ptr_parts(expr.left, sc)
            d = jnp.asarray(self.eval(expr.right, sc), jnp.int32)
            return base, (off + d if expr.op == "+" else off - d)
        if isinstance(expr, c_ast.ArrayRef):
            # PARTIAL indexing decays a sub-array to a pointer
            # (`p = ta[i]` over int ta[2][4] -> base ta, offset i*4).
            idxs, node2 = [], expr
            while isinstance(node2, c_ast.ArrayRef):
                idxs.append(node2.subscript)
                node2 = node2.name
            if isinstance(node2, c_ast.ID):
                base, off0 = self._ptr_parts(node2, sc)
                if not isinstance(base, tuple):
                    arrv = sc.g[base]
                    eff_nd = jnp.ndim(arrv)
                    if isinstance(sc.ctypes.get(base), _CType64):
                        eff_nd -= 1
                    if len(idxs) < eff_nd:
                        shape = jnp.shape(arrv)
                        flat = jnp.int32(0)
                        for d2, ix in enumerate(reversed(idxs)):
                            stride = int(np.prod(shape[d2 + 1:eff_nd],
                                                 dtype=np.int64))
                            flat = flat + jnp.asarray(
                                self.eval(ix, sc), jnp.int32) * stride
                        return base, off0 + flat
        raise CLiftError(
            f"unsupported pointer expression at {getattr(expr, 'coord', '?')}")

    def _array_path(self, node, sc):
        """Flatten a[i][j]... into (array value, index tuple).  A pointer
        parameter that has been walked (``p++``) indexes relative to its
        cursor: ``p[i]`` reads the aliased global at cursor+i."""
        idxs = []
        while isinstance(node, c_ast.ArrayRef):
            idxs.append(node.subscript)
            node = node.name
        if not isinstance(node, c_ast.ID):
            raise CLiftError(f"unsupported array base at {node.coord}")
        name = node.name
        cursor = (sc.locals.get(name) if name in sc.aliases else None)
        base = sc.aliases.get(name, name)
        if name in sc.aliases and isinstance(sc.aliases[name], tuple):
            arr = self._union_read(sc, sc.aliases[name])
        elif name in sc.aliases:
            arr = sc.g[sc.aliases[name]]
        elif (name in self.g_ptrs and name not in sc.locals):
            # Subscripting a GLOBAL pointer (gp[i]) routes through its
            # seated base + cursor, same as _ptr_parts' deref path --
            # sc.read(name) would hand back the int32 cursor scalar.
            seated = self.g_ptr_base.get(name)
            if seated is None:
                raise CLiftError(
                    f"global pointer {name!r} subscripted before any "
                    f"seating at {node.coord}; seat it (p = arr) first")
            arr = sc.g[seated]
            cursor = jnp.asarray(sc.read(name), jnp.int32)
            base = seated
        else:
            arr = sc.read(name)
        idx = tuple(self.eval(i, sc).astype(jnp.int32)
                    for i in reversed(idxs))
        if cursor is not None:
            if len(idx) != 1:
                raise CLiftError(
                    f"walked pointer {name!r} must be 1-D at {node.coord}")
            # Cursor over row-major memory: flatten to element rows.  A
            # 64-bit base keeps its trailing limb-pair axis -- the cursor
            # counts ELEMENTS, and the _CType64 load/store consume (n, 2)
            # rows; a full flatten would index half-pairs.
            ct_c = (sc.ctypes.get(base[0]) if isinstance(base, tuple)
                    else sc.ctype(base))
            if isinstance(ct_c, _CType64):
                if jnp.ndim(arr) > 2:
                    arr = arr.reshape(-1, 2)
            elif jnp.ndim(arr) > 1:
                arr = arr.reshape(-1)
            idx = (idx[0] + cursor,)
        return arr, (idx if len(idx) > 1 else idx[0]), base

    def _store(self, lhs, val, sc):
        if isinstance(lhs, c_ast.ID):
            ct = sc.ctype(lhs.name)
            if ct is not None:
                sc.write(lhs.name, ct.store(val))
                return
            if isinstance(val, _C64):
                # Untyped slot receiving a 64-bit value (early-return
                # carries of 64-bit functions): store the pair as-is.
                sc.write(lhs.name, val)
                return
            old = sc.read(lhs.name)
            sc.write(lhs.name, jnp.asarray(val).astype(old.dtype)
                     if hasattr(old, "dtype") else val)
            return
        if isinstance(lhs, c_ast.ArrayRef):
            arr, idx, base = self._array_path(lhs, sc)
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                stored = (ct.store(val) if ct is not None
                          else jnp.asarray(val).astype(arr.dtype))
                self._union_write(
                    sc, base, arr.at[idx].set(stored.astype(arr.dtype)))
                return
            ct = sc.ctype(base)
            if isinstance(ct, _CType64):
                v64 = _to64(val)
                new = arr.at[idx].set(jnp.stack([v64.lo, v64.hi]))
                orig = sc.read_binding(base)
                if jnp.shape(new) != jnp.shape(orig):
                    # _array_path flattened a cursor view over a
                    # multi-dim 64-bit array to (-1, 2) limb rows;
                    # restore the canonical shape.
                    new = new.reshape(jnp.shape(orig))
                sc.write_binding(base, new)
                return
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            new = arr.at[idx].set(stored.astype(arr.dtype))
            orig = sc.read_binding(base)
            if jnp.shape(new) != jnp.shape(orig):
                # _array_path flattened a cursor view over a multi-dim
                # array; restore the canonical shape.
                new = new.reshape(jnp.shape(orig))
            # base is already alias-RESOLVED: write the binding
            # directly (re-resolving would mis-route when a parameter
            # shadows a global of the same name).
            sc.write_binding(base, new)
            return
        if isinstance(lhs, c_ast.UnaryOp) and lhs.op == "*":
            # Deref store (*p++ = c): C order -- the store targets the
            # pointer value BEFORE any ++/-- side effect, which
            # _ptr_parts implements (p++ yields the old offset).
            base, off = self._ptr_parts(lhs.expr, sc)
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                flat = self._union_read(sc, base)
                stored = (ct.store(val) if ct is not None
                          else jnp.asarray(val).astype(flat.dtype))
                self._union_write(
                    sc, base, flat.at[off].set(stored.astype(flat.dtype)))
                return
            arr = sc.g[base]
            ct = sc.ctypes.get(base)
            if isinstance(ct, _CType64):
                v64 = _to64(val)
                flat = arr.reshape(-1, 2).at[off].set(
                    jnp.stack([v64.lo, v64.hi]))
                sc.write_binding(base, flat.reshape(jnp.shape(arr)))
                return
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            if jnp.ndim(arr) > 1:           # cursors walk row-major memory
                flat = arr.reshape(-1).at[off].set(stored.astype(arr.dtype))
                sc.write_binding(base, flat.reshape(jnp.shape(arr)))
            else:
                sc.write_binding(base,
                                 arr.at[off].set(stored.astype(arr.dtype)))
            return
        raise CLiftError(
            f"unsupported assignment target {type(lhs).__name__}")

    def _assign(self, node, sc):
        op = node.op
        if (op == "=" and isinstance(node.lvalue, c_ast.ID)
                and node.lvalue.name in self.g_ptrs
                and node.lvalue.name not in sc.locals
                and node.lvalue.name not in sc.aliases):
            # GLOBAL pointer (re-)seating: static single base, runtime
            # cursor stored in the int32 cursor global.
            name = node.lvalue.name
            base, off = self._ptr_parts(node.rvalue, sc)
            prev = self.g_ptr_base.get(name)
            if prev is not None and prev != base:
                raise CLiftError(
                    f"global pointer {name!r} re-seated from {prev!r} "
                    f"to {base!r} at {node.coord}: a single static base "
                    "per global pointer is the modeled envelope")
            self.g_ptr_base[name] = base
            sc.write(name, jnp.asarray(off, jnp.int32))
            sc.consts.pop(name, None)
            return off
        if (op == "=" and isinstance(node.lvalue, c_ast.ID)
                and (node.lvalue.name in sc.ptrs
                     or node.lvalue.name in sc.aliases)):
            # Pointer (re-)seating: `p = arr`, `p = q`, `p = p + k`,
            # `p = (T*)s`, `p = &a[k]` -- resolve the RHS to
            # (array, offset) and re-bind the cursor.  An unresolvable
            # RHS refuses loudly in _ptr_parts (the round-3 advisor
            # found the old scalar path silently storing a whole array
            # into the cursor local).
            name = node.lvalue.name
            base, off = self._ptr_parts(node.rvalue, sc)
            union = self._union_bases(sc.aliases.get(name))
            if union is not None and not isinstance(base, tuple):
                # Union pointer: a seat on a member re-bases the cursor
                # into that member's segment of the concatenation.
                off = self._union_offset(sc, union, base) + jnp.asarray(
                    off, jnp.int32)
            else:
                sc.aliases[name] = base
            sc.locals[name] = jnp.asarray(off, jnp.int32)
            sc.consts.pop(name, None)
            return off
        if op == "=":
            const = (self._const_eval(node.rvalue, sc)
                     if isinstance(node.lvalue, c_ast.ID) else None)
            val = self.eval(node.rvalue, sc)
            self._store(node.lvalue, val, sc)
            if isinstance(node.lvalue, c_ast.ID):
                self._const_set(sc, node.lvalue.name, const,
                                sc.ctype(node.lvalue.name))
            return val
        # Compound assignment (+= <<= ...): the lvalue designates ONE
        # location, evaluated ONCE (C11 6.5.16.2) -- a side-effecting
        # lvalue like GSM's rescale `*s++ <<= scalauto` must advance the
        # cursor exactly once, with read and store hitting the SAME
        # element (the old fake-binop path re-evaluated it for the
        # store, double-stepping the cursor).
        bin_op = op[:-1]
        lhs = node.lvalue
        if isinstance(lhs, c_ast.UnaryOp) and lhs.op == "*":
            base, off = self._ptr_parts(lhs.expr, sc)   # effects, once
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                flat0 = self._union_read(sc, base)
                old = flat0[off]
                if ct is not None and ct.bits < 32:
                    old = ct.store(old)
                val = self._apply_binop(bin_op, old,
                                        self.eval(node.rvalue, sc), node)
                stored = (ct.store(val) if ct is not None
                          else jnp.asarray(val).astype(flat0.dtype))
                self._union_write(
                    sc, base,
                    flat0.at[off].set(stored.astype(flat0.dtype)))
                return val
            arr = sc.g[base]
            flat = arr.reshape(-1) if jnp.ndim(arr) > 1 else arr
            ct = sc.ctypes.get(base)
            old = flat[off]
            if ct is not None and ct.bits < 32:
                old = ct.store(old)
            val = self._apply_binop(bin_op, old,
                                    self.eval(node.rvalue, sc), node)
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            new = flat.at[off].set(stored.astype(arr.dtype))
            if jnp.ndim(arr) > 1:
                new = new.reshape(jnp.shape(arr))
            sc.write_binding(base, new)
            return val
        if isinstance(lhs, c_ast.ArrayRef):
            arr, idx, base = self._array_path(lhs, sc)  # subscripts, once
            ct = (sc.ctypes.get(base[0]) if isinstance(base, tuple)
                  else sc.ctype(base))
            old = arr[idx]
            if ct is not None and ct.bits < 32:
                old = ct.store(old)
            val = self._apply_binop(bin_op, old,
                                    self.eval(node.rvalue, sc), node)
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            new = arr.at[idx].set(stored.astype(arr.dtype))
            if isinstance(base, tuple):              # union pointer
                self._union_write(sc, base, new)
                return val
            orig = sc.read_binding(base)
            if jnp.shape(new) != jnp.shape(orig):
                new = new.reshape(jnp.shape(orig))
            sc.write_binding(base, new)
            return val
        # Plain identifier lvalue: no side effects to duplicate.
        fake = c_ast.BinaryOp(bin_op, node.lvalue, node.rvalue, node.coord)
        const = (self._const_eval(fake, sc)
                 if isinstance(node.lvalue, c_ast.ID) else None)
        val = self._binop(fake, sc)
        self._store(node.lvalue, val, sc)
        if isinstance(node.lvalue, c_ast.ID):
            self._const_set(sc, node.lvalue.name, const,
                            sc.ctype(node.lvalue.name))
        return val

    def _call(self, node, sc):
        if not isinstance(node.name, c_ast.ID):
            raise CLiftError(f"unsupported indirect call at {node.coord}")
        fname = node.name.name
        arg_nodes = node.args.exprs if node.args else []
        if fname == "printf":
            # The QEMU loop's observable: everything printed is output.
            # The format string itself is not evaluated (no string
            # model); a 64-bit value prints as its two limbs.
            vals = []
            for a in arg_nodes[1:]:
                v = self.eval(a, sc)
                if isinstance(v, _C64):
                    vals.extend([v.lo, v.hi])
                else:
                    vals.append(jnp.asarray(v))
            if (not vals and isinstance(sc.printed, _NoPrintList)
                    and "__print_buf" in sc.g and arg_nodes
                    and isinstance(arg_nodes[0], c_ast.Constant)
                    and arg_nodes[0].type == "string"):
                # String-only print at a dynamically-reached site: its
                # string-table id is the buffered word.
                text = (arg_nodes[0].value[1:-1]
                        .encode("utf-8").decode("unicode_escape"))
                if text in self.print_strings:
                    sid = self.print_strings.index(text)
                else:
                    self.print_strings.append(text)
                    sid = len(self.print_strings) - 1
                vals = [jnp.uint32(sid)]
            if (vals and isinstance(sc.printed, _NoPrintList)
                    and "__print_buf" in sc.g):
                # UART-buffer model: dynamically-reached prints append
                # into the bounded __print_buf observable (overflowing
                # words drop; __print_cnt keeps the true total).
                buf = sc.g["__print_buf"]
                cnt = sc.g["__print_cnt"]
                for v in vals:
                    idx = jnp.clip(cnt, 0, _PRINT_BUF_WORDS - 1)
                    keep = cnt < _PRINT_BUF_WORDS
                    buf = buf.at[idx].set(
                        jnp.where(keep, jnp.asarray(v).astype(jnp.uint32),
                                  buf[idx]))
                    cnt = cnt + 1
                sc.g["__print_buf"] = buf
                sc.g["__print_cnt"] = cnt
                return jnp.int32(0)
            sc.printed.extend(vals)
            return jnp.int32(0)
        # C array arguments are pointers: a bare ID naming a (possibly
        # already-aliased) global array binds the parameter to that global.
        args = []
        for a in arg_nodes:
            # A pointer CAST on an argument changes the static type only
            # ((unsigned char *)ivec): unwrap it and bind the underlying
            # array/pointer as usual.
            while (isinstance(a, c_ast.Cast)
                   and isinstance(a.to_type.type, c_ast.PtrDecl)):
                a = a.expr
            if isinstance(a, c_ast.UnaryOp) and a.op == "&":
                inner = a.expr
                if (isinstance(inner, c_ast.ID) and inner.name in sc.locals
                        and inner.name not in sc.aliases
                        and jnp.ndim(sc.locals[inner.name]) == 0):
                    # Scalar out-parameter (&num, blowfish's cfb64 state):
                    # copy-in/copy-out through a 1-word transient slot,
                    # like caller-local arrays.
                    args.append(("__alias_scalar_local__", inner.name))
                    continue
                if (isinstance(inner, c_ast.ID) and inner.name in sc.g
                        and jnp.ndim(sc.g[inner.name]) == 0):
                    # Address of a GLOBAL scalar (jpeg's
                    # &OutData_image_width): same slot model, copied
                    # back into the global when the callee returns
                    # (in-call aliasing with direct reads of the same
                    # global is outside the envelope).
                    args.append(("__alias_scalar_global__", inner.name))
                    continue
                # &localarr[k]: caller-LOCAL array element address
                # (motion's &PMV[0]) -- transient slot + cursor k.
                idxs, node2 = [], inner
                while isinstance(node2, c_ast.ArrayRef):
                    idxs.append(node2.subscript)
                    node2 = node2.name
                if (isinstance(node2, c_ast.ID) and node2.name in sc.locals
                        and node2.name not in sc.aliases
                        and jnp.ndim(sc.locals[node2.name]) >= 1):
                    shape = jnp.shape(sc.locals[node2.name])
                    flat = jnp.int32(0)
                    for d, ix in enumerate(reversed(idxs)):
                        stride = int(np.prod(shape[d + 1:],
                                             dtype=np.int64))
                        flat = flat + jnp.asarray(
                            self.eval(ix, sc), jnp.int32) * stride
                    args.append(("__alias_local_off__", node2.name, flat))
                    continue
                # &arr[k] / &glob: a pointer value -- forward base+offset.
                base, off = self._ptr_parts(a, sc)
                args.append(("__alias_off__", base,
                             jnp.asarray(off, jnp.int32)))
                continue
            if isinstance(a, c_ast.ID):
                if (a.name in sc.locals and a.name not in sc.aliases
                        and jnp.ndim(sc.locals[a.name]) >= 1):
                    # A caller-LOCAL array argument: C passes a pointer to
                    # it.  Modeled as copy-in/copy-out through a transient
                    # slot (run_function), sound because the subset has no
                    # overlapping aliases.
                    args.append(("__alias_local__", a.name))
                    continue
                tgt = sc.aliases.get(a.name, a.name)
                if isinstance(tgt, tuple):       # union pointer forwards
                    args.append(("__alias_off__", tgt,
                                 jnp.asarray(sc.locals.get(a.name, 0),
                                             jnp.int32)))
                    continue
                if tgt in sc.g and jnp.ndim(sc.g[tgt]) >= 1:
                    if a.name in sc.aliases and a.name in sc.locals:
                        # A WALKED/SEATED pointer forwards base AND
                        # cursor, so the callee continues from the
                        # caller's position (sha_stream passing
                        # &indata[j][0] onward to sha_update).
                        args.append(("__alias_off__", tgt,
                                     jnp.asarray(sc.locals[a.name],
                                                 jnp.int32)))
                        continue
                    args.append(("__alias__", tgt))
                    continue
            if isinstance(a, c_ast.ArrayRef):
                # PARTIAL indexing of a multi-dim array (motion.c's
                # motion_vector(PMV[0][s], ...)): C decays the sub-array
                # to a pointer -- forward base + flattened row offset so
                # callee writes land in the caller's array.  FULL
                # indexing stays a by-value element.
                idxs, node2 = [], a
                while isinstance(node2, c_ast.ArrayRef):
                    idxs.append(node2.subscript)
                    node2 = node2.name
                if isinstance(node2, c_ast.ID):
                    nm2 = node2.name
                    arrv = cur = None
                    basen, is_local = nm2, False
                    if nm2 in sc.aliases:
                        basen = sc.aliases[nm2]
                        arrv = sc.g.get(basen)
                        cur = sc.locals.get(nm2)
                    elif (nm2 in sc.locals
                            and jnp.ndim(sc.locals[nm2]) >= 1):
                        arrv, is_local = sc.locals[nm2], True
                    elif nm2 in sc.g and jnp.ndim(sc.g[nm2]) >= 1:
                        arrv = sc.g[nm2]
                    eff_nd = None
                    if arrv is not None:
                        eff_nd = jnp.ndim(arrv)
                        # The BASE array's element type decides the
                        # logical arity (a walked cursor's own ctype is
                        # deliberately None, so resolve the base).
                        ctn = (sc.ctype(nm2) if is_local
                               else sc.ctypes.get(basen))
                        if isinstance(ctn, _CType64):
                            eff_nd -= 1     # trailing dim is the limb pair
                    if arrv is not None and len(idxs) < eff_nd:
                        shape = jnp.shape(arrv)
                        flat = jnp.int32(0)
                        for d, ix in enumerate(reversed(idxs)):
                            stride = int(np.prod(shape[d + 1:],
                                                 dtype=np.int64))
                            flat = flat + jnp.asarray(
                                self.eval(ix, sc), jnp.int32) * stride
                        if cur is not None:
                            flat = flat + jnp.asarray(cur, jnp.int32)
                        if is_local:
                            args.append(("__alias_local_off__", nm2,
                                         flat))
                        else:
                            args.append(("__alias_off__", basen, flat))
                        continue
            args.append(self.eval(a, sc))
        if fname == "exit":
            # exit(n) on an error path (jpeg's "Not Jpeg File!"/huffman
            # read error): modeled as an OBSERVABLE poison -- the
            # synthetic global __exit_state records 1+n and joins the
            # output surface.  Fault-free runs never take these paths,
            # so the oracle is exact; under injection the poisoned flag
            # plus divergent outputs classify the run, though in-model
            # execution continues past the exit (documented fidelity
            # envelope -- the QEMU guest would stop).
            code = (args[0] if args else jnp.int32(0))
            # POSIX truncates the exit status to 8 bits; 1+(n & 0xFF)
            # is in [1, 256], never colliding with 0 = ran to end.
            sc.g["__exit_state"] = (
                (jnp.asarray(code, jnp.int32) & jnp.int32(0xFF))
                + jnp.int32(1))
            return jnp.int32(0)
        if fname == "abort":
            raise CLiftError(
                "abort() needs the abort/DUE machinery; model it via "
                "DWC (detect-only strategy) instead")
        fn = self.funcs.get(fname)
        if fn is None:
            raise CLiftError(f"call to undefined function {fname!r} "
                             f"at {node.coord}")
        arg_consts = [None if isinstance(v, tuple)
                      or self._has_effects(n2)
                      else self._const_eval(n2, sc)
                      for n2, v in zip(arg_nodes, args)]
        return self._run_function(fn, args, sc, arg_consts)

    def _walked_names(self, node) -> set:
        """Names subject to POINTER arithmetic: ++/--/assignment on the
        BARE identifier.  Element stores (``a[i] = v``) do not count --
        they write the pointee, not the pointer (mm.c's r_matrix vs
        crc16.c's data_p)."""
        names: set = set()

        class V(c_ast.NodeVisitor):
            def visit_UnaryOp(v, n):
                if (n.op in ("++", "p++", "--", "p--")
                        and isinstance(n.expr, c_ast.ID)):
                    names.add(n.expr.name)
                v.generic_visit(n)

            def visit_Assignment(v, n):
                if isinstance(n.lvalue, c_ast.ID):
                    names.add(n.lvalue.name)
                v.generic_visit(n)

        V().visit(node)
        return names

    # -- desugar pre-pass --------------------------------------------------
    @staticmethod
    def _string_only_printf(stmt) -> bool:
        return (isinstance(stmt, c_ast.FuncCall)
                and isinstance(stmt.name, c_ast.ID)
                and stmt.name.name == "printf"
                and stmt.args is not None
                and len(stmt.args.exprs) == 1
                and isinstance(stmt.args.exprs[0], c_ast.Constant)
                and stmt.args.exprs[0].type == "string")

    def _desugar_fn(self, fndef) -> None:
        """Memoized per-function AST pre-pass, run before execution and
        before the early-return rewrite:

        * ``switch`` -> evaluate-once + ``if``/``else if`` chain (the
          subset's switches are break/return-terminated, CHStone mips.c
          style; fallthrough refuses loudly);
        * ``do {B} while (C)`` -> ``B; while (C) {B}`` (the body AST is
          shared; execution is functional over it);
        * ``while (1)`` whose body always returns at its tail runs
          exactly once -> body inlined (mips.c's outer retry loop), so
          its printfs stay program outputs;
        * a string-only ``printf("...")`` under a branch/loop becomes a
          PRINT SLOT: ``__print_sel_k = <string id>`` with the slot
          initialized to -1 (never printed) and appended to the output
          surface when the function returns.  The reference's oracle IS
          stdout ("RESULT: PASS", unittest/cfg/full.yml) and which
          string prints is data -- a selected-constant output captures
          exactly that bit.  The id -> string table lands in
          ``region.meta['print_strings']``.  printf with VALUE arguments
          inside branches still refuses loudly (a traced per-iteration
          value cannot escape as a fixed output).
        """
        fid = id(fndef)
        if fid in self._desugared:
            return
        self._desugared.add(fid)
        slots = self._print_slots.setdefault(fid, [])
        temps = self._sw_temps.setdefault(fid, [])
        slot_by_node: Dict[int, Tuple[str, int]] = {}

        def as_items(node) -> list:
            if node is None:
                return []
            if isinstance(node, c_ast.Compound):
                return list(node.block_items or [])
            return [node]

        def ends_in_return(items) -> bool:
            if not items:
                return False
            last = items[-1]
            if isinstance(last, c_ast.Return):
                return True
            if isinstance(last, c_ast.Compound):
                return ends_in_return(as_items(last))
            if isinstance(last, c_ast.If) and last.iffalse is not None:
                return (ends_in_return(as_items(last.iftrue))
                        and ends_in_return(as_items(last.iffalse)))
            return False

        def loose_break(items) -> bool:
            """A break/continue that would bind to the statement being
            flattened (not to a nested loop of its own)."""
            for s in items:
                if isinstance(s, (c_ast.Break, c_ast.Continue)):
                    return True
                if isinstance(s, (c_ast.While, c_ast.For, c_ast.DoWhile,
                                  c_ast.Switch)):
                    continue
                if isinstance(s, c_ast.Compound):
                    if loose_break(as_items(s)):
                        return True
                elif isinstance(s, c_ast.If):
                    if (loose_break(as_items(s.iftrue))
                            or loose_break(as_items(s.iffalse))):
                        return True
            return False

        def slot_for(stmt) -> Tuple[str, int]:
            sid = id(stmt)
            if sid not in slot_by_node:
                text = stmt.args.exprs[0].value[1:-1]
                self.print_strings.append(
                    text.encode("utf-8").decode("unicode_escape"))
                k = len(self.print_strings) - 1
                slot_by_node[sid] = (f"__print_sel_{k}", k)
                slots.append(slot_by_node[sid])
            return slot_by_node[sid]

        def xform_block(node, in_branch: bool):
            items = []
            for s in as_items(node):
                items.extend(xform(s, in_branch))
            return c_ast.Compound(items, getattr(node, "coord", None))

        def desugar_switch(sw) -> list:
            body_items = as_items(sw.stmt)
            if isinstance(sw.cond, (c_ast.ID, c_ast.Constant)):
                ctrl, pre = sw.cond, []
            else:
                nm = f"__sw_{len(temps)}"
                temps.append(nm)
                ctrl = c_ast.ID(nm, sw.cond.coord)
                pre = [c_ast.Assignment("=", c_ast.ID(nm, sw.cond.coord),
                                        sw.cond, sw.cond.coord)]
            groups: list = []          # (conds | None-for-default, stmts)
            pending: list = []
            pending_default = False
            for it in body_items:
                if isinstance(it, c_ast.Case):
                    pending.append(it.expr)
                    stmts = list(it.stmts or [])
                elif isinstance(it, c_ast.Default):
                    pending_default = True
                    stmts = list(it.stmts or [])
                else:
                    raise CLiftError(
                        f"unsupported statement between switch cases at "
                        f"{getattr(it, 'coord', '?')}")
                if not stmts:
                    continue                      # label stacking
                if pending_default and pending:
                    raise CLiftError(
                        f"case labels stacked with default at {it.coord} "
                        "are not supported; restructure")
                groups.append((None if pending_default else list(pending),
                               stmts, it.coord))
                pending, pending_default = [], False
            # Validate break/return termination (fallthrough refuses);
            # the FINAL group may simply fall out of the switch.
            cleaned = []
            for gi, (conds, stmts, coord) in enumerate(groups):
                if isinstance(stmts[-1], c_ast.Break):
                    stmts = stmts[:-1]
                elif not ends_in_return(stmts) and gi != len(groups) - 1:
                    raise CLiftError(
                        f"switch case at {coord} falls through; add "
                        "break/return (fallthrough is outside the subset)")
                cleaned.append((conds, stmts, coord))
            default_body = None
            chain_groups = []
            for conds, stmts, coord in cleaned:
                body = xform_block(c_ast.Compound(stmts, coord), True)
                if conds is None:
                    default_body = body
                else:
                    chain_groups.append((conds, body))
            node = default_body
            for conds, body in reversed(chain_groups):
                cond_expr = None
                for cexpr in conds:
                    eq = c_ast.BinaryOp("==", ctrl, cexpr, sw.coord)
                    cond_expr = (eq if cond_expr is None else
                                 c_ast.BinaryOp("||", cond_expr, eq,
                                                sw.coord))
                node = c_ast.If(cond_expr, body, node, sw.coord)
            out_sw = pre + ([node] if node is not None else [])
            # MID-CASE breaks (beyond the stripped terminators) exit the
            # SWITCH, not any enclosing loop: lower them as a forward
            # goto to a label right after the if-chain, BEFORE any
            # enclosing loop's deep-break pass could misbind them.
            swend = None

            def rb(s):
                nonlocal swend
                if isinstance(s, c_ast.Break):
                    if swend is None:
                        swend = f"__swend{self._tmp}"
                        self._tmp += 1
                    return c_ast.Goto(swend, s.coord)
                if isinstance(s, (c_ast.While, c_ast.For, c_ast.DoWhile,
                                  c_ast.Switch)):
                    return s                     # inner construct's own
                if isinstance(s, c_ast.If):
                    return c_ast.If(
                        s.cond,
                        rb(s.iftrue) if s.iftrue is not None else None,
                        rb(s.iffalse) if s.iffalse is not None else None,
                        s.coord)
                if isinstance(s, c_ast.Compound):
                    return c_ast.Compound(
                        [rb(x) for x in (s.block_items or [])], s.coord)
                return s

            out_sw = [rb(s) for s in out_sw]
            if swend is not None:
                out_sw.append(c_ast.Label(
                    swend, c_ast.EmptyStatement(sw.coord), sw.coord))
            return out_sw

        def is_break_if(s) -> bool:
            if not isinstance(s, c_ast.If) or s.iffalse is not None:
                return False
            b = (s.iftrue.block_items or []
                 if isinstance(s.iftrue, c_ast.Compound) else [s.iftrue])
            return len(b) == 1 and isinstance(b[0], c_ast.Break)

        def lower_deep_breaks(loop) -> list:
            """Breaks beyond the `if (c) break;` idiom (jpeg's
            `if (s) { if ((k += n) >= 64) break; ... }`) lower through
            the goto machinery: break -> goto __brkN with the label
            right after the loop."""
            lbl = None

            def replace(s, top):
                nonlocal lbl
                if isinstance(s, c_ast.Break):
                    if top:
                        return s                 # the direct idiom's own
                    if lbl is None:
                        lbl = f"__brk{self._tmp}"
                        self._tmp += 1
                    return c_ast.Goto(lbl, s.coord)
                if isinstance(s, (c_ast.While, c_ast.For, c_ast.DoWhile,
                                  c_ast.Switch)):
                    return s                     # inner loop owns breaks
                if isinstance(s, c_ast.If):
                    if top and is_break_if(s):
                        return s
                    return c_ast.If(
                        s.cond,
                        replace(s.iftrue, False)
                        if s.iftrue is not None else None,
                        replace(s.iffalse, False)
                        if s.iffalse is not None else None, s.coord)
                if isinstance(s, c_ast.Compound):
                    return c_ast.Compound(
                        [replace(x, top) for x in as_items(s)], s.coord)
                return s

            items2 = as_items(loop.stmt)
            new_items = []
            for k, s in enumerate(items2):
                if isinstance(s, c_ast.Break) and k == len(items2) - 1:
                    new_items.append(s)          # run-once trailing break
                else:
                    new_items.append(replace(s, True))
            body2 = c_ast.Compound(new_items, loop.coord)
            if isinstance(loop, c_ast.For):
                new_loop = c_ast.For(loop.init, loop.cond, loop.next,
                                     body2, loop.coord)
            else:
                new_loop = c_ast.While(loop.cond, body2, loop.coord)
            if lbl is None:
                return [new_loop]
            return [new_loop,
                    c_ast.Label(lbl, c_ast.EmptyStatement(loop.coord),
                                loop.coord)]

        def xform(stmt, in_branch: bool) -> list:
            if isinstance(stmt, c_ast.Switch):
                return desugar_switch(stmt)
            if isinstance(stmt, c_ast.DoWhile):
                body = xform_block(stmt.stmt, True)
                if loose_break(as_items(body)):
                    raise CLiftError(
                        f"break/continue in do-while body at {stmt.coord} "
                        "is outside the subset; restructure")
                return [body, c_ast.While(stmt.cond, body, stmt.coord)]
            if isinstance(stmt, c_ast.While):
                body = xform_block(stmt.stmt, True)
                if (_const_int(stmt.cond) and ends_in_return(as_items(body))
                        and not loose_break(as_items(body))):
                    # while(1) whose body always returns: exactly one
                    # iteration -- inline it.
                    return as_items(body)
                return [c_ast.While(stmt.cond, body, stmt.coord)]
            if isinstance(stmt, c_ast.For):
                body = xform_block(stmt.stmt, True)
                return lower_deep_breaks(
                    c_ast.For(stmt.init, stmt.cond, stmt.next, body,
                              stmt.coord))
            if isinstance(stmt, c_ast.If):
                t = (xform_block(stmt.iftrue, True)
                     if stmt.iftrue is not None else None)
                f = (xform_block(stmt.iffalse, True)
                     if stmt.iffalse is not None else None)
                return [c_ast.If(stmt.cond, t, f, stmt.coord)]
            if isinstance(stmt, c_ast.Compound):
                return [xform_block(stmt, in_branch)]
            if in_branch and self._string_only_printf(stmt):
                nm, k = slot_for(stmt)
                return [c_ast.Assignment(
                    "=", c_ast.ID(nm, stmt.coord),
                    c_ast.Constant("int", str(k), stmt.coord), stmt.coord)]
            return [stmt]

        body = xform_block(fndef.body, False)
        fndef.body = self._rewrite_gotos(body, temps)

    def _rewrite_gotos(self, body, temps) -> "c_ast.Compound":
        """Lower FORWARD gotos into skip flags, per enclosing compound:

          goto L;   ->  __goto_L = 1;  (+ exit any FOR loops between)
          L: stmt   ->  __goto_L = 0; <stmt guarded like the rest>

        A label lives at the top level of SOME compound (the function
        body, a loop body, a branch); its gotos may sit anywhere below
        that compound, including inside nested FOR loops (jpeg's
        id_found search: the loop gains a flag-conditional break, and
        the in-loop statements after the jump run under the no-flags
        guard -- one masked partial iteration, no effects).  Statements
        of the label's compound between the goto point and the label
        run under ``if ((flagA | flagB | ...) == 0)`` -- the
        early-return discipline applied to jumps.  Refused loudly:
        backward gotos, gotos escaping while/do-while loops, unknown
        labels."""

        def goto_names(n) -> List[str]:
            out: List[str] = []

            class V(c_ast.NodeVisitor):
                def visit_Goto(v, nn):
                    out.append(nn.name)

            if n is not None:
                V().visit(n)
            return out

        if not goto_names(body):
            return body

        flag: Dict[str, str] = {}

        def flag_for(name: str) -> str:
            if name not in flag:
                flag[name] = f"__goto_{name}"
                temps.append(flag[name])
            return flag[name]

        def no_flags(names, coord):
            expr = None
            for L in names:
                e = c_ast.ID(flag_for(L), coord)
                expr = e if expr is None else c_ast.BinaryOp("|", expr, e,
                                                             coord)
            return c_ast.BinaryOp("==", expr, c_ast.Constant("int", "0"),
                                  coord)

        def as_items(node):
            if node is None:
                return []
            if isinstance(node, c_ast.Compound):
                return list(node.block_items or [])
            return [node]

        def rewrite(stmt, active):
            """Replace active gotos under ``stmt``; loops crossed by a
            jump gain guard+break discipline.  Returns the new stmt."""
            hit = [g for g in goto_names(stmt) if g in active]
            if not hit:
                return stmt
            if isinstance(stmt, c_ast.Goto):
                return c_ast.Assignment(
                    "=", c_ast.ID(flag_for(stmt.name), stmt.coord),
                    c_ast.Constant("int", "1", stmt.coord), stmt.coord)
            if isinstance(stmt, c_ast.Compound):
                return c_ast.Compound(
                    seq_guard(as_items(stmt), active, stmt.coord),
                    stmt.coord)
            if isinstance(stmt, c_ast.If):
                return c_ast.If(
                    stmt.cond,
                    rewrite(stmt.iftrue, active)
                    if stmt.iftrue is not None else None,
                    rewrite(stmt.iffalse, active)
                    if stmt.iffalse is not None else None,
                    stmt.coord)
            if isinstance(stmt, c_ast.For):
                items2 = seq_guard(as_items(stmt.stmt), active, stmt.coord)
                esc = sorted({g for g in goto_names(stmt.stmt)
                              if g in active})
                brk = c_ast.If(
                    c_ast.BinaryOp("==", no_flags(esc, stmt.coord),
                                   c_ast.Constant("int", "0", stmt.coord),
                                   stmt.coord),
                    c_ast.Break(stmt.coord), None, stmt.coord)
                return c_ast.For(stmt.init, stmt.cond, stmt.next,
                                 c_ast.Compound(items2 + [brk],
                                                stmt.coord), stmt.coord)
            if isinstance(stmt, (c_ast.While, c_ast.DoWhile)):
                raise CLiftError(
                    f"goto escaping a while/do-while at {stmt.coord} is "
                    "outside the modeled envelope; restructure")
            if isinstance(stmt, c_ast.Label):
                return c_ast.Label(stmt.name, rewrite(stmt.stmt, active),
                                   stmt.coord)
            raise CLiftError(
                f"goto in unsupported construct {type(stmt).__name__} at "
                f"{getattr(stmt, 'coord', '?')}")

        def seq_guard(stmts, active, coord):
            """Within a compound below the label level: statements after
            a goto point run under the no-flags guard."""
            out = []
            for k, s in enumerate(stmts):
                hit = [g for g in goto_names(s) if g in active]
                if not hit:
                    out.append(s)
                    continue
                out.append(rewrite(s, active))
                rest = seq_guard(stmts[k + 1:], active, coord)
                if rest:
                    wrap = c_ast.If(
                        no_flags(sorted(active), coord),
                        c_ast.Compound(rest, coord), None, coord)
                    self._synth_reason[id(wrap)] = "after a goto point"
                    out.append(wrap)
                return out
            return out

        def process(items, coord):
            """Handle labels at THIS compound level (recursing into
            nested compounds for deeper labels first)."""
            # Recurse structurally so deeper compounds resolve their own
            # label/goto pairs before this level's flags apply.
            def descend(s):
                if isinstance(s, c_ast.Compound):
                    return c_ast.Compound(
                        process(as_items(s), s.coord), s.coord)
                if isinstance(s, c_ast.If):
                    return c_ast.If(
                        s.cond,
                        descend(s.iftrue) if s.iftrue is not None
                        else None,
                        descend(s.iffalse) if s.iffalse is not None
                        else None, s.coord)
                if isinstance(s, (c_ast.For, c_ast.While, c_ast.DoWhile)):
                    body2 = c_ast.Compound(
                        process(as_items(s.stmt), s.coord), s.coord)
                    if isinstance(s, c_ast.For):
                        return c_ast.For(s.init, s.cond, s.next, body2,
                                         s.coord)
                    if isinstance(s, c_ast.While):
                        return c_ast.While(s.cond, body2, s.coord)
                    return c_ast.DoWhile(s.cond, body2, s.coord)
                if isinstance(s, c_ast.Label):
                    return c_ast.Label(s.name, descend(s.stmt), s.coord)
                return s

            items = [descend(s) for s in items]
            labels_here = {it.name: k for k, it in enumerate(items)
                           if isinstance(it, c_ast.Label)}
            if not labels_here:
                return items
            active = set(labels_here)
            # Forward check at this level.
            for k, it in enumerate(items):
                holder = it.stmt if isinstance(it, c_ast.Label) else it
                for g in goto_names(holder):
                    if g in labels_here and labels_here[g] <= k:
                        raise CLiftError(
                            f"backward goto {g!r} is outside the "
                            "modeled envelope (forward jumps only)")
            out: List[object] = []
            seen_goto = False
            for k_i, it in enumerate(items):
                if (seen_goto and isinstance(it, c_ast.Break)
                        and k_i == len(items) - 1):
                    # A trailing break (the run-once while(1) idiom) is
                    # reached on every path: forward-only jumps mean all
                    # this level's labels precede it, and each label
                    # resets its flag -- so by here every guard passes.
                    # It must also STAY a syntactic Break, or
                    # _exec_while no longer recognizes the idiom and the
                    # loop falls to the dynamic-while lowering.
                    out.append(it)
                    continue
                if isinstance(it, c_ast.Label) and it.name in active:
                    out.append(c_ast.Assignment(
                        "=", c_ast.ID(flag_for(it.name), it.coord),
                        c_ast.Constant("int", "0", it.coord), it.coord))
                    inner = rewrite(it.stmt, active)
                    wrap = c_ast.If(no_flags(sorted(active), it.coord),
                                    inner, None, it.coord)
                    self._synth_reason[id(wrap)] = "after a goto point"
                    out.append(wrap)
                    seen_goto = seen_goto or bool(
                        [g for g in goto_names(it.stmt) if g in active])
                    continue
                if seen_goto:
                    inner = rewrite(it, active)
                    wrap = c_ast.If(
                        no_flags(sorted(active),
                                 getattr(it, "coord", None)),
                        inner, None, getattr(it, "coord", None))
                    self._synth_reason[id(wrap)] = "after a goto point"
                    out.append(wrap)
                else:
                    out.append(rewrite(it, active))
                    seen_goto = seen_goto or bool(
                        [g for g in goto_names(it) if g in active])
            return out

        new_items = process(as_items(body), body.coord)
        stray = goto_names(c_ast.Compound(new_items, body.coord))
        if stray:
            raise CLiftError(
                f"goto to unknown/backward label(s) {sorted(set(stray))}; "
                "only forward jumps to a label in an enclosing compound "
                "are modeled")
        return c_ast.Compound(new_items, body.coord)

    def _run_function(self, fndef, args, outer_sc: _Scope,
                      arg_consts: Optional[List[Optional[int]]] = None):
        self._desugar_fn(fndef)
        fid = id(fndef)
        sc = _Scope(outer_sc.g, self.g_ctypes)
        sc.printed = outer_sc.printed       # printf threads through
        # Known-constant GLOBALS flow into the callee (locals shadowing
        # a global keep their constness out of it).
        sc.consts = {n: v for n, v in outer_sc.consts.items()
                     if n not in outer_sc.locals}
        for nm, _k in self._print_slots.get(fid, ()):
            sc.locals[nm] = jnp.int32(-1)   # -1 = this line never printed
            sc.consts[nm] = -1
        for nm in self._sw_temps.get(fid, ()):
            sc.locals[nm] = jnp.int32(0)
            sc.consts.pop(nm, None)
        params = []
        decl = fndef.decl.type
        if decl.args:
            params = [p for p in decl.args.params
                      if not isinstance(p, c_ast.EllipsisParam)
                      and getattr(p, "name", None) is not None]
            if getattr(fndef, "param_decls", None):
                # K&R-style definition (blowfish's OpenSSL-vintage
                # `void BF_encrypt(data, key) BF_LONG *data; ...`):
                # the identifier list carries bare IDs; the real Decls
                # live in param_decls.
                by_name = {d.name: d for d in fndef.param_decls}
                params = [by_name.get(p.name, p) for p in params]
        if len(params) != len(args):
            raise CLiftError(
                f"{fndef.decl.name}: {len(args)} args for {len(params)} "
                "parameters (array parameters pass the global by name)")
        walked = self._walked_names(fndef.body)
        copy_backs: List[Tuple[str, str]] = []
        scalar_backs: List[Tuple[str, str]] = []
        g_scalar_backs: List[Tuple[str, str, object]] = []
        for pi, (p, a) in enumerate(zip(params, args)):
            if (isinstance(a, tuple) and len(a) == 2
                    and a[0] == "__alias_scalar_global__"):
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                gv = sc.g[a[1]]
                sc.g[temp] = jnp.reshape(gv, (1,))
                oct_ = self.g_ctypes.get(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                sc.locals[p.name] = jnp.int32(0)
                g_scalar_backs.append((temp, a[1], gv.dtype))
                continue
            if (isinstance(a, tuple) and len(a) == 2
                    and a[0] == "__alias_scalar_local__"):
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                val0 = outer_sc.locals[a[1]]
                sc.g[temp] = (jnp.stack([val0.lo, val0.hi]).reshape(1, 2)
                              if isinstance(val0, _C64)
                              else jnp.reshape(val0, (1,)))
                oct_ = outer_sc.ctype(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                sc.locals[p.name] = jnp.int32(0)
                scalar_backs.append((temp, a[1]))
                continue
            if isinstance(a, tuple) and a[0] == "__alias_local_off__":
                # Caller-local array element address: transient slot
                # with the cursor starting at the element's offset.
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                sc.g[temp] = outer_sc.locals[a[1]]
                oct_ = outer_sc.ctype(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                sc.locals[p.name] = jnp.asarray(a[2], jnp.int32)
                copy_backs.append((temp, a[1]))
                continue
            if (isinstance(a, tuple) and len(a) == 2
                    and a[0] == "__alias_local__"):
                # Caller-local array passed by reference: copy into a
                # transient slot of the (shared) globals dict, alias the
                # parameter to it, and copy back after the body runs.
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                sc.g[temp] = outer_sc.locals[a[1]]
                oct_ = outer_sc.ctype(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                copy_backs.append((temp, a[1]))
                if p.name in walked:
                    sc.locals[p.name] = jnp.int32(0)
                continue
            if isinstance(a, tuple) and a[0] == "__alias_off__":
                # Forwarded pointer: alias the base, start the cursor at
                # the caller's offset.
                sc.aliases[p.name] = a[1]
                sc.locals[p.name] = jnp.asarray(a[2], jnp.int32)
            elif isinstance(a, tuple) and len(a) == 2 \
                    and a[0] == "__alias__":
                sc.aliases[p.name] = a[1]
                if p.name in walked:
                    # The body does pointer arithmetic on this parameter
                    # (``p++``): give it a walk cursor, carried like any
                    # other local through the body's loops.
                    sc.locals[p.name] = jnp.int32(0)
            else:
                ct = (_ctype_of(getattr(p.type.type, "names", ["int"]),
                                self.typedefs)
                      if isinstance(p.type, c_ast.TypeDecl) else None)
                if ct is not None:
                    sc.locals[p.name] = ct.store(a)
                    sc.ctypes[p.name] = ct
                else:
                    sc.locals[p.name] = a
                kc = arg_consts[pi] if arg_consts else None
                self._const_set(sc, p.name, kc,
                                ct if not isinstance(ct, _CType64)
                                else None)
        # Function-wide pointer pre-seating: a pointer seated over
        # DIFFERENT arrays in different loops (ChenIDct's aptr over x
        # then y) must take its union alias before the first loop
        # traces, not per-loop.
        self._preseat(fndef.body, sc)
        new_items, set_n, val_n, synth = self._rewrite_early_returns(fndef)
        if new_items is not None:
            rett = fndef.decl.type.type
            rct = (_ctype_of(getattr(rett.type, "names", ["int"]),
                             self.typedefs)
                   if isinstance(rett, c_ast.TypeDecl) else None)
            for n in synth:
                if n == val_n and rct is not None:
                    # The carried return value takes the declared return
                    # type from the start: every `return E` then
                    # converts E at the store (C semantics), and a
                    # 64-bit return stays a limb pair across cond
                    # branches (pytree consistency).
                    sc.locals[n] = rct.zero()
                    sc.ctypes[n] = rct
                    if isinstance(rct, _CType64):
                        sc.consts.pop(n, None)
                    else:
                        sc.consts[n] = 0
                else:
                    sc.locals[n] = jnp.int32(0)
                    sc.consts[n] = 0
            self._exec_block(
                c_ast.Compound(new_items, fndef.body.coord), sc)
            ret = sc.locals[val_n]
        else:
            ret = self._exec_block(fndef.body, sc)
        for temp, lname in copy_backs:
            outer_sc.locals[lname] = sc.g.pop(temp)
        for temp, gname, dt in g_scalar_backs:
            slot = sc.g.pop(temp)
            sc.g[gname] = jnp.reshape(slot, ()).astype(dt)
            outer_sc.consts.pop(gname, None)
        for temp, lname in scalar_backs:
            slot = sc.g.pop(temp)
            oct_ = outer_sc.ctype(lname)
            if isinstance(oct_, _CType64):
                pair = slot.reshape(-1, 2)[0]
                outer_sc.locals[lname] = _C64(pair[0], pair[1],
                                              oct_.unsigned)
            else:
                outer_sc.locals[lname] = jnp.reshape(slot, ())
            outer_sc.consts.pop(lname, None)   # written via the slot
        # Global constness after the call: invalidate exactly the
        # globals the callee may write (a callee-LOCAL shadowing a
        # global -- AddRoundKey's `int j, nb;` -- must not kill the
        # caller's knowledge of the global), then flow the callee's
        # known globals back (its view of its own writes is the truth).
        may_write = set(self._assigned_globals(fndef))
        for n in list(outer_sc.consts):
            if n not in outer_sc.locals and n in may_write:
                outer_sc.consts.pop(n, None)
        for n, v in sc.consts.items():
            if n not in sc.locals and n not in outer_sc.locals:
                outer_sc.consts[n] = v
        # A function's print slots join the output surface when it
        # returns.  At a traced call site (inside a loop/branch) the
        # slots flow into the UART buffer when the program has one --
        # only slots that actually fired (id >= 0) append -- otherwise
        # the printed sentinel refuses, as for any in-loop print.
        for nm, _k in self._print_slots.get(fid, ()):
            v = jnp.asarray(sc.locals[nm])
            if (isinstance(sc.printed, _NoPrintList)
                    and "__print_buf" in sc.g):
                buf = sc.g["__print_buf"]
                cnt = sc.g["__print_cnt"]
                fired = v >= 0
                idx = jnp.clip(cnt, 0, _PRINT_BUF_WORDS - 1)
                keep = jnp.logical_and(fired, cnt < _PRINT_BUF_WORDS)
                buf = buf.at[idx].set(
                    jnp.where(keep, v.astype(jnp.uint32), buf[idx]))
                cnt = cnt + fired.astype(jnp.int32)
                sc.g["__print_buf"] = buf
                sc.g["__print_cnt"] = cnt
            else:
                sc.printed.append(v)
        if ret is None:
            return jnp.int32(0)
        # C return-value conversion: the value converts to the declared
        # return type (a narrow return like TI_aes_128.c's galois_mul2
        # 'unsigned char' drops bit 8 HERE, not at some later store).
        rett = fndef.decl.type.type
        if isinstance(rett, c_ast.TypeDecl):
            ct = _ctype_of(getattr(rett.type, "names", ["int"]),
                           self.typedefs)
            ret = ct.store(ret)
        return ret

    # -- statements --------------------------------------------------------
    def _exec_block(self, block, sc: _Scope):
        if block is None:
            return None
        items = block.block_items or [] if isinstance(
            block, c_ast.Compound) else [block]
        for stmt in items:
            ret = self._exec_stmt(stmt, sc)
            if ret is not None:
                return ret
        return None

    def _exec_stmt(self, stmt, sc: _Scope):
        if isinstance(stmt, c_ast.Decl):
            if isinstance(stmt.type, c_ast.ArrayDecl):
                # Local array: zeros or element-wise initializer list.
                dims, t = [], stmt.type
                while isinstance(t, c_ast.ArrayDecl):
                    n = _const_int(t.dim)
                    if n is None:
                        if (t.dim is None and not dims
                                and isinstance(stmt.init, c_ast.InitList)):
                            n = len(stmt.init.exprs)   # char key[] = {..}
                        else:
                            raise CLiftError(
                                f"non-literal local array dim for "
                                f"{stmt.name} at {stmt.coord}")
                    dims.append(n)
                    t = t.type
                ct = _ctype_of(getattr(t.type, "names", ["int"]),
                               self.typedefs)
                if isinstance(ct, _CType64):
                    raise CLiftError(
                        f"long long array {stmt.name!r} at {stmt.coord}: "
                        "64-bit elements are outside the word-addressed "
                        "memory model (locals only)")
                arr = jnp.zeros(tuple(dims), ct.dtype)
                if stmt.init is not None:
                    if not isinstance(stmt.init, c_ast.InitList):
                        raise CLiftError(
                            f"unsupported local array initializer at "
                            f"{stmt.coord}")
                    flat = arr.reshape(-1)
                    exprs = list(stmt.init.exprs)
                    for k, e in enumerate(exprs):
                        flat = flat.at[k].set(
                            ct.store(self.eval(e, sc)).astype(ct.dtype))
                    arr = flat.reshape(tuple(dims))
                sc.locals[stmt.name] = arr
                sc.ctypes[stmt.name] = ct
                return None
            if isinstance(stmt.type, c_ast.PtrDecl):
                # Local pointer: binds to (global-or-copied array, offset).
                sc.ptrs.add(stmt.name)
                if stmt.init is None:
                    # Declared-but-unbound: a bare cursor with no alias
                    # until `p = arr;` re-seats it (adpcm.c's h_ptr);
                    # any deref before that fails loudly.  A function-
                    # wide pre-seat may already have aliased it.
                    sc.locals.setdefault(stmt.name, jnp.int32(0))
                    return None
                base, off = self._ptr_parts(stmt.init, sc)
                union = self._union_bases(sc.aliases.get(stmt.name))
                if union is not None and not isinstance(base, tuple):
                    off = (self._union_offset(sc, union, base)
                           + jnp.asarray(off, jnp.int32))
                else:
                    sc.aliases[stmt.name] = base
                sc.locals[stmt.name] = off
                return None
            ct = _ctype_of(getattr(stmt.type.type, "names", ["int"]),
                           self.typedefs)
            val = (ct.store(self.eval(stmt.init, sc))
                   if stmt.init is not None else ct.zero())
            sc.locals[stmt.name] = val
            sc.ctypes[stmt.name] = ct
            if isinstance(ct, _CType64):
                sc.consts.pop(stmt.name, None)
            else:
                # The model zero-initializes declared scalars, so a
                # no-init local IS the constant 0 at this point.
                self._const_set(
                    sc, stmt.name,
                    0 if stmt.init is None
                    else self._const_eval(stmt.init, sc), ct)
            return None
        if isinstance(stmt, c_ast.DeclList):
            for d in stmt.decls:
                self._exec_stmt(d, sc)
            return None
        if isinstance(stmt, c_ast.Assignment):
            self._assign(stmt, sc)
            return None
        if isinstance(stmt, (c_ast.UnaryOp, c_ast.FuncCall, c_ast.ExprList)):
            self.eval(stmt, sc)
            return None
        if isinstance(stmt, c_ast.If):
            return self._exec_if(stmt, sc)
        if isinstance(stmt, c_ast.For):
            return self._exec_for(stmt, sc)
        if isinstance(stmt, c_ast.While):
            return self._exec_while(stmt, sc)
        if isinstance(stmt, c_ast.Return):
            return (self.eval(stmt.expr, sc) if stmt.expr is not None
                    else jnp.int32(0))
        if isinstance(stmt, c_ast.Compound):
            return self._exec_block(stmt, sc)
        if isinstance(stmt, c_ast.EmptyStatement):
            return None
        raise CLiftError(
            f"unsupported statement {type(stmt).__name__} at {stmt.coord}")

    @staticmethod
    def _base_ids(expr) -> List[str]:
        """Base identifiers a pointer-valued expression could alias
        (static over-approximation for carry discovery)."""
        out: List[str] = []
        stack = [expr]
        while stack:
            e = stack.pop()
            if isinstance(e, c_ast.ID):
                out.append(e.name)
            elif isinstance(e, c_ast.Cast):
                stack.append(e.expr)
            elif isinstance(e, c_ast.UnaryOp) and e.op in ("&", "++", "p++",
                                                           "--", "p--"):
                stack.append(e.expr)
            elif isinstance(e, c_ast.ArrayRef):
                stack.append(e.name)
            elif isinstance(e, c_ast.BinaryOp) and e.op in ("+", "-"):
                stack.extend((e.left, e.right))
        return out

    def _assigned_names(self, node) -> List[str]:
        """Names written anywhere under ``node`` (loop-carry discovery).

        Local POINTERS complicate this: a deref-store ``*p = v`` writes
        the array ``p`` is seated on, so the seated base names (from
        ``T *p = arr;`` declarations and ``p = arr;`` re-seatings in the
        same subtree) are added for every deref-written pointer --
        without them, a callee that walks a global through a local
        pointer (adpcm.c's encode/decode delay lines) would not carry
        that global through the CALLER's loop, silently freezing it."""
        names: List[str] = []
        ptr_decls: set = set()
        seats: Dict[str, List[str]] = {}
        deref_targets: List[str] = []

        class V(c_ast.NodeVisitor):
            def visit_Assignment(v, n):
                t = n.lvalue
                derefed = False
                while isinstance(t, (c_ast.ArrayRef, c_ast.UnaryOp)):
                    # Unwrap a[i]... and deref lvalues (*p = v writes both
                    # the pointee and, via the walk machinery, p's cursor).
                    derefed = True
                    t = t.name if isinstance(t, c_ast.ArrayRef) else t.expr
                if isinstance(t, c_ast.ID):
                    names.append(t.name)
                    if t.name.startswith("__print_sel_"):
                        # Desugared branch print: its slot flows into
                        # the UART buffer at function end.
                        names.extend(["__print_buf", "__print_cnt"])
                    if derefed:
                        deref_targets.append(t.name)
                    elif n.op == "=":
                        seats.setdefault(t.name, []).extend(
                            _Compiler._base_ids(n.rvalue))
                v.generic_visit(n)

            def visit_UnaryOp(v, n):
                if n.op in ("++", "p++", "--", "p--"):
                    t = n.expr
                    while isinstance(t, c_ast.ArrayRef):
                        t = t.name
                    if isinstance(t, c_ast.ID):
                        names.append(t.name)
                v.generic_visit(n)

            def visit_Decl(v, n):
                if n.name:
                    names.append(n.name)
                    if isinstance(n.type, c_ast.PtrDecl):
                        ptr_decls.add(n.name)
                        if n.init is not None:
                            seats.setdefault(n.name, []).extend(
                                _Compiler._base_ids(n.init))
                v.generic_visit(n)

            def visit_FuncCall(v, n):
                # A called function may write globals directly or through
                # an array-pointer parameter; treat ID arguments bound to
                # POINTER/ARRAY parameters (and every callee-assigned
                # name) as written.  Scalar by-value parameters cannot
                # write the caller's variable -- and carrying them would
                # also destroy trace-time concreteness (aes_enc.c's `nb`
                # must stay concrete through the rounds loop for the
                # ciphertext print loop's static bound).
                if isinstance(n.name, c_ast.ID):
                    if n.name.name == "printf":
                        # printf only READS its arguments -- but under
                        # the UART-buffer model it writes the buffer.
                        names.extend(["__print_buf", "__print_cnt"])
                        v.generic_visit(n)
                        return
                    if n.name.name == "exit":
                        # exit() writes the poison observable; without
                        # this the write would die in a branch fork.
                        names.append("__exit_state")
                    callee = self.funcs.get(n.name.name)
                    params = []
                    if (callee is not None
                            and not getattr(callee, "param_decls", None)):
                        decl = callee.decl.type
                        if decl.args:
                            params = [p for p in decl.args.params
                                      if not isinstance(
                                          p, c_ast.EllipsisParam)]
                    for ai, a in enumerate(n.args.exprs if n.args else []):
                        if isinstance(a, c_ast.UnaryOp) and a.op == "&":
                            # Out-parameter (&aSig): the callee writes
                            # through it -- the pointee is written.
                            names.extend(_Compiler._base_ids(a))
                            continue
                        if isinstance(a, c_ast.ArrayRef):
                            # Sub-array argument (PMV[0][s]) decays to a
                            # pointer; conservatively count the base as
                            # written -- unless the callee's parameter
                            # is a by-value scalar (full indexing).
                            if params and ai < len(params):
                                pt = getattr(params[ai], "type", None)
                                if not isinstance(pt, (c_ast.PtrDecl,
                                                       c_ast.ArrayDecl)):
                                    continue
                            t2 = a
                            while isinstance(t2, c_ast.ArrayRef):
                                t2 = t2.name
                            if isinstance(t2, c_ast.ID):
                                names.append(t2.name)
                            continue
                        if not isinstance(a, c_ast.ID):
                            continue
                        if params and ai < len(params):
                            pt = getattr(params[ai], "type", None)
                            if not isinstance(pt, (c_ast.PtrDecl,
                                                   c_ast.ArrayDecl)):
                                continue    # by-value scalar
                        names.append(a.name)
                    if callee is not None:
                        names.extend(self._assigned_globals(callee))
                v.generic_visit(n)

        V().visit(node)
        # Deref-written pointers write their seated arrays.  A GLOBAL
        # pointer seated outside the analyzed node (gp = A before the
        # loop, gp[i] = v inside it) has no local seat entry; its
        # statically-known candidate bases stand in -- without them the
        # written array would drop out of a scan's carry.
        for p in dict.fromkeys(deref_targets):
            names.extend(seats.get(p, ()))
            if p in self.g_ptrs and p not in seats:
                names.extend(sorted(self._g_ptr_static_bases(p)))
        return list(dict.fromkeys(names))

    def _g_ptr_static_base(self, name: str) -> Optional[str]:
        """Static whole-program resolution of a global pointer's base:
        the single base array every seating agrees on (None if
        unseated/ambiguous)."""
        bases = self._g_ptr_static_bases(name)
        return next(iter(bases)) if len(bases) == 1 else None

    def _g_ptr_static_bases(self, name: str) -> frozenset:
        """ALL candidate base arrays a global pointer's seatings alias:
        scan every function for `name = <expr>` seatings, collapsing
        cursor-on-cursor chains.  Empty if never seated."""
        cache = getattr(self, "_g_ptr_seat_cache", None)
        if cache is None:
            cache = {}
            comp = self

            class V(c_ast.NodeVisitor):
                def visit_Assignment(v, n):
                    if (n.op == "=" and isinstance(n.lvalue, c_ast.ID)
                            and n.lvalue.name in comp.g_ptrs):
                        for b in comp._base_ids(n.rvalue):
                            if b != n.lvalue.name:
                                cache.setdefault(n.lvalue.name,
                                                 set()).add(b)
                    v.generic_visit(n)

            for fn in self.funcs.values():
                V().visit(fn.body)
            self._g_ptr_seat_cache = cache
        bases = cache.get(name)
        # Cursors seated on one another (ld_Rdmax = ld_Rdptr) collapse
        # through the other pointer's bases.
        for _ in range(4):
            if not bases:
                return frozenset()
            flat = set()
            again = False
            for b in bases:
                if b in self.g_ptrs:
                    sub = cache.get(b)
                    if sub:
                        flat |= sub
                        again = True
                else:
                    flat.add(b)
            bases = flat
            if not again:
                break
        return frozenset(bases)

    def _assigned_globals(self, fndef) -> List[str]:
        """Names a callee writes OUTSIDE its own scope: its assigned
        names minus its params and local declarations.  A callee-local
        shadowing a global (AddRoundKey's `int j, nb;` vs the global
        nb) must not count as a caller-side write -- it would both
        over-carry and invalidate constant propagation."""
        fid = id(fndef)
        cached = self._assigned_globals_cache.get(fid)
        if cached is not None:
            return cached
        self._assigned_globals_cache[fid] = []     # cut recursion cycles
        names = self._assigned_names(fndef.body)
        local: set = set()
        decl = fndef.decl.type
        if decl.args:
            for p in decl.args.params:
                nm = getattr(p, "name", None)
                if nm:
                    local.add(nm)

        class V(c_ast.NodeVisitor):
            def visit_Decl(v, n):
                if n.name:
                    local.add(n.name)
                v.generic_visit(n)

        V().visit(fndef.body)
        out = [n for n in names if n not in local]
        self._assigned_globals_cache[fid] = out
        return out

    def written_globals(self, fndef, g_names, subst=None):
        """Globals (transitively) written by ``fndef``, following array-
        argument aliasing: a callee's writes through an array parameter
        count against the global the caller passed."""
        subst = subst or {}
        out = set()
        comp = self

        # Local pointer variables (char *p = s;) route stores to their
        # target: track Decl-time bindings AND later re-seatings
        # (``p1 = (LONG *)s1;``) so deref stores through them count
        # against the right global (chains and casts included).
        local_ptr: Dict[str, str] = {}
        ptr_names: set = set()
        multi_seats: Dict[str, set] = {}        # union-pointer candidates

        def resolve(nm):
            for _ in range(8):
                if nm in local_ptr:
                    nm = local_ptr[nm]
                    continue
                if nm in comp.g_ptrs:
                    base = comp._g_ptr_static_base(nm)
                    if base is not None and base != nm:
                        nm = base
                        continue
                break
            return subst.get(nm, nm)

        def resolve_all(nm):
            """Every base a store through ``nm`` may write.  Unlike
            ``resolve``, an AMBIGUOUS global-pointer seating (gp = A in
            one function, gp = B in another) unions every candidate:
            conservatively over-reporting keeps injections into the
            really-written array out of the masked bucket."""
            out_s: set = set()
            frontier, seen = {nm}, set()
            for _ in range(8):
                nxt: set = set()
                for x in frontier:
                    if x in seen:
                        continue
                    seen.add(x)
                    if x in local_ptr:
                        nxt.add(local_ptr[x])
                        continue
                    if x in comp.g_ptrs:
                        bases = comp._g_ptr_static_bases(x) - {x}
                        if bases:
                            nxt.update(bases)
                            continue
                    out_s.add(subst.get(x, x))
                if not nxt:
                    break
                frontier = nxt
            return out_s

        def targets_of(t):
            while isinstance(t, (c_ast.ArrayRef, c_ast.UnaryOp)):
                t = t.name if isinstance(t, c_ast.ArrayRef) else t.expr
            if isinstance(t, c_ast.ID):
                return resolve_all(t.name)
            return set()

        def seat_base(expr):
            """First base identifier a seating RHS aliases, resolved."""
            for cand in _Compiler._base_ids(expr):
                r = resolve(cand)
                if r in g_names or cand in local_ptr or cand in subst:
                    return cand if cand in local_ptr else r
            return None

        class V(c_ast.NodeVisitor):
            def visit_Decl(v, n):
                if isinstance(n.type, c_ast.PtrDecl):
                    ptr_names.add(n.name)
                    if n.init is not None:
                        e = n.init
                        while isinstance(e, c_ast.Cast):
                            e = e.expr
                        if isinstance(e, c_ast.ID):
                            local_ptr[n.name] = e.name
                v.generic_visit(n)

            def visit_Assignment(v, n):
                # Reseating a pointer (``p = p + 1``, ``p1 = (LONG*)s1``,
                # parameter or local pointer variable) writes the walk
                # cursor / rebinds the alias, not the pointed-to global;
                # only element stores (ArrayRef/deref lvalues) write the
                # array.  Record the re-seating so later deref stores
                # route to the right base.
                if (isinstance(n.lvalue, c_ast.ID)
                        and (n.lvalue.name in subst
                             or n.lvalue.name in local_ptr
                             or n.lvalue.name in ptr_names)):
                    if n.op == "=":
                        base = seat_base(n.rvalue)
                        if base is not None and base != n.lvalue.name:
                            local_ptr[n.lvalue.name] = base
                            r = resolve(n.lvalue.name)
                            if r in g_names:
                                multi_seats.setdefault(
                                    n.lvalue.name, set()).add(r)
                    v.generic_visit(n)
                    return
                out.update(t for t in targets_of(n.lvalue)
                           if t in g_names)
                # A deref store through a MULTI-seated (union) pointer
                # may write any of its candidate bases.
                t2 = n.lvalue
                derefed = False
                while isinstance(t2, (c_ast.ArrayRef, c_ast.UnaryOp)):
                    derefed = True
                    t2 = (t2.name if isinstance(t2, c_ast.ArrayRef)
                          else t2.expr)
                if (derefed and isinstance(t2, c_ast.ID)
                        and len(multi_seats.get(t2.name, ())) > 1):
                    out.update(multi_seats[t2.name])
                v.generic_visit(n)

            def visit_UnaryOp(v, n):
                if n.op in ("++", "p++", "--", "p--"):
                    # Same rule: ++/-- on a bare pointer ID is cursor
                    # arithmetic.
                    if (isinstance(n.expr, c_ast.ID)
                            and (n.expr.name in subst
                                 or n.expr.name in local_ptr)):
                        return
                    out.update(t for t in targets_of(n.expr)
                               if t in g_names)
                v.generic_visit(n)

            def visit_FuncCall(v, n):
                if isinstance(n.name, c_ast.ID):
                    if (n.name.name == "exit"
                            and "__exit_state" in g_names):
                        out.add("__exit_state")
                    if n.name.name == "printf":
                        out.update({"__print_buf", "__print_cnt"}
                                   & set(g_names))
                    callee = comp.funcs.get(n.name.name)
                    if callee is not None:
                        decl = callee.decl.type
                        params = ([p.name for p in decl.args.params
                                   if not isinstance(p, c_ast.EllipsisParam)
                                   and p.name is not None]
                                  if decl.args else [])
                        sub2 = {}
                        args = n.args.exprs if n.args else []
                        for p, a in zip(params, args):
                            if isinstance(a, c_ast.ID):
                                tgt = resolve(a.name)
                                if tgt in g_names:
                                    sub2[p] = tgt
                            elif (isinstance(a, c_ast.UnaryOp)
                                    and a.op == "&"):
                                # &global out-param: the callee may
                                # write the pointee.
                                for b in comp._base_ids(a):
                                    if resolve(b) in g_names:
                                        out.add(resolve(b))
                        out.update(comp.written_globals(
                            callee, g_names, sub2))
                v.generic_visit(n)

        V().visit(fndef.body)
        return out

    @staticmethod
    def _union_bases(alias) -> Optional[Tuple[str, ...]]:
        """The member tuple of a union alias, or None for plain ones."""
        return alias if isinstance(alias, tuple) else None

    def _union_offset(self, sc: _Scope, bases: Tuple[str, ...],
                      member: str):
        off = 0
        for b in bases:
            if b == member:
                return jnp.int32(off)
            off += int(np.prod(jnp.shape(sc.g[b])))
        raise CLiftError(
            f"array {member!r} is not a member of the union pointer "
            f"over {bases}")

    def _union_read(self, sc: _Scope, bases: Tuple[str, ...]):
        return jnp.concatenate([sc.g[b].reshape(-1) for b in bases])

    def _union_write(self, sc: _Scope, bases: Tuple[str, ...],
                     flat) -> None:
        off = 0
        for b in bases:
            n = int(np.prod(jnp.shape(sc.g[b])))
            sc.write_binding(b, flat[off:off + n].reshape(
                jnp.shape(sc.g[b])))
            off += n

    def _preseat(self, node, sc: _Scope) -> None:
        """Seat outer-declared pointers whose FIRST seating happens inside
        ``node`` (a loop body or branch) before tracing it: the alias map
        is trace-time state, so the seating must be hoisted.  A single
        static base seats plainly; MULTIPLE same-dtype candidate bases
        (jpeg's huffman tables: `p = ac_tbl[i]` in one branch,
        `p = dc_tbl[i]` in the other) seat as a UNION pointer -- the
        cursor indexes the concatenation of the members, reads gather
        from it, writes split back, so the runtime branch merely picks
        the cursor's segment.  Anything else is left for _guard_reseat's
        loud refusal."""
        seats: Dict[str, List[str]] = {}
        decl_ptrs: set = set()

        class V(c_ast.NodeVisitor):
            def visit_Assignment(v, n):
                if n.op == "=" and isinstance(n.lvalue, c_ast.ID):
                    seats.setdefault(n.lvalue.name, []).extend(
                        _Compiler._base_ids(n.rvalue))
                v.generic_visit(n)

            def visit_Decl(v, n):
                if isinstance(n.type, c_ast.PtrDecl) and n.name:
                    decl_ptrs.add(n.name)
                    if n.init is not None:
                        seats.setdefault(n.name, []).extend(
                            _Compiler._base_ids(n.init))
                v.generic_visit(n)

        V().visit(node)
        for p, cands in seats.items():
            if (p not in sc.ptrs and p not in decl_ptrs) \
                    or p in sc.aliases:
                continue
            bases = {sc.aliases.get(c, c) for c in cands}
            bases = {b for b in bases
                     if b in sc.g and jnp.ndim(sc.g[b]) >= 1}
            if len(bases) == 1:
                sc.aliases[p] = bases.pop()
            elif len(bases) > 1:
                members = tuple(sorted(bases))
                dts = {sc.g[b].dtype for b in members}

                def ctkey(b):
                    ct = sc.ctypes.get(b)
                    # None and any 32-bit ctype behave identically on
                    # the lane model (no store narrowing); only NARROW
                    # members must match exactly.  64-bit members never
                    # unify (the limb-pair access paths do not speak
                    # unions) -- a unique key forces the loud
                    # _guard_reseat refusal instead.
                    if ct is not None and ct.bits == 64:
                        return ("w64", b)
                    if ct is None or ct.bits == 32:
                        return "w32"
                    return (ct.dtype, ct.bits, ct.unsigned)

                if len(dts) == 1 and len({ctkey(b) for b in members}) == 1:
                    sc.aliases[p] = members

    def _guard_reseat(self, sc, sub, coord):
        """Refuse pointer re-seating to a DIFFERENT array inside a traced
        sub-region (loop body/branch): the aliased base is resolved at
        trace time, so a per-iteration/per-branch base change cannot be
        expressed (same-base re-seating -- a cursor reset -- is a traced
        value write and passes)."""
        for n in sc.ptrs | set(sc.aliases):
            if sub.aliases.get(n) != sc.aliases.get(n):
                raise CLiftError(
                    f"pointer {n!r} re-seated to a different array inside "
                    f"a traced branch/loop at {coord}; hoist the "
                    "re-seating or restructure")

    def _loop_carry(self, stmt, sc) -> List[str]:
        """Variables the loop body writes that already exist in scope (the
        scan/while carry); body-local declarations stay local."""
        # A name that is itself a local (incl. a pointer parameter's walk
        # cursor, which shares its name with an alias) carries as that
        # local.  A WALKED pointer name additionally carries its aliased
        # global: ``p[0] = v`` inside the loop stores into the global
        # while ``p++`` moves the cursor, and both writes must survive
        # the iteration (a read-only extra carry is loop-invariant and
        # hoisted by XLA).
        assigned: List[str] = []

        def add_alias(alias):
            if isinstance(alias, tuple):
                assigned.extend(alias)           # union: every member
            else:
                assigned.append(alias)

        for n in self._assigned_names(stmt):
            if n in sc.locals:
                assigned.append(n)
                if n in sc.aliases:
                    add_alias(sc.aliases[n])
            else:
                add_alias(sc.aliases.get(n, n))
        return [n for n in dict.fromkeys(assigned)
                if n in sc.locals or n in sc.g]

    @staticmethod
    def _has_return(node) -> bool:
        found = []

        class V(c_ast.NodeVisitor):
            def visit_Return(v, n):
                found.append(n)

        V().visit(node)
        return bool(found)

    def _rewrite_early_returns(self, fndef):
        """Lower structured early returns to a carried flag pair.

        ``return E`` anywhere becomes ``if (!__ret_set) { __ret_val = E;
        __ret_set = 1; }``; every statement after a return-containing
        one runs under ``if (!__ret_set)``; every loop whose subtree
        returns gains ``&& !__ret_set`` in its condition with the
        for-next moved into the body under the same guard (the exact
        discipline of the break lowering, applied function-wide) -- so
        ``if (hash[i] != golden[i]) return 1;`` inside a scan loop
        (checkGolden, sha256_common_tmr.c:191-198) exits with C's
        semantics.  Loop conditions become PURE carried variables primed
        before the loop and re-evaluated at the end of each body under
        the guard -- C's return exits WITHOUT re-testing the condition,
        so a side-effecting condition must not run on the returning
        exit.  Returns (new_body_items, set_name, val_name, synth_names)
        where synth_names are locals the caller must pre-create, or
        (None, None, None, None) when the body has no early return."""
        items = list(fndef.body.block_items or [])
        early = any(self._has_return(s) for s in items[:-1]) or (
            items and not isinstance(items[-1], c_ast.Return)
            and self._has_return(items[-1]))
        if not early:
            return None, None, None, None
        set_n = f"__ret_set{self._tmp}"
        val_n = f"__ret_val{self._tmp}"
        self._tmp += 1
        synth_names = [set_n, val_n]
        not_set = lambda coord: c_ast.BinaryOp(  # noqa: E731
            "==", c_ast.ID(set_n), c_ast.Constant("int", "0"), coord)

        def ret_to_set(n):
            expr = n.expr if n.expr is not None else c_ast.Constant(
                "int", "0")
            body = c_ast.Compound([
                c_ast.Assignment("=", c_ast.ID(val_n), expr, n.coord),
                c_ast.Assignment("=", c_ast.ID(set_n),
                                 c_ast.Constant("int", "1"), n.coord),
            ], n.coord)
            return c_ast.If(not_set(n.coord), body, None, n.coord)

        def xform(s):
            """Transform ONE statement in place-ish; returns new stmt."""
            if isinstance(s, c_ast.Return):
                return ret_to_set(s)
            if not self._has_return(s):
                return s
            if isinstance(s, c_ast.Compound):
                return c_ast.Compound(seq(list(s.block_items or [])),
                                      s.coord)
            if isinstance(s, c_ast.If):
                return c_ast.If(
                    s.cond,
                    xform(s.iftrue) if s.iftrue is not None else None,
                    xform(s.iffalse) if s.iffalse is not None else None,
                    s.coord)
            if isinstance(s, (c_ast.For, c_ast.While)):
                cond = getattr(s, "cond", None)
                guard = not_set(s.coord)
                body_items = (list(s.stmt.block_items or [])
                              if isinstance(s.stmt, c_ast.Compound)
                              else [s.stmt])
                body_items = seq(body_items)
                nxt = getattr(s, "next", None)
                if nxt is not None:
                    body_items.append(
                        c_ast.If(not_set(s.coord), nxt, None, s.coord))
                # Pure carried condition: primed before the loop,
                # re-evaluated (effects included) at the body end under
                # the !set guard so the returning exit never re-runs it.
                cnd = f"__cnd{self._tmp}"
                self._tmp += 1
                synth_names.append(cnd)
                pre = []
                init = getattr(s, "init", None)
                if init is not None:
                    pre.append(init)
                if cond is not None:
                    cond_val = c_ast.BinaryOp(
                        "!=", cond, c_ast.Constant("int", "0"), s.coord)
                    prime = c_ast.If(
                        guard,
                        c_ast.Assignment("=", c_ast.ID(cnd), cond_val,
                                         s.coord),
                        None, s.coord)
                    body_items.append(c_ast.Assignment(
                        "=", c_ast.ID(cnd), c_ast.Constant("int", "0"),
                        s.coord))
                    body_items.append(c_ast.If(
                        guard,
                        c_ast.Assignment("=", c_ast.ID(cnd), cond_val,
                                         s.coord),
                        None, s.coord))
                else:
                    prime = c_ast.Assignment(
                        "=", c_ast.ID(cnd), guard, s.coord)
                    body_items.append(c_ast.Assignment(
                        "=", c_ast.ID(cnd), guard, s.coord))
                pre.append(c_ast.Assignment(
                    "=", c_ast.ID(cnd), c_ast.Constant("int", "0"),
                    s.coord))
                pre.append(prime)
                new_body = c_ast.Compound(body_items, s.coord)
                loop = c_ast.For(None, c_ast.ID(cnd), None, new_body,
                                 s.coord)
                return c_ast.Compound(pre + [loop], s.coord)
            raise CLiftError(
                f"return in unsupported construct "
                f"{type(s).__name__} at {getattr(s, 'coord', '?')}")

        def seq(stmts):
            out = []
            for k, s in enumerate(stmts):
                if not self._has_return(s):
                    out.append(s)
                    continue
                out.append(xform(s))
                rest = seq(stmts[k + 1:])
                if rest:
                    wrap = c_ast.If(
                        not_set(getattr(s, "coord", None)),
                        c_ast.Compound(rest, getattr(s, "coord", None)),
                        None, getattr(s, "coord", None))
                    self._synth_reason[id(wrap)] = \
                        "after an early-return point"
                    out.append(wrap)
                return out
            return out

        return seq(items), set_n, val_n, synth_names

    def _rewrite_breaks(self, stmt, sc: _Scope):
        """Lower mid-loop conditional breaks (``if (c) break;``) to a
        carried break flag: the loop condition gains ``&& !brk`` and
        every statement after the break point runs under ``if (!brk)``,
        so the exit is exact -- same iteration count, same final state
        as the C program (sha256_tmr.c's for-100 early exit; the
        quicksort error-break idiom).  Returns a rewritten For (or the
        original when the body has no breaks).  Breaks in any other
        position refuse loudly; breaks inside NESTED loops belong to
        those loops and are left alone."""
        items = (list(stmt.stmt.block_items or [])
                 if isinstance(stmt.stmt, c_ast.Compound) else [stmt.stmt])
        if not any(self._count_breaks(s) for s in items
                   if not isinstance(s, (c_ast.While, c_ast.For))):
            return stmt
        brk = f"__brk{self._tmp}"
        self._tmp += 1
        sc.locals[brk] = jnp.int32(0)

        def is_break_if(s):
            """``if (c) break;`` / ``if (c) { break; }`` with no else."""
            if not isinstance(s, c_ast.If) or s.iffalse is not None:
                return False
            body = (s.iftrue.block_items or []
                    if isinstance(s.iftrue, c_ast.Compound) else [s.iftrue])
            return len(body) == 1 and isinstance(body[0], c_ast.Break)

        def rewrite(seq):
            out = []
            for k, s in enumerate(seq):
                if isinstance(s, (c_ast.While, c_ast.For)):
                    out.append(s)          # inner loop owns its breaks
                    continue
                if is_break_if(s):
                    set_brk = c_ast.Assignment(
                        "=", c_ast.ID(brk),
                        c_ast.Constant("int", "1"), s.coord)
                    out.append(c_ast.If(s.cond, set_brk, None, s.coord))
                    rest = rewrite(seq[k + 1:])
                    if rest:
                        guard = c_ast.BinaryOp(
                            "==", c_ast.ID(brk),
                            c_ast.Constant("int", "0"), s.coord)
                        wrap = c_ast.If(
                            guard, c_ast.Compound(rest, s.coord), None,
                            s.coord)
                        self._synth_reason[id(wrap)] = \
                            "after a mid-loop break point"
                        out.append(wrap)
                    return out
                if self._count_breaks(s):
                    raise CLiftError(
                        f"break in unsupported position at "
                        f"{getattr(s, 'coord', '?')}; only the "
                        "'if (cond) break;' idiom is lowered")
                out.append(s)
            return out

        body_stmts = rewrite(items)
        not_brk = c_ast.BinaryOp("==", c_ast.ID(brk),
                                 c_ast.Constant("int", "0"), stmt.coord)
        # C does not run the increment on the broken-out iteration: move
        # the next-expression into the body under the !brk guard (an If
        # STATEMENT, so its side effects are genuinely masked -- a
        # ternary would evaluate both arms under tracing).
        if stmt.next is not None:
            body_stmts.append(c_ast.If(not_brk, stmt.next, None,
                                       stmt.coord))
        # The loop condition becomes a PURE carried variable: C's break
        # exits WITHOUT re-testing the condition, so a side-effecting
        # condition (while (g--)) must not be evaluated on the
        # broken-out exit.  The variable is primed here (the pre-loop
        # test, effects apply once) and re-evaluated at the END of the
        # body under the !brk guard.
        cnd = f"__cnd{self._tmp}"
        self._tmp += 1
        sc.locals[cnd] = jnp.int32(0)
        if stmt.cond is not None:
            cond_val = c_ast.BinaryOp("!=", stmt.cond,
                                      c_ast.Constant("int", "0"),
                                      stmt.coord)
            self._exec_stmt(c_ast.Assignment("=", c_ast.ID(cnd),
                                             cond_val, stmt.coord), sc)
            body_stmts.append(c_ast.Assignment(
                "=", c_ast.ID(cnd), c_ast.Constant("int", "0"),
                stmt.coord))
            body_stmts.append(c_ast.If(
                not_brk,
                c_ast.Assignment("=", c_ast.ID(cnd), cond_val,
                                 stmt.coord),
                None, stmt.coord))
        else:
            self._exec_stmt(c_ast.Assignment(
                "=", c_ast.ID(cnd), c_ast.Constant("int", "1"),
                stmt.coord), sc)
            body_stmts.append(c_ast.Assignment(
                "=", c_ast.ID(cnd), not_brk, stmt.coord))
        new_body = c_ast.Compound(body_stmts, stmt.stmt.coord)
        return c_ast.For(None, c_ast.ID(cnd), None, new_body, stmt.coord)

    @staticmethod
    def _contains_printf(node) -> bool:
        found: List[object] = []

        class V(c_ast.NodeVisitor):
            def visit_FuncCall(v, n):
                if isinstance(n.name, c_ast.ID) and n.name.name == "printf":
                    found.append(n)
                v.generic_visit(n)

        V().visit(node)
        return bool(found)

    def _exec_for(self, stmt, sc: _Scope):
        if stmt.init is not None:
            self._exec_stmt(stmt.init, sc)
        # PRINT-ONLY loop (aes.c dumping the ciphertext bytes): a loop
        # whose body writes nothing (beyond print slots) but prints
        # per-iteration values.  Its observable IS the printed sequence,
        # so it unrolls at trace time under a concrete bound -- each
        # iteration's printf appends one program output.  A traced bound
        # refuses loudly (the output arity must be static).
        if (stmt.cond is not None and stmt.stmt is not None
                and self._contains_printf(stmt.stmt)
                and all(n.startswith("__print_sel_")
                        or n in ("__print_buf", "__print_cnt")
                        for n in self._assigned_names(stmt.stmt))):
            for _ in range(4096):
                live = (self._const_eval(stmt.cond, sc)
                        if not self._has_effects(stmt.cond) else None)
                if live is None:
                    raise CLiftError(
                        f"print-only loop at {stmt.coord} has a traced "
                        "bound; the number of printed outputs must be "
                        "static")
                if not live:
                    return None
                ret = self._exec_block(stmt.stmt, sc)
                if ret is not None:
                    raise CLiftError(
                        f"return inside a loop at {stmt.coord}; "
                        "restructure")
                if stmt.next is not None:
                    self.eval(stmt.next, sc)
            raise CLiftError(
                f"print-only loop at {stmt.coord} exceeds the 4096-"
                "iteration unroll bound")
        stmt = self._rewrite_breaks(stmt, sc)
        self._preseat(stmt, sc)
        carry_names = self._loop_carry(stmt, sc)

        def pack():
            return tuple(sc.read_binding(n) for n in carry_names)

        def unpack(sub_sc, vals):
            for n, v in zip(carry_names, vals):
                sub_sc.write_binding(n, v)
                sub_sc.consts.pop(n, None)   # traced write: value unknown

        trip = self._static_trip(stmt, sc)
        if trip is not None:
            def body(carry, _):
                sub = sc.fork(no_print_at=stmt.coord)
                # Per-iteration prints become STACKED scan outputs (one
                # [trip]-shaped observable per printed value, dfmul's
                # per-vector diagnostic line); the arity is fixed by the
                # single body trace.  Branch prints inside the body
                # still go through slots / loud refusals as usual.
                sub.printed = []
                unpack(sub, carry)
                ret = self._exec_block(stmt.stmt, sub)
                if ret is not None:
                    raise CLiftError(
                        f"return inside a loop at {stmt.coord}; restructure")
                if stmt.next is not None:
                    self.eval(stmt.next, sub)
                self._guard_reseat(sc, sub, stmt.coord)
                return (tuple(sub.read_binding(n) for n in carry_names),
                        tuple(jnp.asarray(p) for p in sub.printed))

            out, ys = jax.lax.scan(body, pack(), None, length=trip)
            unpack(sc, out)
            if ys:
                if (isinstance(sc.printed, _NoPrintList)
                        and "__print_buf" in sc.g
                        and all(jnp.ndim(y) == 1 for y in ys)):
                    # Stacked prints inside a DYNAMIC outer context flow
                    # into the UART buffer in true stdout order
                    # (iteration-major interleave).
                    flat = jnp.stack(
                        [y.astype(jnp.uint32) for y in ys],
                        axis=1).reshape(-1)
                    buf = sc.g["__print_buf"]
                    cnt = sc.g["__print_cnt"]
                    idx = cnt + jnp.arange(flat.size, dtype=jnp.int32)
                    # mode="drop" discards out-of-range writes outright:
                    # clipping them onto the last word would scatter
                    # duplicate indices with conflicting values, and JAX
                    # leaves duplicate-index order unspecified -- the
                    # legit final word could lose to a stale overflow row
                    # exactly when the buffer fills.
                    buf = buf.at[idx].set(flat, mode="drop")
                    sc.g["__print_buf"] = buf
                    sc.g["__print_cnt"] = cnt + flat.size
                else:
                    sc.printed.extend(list(ys))
            return None

        # A side-effecting condition (C's `while (length--)`) cannot be
        # evaluated in the while cond function -- writes made there are
        # discarded.  Rotate the loop instead: evaluate the condition once
        # up front (its effects apply), carry its truth value, and have
        # each iteration run body+next then re-evaluate the condition with
        # effects inside the body.  Exact C semantics, including the final
        # value of the side-effected variable after the failing test.
        if stmt.cond is not None and self._loop_carry(stmt.cond, sc):
            # int32 truth carry, not bool: every loop carry can become an
            # injectable region leaf, and the memory map is 32-bit words.
            t0 = self._truth(self.eval(stmt.cond, sc)).astype(jnp.int32)

            def cond_rot(carry):
                return jnp.not_equal(carry[-1], 0)

            def body_rot(carry):
                sub = sc.fork(no_print_at=stmt.coord)
                unpack(sub, carry[:-1])
                ret = self._exec_block(stmt.stmt, sub)
                if ret is not None:
                    raise CLiftError(
                        f"return inside a loop at {stmt.coord}; "
                        "restructure")
                if stmt.next is not None:
                    self.eval(stmt.next, sub)
                t = self._truth(self.eval(stmt.cond, sub)
                                ).astype(jnp.int32)
                self._guard_reseat(sc, sub, stmt.coord)
                return tuple(sub.read_binding(n) for n in carry_names) + (t,)

            out = jax.lax.while_loop(cond_rot, body_rot, pack() + (t0,))
            unpack(sc, out[:-1])
            return None

        # General for: lower as while with explicit cond/next.
        def cond_f(carry):
            sub = sc.fork(no_print_at=stmt.coord)
            unpack(sub, carry)
            c = (self.eval(stmt.cond, sub) if stmt.cond is not None
                 else jnp.int32(1))
            return self._truth(c)

        def body_f(carry):
            sub = sc.fork(no_print_at=stmt.coord)
            unpack(sub, carry)
            ret = self._exec_block(stmt.stmt, sub)
            if ret is not None:
                raise CLiftError(
                    f"return inside a loop at {stmt.coord}; restructure")
            if stmt.next is not None:
                self.eval(stmt.next, sub)
            self._guard_reseat(sc, sub, stmt.coord)
            return tuple(sub.read_binding(n) for n in carry_names)

        out = jax.lax.while_loop(cond_f, body_f, pack())
        unpack(sc, out)
        return None

    def _count_breaks(self, node) -> int:
        count = 0

        class V(c_ast.NodeVisitor):
            def visit_Break(v, n):
                nonlocal count
                count += 1

            def visit_While(v, n):      # breaks inside nested loops bind
                pass                    # to THOSE loops; don't descend

            def visit_For(v, n):
                pass

        V().visit(node)
        return count

    def _exec_while(self, stmt, sc: _Scope):
        # The run-once idiom ``while (1) { ...; break; }`` (sha256.c's
        # main): a body whose LAST top-level statement is the loop's only
        # break executes exactly once under the condition -- and with a
        # static-true condition it inlines into the enclosing scope, so
        # printf stays a program output.
        items = (stmt.stmt.block_items or []
                 if isinstance(stmt.stmt, c_ast.Compound) else [stmt.stmt])
        if items and isinstance(items[-1], c_ast.Break):
            body = c_ast.Compound(list(items[:-1]), stmt.stmt.coord)
            if self._count_breaks(body):
                raise CLiftError(
                    f"break before the tail of the loop at {stmt.coord}; "
                    "restructure")
            if _const_int(stmt.cond):
                return self._exec_block(body, sc)
            return self._exec_stmt(
                c_ast.If(stmt.cond, body, None, stmt.coord), sc)
        fake = c_ast.For(None, stmt.cond, None, stmt.stmt, stmt.coord)
        return self._exec_for(fake, sc)

    def _static_trip(self, stmt, sc) -> Optional[int]:
        """Trip count for the canonical `for (i = A; i < B; i++)` shape
        with literal A/B and the loop variable not written in the body."""
        init, cond, nxt = stmt.init, stmt.cond, stmt.next
        if init is None or cond is None or nxt is None:
            return None
        # init: i = A (assignment or single decl)
        if isinstance(init, c_ast.DeclList) and len(init.decls) == 1:
            var, a = init.decls[0].name, _const_int(init.decls[0].init)
        elif isinstance(init, c_ast.Assignment) and init.op == "=" \
                and isinstance(init.lvalue, c_ast.ID):
            var, a = init.lvalue.name, _const_int(init.rvalue)
        else:
            return None
        if a is None:
            return None
        if not (isinstance(cond, c_ast.BinaryOp) and cond.op in ("<", "<=")
                and isinstance(cond.left, c_ast.ID)
                and cond.left.name == var):
            return None
        b = _const_int(cond.right)
        if b is None:
            return None
        inc_ok = (isinstance(nxt, c_ast.UnaryOp)
                  and nxt.op in ("++", "p++")
                  and isinstance(nxt.expr, c_ast.ID)
                  and nxt.expr.name == var)
        if not inc_ok:
            return None
        # The loop variable must not be written inside the body (the scan
        # carries it via the next-expression only).
        if var in self._assigned_names(stmt.stmt):
            return None
        trip = (b - a) + (1 if cond.op == "<=" else 0)
        return max(0, trip)

    def _exec_if(self, stmt, sc: _Scope):
        self._preseat(stmt, sc)
        if not self._has_effects(stmt.cond):
            kc = self._const_eval(stmt.cond, sc)
            if kc is not None:
                # Statically-decided predicate: execute only the taken
                # branch INLINE (exact C semantics; keeps trace-time
                # constants known -- aes_enc.c's switch on a literal
                # `type` must yield a known nb for the ciphertext print
                # loop -- and keeps prints in statically-taken branches
                # legal program outputs).
                node = stmt.iftrue if kc else stmt.iffalse
                return (self._exec_block(node, sc)
                        if node is not None else None)
        cval = self.eval(stmt.cond, sc)      # cond effects apply once
        carry_names = self._loop_carry(stmt, sc)
        c = self._truth(cval)

        def branch(node):
            def run(vals):
                sub = sc.fork(
                    no_print_at=stmt.coord,
                    no_print_reason=self._synth_reason.get(id(stmt)))
                for n, v in zip(carry_names, vals):
                    sub.write_binding(n, v)
                if node is not None:
                    ret = self._exec_block(node, sub)
                    if ret is not None:
                        raise CLiftError(
                            f"return inside if at {stmt.coord}; restructure")
                self._guard_reseat(sc, sub, stmt.coord)
                return tuple(sub.read_binding(n) for n in carry_names)
            return run

        vals = tuple(sc.read_binding(n) for n in carry_names)
        out = jax.lax.cond(c, branch(stmt.iftrue), branch(stmt.iffalse),
                           vals)
        for n, v in zip(carry_names, out):
            sc.write_binding(n, v)
            sc.consts.pop(n, None)           # traced write: value unknown
        return None


# ---------------------------------------------------------------------------
# Translation-unit ingestion
# ---------------------------------------------------------------------------

def _string_bytes(lit: str) -> List[int]:
    """Decode a C string literal (quotes included) to its bytes + NUL."""
    body = lit[1:-1]
    decoded = body.encode("utf-8").decode("unicode_escape")
    return [b for b in decoded.encode("latin-1")] + [0]


def _normalize_init(vals: np.ndarray, ct: _CType) -> np.ndarray:
    """C conversion of initializer values into the declared type's lane."""
    if ct.bits == 32:
        return (vals & 0xFFFFFFFF).astype(np.uint32)
    mask = (1 << ct.bits) - 1
    v = (vals & mask).astype(np.int64)
    if not ct.unsigned:
        sign = 1 << (ct.bits - 1)
        v = ((v ^ sign) - sign)
    return v.astype(np.int64)


def _parse_globals(tu, typedefs):
    """Global declarations -> ({name: jnp array}, {name: _CType}).

    C linkage rules across the linked TUs: an ``extern`` declaration or
    a tentative (initializer-less) definition never OVERWRITES an
    earlier entry -- a shared header included by several TUs (CHStone
    sha.h's ``extern const int in_i[VSIZE]``) must not zero out the
    defining TU's initializer, in either include order."""
    out: Dict[str, jax.Array] = {}
    ctypes: Dict[str, _CType] = {}
    inited: set = set()
    g_ptrs: set = set()          # uninitialized pointer globals (cursors)

    def flat_init(init) -> List[int]:
        if isinstance(init, c_ast.InitList):
            vals = []
            for e in init.exprs:
                vals.extend(flat_init(e))
            return vals
        v = _const_int(init)
        if v is None:
            raise CLiftError(f"unsupported global initializer at "
                             f"{init.coord}")
        return [v]

    for ext in tu.ext:
        if not isinstance(ext, c_ast.Decl) or isinstance(
                ext.type, c_ast.FuncDecl):
            continue
        t = ext.type
        shape = []
        deferred = False
        while isinstance(t, c_ast.ArrayDecl):
            n = _const_int(t.dim)
            if n is None:
                # Unsized outer dim (char key[] = {...}): C sizes it from
                # the initializer.
                if (t.dim is None and not shape
                        and isinstance(ext.init, c_ast.InitList)):
                    n = len(ext.init.exprs)
                elif t.dim is None and ext.init is None:
                    # extern/tentative unsized array (motion.h's
                    # `extern const unsigned char inRdbfr[];`): an
                    # incomplete type the defining declaration
                    # completes; defer -- never-defined names fail
                    # loudly at first read.
                    deferred = True
                    break
                else:
                    raise CLiftError(
                        f"non-literal array dim for {ext.name}")
            shape.append(n)
            t = t.type
        if deferred:
            continue
        if isinstance(t, c_ast.PtrDecl):
            # Two pointer-global shapes: a char pointer initialized with
            # a string literal (crc16.c's message) becomes the byte
            # array itself; an UNINITIALIZED pointer global (motion's
            # ld_Rdptr) becomes an int32 CURSOR global -- runtime,
            # injectable pointer state -- whose aliased base array is
            # resolved at its first seating (single static base).
            inner = t.type
            if (isinstance(inner, c_ast.TypeDecl)
                    and isinstance(ext.init, c_ast.Constant)
                    and ext.init.type == "string"):
                ct = _ctype_of(inner.type.names, typedefs)
                vals = np.array(_string_bytes(ext.init.value), np.int64)
                out[ext.name] = jnp.asarray(
                    _normalize_init(vals, ct)).astype(ct.dtype)
                ctypes[ext.name] = ct
                continue
            if ext.init is None:
                if ext.name not in out:
                    out[ext.name] = jnp.int32(0)
                    g_ptrs.add(ext.name)
                continue
            raise CLiftError(
                f"unsupported pointer global {ext.name!r} (only char* "
                "with a string-literal initializer, or an uninitialized "
                "pointer seated at runtime, is modeled)")
        if isinstance(t, c_ast.TypeDecl):
            ct = _ctype_of(t.type.names, typedefs)
            if isinstance(ct, _CType64) and not shape:
                raise CLiftError(
                    f"long long global scalar {ext.name!r}: model it as "
                    "an element of a 64-bit array (limb-pair layout) or "
                    "a local")
        else:
            raise CLiftError(f"unsupported global type for {ext.name}")
        if isinstance(ct, _CType64):
            # 64-bit ARRAY: (dims..., 2) uint32 limb pairs -- each
            # element is two 32-bit memory words (lo, hi), exactly the
            # real layout, so the word-addressed injection map holds
            # (dfmul/dfdiv test vectors).
            total = int(np.prod(shape))
            if ext.init is not None:
                vals = [(_const_int(e) if not isinstance(e, c_ast.InitList)
                         else None) for e in ext.init.exprs]
                if any(v is None for v in vals):
                    raise CLiftError(
                        f"unsupported 64-bit initializer for {ext.name}")
                vals += [0] * (total - len(vals))
                pairs = np.array([[v & 0xFFFFFFFF, (v >> 32) & 0xFFFFFFFF]
                                  for v in vals], dtype=np.uint32)
                arr = jnp.asarray(pairs).reshape(tuple(shape) + (2,))
                inited.add(ext.name)
            else:
                if ext.name in out:
                    continue
                arr = jnp.zeros(tuple(shape) + (2,), jnp.uint32)
            out[ext.name] = arr
            ctypes[ext.name] = ct
            continue
        if ext.init is not None:
            # int64 container so negative initializers wrap mod 2^32 (C
            # conversion to a 32-bit lane); partial initializer lists
            # zero-fill the tail, per C aggregate-initialization rules.
            vals = np.array(flat_init(ext.init), dtype=np.int64)
            total = int(np.prod(shape)) if shape else 1
            if len(vals) > total:
                raise CLiftError(
                    f"{ext.name}: {len(vals)} initializers for "
                    f"{total} elements")
            vals = np.concatenate(
                [vals, np.zeros(total - len(vals), np.int64)])
            arr = jnp.asarray(
                _normalize_init(vals, ct)).astype(ct.dtype)
            arr = arr.reshape(shape) if shape else arr.reshape(())
            inited.add(ext.name)
        else:
            if ext.name in out:
                # extern/tentative re-declaration of an existing name:
                # keep the existing (possibly initialized) definition.
                continue
            arr = jnp.zeros(tuple(shape) if shape else (), ct.dtype)
        out[ext.name] = arr
        ctypes[ext.name] = ct
    return out, ctypes, g_ptrs


_PRINT_BUF_WORDS = 256


def _static_for_shape(n) -> bool:
    """AST-only mirror of _static_trip's canonical literal-bound shape."""
    init, cond, nxt = n.init, n.cond, n.next
    if init is None or cond is None or nxt is None:
        return False
    if isinstance(init, c_ast.DeclList) and len(init.decls) == 1:
        var, a = init.decls[0].name, _const_int(init.decls[0].init)
    elif (isinstance(init, c_ast.Assignment) and init.op == "="
          and isinstance(init.lvalue, c_ast.ID)):
        var, a = init.lvalue.name, _const_int(init.rvalue)
    else:
        return False
    if a is None:
        return False
    if not (isinstance(cond, c_ast.BinaryOp) and cond.op in ("<", "<=")
            and isinstance(cond.left, c_ast.ID) and cond.left.name == var):
        return False
    if _const_int(cond.right) is None:
        return False
    if not (isinstance(nxt, c_ast.UnaryOp) and nxt.op in ("++", "p++")
            and isinstance(nxt.expr, c_ast.ID) and nxt.expr.name == var):
        return False

    # Mirror _static_trip's last condition: the loop variable must not
    # be written in the body (else the runtime classifier disagrees).
    written: List[bool] = []

    class _W(c_ast.NodeVisitor):
        def visit_Assignment(self, nn):
            if isinstance(nn.lvalue, c_ast.ID) and nn.lvalue.name == var:
                written.append(True)
            self.generic_visit(nn)

        def visit_UnaryOp(self, nn):
            if (nn.op in ("++", "p++", "--", "p--")
                    and isinstance(nn.expr, c_ast.ID)
                    and nn.expr.name == var):
                written.append(True)
            self.generic_visit(nn)

    _W().visit(n.stmt)
    return not written


def _needs_print_buffer(funcs) -> bool:
    """Does any value-printing printf sit where the printed arity
    cannot be static (dynamic loop, or branch under any loop)?"""
    need: List[bool] = []

    def walk(n, dyn_loop: int, any_loop: int, branch: int):
        if n is None or not isinstance(n, c_ast.Node):
            return
        if isinstance(n, (c_ast.While, c_ast.DoWhile)):
            walk(n.stmt, dyn_loop + 1, any_loop + 1, branch)
            return
        if isinstance(n, c_ast.For):
            d = 0 if _static_for_shape(n) else 1
            walk(n.stmt, dyn_loop + d, any_loop + 1, branch)
            return
        if isinstance(n, c_ast.If):
            walk(n.iftrue, dyn_loop, any_loop, branch + 1)
            walk(n.iffalse, dyn_loop, any_loop, branch + 1)
            return
        if (isinstance(n, c_ast.FuncCall)
                and isinstance(n.name, c_ast.ID)
                and n.name.name == "printf"
                and n.args is not None and len(n.args.exprs) > 1):
            if dyn_loop > 0 or (any_loop > 0 and branch > 0):
                need.append(True)
            return
        for _, ch in n.children():
            walk(ch, dyn_loop, any_loop, branch)

    for fn in funcs.values():
        walk(fn.body, 0, 0, 0)
    return bool(need)


def parse_c_sources(paths: Sequence[str]):
    """Parse + link the restricted-C sources into (tu, globals, funcs,
    typedefs, coast_annotations)."""
    if not _HAVE_PYCPARSER:
        raise CLiftError("pycparser is unavailable on this host")
    include_dirs = sorted({os.path.dirname(os.path.abspath(p))
                           for p in paths})
    texts, anns = [], []
    name_flags: Dict[str, bool] = {}
    for p in paths:
        # Per-translation-unit preprocessing state (object-like AND
        # function-like defines), matching C: a macro from one source
        # file must not leak into the next.  Includes share the
        # including file's tables (textual inclusion).
        with open(p) as f:
            src, _, ann, _ = preprocess(f.read(), include_dirs,
                                        name_flags=name_flags,
                                        fdefines={})
        texts.append(src)
        anns.extend(ann)
    parser = c_parser.CParser()
    try:
        tu = parser.parse(_PRELUDE + "\n".join(texts),
                          filename="<coast_tpu>")
    except Exception as e:          # pycparser ParseError and lexer errors
        raise CLiftError(f"C parse error: {e}") from e

    typedefs: Dict[str, object] = {}
    funcs: Dict[str, object] = {}
    for ext in tu.ext:
        if isinstance(ext, c_ast.Typedef):
            base = ext.type
            if isinstance(base, c_ast.TypeDecl):
                names = getattr(base.type, "names", ["int"])
                typedefs[ext.name] = _ctype_of(names, typedefs)
        elif isinstance(ext, c_ast.FuncDef):
            funcs[ext.decl.name] = ext
    globals_, g_ctypes, g_ptrs = _parse_globals(tu, typedefs)

    # Any exit() call introduces the synthetic observable __exit_state
    # (0 = ran to completion; 1+n = exited with code n).
    class _ExitScan(c_ast.NodeVisitor):
        found = False

        def visit_FuncCall(self, n):
            if isinstance(n.name, c_ast.ID) and n.name.name == "exit":
                _ExitScan.found = True
            self.generic_visit(n)

    for fn in funcs.values():
        _ExitScan().visit(fn.body)
    if _ExitScan.found:
        globals_["__exit_state"] = jnp.int32(0)
        g_ctypes["__exit_state"] = _CType(jnp.int32, 32, False)

    # Value prints whose arity cannot be static -- under a dynamic loop
    # or under a branch inside any loop (jpeg's for(;;) marker loop) --
    # get the UART-buffer model: a synthetic bounded __print_buf plus
    # __print_cnt become the stdout observable.  Only created when
    # needed, so every other program's leaf layout is untouched.
    if _needs_print_buffer(funcs):
        globals_["__print_buf"] = jnp.zeros(_PRINT_BUF_WORDS, jnp.uint32)
        globals_["__print_cnt"] = jnp.int32(0)
        g_ctypes["__print_cnt"] = _CType(jnp.int32, 32, False)
        g_ctypes["__print_buf"] = _CType(jnp.uint32, 32, True)
    return (tu, globals_, funcs, typedefs, anns, name_flags, g_ctypes,
            g_ptrs)


def lift_c(name: str,
           sources: Sequence[str],
           *,
           entry: str = "main",
           annotations: Optional[Dict[str, LeafSpec]] = None,
           default_xmr: Optional[bool] = None,
           max_steps: Optional[int] = None,
           meta: Optional[dict] = None) -> Region:
    """Ingest C sources and derive a protected Region.

    Globals become the lifted function's inputs (hence injectable leaves
    named by ``lift_fn``'s layout); written globals plus every value the
    program printf'd become its outputs.  ``entry`` (default ``main``) is
    executed.  COAST.h macros in the source set ``default_xmr`` unless
    overridden."""
    (tu, globals_, funcs, typedefs, anns, name_flags, g_ctypes,
     g_ptrs) = parse_c_sources(sources)
    if entry not in funcs:
        raise CLiftError(
            f"entry function {entry!r} not defined; have "
            f"{sorted(funcs)}")
    if default_xmr is None:
        default_xmr = "__DEFAULT_NO_xMR" not in anns

    comp = _Compiler(tu, typedefs, funcs, name, g_ctypes,
                     g_ptrs=g_ptrs)
    g_names = sorted(globals_)
    out_globals = sorted(comp.written_globals(funcs[entry], set(g_names)))

    def program(*g_vals):
        sc = _Scope(dict(zip(g_names, g_vals)), g_ctypes)
        comp._run_function(funcs[entry], [], sc)
        outs = [sc.g[n] for n in out_globals] + list(sc.printed)
        return tuple(outs)

    example = [globals_[n] for n in g_names]
    region = lift_fn(
        name, program, *example,
        annotations=annotations, default_xmr=default_xmr,
        max_steps=max_steps,
        meta={"frontend": "c", "sources": [os.path.basename(s)
                                           for s in sources],
              "source_paths": [os.path.realpath(s) for s in sources],
              "coast_annotations": sorted(set(anns)),
              "global_xmr": {n: f for n, f in sorted(name_flags.items())
                             if n in globals_},
              "observed_globals": out_globals, **(meta or {})})
    # The print-slot string table fills while lift_fn TRACES the program
    # (the desugar pass runs at first execution), so attach it after.
    region.meta["print_strings"] = list(comp.print_strings)

    # Per-declaration __xMR/__NO_xMR annotations, lowered the way the
    # reference's engine consumes them (tests/mm_common/mm_tmr.c):
    #
    #   * an annotated FUNCTION replicates its computation -- its locals
    #     become the lifted loop machinery (carries, indices, _phase), so
    #     those leaves inherit the function scope;
    #   * an annotated GLOBAL maps onto the state leaf its argument
    #     position became -- except UNWRITTEN globals, which the
    #     reference never clones regardless of annotation (the
    #     unwritten-global rule, cloning.cpp:62-288), so RO leaves keep
    #     the shared default;
    #   * globals consumed only through a transformed value have no
    #     single leaf; warn, do not drop silently.
    import dataclasses as _dc
    from coast_tpu.ir.region import KIND_RO
    arg_leaves = region.meta.get("arg_leaves", {})
    global_leaves = set()
    for gname, flag in sorted(name_flags.items()):
        if gname not in globals_:
            continue
        idx = g_names.index(gname)
        leaf = arg_leaves.get(idx)
        if leaf is None:
            import warnings
            warnings.warn(
                f"lift_c: __xMR annotation on global {gname!r} could not "
                "be mapped to a state leaf (the value is transformed "
                "before its first loop use); the region default applies",
                stacklevel=2)
            continue
        global_leaves.add(leaf)
        if region.spec[leaf].kind == KIND_RO:
            continue                      # unwritten: never cloned
        if region.spec[leaf].xmr is None:     # explicit API override wins
            region.spec[leaf] = _dc.replace(region.spec[leaf], xmr=flag)
    # Every GLOBAL's leaf (annotated or not) keeps its own scope: the
    # function-level blanket below covers only the machinery derived
    # from function LOCALS -- an unannotated global under
    # __DEFAULT_NO_xMR stays unprotected, as in the reference.
    all_global_leaves = {arg_leaves[g_names.index(n)]
                         for n in g_names
                         if g_names.index(n) in arg_leaves}
    fn_flags = [f for n, f in name_flags.items() if n in funcs]
    if fn_flags and all(fn_flags):
        # Every annotated function is __xMR (and at least one is): the
        # stepped machinery derived from their locals is inside the
        # sphere of replication.
        for leaf, spec in region.spec.items():
            if leaf in all_global_leaves or spec.kind == KIND_RO:
                continue
            if spec.xmr is None:
                region.spec[leaf] = _dc.replace(spec, xmr=True)
    elif fn_flags:
        # Mixed / __NO_xMR function scopes cannot be attributed to
        # individual leaves (locals from different functions fuse into
        # one stepped machinery); never drop annotations silently.
        import warnings
        warnings.warn(
            "lift_c: mixed function-level __xMR/__NO_xMR annotations "
            "cannot be lowered per-function (their locals fuse into one "
            "stepped machinery); the region default applies to "
            "machinery leaves.  Annotate globals, or split the scopes "
            "with lift_fn annotations.", stacklevel=2)
    region.validate()
    return region
