"""Restricted-C frontend: ingest reference benchmark sources directly.

The reference protects arbitrary programs handed to ``opt`` as LLVM IR
(cloning.cpp:62-288); its benchmarks are C files under tests/.  This
module closes the ingestion boundary at demo scale (SURVEY §7's
``-replicateTarget=tpu`` fallback, "a source-level frontend for the
benchmarks"): it parses a restricted C subset with pycparser, compiles
the AST to a jittable JAX function (globals become function inputs,
``printf`` arguments become observed outputs), and hands that function
to ``lift_fn`` -- so every top-level C loop becomes a stepped phase of
the derived Region and the whole existing protection/injection stack
applies unchanged.

Supported subset (enough for tests/mm_common/mm.c and friends; refusals
are loud and name the construct):

  * global scalars/arrays of 32-bit integer types, with initializers;
  * ``typedef`` of integer types; ``#define NAME literal``;
  * functions with int parameters/locals, ``for`` loops (any bounds --
    statically-counted loops lower to ``lax.scan``, general ones to
    ``lax.while_loop``), ``if``/``else``, ternaries, assignments
    (including ``+=`` family, ``++``/``--``), array subscripts,
    integer arithmetic/bitwise/comparison ops, calls to other functions
    defined in the same translation unit, and ``printf`` (its arguments
    become program outputs -- the reference's QEMU loop greps stdout, so
    stdout IS the observable; prints must sit OUTSIDE loops/branches,
    where the printed value is a well-defined program output);
  * narrow integer types (char/short/uint8_t/uint16_t): modeled with
    exact C value semantics -- values live promoted in int32 lanes and
    every store/cast re-normalizes (mask + sign-extend), so byte/short
    wraparound (CRC state machines) is bit-exact; memory LAYOUT stays
    one lane word per element (the word-addressed injection model;
    bits above the declared width are masked at read, since they do
    not exist in real byte memory);
  * pointer parameters walked over a global array (``*p++``, ``p[i]``
    after ``p++``, ``p + k``, ``p = p + 1``), char-pointer globals
    initialized with a string literal (crc16.c's message), LOCAL
    pointer variables bound to arrays (``char *p = s;`` incl. through
    pointer casts), and deref stores (``*p++ = c``) -- a pointer is an
    int32 walk cursor over its aliased array;
  * caller-LOCAL arrays passed by reference (sha256.c's
    ``sha256_hash(data, bitlen, state, ...)``): modeled as
    copy-in/copy-out through a transient slot, sound because the
    subset has no overlapping aliases;
  * local array declarations (``uint32_t m[64]``), function-like
    macros with continuation lines (ROTRIGHT, DBL_INT_ADD), comma
    expressions in ``for`` init/next, character constants;
  * ``while``/``for`` conditions with side effects (``while
    (length--)``) via a rotated loop lowering; the run-once
    ``while (1) { ...; break; }`` idiom; mid-loop conditional breaks
    (``if (c) break;`` -- lowered to a carried flag with exact C
    semantics: the broken-out iteration skips the rest of the body AND
    the for-next); structured early ``return``s anywhere in a function
    (carried flag pair, same masking discipline; a printf AFTER an
    early-return point refuses loudly -- whether it prints would be
    data-dependent, so it cannot be a fixed program output) -- other
    break/goto placements refuse loudly;
  * COAST.h annotation macros are stripped and recorded
    (``__DEFAULT_NO_xMR``, ``__xMR``, ``__NO_xMR``).

Integer model: ILP32, matching the reference's Cortex-A9/MSP430 targets
-- ``int``/``long``/pointers-free code where ``unsigned long`` is 32
bits.  All arithmetic is mod-2^32 (uint32) or int32.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.frontend.lifter import LiftError, lift_fn
from coast_tpu.ir.region import LeafSpec, Region

try:
    from pycparser import c_ast, c_parser
    _HAVE_PYCPARSER = True
except Exception:  # pragma: no cover - pycparser ships with cffi
    _HAVE_PYCPARSER = False


from coast_tpu.frontend.c_types import (           # noqa: F401  (re-export)
    _PRINT_BUF_WORDS, CLiftError, _C64, _CType, _CType64, _NoPrintList,
    _Scope, _c64_add,
    _c64_divmod, _c64_lt, _c64_mul, _c64_neg, _c64_shl, _c64_shr,
    _const_int, _ctype_of, _mulhi_u32, _to64)
from coast_tpu.frontend.c_preproc import (         # noqa: F401  (re-export)
    _COAST_MACROS, _COAST_STRIP_CALLS, _COAST_STRIP_TOKENS, _PRELUDE,
    _strip_comments, preprocess)
from coast_tpu.frontend.c_eval import _EvalMixin
from coast_tpu.frontend.c_flow import _FlowMixin


class _Compiler(_EvalMixin, _FlowMixin):
    def __init__(self, tu, typedefs, funcs, name: str,
                 g_ctypes: Optional[Dict[str, _CType]] = None,
                 g_ptrs: Optional[set] = None):
        self.tu = tu
        self.typedefs = typedefs
        self.funcs = funcs
        self.name = name
        self.g_ctypes = dict(g_ctypes or {})
        # Global pointer variables: their int32 CURSOR lives in the
        # globals dict (runtime, injectable state); the aliased base
        # array is static, resolved at the first seating and required
        # to stay the same (motion's ld_Rdptr over ld_Rdbfr).
        self.g_ptrs: set = set(g_ptrs or ())
        self.g_ptr_base: Dict[str, str] = {}
        self._tmp = 0          # transient copy-in/out slot counter
        # id(node) -> reason, for synthesized guard Ifs whose printf
        # refusal should name the REAL construct (pycparser nodes have
        # __slots__, so no attribute can be set on them).
        self._synth_reason = {}
        # Desugar pre-pass state (switch / do-while / while(1)-unroll /
        # branch print slots), memoized per function definition.
        self._desugared: set = set()
        self._print_slots: Dict[int, List[Tuple[str, int]]] = {}
        self._sw_temps: Dict[int, List[str]] = {}
        self._assigned_globals_cache: Dict[int, List[str]] = {}
        self.print_strings: List[str] = []     # slot id -> format string

    def _run_function(self, fndef, args, outer_sc: _Scope,
                      arg_consts: Optional[List[Optional[int]]] = None):
        self._desugar_fn(fndef)
        fid = id(fndef)
        sc = _Scope(outer_sc.g, self.g_ctypes)
        sc.printed = outer_sc.printed       # printf threads through
        # Known-constant GLOBALS flow into the callee (locals shadowing
        # a global keep their constness out of it).
        sc.consts = {n: v for n, v in outer_sc.consts.items()
                     if n not in outer_sc.locals}
        for nm, _k in self._print_slots.get(fid, ()):
            sc.locals[nm] = jnp.int32(-1)   # -1 = this line never printed
            sc.consts[nm] = -1
        for nm in self._sw_temps.get(fid, ()):
            sc.locals[nm] = jnp.int32(0)
            sc.consts.pop(nm, None)
        params = []
        decl = fndef.decl.type
        if decl.args:
            params = [p for p in decl.args.params
                      if not isinstance(p, c_ast.EllipsisParam)
                      and getattr(p, "name", None) is not None]
            if getattr(fndef, "param_decls", None):
                # K&R-style definition (blowfish's OpenSSL-vintage
                # `void BF_encrypt(data, key) BF_LONG *data; ...`):
                # the identifier list carries bare IDs; the real Decls
                # live in param_decls.
                by_name = {d.name: d for d in fndef.param_decls}
                params = [by_name.get(p.name, p) for p in params]
        if len(params) != len(args):
            raise CLiftError(
                f"{fndef.decl.name}: {len(args)} args for {len(params)} "
                "parameters (array parameters pass the global by name)")
        walked = self._walked_names(fndef.body)
        copy_backs: List[Tuple[str, str]] = []
        scalar_backs: List[Tuple[str, str]] = []
        g_scalar_backs: List[Tuple[str, str, object]] = []
        for pi, (p, a) in enumerate(zip(params, args)):
            if (isinstance(a, tuple) and len(a) == 2
                    and a[0] == "__alias_scalar_global__"):
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                gv = sc.g[a[1]]
                sc.g[temp] = jnp.reshape(gv, (1,))
                oct_ = self.g_ctypes.get(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                sc.locals[p.name] = jnp.int32(0)
                g_scalar_backs.append((temp, a[1], gv.dtype))
                continue
            if (isinstance(a, tuple) and len(a) == 2
                    and a[0] == "__alias_scalar_local__"):
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                val0 = outer_sc.locals[a[1]]
                sc.g[temp] = (jnp.stack([val0.lo, val0.hi]).reshape(1, 2)
                              if isinstance(val0, _C64)
                              else jnp.reshape(val0, (1,)))
                oct_ = outer_sc.ctype(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                sc.locals[p.name] = jnp.int32(0)
                scalar_backs.append((temp, a[1]))
                continue
            if isinstance(a, tuple) and a[0] == "__alias_local_off__":
                # Caller-local array element address: transient slot
                # with the cursor starting at the element's offset.
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                sc.g[temp] = outer_sc.locals[a[1]]
                oct_ = outer_sc.ctype(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                sc.locals[p.name] = jnp.asarray(a[2], jnp.int32)
                copy_backs.append((temp, a[1]))
                continue
            if (isinstance(a, tuple) and len(a) == 2
                    and a[0] == "__alias_local__"):
                # Caller-local array passed by reference: copy into a
                # transient slot of the (shared) globals dict, alias the
                # parameter to it, and copy back after the body runs.
                temp = f"__loc{self._tmp}"
                self._tmp += 1
                sc.g[temp] = outer_sc.locals[a[1]]
                oct_ = outer_sc.ctype(a[1])
                if oct_ is not None:
                    sc.ctypes[temp] = oct_
                sc.aliases[p.name] = temp
                copy_backs.append((temp, a[1]))
                if p.name in walked:
                    sc.locals[p.name] = jnp.int32(0)
                continue
            if isinstance(a, tuple) and a[0] == "__alias_off__":
                # Forwarded pointer: alias the base, start the cursor at
                # the caller's offset.
                sc.aliases[p.name] = a[1]
                sc.locals[p.name] = jnp.asarray(a[2], jnp.int32)
            elif isinstance(a, tuple) and len(a) == 2 \
                    and a[0] == "__alias__":
                sc.aliases[p.name] = a[1]
                if p.name in walked:
                    # The body does pointer arithmetic on this parameter
                    # (``p++``): give it a walk cursor, carried like any
                    # other local through the body's loops.
                    sc.locals[p.name] = jnp.int32(0)
            else:
                ct = (_ctype_of(getattr(p.type.type, "names", ["int"]),
                                self.typedefs)
                      if isinstance(p.type, c_ast.TypeDecl) else None)
                if ct is not None:
                    sc.locals[p.name] = ct.store(a)
                    sc.ctypes[p.name] = ct
                else:
                    sc.locals[p.name] = a
                kc = arg_consts[pi] if arg_consts else None
                self._const_set(sc, p.name, kc,
                                ct if not isinstance(ct, _CType64)
                                else None)
        # Function-wide pointer pre-seating: a pointer seated over
        # DIFFERENT arrays in different loops (ChenIDct's aptr over x
        # then y) must take its union alias before the first loop
        # traces, not per-loop.
        self._preseat(fndef.body, sc)
        new_items, set_n, val_n, synth = self._rewrite_early_returns(fndef)
        if new_items is not None:
            rett = fndef.decl.type.type
            rct = (_ctype_of(getattr(rett.type, "names", ["int"]),
                             self.typedefs)
                   if isinstance(rett, c_ast.TypeDecl) else None)
            for n in synth:
                if n == val_n and rct is not None:
                    # The carried return value takes the declared return
                    # type from the start: every `return E` then
                    # converts E at the store (C semantics), and a
                    # 64-bit return stays a limb pair across cond
                    # branches (pytree consistency).
                    sc.locals[n] = rct.zero()
                    sc.ctypes[n] = rct
                    if isinstance(rct, _CType64):
                        sc.consts.pop(n, None)
                    else:
                        sc.consts[n] = 0
                else:
                    sc.locals[n] = jnp.int32(0)
                    sc.consts[n] = 0
            self._exec_block(
                c_ast.Compound(new_items, fndef.body.coord), sc)
            ret = sc.locals[val_n]
        else:
            ret = self._exec_block(fndef.body, sc)
        for temp, lname in copy_backs:
            outer_sc.locals[lname] = sc.g.pop(temp)
        for temp, gname, dt in g_scalar_backs:
            slot = sc.g.pop(temp)
            sc.g[gname] = jnp.reshape(slot, ()).astype(dt)
            outer_sc.consts.pop(gname, None)
        for temp, lname in scalar_backs:
            slot = sc.g.pop(temp)
            oct_ = outer_sc.ctype(lname)
            if isinstance(oct_, _CType64):
                pair = slot.reshape(-1, 2)[0]
                outer_sc.locals[lname] = _C64(pair[0], pair[1],
                                              oct_.unsigned)
            else:
                outer_sc.locals[lname] = jnp.reshape(slot, ())
            outer_sc.consts.pop(lname, None)   # written via the slot
        # Global constness after the call: invalidate exactly the
        # globals the callee may write (a callee-LOCAL shadowing a
        # global -- AddRoundKey's `int j, nb;` -- must not kill the
        # caller's knowledge of the global), then flow the callee's
        # known globals back (its view of its own writes is the truth).
        may_write = set(self._assigned_globals(fndef))
        for n in list(outer_sc.consts):
            if n not in outer_sc.locals and n in may_write:
                outer_sc.consts.pop(n, None)
        for n, v in sc.consts.items():
            if n not in sc.locals and n not in outer_sc.locals:
                outer_sc.consts[n] = v
        # A function's print slots join the output surface when it
        # returns.  At a traced call site (inside a loop/branch) the
        # slots flow into the UART buffer when the program has one --
        # only slots that actually fired (id >= 0) append -- otherwise
        # the printed sentinel refuses, as for any in-loop print.
        for nm, _k in self._print_slots.get(fid, ()):
            v = jnp.asarray(sc.locals[nm])
            if (isinstance(sc.printed, _NoPrintList)
                    and "__print_buf" in sc.g):
                buf = sc.g["__print_buf"]
                cnt = sc.g["__print_cnt"]
                fired = v >= 0
                idx = jnp.clip(cnt, 0, _PRINT_BUF_WORDS - 1)
                keep = jnp.logical_and(fired, cnt < _PRINT_BUF_WORDS)
                buf = buf.at[idx].set(
                    jnp.where(keep, v.astype(jnp.uint32), buf[idx]))
                cnt = cnt + fired.astype(jnp.int32)
                sc.g["__print_buf"] = buf
                sc.g["__print_cnt"] = cnt
            else:
                sc.printed.append(v)
        if ret is None:
            return jnp.int32(0)
        # C return-value conversion: the value converts to the declared
        # return type (a narrow return like TI_aes_128.c's galois_mul2
        # 'unsigned char' drops bit 8 HERE, not at some later store).
        rett = fndef.decl.type.type
        if isinstance(rett, c_ast.TypeDecl):
            ct = _ctype_of(getattr(rett.type, "names", ["int"]),
                           self.typedefs)
            ret = ct.store(ret)
        return ret

    # -- statements --------------------------------------------------------
    def _exec_block(self, block, sc: _Scope):
        if block is None:
            return None
        items = block.block_items or [] if isinstance(
            block, c_ast.Compound) else [block]
        for stmt in items:
            ret = self._exec_stmt(stmt, sc)
            if ret is not None:
                return ret
        return None

    def _exec_stmt(self, stmt, sc: _Scope):
        if isinstance(stmt, c_ast.Decl):
            if isinstance(stmt.type, c_ast.ArrayDecl):
                # Local array: zeros or element-wise initializer list.
                dims, t = [], stmt.type
                while isinstance(t, c_ast.ArrayDecl):
                    n = _const_int(t.dim)
                    if n is None:
                        if (t.dim is None and not dims
                                and isinstance(stmt.init, c_ast.InitList)):
                            n = len(stmt.init.exprs)   # char key[] = {..}
                        else:
                            raise CLiftError(
                                f"non-literal local array dim for "
                                f"{stmt.name} at {stmt.coord}")
                    dims.append(n)
                    t = t.type
                ct = _ctype_of(getattr(t.type, "names", ["int"]),
                               self.typedefs)
                if isinstance(ct, _CType64):
                    raise CLiftError(
                        f"long long array {stmt.name!r} at {stmt.coord}: "
                        "64-bit elements are outside the word-addressed "
                        "memory model (locals only)")
                arr = jnp.zeros(tuple(dims), ct.dtype)
                if stmt.init is not None:
                    if not isinstance(stmt.init, c_ast.InitList):
                        raise CLiftError(
                            f"unsupported local array initializer at "
                            f"{stmt.coord}")
                    flat = arr.reshape(-1)
                    exprs = list(stmt.init.exprs)
                    for k, e in enumerate(exprs):
                        flat = flat.at[k].set(
                            ct.store(self.eval(e, sc)).astype(ct.dtype))
                    arr = flat.reshape(tuple(dims))
                sc.locals[stmt.name] = arr
                sc.ctypes[stmt.name] = ct
                return None
            if isinstance(stmt.type, c_ast.PtrDecl):
                # Local pointer: binds to (global-or-copied array, offset).
                sc.ptrs.add(stmt.name)
                if stmt.init is None:
                    # Declared-but-unbound: a bare cursor with no alias
                    # until `p = arr;` re-seats it (adpcm.c's h_ptr);
                    # any deref before that fails loudly.  A function-
                    # wide pre-seat may already have aliased it.
                    sc.locals.setdefault(stmt.name, jnp.int32(0))
                    return None
                base, off = self._ptr_parts(stmt.init, sc)
                union = self._union_bases(sc.aliases.get(stmt.name))
                if union is not None and not isinstance(base, tuple):
                    off = (self._union_offset(sc, union, base)
                           + jnp.asarray(off, jnp.int32))
                else:
                    sc.aliases[stmt.name] = base
                sc.locals[stmt.name] = off
                return None
            ct = _ctype_of(getattr(stmt.type.type, "names", ["int"]),
                           self.typedefs)
            val = (ct.store(self.eval(stmt.init, sc))
                   if stmt.init is not None else ct.zero())
            sc.locals[stmt.name] = val
            sc.ctypes[stmt.name] = ct
            if isinstance(ct, _CType64):
                sc.consts.pop(stmt.name, None)
            else:
                # The model zero-initializes declared scalars, so a
                # no-init local IS the constant 0 at this point.
                self._const_set(
                    sc, stmt.name,
                    0 if stmt.init is None
                    else self._const_eval(stmt.init, sc), ct)
            return None
        if isinstance(stmt, c_ast.DeclList):
            for d in stmt.decls:
                self._exec_stmt(d, sc)
            return None
        if isinstance(stmt, c_ast.Assignment):
            self._assign(stmt, sc)
            return None
        if isinstance(stmt, (c_ast.UnaryOp, c_ast.FuncCall, c_ast.ExprList)):
            self.eval(stmt, sc)
            return None
        if isinstance(stmt, c_ast.If):
            return self._exec_if(stmt, sc)
        if isinstance(stmt, c_ast.For):
            return self._exec_for(stmt, sc)
        if isinstance(stmt, c_ast.While):
            return self._exec_while(stmt, sc)
        if isinstance(stmt, c_ast.Return):
            return (self.eval(stmt.expr, sc) if stmt.expr is not None
                    else jnp.int32(0))
        if isinstance(stmt, c_ast.Compound):
            return self._exec_block(stmt, sc)
        if isinstance(stmt, c_ast.EmptyStatement):
            return None
        raise CLiftError(
            f"unsupported statement {type(stmt).__name__} at {stmt.coord}")

    @staticmethod
    def _base_ids(expr) -> List[str]:
        """Base identifiers a pointer-valued expression could alias
        (static over-approximation for carry discovery)."""
        out: List[str] = []
        stack = [expr]
        while stack:
            e = stack.pop()
            if isinstance(e, c_ast.ID):
                out.append(e.name)
            elif isinstance(e, c_ast.Cast):
                stack.append(e.expr)
            elif isinstance(e, c_ast.UnaryOp) and e.op in ("&", "++", "p++",
                                                           "--", "p--"):
                stack.append(e.expr)
            elif isinstance(e, c_ast.ArrayRef):
                stack.append(e.name)
            elif isinstance(e, c_ast.BinaryOp) and e.op in ("+", "-"):
                stack.extend((e.left, e.right))
        return out

    def _assigned_names(self, node) -> List[str]:
        """Names written anywhere under ``node`` (loop-carry discovery).

        Local POINTERS complicate this: a deref-store ``*p = v`` writes
        the array ``p`` is seated on, so the seated base names (from
        ``T *p = arr;`` declarations and ``p = arr;`` re-seatings in the
        same subtree) are added for every deref-written pointer --
        without them, a callee that walks a global through a local
        pointer (adpcm.c's encode/decode delay lines) would not carry
        that global through the CALLER's loop, silently freezing it."""
        names: List[str] = []
        ptr_decls: set = set()
        seats: Dict[str, List[str]] = {}
        deref_targets: List[str] = []

        class V(c_ast.NodeVisitor):
            def visit_Assignment(v, n):
                t = n.lvalue
                derefed = False
                while isinstance(t, (c_ast.ArrayRef, c_ast.UnaryOp)):
                    # Unwrap a[i]... and deref lvalues (*p = v writes both
                    # the pointee and, via the walk machinery, p's cursor).
                    derefed = True
                    t = t.name if isinstance(t, c_ast.ArrayRef) else t.expr
                if isinstance(t, c_ast.ID):
                    names.append(t.name)
                    if t.name.startswith("__print_sel_"):
                        # Desugared branch print: its slot flows into
                        # the UART buffer at function end.
                        names.extend(["__print_buf", "__print_cnt"])
                    if derefed:
                        deref_targets.append(t.name)
                    elif n.op == "=":
                        seats.setdefault(t.name, []).extend(
                            _Compiler._base_ids(n.rvalue))
                v.generic_visit(n)

            def visit_UnaryOp(v, n):
                if n.op in ("++", "p++", "--", "p--"):
                    t = n.expr
                    while isinstance(t, c_ast.ArrayRef):
                        t = t.name
                    if isinstance(t, c_ast.ID):
                        names.append(t.name)
                v.generic_visit(n)

            def visit_Decl(v, n):
                if n.name:
                    names.append(n.name)
                    if isinstance(n.type, c_ast.PtrDecl):
                        ptr_decls.add(n.name)
                        if n.init is not None:
                            seats.setdefault(n.name, []).extend(
                                _Compiler._base_ids(n.init))
                v.generic_visit(n)

            def visit_FuncCall(v, n):
                # A called function may write globals directly or through
                # an array-pointer parameter; treat ID arguments bound to
                # POINTER/ARRAY parameters (and every callee-assigned
                # name) as written.  Scalar by-value parameters cannot
                # write the caller's variable -- and carrying them would
                # also destroy trace-time concreteness (aes_enc.c's `nb`
                # must stay concrete through the rounds loop for the
                # ciphertext print loop's static bound).
                if isinstance(n.name, c_ast.ID):
                    if n.name.name == "printf":
                        # printf only READS its arguments -- but under
                        # the UART-buffer model it writes the buffer.
                        names.extend(["__print_buf", "__print_cnt"])
                        v.generic_visit(n)
                        return
                    if n.name.name == "exit":
                        # exit() writes the poison observable; without
                        # this the write would die in a branch fork.
                        names.append("__exit_state")
                    callee = self.funcs.get(n.name.name)
                    params = []
                    if (callee is not None
                            and not getattr(callee, "param_decls", None)):
                        decl = callee.decl.type
                        if decl.args:
                            params = [p for p in decl.args.params
                                      if not isinstance(
                                          p, c_ast.EllipsisParam)]
                    for ai, a in enumerate(n.args.exprs if n.args else []):
                        if isinstance(a, c_ast.UnaryOp) and a.op == "&":
                            # Out-parameter (&aSig): the callee writes
                            # through it -- the pointee is written.
                            names.extend(_Compiler._base_ids(a))
                            continue
                        if isinstance(a, c_ast.ArrayRef):
                            # Sub-array argument (PMV[0][s]) decays to a
                            # pointer; conservatively count the base as
                            # written -- unless the callee's parameter
                            # is a by-value scalar (full indexing).
                            if params and ai < len(params):
                                pt = getattr(params[ai], "type", None)
                                if not isinstance(pt, (c_ast.PtrDecl,
                                                       c_ast.ArrayDecl)):
                                    continue
                            t2 = a
                            while isinstance(t2, c_ast.ArrayRef):
                                t2 = t2.name
                            if isinstance(t2, c_ast.ID):
                                names.append(t2.name)
                            continue
                        if not isinstance(a, c_ast.ID):
                            continue
                        if params and ai < len(params):
                            pt = getattr(params[ai], "type", None)
                            if not isinstance(pt, (c_ast.PtrDecl,
                                                   c_ast.ArrayDecl)):
                                continue    # by-value scalar
                        names.append(a.name)
                    if callee is not None:
                        names.extend(self._assigned_globals(callee))
                v.generic_visit(n)

        V().visit(node)
        # Deref-written pointers write their seated arrays.  A GLOBAL
        # pointer seated outside the analyzed node (gp = A before the
        # loop, gp[i] = v inside it) has no local seat entry; its
        # statically-known candidate bases stand in -- without them the
        # written array would drop out of a scan's carry.
        for p in dict.fromkeys(deref_targets):
            names.extend(seats.get(p, ()))
            if p in self.g_ptrs and p not in seats:
                names.extend(sorted(self._g_ptr_static_bases(p)))
        return list(dict.fromkeys(names))

    def _g_ptr_static_base(self, name: str) -> Optional[str]:
        """Static whole-program resolution of a global pointer's base:
        the single base array every seating agrees on (None if
        unseated/ambiguous)."""
        bases = self._g_ptr_static_bases(name)
        return next(iter(bases)) if len(bases) == 1 else None

    def _g_ptr_static_bases(self, name: str) -> frozenset:
        """ALL candidate base arrays a global pointer's seatings alias:
        scan every function for `name = <expr>` seatings, collapsing
        cursor-on-cursor chains.  Empty if never seated."""
        cache = getattr(self, "_g_ptr_seat_cache", None)
        if cache is None:
            cache = {}
            comp = self

            class V(c_ast.NodeVisitor):
                def visit_Assignment(v, n):
                    if (n.op == "=" and isinstance(n.lvalue, c_ast.ID)
                            and n.lvalue.name in comp.g_ptrs):
                        for b in comp._base_ids(n.rvalue):
                            if b != n.lvalue.name:
                                cache.setdefault(n.lvalue.name,
                                                 set()).add(b)
                    v.generic_visit(n)

            for fn in self.funcs.values():
                V().visit(fn.body)
            self._g_ptr_seat_cache = cache
        bases = cache.get(name)
        # Cursors seated on one another (ld_Rdmax = ld_Rdptr) collapse
        # through the other pointer's bases.
        for _ in range(4):
            if not bases:
                return frozenset()
            flat = set()
            again = False
            for b in bases:
                if b in self.g_ptrs:
                    sub = cache.get(b)
                    if sub:
                        flat |= sub
                        again = True
                else:
                    flat.add(b)
            bases = flat
            if not again:
                break
        return frozenset(bases)

    def _assigned_globals(self, fndef) -> List[str]:
        """Names a callee writes OUTSIDE its own scope: its assigned
        names minus its params and local declarations.  A callee-local
        shadowing a global (AddRoundKey's `int j, nb;` vs the global
        nb) must not count as a caller-side write -- it would both
        over-carry and invalidate constant propagation."""
        fid = id(fndef)
        cached = self._assigned_globals_cache.get(fid)
        if cached is not None:
            return cached
        self._assigned_globals_cache[fid] = []     # cut recursion cycles
        names = self._assigned_names(fndef.body)
        local: set = set()
        decl = fndef.decl.type
        if decl.args:
            for p in decl.args.params:
                nm = getattr(p, "name", None)
                if nm:
                    local.add(nm)

        class V(c_ast.NodeVisitor):
            def visit_Decl(v, n):
                if n.name:
                    local.add(n.name)
                v.generic_visit(n)

        V().visit(fndef.body)
        out = [n for n in names if n not in local]
        self._assigned_globals_cache[fid] = out
        return out

    def written_globals(self, fndef, g_names, subst=None):
        """Globals (transitively) written by ``fndef``, following array-
        argument aliasing: a callee's writes through an array parameter
        count against the global the caller passed."""
        subst = subst or {}
        out = set()
        comp = self

        # Local pointer variables (char *p = s;) route stores to their
        # target: track Decl-time bindings AND later re-seatings
        # (``p1 = (LONG *)s1;``) so deref stores through them count
        # against the right global (chains and casts included).
        local_ptr: Dict[str, str] = {}
        ptr_names: set = set()
        multi_seats: Dict[str, set] = {}        # union-pointer candidates

        def resolve(nm):
            for _ in range(8):
                if nm in local_ptr:
                    nm = local_ptr[nm]
                    continue
                if nm in comp.g_ptrs:
                    base = comp._g_ptr_static_base(nm)
                    if base is not None and base != nm:
                        nm = base
                        continue
                break
            return subst.get(nm, nm)

        def resolve_all(nm):
            """Every base a store through ``nm`` may write.  Unlike
            ``resolve``, an AMBIGUOUS global-pointer seating (gp = A in
            one function, gp = B in another) unions every candidate:
            conservatively over-reporting keeps injections into the
            really-written array out of the masked bucket."""
            out_s: set = set()
            frontier, seen = {nm}, set()
            for _ in range(8):
                nxt: set = set()
                for x in frontier:
                    if x in seen:
                        continue
                    seen.add(x)
                    if x in local_ptr:
                        nxt.add(local_ptr[x])
                        continue
                    if x in comp.g_ptrs:
                        bases = comp._g_ptr_static_bases(x) - {x}
                        if bases:
                            nxt.update(bases)
                            continue
                    out_s.add(subst.get(x, x))
                if not nxt:
                    break
                frontier = nxt
            return out_s

        def targets_of(t):
            while isinstance(t, (c_ast.ArrayRef, c_ast.UnaryOp)):
                t = t.name if isinstance(t, c_ast.ArrayRef) else t.expr
            if isinstance(t, c_ast.ID):
                return resolve_all(t.name)
            return set()

        def seat_base(expr):
            """First base identifier a seating RHS aliases, resolved."""
            for cand in _Compiler._base_ids(expr):
                r = resolve(cand)
                if r in g_names or cand in local_ptr or cand in subst:
                    return cand if cand in local_ptr else r
            return None

        class V(c_ast.NodeVisitor):
            def visit_Decl(v, n):
                if isinstance(n.type, c_ast.PtrDecl):
                    ptr_names.add(n.name)
                    if n.init is not None:
                        e = n.init
                        while isinstance(e, c_ast.Cast):
                            e = e.expr
                        if isinstance(e, c_ast.ID):
                            local_ptr[n.name] = e.name
                v.generic_visit(n)

            def visit_Assignment(v, n):
                # Reseating a pointer (``p = p + 1``, ``p1 = (LONG*)s1``,
                # parameter or local pointer variable) writes the walk
                # cursor / rebinds the alias, not the pointed-to global;
                # only element stores (ArrayRef/deref lvalues) write the
                # array.  Record the re-seating so later deref stores
                # route to the right base.
                if (isinstance(n.lvalue, c_ast.ID)
                        and (n.lvalue.name in subst
                             or n.lvalue.name in local_ptr
                             or n.lvalue.name in ptr_names)):
                    if n.op == "=":
                        base = seat_base(n.rvalue)
                        if base is not None and base != n.lvalue.name:
                            local_ptr[n.lvalue.name] = base
                            r = resolve(n.lvalue.name)
                            if r in g_names:
                                multi_seats.setdefault(
                                    n.lvalue.name, set()).add(r)
                    v.generic_visit(n)
                    return
                out.update(t for t in targets_of(n.lvalue)
                           if t in g_names)
                # A deref store through a MULTI-seated (union) pointer
                # may write any of its candidate bases.
                t2 = n.lvalue
                derefed = False
                while isinstance(t2, (c_ast.ArrayRef, c_ast.UnaryOp)):
                    derefed = True
                    t2 = (t2.name if isinstance(t2, c_ast.ArrayRef)
                          else t2.expr)
                if (derefed and isinstance(t2, c_ast.ID)
                        and len(multi_seats.get(t2.name, ())) > 1):
                    out.update(multi_seats[t2.name])
                v.generic_visit(n)

            def visit_UnaryOp(v, n):
                if n.op in ("++", "p++", "--", "p--"):
                    # Same rule: ++/-- on a bare pointer ID is cursor
                    # arithmetic.
                    if (isinstance(n.expr, c_ast.ID)
                            and (n.expr.name in subst
                                 or n.expr.name in local_ptr)):
                        return
                    out.update(t for t in targets_of(n.expr)
                               if t in g_names)
                v.generic_visit(n)

            def visit_FuncCall(v, n):
                if isinstance(n.name, c_ast.ID):
                    if (n.name.name == "exit"
                            and "__exit_state" in g_names):
                        out.add("__exit_state")
                    if n.name.name == "printf":
                        out.update({"__print_buf", "__print_cnt"}
                                   & set(g_names))
                    callee = comp.funcs.get(n.name.name)
                    if callee is not None:
                        decl = callee.decl.type
                        params = ([p.name for p in decl.args.params
                                   if not isinstance(p, c_ast.EllipsisParam)
                                   and p.name is not None]
                                  if decl.args else [])
                        sub2 = {}
                        args = n.args.exprs if n.args else []
                        for p, a in zip(params, args):
                            if isinstance(a, c_ast.ID):
                                tgt = resolve(a.name)
                                if tgt in g_names:
                                    sub2[p] = tgt
                            elif (isinstance(a, c_ast.UnaryOp)
                                    and a.op == "&"):
                                # &global out-param: the callee may
                                # write the pointee.
                                for b in comp._base_ids(a):
                                    if resolve(b) in g_names:
                                        out.add(resolve(b))
                        out.update(comp.written_globals(
                            callee, g_names, sub2))
                v.generic_visit(n)

        V().visit(fndef.body)
        return out

    @staticmethod
    def _union_bases(alias) -> Optional[Tuple[str, ...]]:
        """The member tuple of a union alias, or None for plain ones."""
        return alias if isinstance(alias, tuple) else None

    def _union_offset(self, sc: _Scope, bases: Tuple[str, ...],
                      member: str):
        off = 0
        for b in bases:
            if b == member:
                return jnp.int32(off)
            off += int(np.prod(jnp.shape(sc.g[b])))
        raise CLiftError(
            f"array {member!r} is not a member of the union pointer "
            f"over {bases}")

    def _union_read(self, sc: _Scope, bases: Tuple[str, ...]):
        return jnp.concatenate([sc.g[b].reshape(-1) for b in bases])

    def _union_write(self, sc: _Scope, bases: Tuple[str, ...],
                     flat) -> None:
        off = 0
        for b in bases:
            n = int(np.prod(jnp.shape(sc.g[b])))
            sc.write_binding(b, flat[off:off + n].reshape(
                jnp.shape(sc.g[b])))
            off += n

    def _preseat(self, node, sc: _Scope) -> None:
        """Seat outer-declared pointers whose FIRST seating happens inside
        ``node`` (a loop body or branch) before tracing it: the alias map
        is trace-time state, so the seating must be hoisted.  A single
        static base seats plainly; MULTIPLE same-dtype candidate bases
        (jpeg's huffman tables: `p = ac_tbl[i]` in one branch,
        `p = dc_tbl[i]` in the other) seat as a UNION pointer -- the
        cursor indexes the concatenation of the members, reads gather
        from it, writes split back, so the runtime branch merely picks
        the cursor's segment.  Anything else is left for _guard_reseat's
        loud refusal."""
        seats: Dict[str, List[str]] = {}
        decl_ptrs: set = set()

        class V(c_ast.NodeVisitor):
            def visit_Assignment(v, n):
                if n.op == "=" and isinstance(n.lvalue, c_ast.ID):
                    seats.setdefault(n.lvalue.name, []).extend(
                        _Compiler._base_ids(n.rvalue))
                v.generic_visit(n)

            def visit_Decl(v, n):
                if isinstance(n.type, c_ast.PtrDecl) and n.name:
                    decl_ptrs.add(n.name)
                    if n.init is not None:
                        seats.setdefault(n.name, []).extend(
                            _Compiler._base_ids(n.init))
                v.generic_visit(n)

        V().visit(node)
        for p, cands in seats.items():
            if (p not in sc.ptrs and p not in decl_ptrs) \
                    or p in sc.aliases:
                continue
            bases = {sc.aliases.get(c, c) for c in cands}
            bases = {b for b in bases
                     if b in sc.g and jnp.ndim(sc.g[b]) >= 1}
            if len(bases) == 1:
                sc.aliases[p] = bases.pop()
            elif len(bases) > 1:
                members = tuple(sorted(bases))
                dts = {sc.g[b].dtype for b in members}

                def ctkey(b):
                    ct = sc.ctypes.get(b)
                    # None and any 32-bit ctype behave identically on
                    # the lane model (no store narrowing); only NARROW
                    # members must match exactly.  64-bit members never
                    # unify (the limb-pair access paths do not speak
                    # unions) -- a unique key forces the loud
                    # _guard_reseat refusal instead.
                    if ct is not None and ct.bits == 64:
                        return ("w64", b)
                    if ct is None or ct.bits == 32:
                        return "w32"
                    return (ct.dtype, ct.bits, ct.unsigned)

                if len(dts) == 1 and len({ctkey(b) for b in members}) == 1:
                    sc.aliases[p] = members

    def _guard_reseat(self, sc, sub, coord):
        """Refuse pointer re-seating to a DIFFERENT array inside a traced
        sub-region (loop body/branch): the aliased base is resolved at
        trace time, so a per-iteration/per-branch base change cannot be
        expressed (same-base re-seating -- a cursor reset -- is a traced
        value write and passes)."""
        for n in sc.ptrs | set(sc.aliases):
            if sub.aliases.get(n) != sc.aliases.get(n):
                raise CLiftError(
                    f"pointer {n!r} re-seated to a different array inside "
                    f"a traced branch/loop at {coord}; hoist the "
                    "re-seating or restructure")

    def _loop_carry(self, stmt, sc) -> List[str]:
        """Variables the loop body writes that already exist in scope (the
        scan/while carry); body-local declarations stay local."""
        # A name that is itself a local (incl. a pointer parameter's walk
        # cursor, which shares its name with an alias) carries as that
        # local.  A WALKED pointer name additionally carries its aliased
        # global: ``p[0] = v`` inside the loop stores into the global
        # while ``p++`` moves the cursor, and both writes must survive
        # the iteration (a read-only extra carry is loop-invariant and
        # hoisted by XLA).
        assigned: List[str] = []

        def add_alias(alias):
            if isinstance(alias, tuple):
                assigned.extend(alias)           # union: every member
            else:
                assigned.append(alias)

        for n in self._assigned_names(stmt):
            if n in sc.locals:
                assigned.append(n)
                if n in sc.aliases:
                    add_alias(sc.aliases[n])
            else:
                add_alias(sc.aliases.get(n, n))
        return [n for n in dict.fromkeys(assigned)
                if n in sc.locals or n in sc.g]


def _string_bytes(lit: str) -> List[int]:
    """Decode a C string literal (quotes included) to its bytes + NUL."""
    body = lit[1:-1]
    decoded = body.encode("utf-8").decode("unicode_escape")
    return [b for b in decoded.encode("latin-1")] + [0]


def _normalize_init(vals: np.ndarray, ct: _CType) -> np.ndarray:
    """C conversion of initializer values into the declared type's lane."""
    if ct.bits == 32:
        return (vals & 0xFFFFFFFF).astype(np.uint32)
    mask = (1 << ct.bits) - 1
    v = (vals & mask).astype(np.int64)
    if not ct.unsigned:
        sign = 1 << (ct.bits - 1)
        v = ((v ^ sign) - sign)
    return v.astype(np.int64)


def _parse_globals(tu, typedefs):
    """Global declarations -> ({name: jnp array}, {name: _CType}).

    C linkage rules across the linked TUs: an ``extern`` declaration or
    a tentative (initializer-less) definition never OVERWRITES an
    earlier entry -- a shared header included by several TUs (CHStone
    sha.h's ``extern const int in_i[VSIZE]``) must not zero out the
    defining TU's initializer, in either include order."""
    out: Dict[str, jax.Array] = {}
    ctypes: Dict[str, _CType] = {}
    inited: set = set()
    g_ptrs: set = set()          # uninitialized pointer globals (cursors)

    def flat_init(init) -> List[int]:
        if isinstance(init, c_ast.InitList):
            vals = []
            for e in init.exprs:
                vals.extend(flat_init(e))
            return vals
        v = _const_int(init)
        if v is None:
            raise CLiftError(f"unsupported global initializer at "
                             f"{init.coord}")
        return [v]

    for ext in tu.ext:
        if not isinstance(ext, c_ast.Decl) or isinstance(
                ext.type, c_ast.FuncDecl):
            continue
        t = ext.type
        shape = []
        deferred = False
        while isinstance(t, c_ast.ArrayDecl):
            n = _const_int(t.dim)
            if n is None:
                # Unsized outer dim (char key[] = {...}): C sizes it from
                # the initializer.
                if (t.dim is None and not shape
                        and isinstance(ext.init, c_ast.InitList)):
                    n = len(ext.init.exprs)
                elif t.dim is None and ext.init is None:
                    # extern/tentative unsized array (motion.h's
                    # `extern const unsigned char inRdbfr[];`): an
                    # incomplete type the defining declaration
                    # completes; defer -- never-defined names fail
                    # loudly at first read.
                    deferred = True
                    break
                else:
                    raise CLiftError(
                        f"non-literal array dim for {ext.name}")
            shape.append(n)
            t = t.type
        if deferred:
            continue
        if isinstance(t, c_ast.PtrDecl):
            # Two pointer-global shapes: a char pointer initialized with
            # a string literal (crc16.c's message) becomes the byte
            # array itself; an UNINITIALIZED pointer global (motion's
            # ld_Rdptr) becomes an int32 CURSOR global -- runtime,
            # injectable pointer state -- whose aliased base array is
            # resolved at its first seating (single static base).
            inner = t.type
            if (isinstance(inner, c_ast.TypeDecl)
                    and isinstance(ext.init, c_ast.Constant)
                    and ext.init.type == "string"):
                ct = _ctype_of(inner.type.names, typedefs)
                vals = np.array(_string_bytes(ext.init.value), np.int64)
                out[ext.name] = jnp.asarray(
                    _normalize_init(vals, ct)).astype(ct.dtype)
                ctypes[ext.name] = ct
                continue
            if ext.init is None:
                if ext.name not in out:
                    out[ext.name] = jnp.int32(0)
                    g_ptrs.add(ext.name)
                continue
            raise CLiftError(
                f"unsupported pointer global {ext.name!r} (only char* "
                "with a string-literal initializer, or an uninitialized "
                "pointer seated at runtime, is modeled)")
        if isinstance(t, c_ast.TypeDecl):
            ct = _ctype_of(t.type.names, typedefs)
            if isinstance(ct, _CType64) and not shape:
                raise CLiftError(
                    f"long long global scalar {ext.name!r}: model it as "
                    "an element of a 64-bit array (limb-pair layout) or "
                    "a local")
        else:
            raise CLiftError(f"unsupported global type for {ext.name}")
        if isinstance(ct, _CType64):
            # 64-bit ARRAY: (dims..., 2) uint32 limb pairs -- each
            # element is two 32-bit memory words (lo, hi), exactly the
            # real layout, so the word-addressed injection map holds
            # (dfmul/dfdiv test vectors).
            total = int(np.prod(shape))
            if ext.init is not None:
                vals = [(_const_int(e) if not isinstance(e, c_ast.InitList)
                         else None) for e in ext.init.exprs]
                if any(v is None for v in vals):
                    raise CLiftError(
                        f"unsupported 64-bit initializer for {ext.name}")
                vals += [0] * (total - len(vals))
                pairs = np.array([[v & 0xFFFFFFFF, (v >> 32) & 0xFFFFFFFF]
                                  for v in vals], dtype=np.uint32)
                arr = jnp.asarray(pairs).reshape(tuple(shape) + (2,))
                inited.add(ext.name)
            else:
                if ext.name in out:
                    continue
                arr = jnp.zeros(tuple(shape) + (2,), jnp.uint32)
            out[ext.name] = arr
            ctypes[ext.name] = ct
            continue
        if ext.init is not None:
            # int64 container so negative initializers wrap mod 2^32 (C
            # conversion to a 32-bit lane); partial initializer lists
            # zero-fill the tail, per C aggregate-initialization rules.
            vals = np.array(flat_init(ext.init), dtype=np.int64)
            total = int(np.prod(shape)) if shape else 1
            if len(vals) > total:
                raise CLiftError(
                    f"{ext.name}: {len(vals)} initializers for "
                    f"{total} elements")
            vals = np.concatenate(
                [vals, np.zeros(total - len(vals), np.int64)])
            arr = jnp.asarray(
                _normalize_init(vals, ct)).astype(ct.dtype)
            arr = arr.reshape(shape) if shape else arr.reshape(())
            inited.add(ext.name)
        else:
            if ext.name in out:
                # extern/tentative re-declaration of an existing name:
                # keep the existing (possibly initialized) definition.
                continue
            arr = jnp.zeros(tuple(shape) if shape else (), ct.dtype)
        out[ext.name] = arr
        ctypes[ext.name] = ct
    return out, ctypes, g_ptrs




def _static_for_shape(n) -> bool:
    """AST-only mirror of _static_trip's canonical literal-bound shape."""
    init, cond, nxt = n.init, n.cond, n.next
    if init is None or cond is None or nxt is None:
        return False
    if isinstance(init, c_ast.DeclList) and len(init.decls) == 1:
        var, a = init.decls[0].name, _const_int(init.decls[0].init)
    elif (isinstance(init, c_ast.Assignment) and init.op == "="
          and isinstance(init.lvalue, c_ast.ID)):
        var, a = init.lvalue.name, _const_int(init.rvalue)
    else:
        return False
    if a is None:
        return False
    if not (isinstance(cond, c_ast.BinaryOp) and cond.op in ("<", "<=")
            and isinstance(cond.left, c_ast.ID) and cond.left.name == var):
        return False
    if _const_int(cond.right) is None:
        return False
    if not (isinstance(nxt, c_ast.UnaryOp) and nxt.op in ("++", "p++")
            and isinstance(nxt.expr, c_ast.ID) and nxt.expr.name == var):
        return False

    # Mirror _static_trip's last condition: the loop variable must not
    # be written in the body (else the runtime classifier disagrees).
    written: List[bool] = []

    class _W(c_ast.NodeVisitor):
        def visit_Assignment(self, nn):
            if isinstance(nn.lvalue, c_ast.ID) and nn.lvalue.name == var:
                written.append(True)
            self.generic_visit(nn)

        def visit_UnaryOp(self, nn):
            if (nn.op in ("++", "p++", "--", "p--")
                    and isinstance(nn.expr, c_ast.ID)
                    and nn.expr.name == var):
                written.append(True)
            self.generic_visit(nn)

    _W().visit(n.stmt)
    return not written


def _needs_print_buffer(funcs) -> bool:
    """Does any value-printing printf sit where the printed arity
    cannot be static (dynamic loop, or branch under any loop)?"""
    need: List[bool] = []

    def walk(n, dyn_loop: int, any_loop: int, branch: int):
        if n is None or not isinstance(n, c_ast.Node):
            return
        if isinstance(n, (c_ast.While, c_ast.DoWhile)):
            walk(n.stmt, dyn_loop + 1, any_loop + 1, branch)
            return
        if isinstance(n, c_ast.For):
            d = 0 if _static_for_shape(n) else 1
            walk(n.stmt, dyn_loop + d, any_loop + 1, branch)
            return
        if isinstance(n, c_ast.If):
            walk(n.iftrue, dyn_loop, any_loop, branch + 1)
            walk(n.iffalse, dyn_loop, any_loop, branch + 1)
            return
        if (isinstance(n, c_ast.FuncCall)
                and isinstance(n.name, c_ast.ID)
                and n.name.name == "printf"
                and n.args is not None and len(n.args.exprs) > 1):
            if dyn_loop > 0 or (any_loop > 0 and branch > 0):
                need.append(True)
            return
        for _, ch in n.children():
            walk(ch, dyn_loop, any_loop, branch)

    for fn in funcs.values():
        walk(fn.body, 0, 0, 0)
    return bool(need)


def parse_c_sources(paths: Sequence[str]):
    """Parse + link the restricted-C sources into (tu, globals, funcs,
    typedefs, coast_annotations)."""
    if not _HAVE_PYCPARSER:
        raise CLiftError("pycparser is unavailable on this host")
    include_dirs = sorted({os.path.dirname(os.path.abspath(p))
                           for p in paths})
    texts, anns = [], []
    name_flags: Dict[str, bool] = {}
    for p in paths:
        # Per-translation-unit preprocessing state (object-like AND
        # function-like defines), matching C: a macro from one source
        # file must not leak into the next.  Includes share the
        # including file's tables (textual inclusion).
        with open(p) as f:
            src, _, ann, _ = preprocess(f.read(), include_dirs,
                                        name_flags=name_flags,
                                        fdefines={})
        texts.append(src)
        anns.extend(ann)
    parser = c_parser.CParser()
    try:
        tu = parser.parse(_PRELUDE + "\n".join(texts),
                          filename="<coast_tpu>")
    except Exception as e:          # pycparser ParseError and lexer errors
        raise CLiftError(f"C parse error: {e}") from e

    typedefs: Dict[str, object] = {}
    funcs: Dict[str, object] = {}
    for ext in tu.ext:
        if isinstance(ext, c_ast.Typedef):
            base = ext.type
            if isinstance(base, c_ast.TypeDecl):
                names = getattr(base.type, "names", ["int"])
                typedefs[ext.name] = _ctype_of(names, typedefs)
        elif isinstance(ext, c_ast.FuncDef):
            funcs[ext.decl.name] = ext
    globals_, g_ctypes, g_ptrs = _parse_globals(tu, typedefs)

    # Any exit() call introduces the synthetic observable __exit_state
    # (0 = ran to completion; 1+n = exited with code n).
    class _ExitScan(c_ast.NodeVisitor):
        found = False

        def visit_FuncCall(self, n):
            if isinstance(n.name, c_ast.ID) and n.name.name == "exit":
                _ExitScan.found = True
            self.generic_visit(n)

    for fn in funcs.values():
        _ExitScan().visit(fn.body)
    if _ExitScan.found:
        globals_["__exit_state"] = jnp.int32(0)
        g_ctypes["__exit_state"] = _CType(jnp.int32, 32, False)

    # Value prints whose arity cannot be static -- under a dynamic loop
    # or under a branch inside any loop (jpeg's for(;;) marker loop) --
    # get the UART-buffer model: a synthetic bounded __print_buf plus
    # __print_cnt become the stdout observable.  Only created when
    # needed, so every other program's leaf layout is untouched.
    if _needs_print_buffer(funcs):
        globals_["__print_buf"] = jnp.zeros(_PRINT_BUF_WORDS, jnp.uint32)
        globals_["__print_cnt"] = jnp.int32(0)
        g_ctypes["__print_cnt"] = _CType(jnp.int32, 32, False)
        g_ctypes["__print_buf"] = _CType(jnp.uint32, 32, True)
    return (tu, globals_, funcs, typedefs, anns, name_flags, g_ctypes,
            g_ptrs)


def lift_c(name: str,
           sources: Sequence[str],
           *,
           entry: str = "main",
           annotations: Optional[Dict[str, LeafSpec]] = None,
           default_xmr: Optional[bool] = None,
           max_steps: Optional[int] = None,
           meta: Optional[dict] = None) -> Region:
    """Ingest C sources and derive a protected Region.

    Globals become the lifted function's inputs (hence injectable leaves
    named by ``lift_fn``'s layout); written globals plus every value the
    program printf'd become its outputs.  ``entry`` (default ``main``) is
    executed.  COAST.h macros in the source set ``default_xmr`` unless
    overridden."""
    (tu, globals_, funcs, typedefs, anns, name_flags, g_ctypes,
     g_ptrs) = parse_c_sources(sources)
    if entry not in funcs:
        raise CLiftError(
            f"entry function {entry!r} not defined; have "
            f"{sorted(funcs)}")
    if default_xmr is None:
        default_xmr = "__DEFAULT_NO_xMR" not in anns

    comp = _Compiler(tu, typedefs, funcs, name, g_ctypes,
                     g_ptrs=g_ptrs)
    g_names = sorted(globals_)
    out_globals = sorted(comp.written_globals(funcs[entry], set(g_names)))

    def program(*g_vals):
        sc = _Scope(dict(zip(g_names, g_vals)), g_ctypes)
        comp._run_function(funcs[entry], [], sc)
        outs = [sc.g[n] for n in out_globals] + list(sc.printed)
        return tuple(outs)

    example = [globals_[n] for n in g_names]
    region = lift_fn(
        name, program, *example,
        annotations=annotations, default_xmr=default_xmr,
        max_steps=max_steps,
        meta={"frontend": "c", "sources": [os.path.basename(s)
                                           for s in sources],
              "source_paths": [os.path.realpath(s) for s in sources],
              "coast_annotations": sorted(set(anns)),
              "global_xmr": {n: f for n, f in sorted(name_flags.items())
                             if n in globals_},
              "observed_globals": out_globals, **(meta or {})})
    # The print-slot string table fills while lift_fn TRACES the program
    # (the desugar pass runs at first execution), so attach it after.
    region.meta["print_strings"] = list(comp.print_strings)

    # Per-declaration __xMR/__NO_xMR annotations, lowered the way the
    # reference's engine consumes them (tests/mm_common/mm_tmr.c):
    #
    #   * an annotated FUNCTION replicates its computation -- its locals
    #     become the lifted loop machinery (carries, indices, _phase), so
    #     those leaves inherit the function scope;
    #   * an annotated GLOBAL maps onto the state leaf its argument
    #     position became -- except UNWRITTEN globals, which the
    #     reference never clones regardless of annotation (the
    #     unwritten-global rule, cloning.cpp:62-288), so RO leaves keep
    #     the shared default;
    #   * globals consumed only through a transformed value have no
    #     single leaf; warn, do not drop silently.
    import dataclasses as _dc
    from coast_tpu.ir.region import KIND_RO
    arg_leaves = region.meta.get("arg_leaves", {})
    global_leaves = set()
    for gname, flag in sorted(name_flags.items()):
        if gname not in globals_:
            continue
        idx = g_names.index(gname)
        leaf = arg_leaves.get(idx)
        if leaf is None:
            import warnings
            warnings.warn(
                f"lift_c: __xMR annotation on global {gname!r} could not "
                "be mapped to a state leaf (the value is transformed "
                "before its first loop use); the region default applies",
                stacklevel=2)
            continue
        global_leaves.add(leaf)
        if region.spec[leaf].kind == KIND_RO:
            continue                      # unwritten: never cloned
        if region.spec[leaf].xmr is None:     # explicit API override wins
            region.spec[leaf] = _dc.replace(region.spec[leaf], xmr=flag)
    # Every GLOBAL's leaf (annotated or not) keeps its own scope: the
    # function-level blanket below covers only the machinery derived
    # from function LOCALS -- an unannotated global under
    # __DEFAULT_NO_xMR stays unprotected, as in the reference.
    all_global_leaves = {arg_leaves[g_names.index(n)]
                         for n in g_names
                         if g_names.index(n) in arg_leaves}
    fn_flags = [f for n, f in name_flags.items() if n in funcs]
    if fn_flags and all(fn_flags):
        # Every annotated function is __xMR (and at least one is): the
        # stepped machinery derived from their locals is inside the
        # sphere of replication.
        for leaf, spec in region.spec.items():
            if leaf in all_global_leaves or spec.kind == KIND_RO:
                continue
            if spec.xmr is None:
                region.spec[leaf] = _dc.replace(spec, xmr=True)
    elif fn_flags:
        # Mixed / __NO_xMR function scopes cannot be attributed to
        # individual leaves (locals from different functions fuse into
        # one stepped machinery); never drop annotations silently.
        import warnings
        warnings.warn(
            "lift_c: mixed function-level __xMR/__NO_xMR annotations "
            "cannot be lowered per-function (their locals fuse into one "
            "stepped machinery); the region default applies to "
            "machinery leaves.  Annotate globals, or split the scopes "
            "with lift_fn annotations.", stacklevel=2)
    region.validate()
    return region
