"""Frontend: automatic region lifting.

The reference's engine protects *arbitrary programs*: ``opt`` discovers
what to clone from the module itself (populateValuesToClone,
projects/dataflowProtection/cloning.cpp:62-288) -- the user only annotates
scope.  This package is the TPU-native analogue: it takes a user's plain
jittable function (or a stepped function over a state dict) and *derives*
the protected Region -- state discovery, LeafSpec kind classification from
jaxpr provenance, termination analysis, golden self-check, and a control
block graph -- so no hand-written spec is needed.
"""

from coast_tpu.frontend.lifter import LiftError, lift_fn, lift_step


def lift_c(*args, **kwargs):
    """Restricted-C ingestion (frontend.c_lifter.lift_c); imported lazily
    so the pycparser dependency stays off the default import path."""
    from coast_tpu.frontend.c_lifter import lift_c as _lift_c
    return _lift_c(*args, **kwargs)


__all__ = ["lift_step", "lift_fn", "lift_c", "LiftError"]
