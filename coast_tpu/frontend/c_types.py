"""Restricted-C type model: the ILP32 integer lattice, the 64-bit
limb-pair (_C64) arithmetic, scopes, and the shared error type.
Split out of c_lifter.py (round 5); see its module docstring for the
overall frontend contract and reference citations.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.frontend.lifter import LiftError

try:
    from pycparser import c_ast, c_parser
    _HAVE_PYCPARSER = True
except Exception:  # pragma: no cover - pycparser ships with cffi
    _HAVE_PYCPARSER = False



class CLiftError(LiftError):
    """Unsupported C construct; the message names it and the location."""


_UNSIGNED = {"unsigned", "uint32_t", "_Bool"}
_NARROW = {"char": 8, "short": 16, "uint8_t": 8, "int8_t": 8,
           "uint16_t": 16, "int16_t": 16}




# UART print-buffer capacity in 32-bit words (dynamic-context
# printf capture; see c_lifter._parse_globals / c_flow scan flush).
_PRINT_BUF_WORDS = 256


class _CType:
    """A C integer type on the 32-bit lane model.

    Narrow (8/16-bit) values live in int32 lanes holding their PROMOTED
    value (C's integer promotions take unsigned char/short to int, which
    int32 represents exactly), and every STORE to a narrow lvalue
    re-normalizes: mask to the declared width, sign-extend if signed --
    the mod-2^8/2^16 wraparound semantics the reference's byte/short
    benchmarks rely on (crc16.c's ``unsigned char x``/``unsigned short
    crc``).  Memory LAYOUT stays one lane word per element (the
    injection model is word-addressed; byte packing is out of scope and
    documented in docs/lifter.md)."""

    __slots__ = ("dtype", "bits", "unsigned")

    def __init__(self, dtype, bits: int = 32, unsigned: bool = False):
        self.dtype = dtype
        self.bits = bits
        self.unsigned = unsigned

    def store(self, v):
        """Normalize a value being stored into this type's lane."""
        if isinstance(v, _C64):
            v = v.lo                    # C conversion 64 -> 32: mod 2^32
        v = jnp.asarray(v)
        if self.bits == 32:
            return v.astype(self.dtype)
        mask = (1 << self.bits) - 1
        v = v.astype(jnp.int32) & mask
        if not self.unsigned:
            sign = 1 << (self.bits - 1)
            v = (v ^ sign) - sign
        return v

    def zero(self):
        return jnp.zeros((), self.dtype)


@jax.tree_util.register_pytree_node_class
class _C64:
    """A 64-bit C integer as a uint32 limb pair (lo, hi).

    JAX's x64 mode stays off (the whole lane/memory model is 32-bit
    words, matching the reference's ILP32 targets); ``long long``
    values instead live as two 32-bit lanes with explicit carry
    arithmetic -- the same limb model the df64 softfloat re-expression
    uses (models/chstone/df64.py).  Registered as a pytree so 64-bit
    locals carry through lax.scan/cond like any other value."""

    def __init__(self, lo, hi, unsigned: bool = False):
        self.lo = jnp.asarray(lo, jnp.uint32)
        self.hi = jnp.asarray(hi, jnp.uint32)
        self.unsigned = bool(unsigned)

    def tree_flatten(self):
        return (self.lo, self.hi), self.unsigned

    @classmethod
    def tree_unflatten(cls, aux, children):
        # Bypass __init__: jax's tree-structure checks unflatten with
        # sentinel (non-array) leaves, and the strict constructor must
        # keep raising on real misuse.
        obj = object.__new__(cls)
        obj.lo, obj.hi = children
        obj.unsigned = aux
        return obj

    def with_sign(self, unsigned: bool) -> "_C64":
        return _C64(self.lo, self.hi, unsigned)


def _to64(v, unsigned_hint: bool = False) -> _C64:
    """C conversion of a value to a 64-bit integer."""
    if isinstance(v, _C64):
        return v
    v = jnp.asarray(v)
    if v.dtype == jnp.uint32 or unsigned_hint:
        return _C64(v, jnp.uint32(0), True)
    v32 = v.astype(jnp.int32)
    hi = jnp.where(v32 < 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    return _C64(v32, hi, False)


def _mulhi_u32(x, y):
    """High 32 bits of the exact 64-bit product of two uint32 (16-bit
    limb decomposition; every partial product fits uint32)."""
    x = jnp.asarray(x, jnp.uint32)
    y = jnp.asarray(y, jnp.uint32)
    xl, xh = x & 0xFFFF, x >> 16
    yl, yh = y & 0xFFFF, y >> 16
    ll = xl * yl
    lh = xl * yh
    hl = xh * yl
    hh = xh * yh
    cross = (ll >> 16) + (lh & 0xFFFF) + (hl & 0xFFFF)
    return hh + (lh >> 16) + (hl >> 16) + (cross >> 16)


def _c64_add(a: _C64, b: _C64, unsigned: bool) -> _C64:
    lo = a.lo + b.lo
    carry = (lo < a.lo).astype(jnp.uint32)
    return _C64(lo, a.hi + b.hi + carry, unsigned)


def _c64_neg(a: _C64) -> _C64:
    return _c64_add(_C64(~a.lo, ~a.hi, a.unsigned),
                    _C64(1, 0, a.unsigned), a.unsigned)


def _c64_mul(a: _C64, b: _C64, unsigned: bool) -> _C64:
    # Product mod 2^64: lo-lo full product + cross terms into hi.
    lo = a.lo * b.lo
    hi = _mulhi_u32(a.lo, b.lo) + a.lo * b.hi + a.hi * b.lo
    return _C64(lo, hi, unsigned)


def _c64_shl(a: _C64, s) -> _C64:
    s = jnp.asarray(s, jnp.uint32) & 63
    sl = jnp.clip(s, 0, 31)
    sr = jnp.clip(32 - s.astype(jnp.int32), 0, 31).astype(jnp.uint32)
    lo_small = a.lo << sl
    hi_small = (a.hi << sl) | jnp.where(s > 0, a.lo >> sr, jnp.uint32(0))
    big = jnp.clip(s - 32, 0, 31)
    lo = jnp.where(s < 32, lo_small, jnp.uint32(0))
    hi = jnp.where(s < 32, hi_small, a.lo << big)
    return _C64(lo, hi, a.unsigned)


def _c64_shr(a: _C64, s) -> _C64:
    """C >> on the 64-bit value: logical for unsigned, arithmetic for
    signed (the left operand's type governs, C11 6.5.7)."""
    s = jnp.asarray(s, jnp.uint32) & 63
    sl = jnp.clip(s, 0, 31)
    sr = jnp.clip(32 - s.astype(jnp.int32), 0, 31).astype(jnp.uint32)
    fill = (jnp.uint32(0) if a.unsigned else
            jnp.where(a.hi.astype(jnp.int32) < 0,
                      jnp.uint32(0xFFFFFFFF), jnp.uint32(0)))
    hi_sh = ((a.hi >> sl) if a.unsigned
             else (a.hi.astype(jnp.int32) >> sl.astype(jnp.int32)
                   ).astype(jnp.uint32))
    lo_small = (a.lo >> sl) | jnp.where(s > 0, a.hi << sr, jnp.uint32(0))
    big = jnp.clip(s - 32, 0, 31)
    lo_big = ((a.hi >> big) if a.unsigned
              else (a.hi.astype(jnp.int32) >> big.astype(jnp.int32)
                    ).astype(jnp.uint32))
    lo = jnp.where(s < 32, lo_small, lo_big)
    hi = jnp.where(s < 32, hi_sh, fill)
    return _C64(lo, hi, a.unsigned)


def _c64_divmod(a: _C64, b: _C64) -> Tuple[_C64, _C64]:
    """Unsigned 64/64 division: 64-step restoring shift-subtract on
    limb pairs (softfloat's estimateDiv128To64 path).  The classic
    overflow trick keeps the remainder in 64 bits: when the shifted
    remainder wraps past 2^64 its true value exceeds the divisor, so
    the subtraction is taken and the mod-2^64 result is exact."""

    def step(i, st):
        qlo, qhi, rlo, rhi = st
        bit = 63 - i
        nbit = jnp.where(
            bit >= 32,
            (a.hi >> jnp.uint32(jnp.clip(bit - 32, 0, 31))) & 1,
            (a.lo >> jnp.uint32(jnp.clip(bit, 0, 31))) & 1)
        ov = rhi >> 31
        r2 = _c64_shl(_C64(rlo, rhi, True), 1)
        r2 = _C64(r2.lo | nbit, r2.hi, True)
        ge = jnp.logical_or(
            ov.astype(bool),
            jnp.logical_not(_c64_lt(r2, b, True)))
        r3 = _c64_add(r2, _c64_neg(b), True)
        rlo2 = jnp.where(ge, r3.lo, r2.lo)
        rhi2 = jnp.where(ge, r3.hi, r2.hi)
        q2 = _c64_shl(_C64(qlo, qhi, True), 1)
        qlo2 = q2.lo | ge.astype(jnp.uint32)
        return (qlo2, q2.hi, rlo2, rhi2)

    z = jnp.uint32(0)
    qlo, qhi, rlo, rhi = jax.lax.fori_loop(0, 64, step, (z, z, z, z))
    # b == 0 is C UB; pin it to q=~0, r=a (softfloat never divides by 0).
    bz = jnp.equal(b.lo | b.hi, 0)
    q = _C64(jnp.where(bz, jnp.uint32(0xFFFFFFFF), qlo),
             jnp.where(bz, jnp.uint32(0xFFFFFFFF), qhi), True)
    r = _C64(jnp.where(bz, a.lo, rlo), jnp.where(bz, a.hi, rhi), True)
    return q, r


def _c64_lt(a: _C64, b: _C64, unsigned: bool):
    if unsigned:
        hi_lt = jnp.less(a.hi, b.hi)
        hi_eq = jnp.equal(a.hi, b.hi)
    else:
        hi_lt = jnp.less(a.hi.astype(jnp.int32), b.hi.astype(jnp.int32))
        hi_eq = jnp.equal(a.hi, b.hi)
    return jnp.logical_or(hi_lt, jnp.logical_and(hi_eq,
                                                 jnp.less(a.lo, b.lo)))


class _CType64(_CType):
    """``long long`` on the limb-pair model (no memory layout: 64-bit
    GLOBALS/arrays are outside the word-addressed injection map and
    refuse at declaration; 64-bit LOCALS are register values)."""

    def __init__(self, unsigned: bool = False):
        super().__init__(jnp.uint32, 64, unsigned)

    def store(self, v):
        # Extension is governed by the SOURCE's signedness (in _to64);
        # the declared type only sets the result's signedness.
        v64 = _to64(v)
        return _C64(v64.lo, v64.hi, self.unsigned)

    def zero(self):
        return _C64(0, 0, self.unsigned)


def _ctype_of(names: List[str], typedefs: Dict[str, object]) -> _CType:
    """ILP32 _CType for a declared type-name list (``long long`` -> the
    64-bit limb-pair type)."""
    for n in names:
        if n in typedefs:
            return typedefs[n]
    uns = any(n in _UNSIGNED for n in names) or "unsigned" in names
    # Plain char is UNSIGNED on the reference's ARM targets (AAPCS).
    if "char" in names and "signed" not in names:
        uns = True
    if names.count("long") >= 2:
        return _CType64(uns)
    bits = 32
    for n in names:
        if n in _NARROW:
            bits = _NARROW[n]
    if bits == 32:
        return _CType(jnp.uint32 if uns else jnp.int32, 32, uns)
    return _CType(jnp.int32, bits, uns)


# ---------------------------------------------------------------------------
# AST -> JAX compiler
# ---------------------------------------------------------------------------

class _NoPrintList(list):
    """printf sentinel for traced sub-regions (loops, branches)."""

    def __init__(self, coord, reason=None):
        super().__init__()
        self.coord = coord
        self.reason = reason

    def _refuse(self):
        if self.reason:
            raise CLiftError(
                f"printf {self.reason} at {self.coord}: whether the "
                "print happens would depend on traced values, so it "
                "cannot be a fixed program output; print before the "
                "early exit or restructure")
        raise CLiftError(
            f"printf inside a loop or branch at {self.coord}: per-"
            "iteration prints would be traced values that cannot escape "
            "the loop; move the printf after the loop (print the final "
            "value) or restructure")

    def append(self, _):
        self._refuse()

    def extend(self, _):
        self._refuse()


class _Scope:
    """Name -> traced value, with global-write tracking.

    ``aliases`` implements C's array-argument pointer semantics at the
    only granularity the subset needs: an array parameter whose call
    argument names a GLOBAL array reads/writes that global directly
    (matrix_multiply(first_matrix, ..., results_matrix) mutates
    results_matrix, exactly as the pointer would)."""

    def __init__(self, globals_: Dict[str, jax.Array],
                 ctypes: Optional[Dict[str, "_CType"]] = None):
        self.g = globals_          # shared, mutated in place
        self.locals: Dict[str, jax.Array] = {}
        self.aliases: Dict[str, str] = {}       # param name -> global name
        self.ptrs: set = set()                  # declared pointer locals
        self.ctypes: Dict[str, _CType] = dict(ctypes or {})
        self.printed: List[jax.Array] = []
        # Constant shadow environment: scalar names whose CURRENT value
        # is a compile-time-known int.  Inside jax.make_jaxpr every jnp
        # value -- literals included -- is an abstract tracer, so
        # trace-time control decisions (statically-taken branches,
        # print-loop bounds) need classic constant propagation on the
        # side.  Absent = unknown; every traced write invalidates.
        self.consts: Dict[str, int] = {}

    def fork(self, no_print_at=None, no_print_reason=None):
        """Child scope for a traced sub-region (loop body/cond, branch).
        ``no_print_at`` arms the printf guard: values printed inside a
        traced sub-region are scan/cond tracers that cannot escape to the
        program output, so the guard refuses loudly instead of letting
        an opaque tracer-leak KeyError surface at lift time."""
        sub = _Scope(dict(self.g), self.ctypes)
        sub.locals = dict(self.locals)
        sub.aliases = dict(self.aliases)
        sub.ptrs = set(self.ptrs)
        sub.consts = dict(self.consts)
        sub.printed = (self.printed if no_print_at is None
                       else _NoPrintList(no_print_at, no_print_reason))
        return sub

    def read(self, name: str):
        # Locals FIRST: a pointer parameter holds its walk cursor as a
        # local under its own name while aliasing the pointed-to global
        # (``*p++`` support; _Compiler._ptr_parts).
        if name in self.locals:
            return self.locals[name]
        name = self.aliases.get(name, name)
        if name in self.locals:
            return self.locals[name]
        if name in self.g:
            return self.g[name]
        raise CLiftError(f"undeclared identifier {name!r}")

    def write(self, name: str, val):
        if name in self.locals:
            self.locals[name] = val
            return
        name = self.aliases.get(name, name)
        if name in self.locals:
            self.locals[name] = val
        elif name in self.g:
            self.g[name] = val
        else:
            self.locals[name] = val

    def read_binding(self, name: str):
        """Read an already-RESOLVED binding (a local name or a global/
        transient-slot name) with NO alias resolution.  Loop/branch
        carries hold resolved names; re-resolving them through this
        scope's alias map would mis-route when a parameter shadows a
        global of the same name (sha256_hash's ``data`` param vs the
        global ``data``)."""
        if name in self.locals:
            return self.locals[name]
        if name in self.g:
            return self.g[name]
        raise CLiftError(f"unbound carry name {name!r}")

    def write_binding(self, name: str, val):
        if name in self.locals:
            self.locals[name] = val
        else:
            self.g[name] = val

    def ctype(self, name: str) -> Optional["_CType"]:
        if name in self.locals:
            # The local's own declared type.  A pointer parameter's walk
            # cursor deliberately has none: it is a plain int32 offset,
            # NOT the narrow pointee type the alias would resolve to.
            return self.ctypes.get(name)
        return self.ctypes.get(self.aliases.get(name, name))


def _const_int(node) -> Optional[int]:
    # pycparser types suffixed literals "unsigned int"/"long int"/etc.
    if isinstance(node, c_ast.Constant) and "int" in node.type:
        return int(node.value.rstrip("uUlL"), 0)
    if isinstance(node, c_ast.UnaryOp) and node.op in ("-", "+", "~"):
        v = _const_int(node.expr)
        if v is None:
            return None
        return {"-": -v, "+": v, "~": ~v}[node.op]
    if isinstance(node, c_ast.BinaryOp):
        # Constant folding for dimension/label expressions (blowfish's
        # `BF_ROUNDS + 2`); division is C truncation toward zero.
        a, b = _const_int(node.left), _const_int(node.right)
        if a is None or b is None:
            return None
        try:
            return {
                "+": lambda: a + b, "-": lambda: a - b,
                "*": lambda: a * b,
                "/": lambda: int(a / b) if b else None,
                "%": lambda: a - int(a / b) * b if b else None,
                "<<": lambda: a << b, ">>": lambda: a >> b,
                "&": lambda: a & b, "|": lambda: a | b,
                "^": lambda: a ^ b,
            }[node.op]()
        except KeyError:
            return None
    return None


