"""Expression evaluation for the restricted-C compiler: constant
propagation, the usual arithmetic conversions, 64-bit limb lowering,
pointer/array paths, stores, compound assignment, and calls.  Mixin
methods of _Compiler (c_lifter.py); split out in round 5.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.frontend.lifter import LiftError

try:
    from pycparser import c_ast, c_parser
    _HAVE_PYCPARSER = True
except Exception:  # pragma: no cover - pycparser ships with cffi
    _HAVE_PYCPARSER = False

from coast_tpu.frontend.c_types import (
    _PRINT_BUF_WORDS, CLiftError, _C64, _CType, _CType64, _NoPrintList, _Scope,
    _c64_add, _c64_divmod, _c64_lt, _c64_mul, _c64_neg, _c64_shl,
    _c64_shr, _const_int, _ctype_of, _mulhi_u32, _to64)


class _EvalMixin:
    """Expression/memory evaluation half of _Compiler."""

    # -- trace-time constant propagation -----------------------------------
    @staticmethod
    def _wrap32(v: int) -> int:
        """Canonical signed-32 representation of a mod-2^32 value."""
        v &= 0xFFFFFFFF
        return v - (1 << 32) if v >= 0x80000000 else v

    @staticmethod
    def _has_effects(node) -> bool:
        """Does evaluating ``node`` have side effects (writes/calls)?"""
        found: List[object] = []

        class V(c_ast.NodeVisitor):
            def visit_Assignment(v, n):
                found.append(n)

            def visit_FuncCall(v, n):
                found.append(n)

            def visit_UnaryOp(v, n):
                if n.op in ("++", "p++", "--", "p--"):
                    found.append(n)
                v.generic_visit(n)

        if node is not None:
            V().visit(node)
        return bool(found)

    def _const_eval(self, node, sc: _Scope) -> Optional[int]:
        """Compile-time value of a PURE expression, or None if unknown.

        Conservative by construction: every fold either matches the C
        (ILP32) result exactly or returns None -- ordered comparisons
        and ``>>`` bail out when a sign-domain ambiguity could flip the
        result.  Values are kept in canonical signed-32 form."""
        if isinstance(node, c_ast.Constant):
            if "char" in node.type and node.value.startswith("'"):
                body = node.value[1:-1].encode().decode("unicode_escape")
                return ord(body)
            if "int" in node.type:
                v = int(node.value.rstrip("uUlL"), 0)
                return self._wrap32(v) if v <= 0xFFFFFFFF else None
            return None
        if isinstance(node, c_ast.ID):
            return sc.consts.get(node.name)
        if isinstance(node, c_ast.Cast):
            if isinstance(node.to_type.type, c_ast.PtrDecl):
                return None
            v = self._const_eval(node.expr, sc)
            if v is None:
                return None
            ct = _ctype_of(node.to_type.type.type.names, self.typedefs)
            if isinstance(ct, _CType64):
                return None
            return self._norm_const(ct, v)
        if isinstance(node, c_ast.UnaryOp):
            if node.op not in ("-", "+", "~", "!"):
                return None
            v = self._const_eval(node.expr, sc)
            if v is None:
                return None
            if node.op == "!":
                return int(v == 0)
            return self._wrap32({"-": -v, "+": v, "~": ~v}[node.op])
        if isinstance(node, c_ast.TernaryOp):
            c = self._const_eval(node.cond, sc)
            if c is None:
                return None
            return self._const_eval(node.iftrue if c else node.iffalse, sc)
        if isinstance(node, c_ast.BinaryOp):
            a = self._const_eval(node.left, sc)
            if a is None:
                return None
            if node.op in ("&&", "||"):
                if node.op == "&&" and a == 0:
                    return 0
                if node.op == "||" and a != 0:
                    return 1
                b = self._const_eval(node.right, sc)
                return None if b is None else int(b != 0)
            b = self._const_eval(node.right, sc)
            if b is None:
                return None
            op = node.op
            if op in ("==", "!="):
                eq = (a & 0xFFFFFFFF) == (b & 0xFFFFFFFF)
                return int(eq if op == "==" else not eq)
            if op in ("<", ">", "<=", ">="):
                # int vs unsigned compare agree only when both
                # operands are non-negative in the signed view.
                if a < 0 or b < 0:
                    return None
                return int({"<": a < b, ">": a > b,
                            "<=": a <= b, ">=": a >= b}[op])
            if op == ">>":
                if a < 0:
                    return None          # arithmetic-vs-logical ambiguity
                return a >> (b & 31)
            if op == "<<":
                return self._wrap32(a << (b & 31))
            if op in ("+", "-", "*", "&", "|", "^"):
                return self._wrap32({"+": a + b, "-": a - b, "*": a * b,
                                     "&": a & b, "|": a | b,
                                     "^": a ^ b}[op])
            if op in ("/", "%"):
                # C truncates toward zero; Python floors -- fold only
                # the unambiguous non-negative case.
                if a < 0 or b <= 0:
                    return None
                return a // b if op == "/" else a % b
            return None
        return None

    @staticmethod
    def _norm_const(ct: _CType, v: int) -> int:
        """C conversion of a known value into the declared type."""
        mask = (1 << ct.bits) - 1
        v &= mask
        if not ct.unsigned and v >= (1 << (ct.bits - 1)):
            v -= 1 << ct.bits
        return v

    def _const_set(self, sc: _Scope, name: str, v: Optional[int],
                   ct: Optional[_CType] = None) -> None:
        if v is None:
            sc.consts.pop(name, None)
        else:
            if ct is not None and not isinstance(ct, _CType64):
                v = self._norm_const(ct, v)
            sc.consts[name] = v

    # -- expressions -------------------------------------------------------
    def eval(self, node, sc: _Scope):
        if isinstance(node, c_ast.Constant):
            if "char" in node.type and node.value.startswith("'"):
                # Character constant: type int in C.
                body = node.value[1:-1].encode().decode("unicode_escape")
                return jnp.int32(ord(body))
            if "int" in node.type:
                v = node.value.rstrip("uUlL")
                base = int(v, 0)
                # C type of the literal: explicit u suffix, or a hex/octal
                # literal too big for int (0xffffffff is unsigned int in
                # ILP32; decimal literals never become unsigned).
                uns = ("u" in node.value.lower()
                       or (base > 0x7FFFFFFF
                           and v.lower().startswith("0")))
                if base > 0xFFFFFFFF:
                    # Literal outside 32 bits: a long long constant.
                    return _C64(base & 0xFFFFFFFF,
                                (base >> 32) & 0xFFFFFFFF, uns)
                return (jnp.uint32(base & 0xFFFFFFFF) if uns
                        else jnp.int32(np.int32(base & 0xFFFFFFFF)))
            raise CLiftError(f"unsupported constant type {node.type!r}")
        if isinstance(node, c_ast.ExprList):
            # C comma expression: evaluate left to right, value is last.
            v = jnp.int32(0)
            for e in node.exprs:
                v = self.eval(e, sc)
            return v
        if isinstance(node, c_ast.ID):
            v = sc.read(node.name)
            ct = sc.ctype(node.name)
            # Narrow SCALAR reads re-normalize: an injected bit above the
            # declared width does not exist in real byte/short memory, so
            # the promoted value masks it (docs/lifter.md, layout
            # envelope).  Arrays pass through untouched -- an ID naming an
            # array is C pointer decay, not a value read.
            if ct is not None and ct.bits < 32 and jnp.ndim(v) == 0:
                return ct.store(v)
            return v
        if isinstance(node, c_ast.ArrayRef):
            arr, idx, base = self._array_path(node, sc)
            ct = (sc.ctypes.get(base[0]) if isinstance(base, tuple)
                  else sc.ctype(base))
            if isinstance(ct, _CType64):
                row = arr[idx]                  # (..., 2) limb pair
                return _C64(row[..., 0], row[..., 1], ct.unsigned)
            v = arr[idx]
            return (ct.store(v) if ct is not None and ct.bits < 32
                    else v)
        if isinstance(node, c_ast.BinaryOp):
            return self._binop(node, sc)
        if isinstance(node, c_ast.UnaryOp):
            return self._unop(node, sc)
        if isinstance(node, c_ast.TernaryOp):
            c = self.eval(node.cond, sc)
            a = self.eval(node.iftrue, sc)
            b = self.eval(node.iffalse, sc)
            if isinstance(a, _C64) or isinstance(b, _C64):
                a64, b64 = _to64(a), _to64(b)
                t_ = self._truth(c)
                return _C64(jnp.where(t_, a64.lo, b64.lo),
                            jnp.where(t_, a64.hi, b64.hi),
                            a64.unsigned or b64.unsigned)
            a, b = self._usual_conv(a, b)
            return jnp.where(jnp.not_equal(c, 0), a, b)
        if isinstance(node, c_ast.FuncCall):
            return self._call(node, sc)
        if isinstance(node, c_ast.Cast):
            if isinstance(node.to_type.type, c_ast.PtrDecl):
                raise CLiftError(
                    f"pointer cast in value position at {node.coord}; "
                    "pointer casts are modeled only where a pointer "
                    "flows (seatings, call arguments, derefs)")
            ct = _ctype_of(node.to_type.type.type.names, self.typedefs)
            # C cast semantics: value converted to the target type --
            # truncate + re-sign for narrow targets, plain dtype change
            # for 32-bit ones.
            return ct.store(self.eval(node.expr, sc))
        if isinstance(node, c_ast.Assignment):
            # expression-position assignment (e.g. in for-next)
            return self._assign(node, sc)
        raise CLiftError(
            f"unsupported expression {type(node).__name__} at {node.coord}")

    def _usual_conv(self, a, b):
        """C usual arithmetic conversions, ILP32 32-bit lane: if either
        side is unsigned, both are."""
        a = jnp.asarray(a)
        b = jnp.asarray(b)
        if a.dtype == jnp.uint32 or b.dtype == jnp.uint32:
            return a.astype(jnp.uint32), b.astype(jnp.uint32)
        return a.astype(jnp.int32), b.astype(jnp.int32)

    @staticmethod
    def _truth(v):
        """C truth value of a scalar or limb-pair value."""
        if isinstance(v, _C64):
            return jnp.not_equal(v.lo | v.hi, 0)
        return jnp.not_equal(jnp.asarray(v), 0)

    def _ptrish(self, node, sc) -> bool:
        """Is this expression a pointer value (decayed array, walked or
        global pointer, &-expr, pointer +/- offset)?"""
        if isinstance(node, c_ast.ID):
            if node.name in sc.aliases:
                return True
            if (node.name in self.g_ptrs
                    and node.name not in sc.locals):
                return True
            tgt = node.name
            return tgt in sc.g and jnp.ndim(sc.g[tgt]) >= 1
        if isinstance(node, c_ast.Cast):
            return (isinstance(node.to_type.type, c_ast.PtrDecl)
                    and self._ptrish(node.expr, sc))
        if isinstance(node, c_ast.UnaryOp) and node.op == "&":
            return True
        if isinstance(node, c_ast.BinaryOp) and node.op in ("+", "-"):
            return (self._ptrish(node.left, sc)
                    or self._ptrish(node.right, sc))
        return False

    def _binop(self, node, sc):
        if (node.op in ("==", "!=", "<", ">", "<=", ">=", "-")
                and (self._ptrish(node.left, sc)
                     or self._ptrish(node.right, sc))):
            # Pointer comparison / difference: both sides resolve to
            # (base, offset); same base -> compare/subtract offsets
            # (element-indexed cursors, matching C's element units).
            ba, oa = self._ptr_parts(node.left, sc)
            bb, ob = self._ptr_parts(node.right, sc)
            if ba != bb:
                raise CLiftError(
                    f"pointer {node.op} across different arrays "
                    f"({ba!r} vs {bb!r}) at {node.coord}")
            return self._apply_binop(node.op, jnp.asarray(oa, jnp.int32),
                                     jnp.asarray(ob, jnp.int32), node)
        a = self.eval(node.left, sc)
        b = self.eval(node.right, sc)
        return self._apply_binop(node.op, a, b, node)

    def _apply_binop(self, op, a, b, node):
        if op in ("&&", "||"):
            az = self._truth(a)
            bz = self._truth(b)
            r = jnp.logical_and(az, bz) if op == "&&" else jnp.logical_or(az, bz)
            return r.astype(jnp.int32)
        if isinstance(a, _C64) or isinstance(b, _C64):
            return self._binop64(op, a, b, node)
        a, b = self._usual_conv(a, b)
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return jax.lax.div(a, b) if a.dtype == jnp.int32 else a // b
        if op == "%":
            return jax.lax.rem(a, b) if a.dtype == jnp.int32 else a % b
        if op == "^":
            return a ^ b
        if op == "&":
            return a & b
        if op == "|":
            return a | b
        if op == "<<":
            return a << b
        if op == ">>":
            return a >> b
        cmp = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
               ">": jnp.greater, "<=": jnp.less_equal,
               ">=": jnp.greater_equal}.get(op)
        if cmp is not None:
            return cmp(a, b).astype(jnp.int32)
        raise CLiftError(f"unsupported binary op {op!r} at {node.coord}")

    def _binop64(self, op, a, b, node):
        """Binary ops with a 64-bit (limb-pair) operand."""
        if op in ("<<", ">>"):
            # The SHIFT COUNT is not subject to the usual conversions:
            # a << amount keeps a's type; the amount reduces to int.
            a64 = _to64(a)
            s = b.lo if isinstance(b, _C64) else jnp.asarray(b, jnp.uint32)
            return _c64_shl(a64, s) if op == "<<" else _c64_shr(a64, s)
        a64, b64 = _to64(a), _to64(b)
        unsigned = a64.unsigned or b64.unsigned
        if op == "+":
            return _c64_add(a64, b64, unsigned)
        if op == "-":
            return _c64_add(a64, _c64_neg(b64), unsigned)
        if op == "*":
            return _c64_mul(a64, b64, unsigned)
        if op in ("/", "%"):
            if not unsigned:
                raise CLiftError(
                    f"signed 64-bit {op} at {node.coord} is outside the "
                    "modeled envelope (softfloat divides unsigned)")
            q, r = _c64_divmod(a64, b64)
            return q if op == "/" else r
        if op == "&":
            return _C64(a64.lo & b64.lo, a64.hi & b64.hi, unsigned)
        if op == "|":
            return _C64(a64.lo | b64.lo, a64.hi | b64.hi, unsigned)
        if op == "^":
            return _C64(a64.lo ^ b64.lo, a64.hi ^ b64.hi, unsigned)
        if op == "==":
            return jnp.logical_and(jnp.equal(a64.lo, b64.lo),
                                   jnp.equal(a64.hi, b64.hi)
                                   ).astype(jnp.int32)
        if op == "!=":
            return jnp.logical_or(jnp.not_equal(a64.lo, b64.lo),
                                  jnp.not_equal(a64.hi, b64.hi)
                                  ).astype(jnp.int32)
        if op == "<":
            return _c64_lt(a64, b64, unsigned).astype(jnp.int32)
        if op == ">":
            return _c64_lt(b64, a64, unsigned).astype(jnp.int32)
        if op == "<=":
            return jnp.logical_not(_c64_lt(b64, a64, unsigned)
                                   ).astype(jnp.int32)
        if op == ">=":
            return jnp.logical_not(_c64_lt(a64, b64, unsigned)
                                   ).astype(jnp.int32)
        raise CLiftError(
            f"unsupported 64-bit binary op {op!r} at {node.coord} "
            "(long long supports + - * & | ^ << >> and comparisons)")

    def _unop(self, node, sc):
        op = node.op
        if op in ("++", "p++", "--", "p--"):
            name = node.expr
            old = self.eval(name, sc)
            if isinstance(old, _C64):
                one = _C64(1, 0, old.unsigned)
                new = (_c64_add(old, one, old.unsigned) if "++" in op
                       else _c64_add(old, _c64_neg(one), old.unsigned))
            else:
                delta = jnp.asarray(1, old.dtype)
                new = old + delta if "++" in op else old - delta
            self._store(name, new, sc)
            if isinstance(name, c_ast.ID):
                prev = sc.consts.get(name.name)
                self._const_set(
                    sc, name.name,
                    None if prev is None else
                    self._wrap32(prev + (1 if "++" in op else -1)),
                    sc.ctype(name.name))
            return old if op.startswith("p") else new
        if op == "*":
            base, off = self._ptr_parts(node.expr, sc)
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                v = self._union_read(sc, base)[off]
                return (ct.store(v) if ct is not None and ct.bits < 32
                        else v)
            arr = sc.g[base]
            ct = sc.ctypes.get(base)
            if isinstance(ct, _CType64):
                row = arr.reshape(-1, 2)[off]   # limb-pair element
                return _C64(row[0], row[1], ct.unsigned)
            if jnp.ndim(arr) > 1:
                arr = arr.reshape(-1)       # cursors walk row-major memory
            v = arr[off]
            return (ct.store(v) if ct is not None and ct.bits < 32
                    else v)
        if op == "sizeof":
            return jnp.int32(self._sizeof(node.expr, sc))
        v = self.eval(node.expr, sc)
        if isinstance(v, _C64):
            if op == "-":
                return _c64_neg(v)
            if op == "+":
                return v
            if op == "~":
                return _C64(~v.lo, ~v.hi, v.unsigned)
            if op == "!":
                return jnp.equal(v.lo | v.hi, 0).astype(jnp.int32)
            raise CLiftError(
                f"unsupported unary op {op!r} on long long at {node.coord}")
        if op == "-":
            return -v
        if op == "+":
            return v
        if op == "~":
            return ~v
        if op == "!":
            return jnp.equal(v, 0).astype(jnp.int32)
        raise CLiftError(f"unsupported unary op {op!r} at {node.coord}")

    def _sizeof(self, expr, sc) -> int:
        """C sizeof in the REAL C layout (not the lane layout): element
        count times the declared element width in bytes.  The benchmarks
        use it for byte-array lengths (aes.c's sizeof(input))."""
        if isinstance(expr, c_ast.Typename):
            ct = _ctype_of(getattr(expr.type.type, "names", ["int"]),
                           self.typedefs)
            return ct.bits // 8
        if isinstance(expr, c_ast.ID):
            name = expr.name
            if name in sc.aliases:
                # Array/pointer PARAMETERS and local pointer variables
                # decay: C's sizeof is the pointer size (ILP32: 4), the
                # classic sizeof-of-parameter trap included.
                return 4
            arr = sc.read(name)
            ct = sc.ctype(name)
            width = (ct.bits // 8) if ct is not None else 4
            n = int(np.prod(arr.shape)) if jnp.ndim(arr) else 1
            return n * width
        raise CLiftError(
            f"unsupported sizeof operand at {getattr(expr, 'coord', '?')}")

    def _ptr_parts(self, expr, sc) -> Tuple[str, jax.Array]:
        """Resolve a pointer-valued expression to (global name, offset).

        The subset's pointers are walked array parameters: ``p`` (cursor
        or start), ``p++``/``++p``/``p--``/``--p`` (cursor effect applies,
        value is the C-correct old/new pointer), and ``p + e``.  This is
        the shape the reference's byte-stream benchmarks use
        (crc16.c:26 ``*data_p++``)."""
        if isinstance(expr, c_ast.ID) and expr.name in sc.aliases:
            return (sc.aliases[expr.name],
                    jnp.asarray(sc.locals.get(expr.name, 0), jnp.int32))
        if (isinstance(expr, c_ast.ID) and expr.name in self.g_ptrs
                and expr.name not in sc.locals):
            base = self.g_ptr_base.get(expr.name)
            if base is None:
                raise CLiftError(
                    f"global pointer {expr.name!r} used before any "
                    "seating; seat it (p = arr) first")
            return base, jnp.asarray(sc.read(expr.name), jnp.int32)
        if isinstance(expr, c_ast.ID) and expr.name in sc.locals:
            # A LOCAL array (possibly shadowing a same-name global)
            # cannot be a pointer target -- aliases only bind into the
            # globals dict.  Refuse loudly instead of silently binding
            # the shadowed global.
            raise CLiftError(
                f"pointer to local array {expr.name!r} at "
                f"{getattr(expr, 'coord', '?')} is not supported; make "
                "the array a global or pass it as a call argument")
        if (isinstance(expr, c_ast.ID) and expr.name in sc.g
                and jnp.ndim(sc.g[expr.name]) >= 1):
            # A global array name decays to a pointer to its start.
            return expr.name, jnp.int32(0)
        if (isinstance(expr, c_ast.UnaryOp)
                and expr.op in ("++", "p++", "--", "p--")
                and isinstance(expr.expr, c_ast.ID)):
            nm = expr.expr.name
            if nm in sc.aliases:
                if nm not in sc.locals:
                    raise CLiftError(
                        f"pointer arithmetic on unwalked parameter "
                        f"{nm!r} at {expr.coord}")
                off = self._unop(expr, sc)      # applies the cursor effect
                return sc.aliases[nm], jnp.asarray(off, jnp.int32)
            if nm in self.g_ptrs and nm not in sc.locals:
                base = self.g_ptr_base.get(nm)
                if base is None:
                    raise CLiftError(
                        f"global pointer {nm!r} walked before any "
                        f"seating at {expr.coord}")
                off = self._unop(expr, sc)      # global cursor effect
                return base, jnp.asarray(off, jnp.int32)
        if isinstance(expr, c_ast.Cast):
            # Pointer casts ((void*)buf, (char*)p) change the static type,
            # not the address: pass through.  The pointee's ctype stays
            # the ALIASED array's -- reinterpreting an int array as bytes
            # would need sub-word addressing, outside the lane model.
            return self._ptr_parts(expr.expr, sc)
        if isinstance(expr, c_ast.UnaryOp) and expr.op == "&":
            # Address-of: &arr -> (arr, 0); &arr[k] -> (arr, k); multi-dim
            # &arr[j][k] -> (arr, j*cols + k) -- the cursor indexes the
            # row-major FLATTENED array (sha_stream's &indata[j][0]).
            inner = expr.expr
            if isinstance(inner, c_ast.ArrayRef):
                idxs, node2 = [], inner
                while isinstance(node2, c_ast.ArrayRef):
                    idxs.append(node2.subscript)
                    node2 = node2.name
                if isinstance(node2, c_ast.ID):
                    base, off = self._ptr_parts(node2, sc)
                    shape = jnp.shape(sc.g[base])
                    idxs = list(reversed(idxs))
                    if len(idxs) > len(shape):
                        raise CLiftError(
                            f"too many subscripts under & at {expr.coord}")
                    flat = jnp.int32(0)
                    for d, ix in enumerate(idxs):
                        stride = int(np.prod(shape[d + 1:], dtype=np.int64))
                        flat = flat + jnp.asarray(
                            self.eval(ix, sc), jnp.int32) * stride
                    return base, off + flat
            if (isinstance(inner, c_ast.ID) and inner.name in sc.locals
                    and inner.name not in sc.aliases
                    and jnp.ndim(sc.locals[inner.name]) == 0):
                raise CLiftError(
                    f"address-of scalar {inner.name!r} at "
                    f"{getattr(expr, 'coord', '?')} is not supported "
                    "(no out-parameter model; return the value instead)")
            return self._ptr_parts(inner, sc)
        if isinstance(expr, c_ast.BinaryOp) and expr.op in ("+", "-"):
            base, off = self._ptr_parts(expr.left, sc)
            d = jnp.asarray(self.eval(expr.right, sc), jnp.int32)
            return base, (off + d if expr.op == "+" else off - d)
        if isinstance(expr, c_ast.ArrayRef):
            # PARTIAL indexing decays a sub-array to a pointer
            # (`p = ta[i]` over int ta[2][4] -> base ta, offset i*4).
            idxs, node2 = [], expr
            while isinstance(node2, c_ast.ArrayRef):
                idxs.append(node2.subscript)
                node2 = node2.name
            if isinstance(node2, c_ast.ID):
                base, off0 = self._ptr_parts(node2, sc)
                if not isinstance(base, tuple):
                    arrv = sc.g[base]
                    eff_nd = jnp.ndim(arrv)
                    if isinstance(sc.ctypes.get(base), _CType64):
                        eff_nd -= 1
                    if len(idxs) < eff_nd:
                        shape = jnp.shape(arrv)
                        flat = jnp.int32(0)
                        for d2, ix in enumerate(reversed(idxs)):
                            stride = int(np.prod(shape[d2 + 1:eff_nd],
                                                 dtype=np.int64))
                            flat = flat + jnp.asarray(
                                self.eval(ix, sc), jnp.int32) * stride
                        return base, off0 + flat
        raise CLiftError(
            f"unsupported pointer expression at {getattr(expr, 'coord', '?')}")

    def _array_path(self, node, sc):
        """Flatten a[i][j]... into (array value, index tuple).  A pointer
        parameter that has been walked (``p++``) indexes relative to its
        cursor: ``p[i]`` reads the aliased global at cursor+i."""
        idxs = []
        while isinstance(node, c_ast.ArrayRef):
            idxs.append(node.subscript)
            node = node.name
        if not isinstance(node, c_ast.ID):
            raise CLiftError(f"unsupported array base at {node.coord}")
        name = node.name
        cursor = (sc.locals.get(name) if name in sc.aliases else None)
        base = sc.aliases.get(name, name)
        if name in sc.aliases and isinstance(sc.aliases[name], tuple):
            arr = self._union_read(sc, sc.aliases[name])
        elif name in sc.aliases:
            arr = sc.g[sc.aliases[name]]
        elif (name in self.g_ptrs and name not in sc.locals):
            # Subscripting a GLOBAL pointer (gp[i]) routes through its
            # seated base + cursor, same as _ptr_parts' deref path --
            # sc.read(name) would hand back the int32 cursor scalar.
            seated = self.g_ptr_base.get(name)
            if seated is None:
                raise CLiftError(
                    f"global pointer {name!r} subscripted before any "
                    f"seating at {node.coord}; seat it (p = arr) first")
            arr = sc.g[seated]
            cursor = jnp.asarray(sc.read(name), jnp.int32)
            base = seated
        else:
            arr = sc.read(name)
        idx = tuple(self.eval(i, sc).astype(jnp.int32)
                    for i in reversed(idxs))
        if cursor is not None:
            if len(idx) != 1:
                raise CLiftError(
                    f"walked pointer {name!r} must be 1-D at {node.coord}")
            # Cursor over row-major memory: flatten to element rows.  A
            # 64-bit base keeps its trailing limb-pair axis -- the cursor
            # counts ELEMENTS, and the _CType64 load/store consume (n, 2)
            # rows; a full flatten would index half-pairs.
            ct_c = (sc.ctypes.get(base[0]) if isinstance(base, tuple)
                    else sc.ctype(base))
            if isinstance(ct_c, _CType64):
                if jnp.ndim(arr) > 2:
                    arr = arr.reshape(-1, 2)
            elif jnp.ndim(arr) > 1:
                arr = arr.reshape(-1)
            idx = (idx[0] + cursor,)
        return arr, (idx if len(idx) > 1 else idx[0]), base

    def _store(self, lhs, val, sc):
        if isinstance(lhs, c_ast.ID):
            ct = sc.ctype(lhs.name)
            if ct is not None:
                sc.write(lhs.name, ct.store(val))
                return
            if isinstance(val, _C64):
                # Untyped slot receiving a 64-bit value (early-return
                # carries of 64-bit functions): store the pair as-is.
                sc.write(lhs.name, val)
                return
            old = sc.read(lhs.name)
            sc.write(lhs.name, jnp.asarray(val).astype(old.dtype)
                     if hasattr(old, "dtype") else val)
            return
        if isinstance(lhs, c_ast.ArrayRef):
            arr, idx, base = self._array_path(lhs, sc)
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                stored = (ct.store(val) if ct is not None
                          else jnp.asarray(val).astype(arr.dtype))
                self._union_write(
                    sc, base, arr.at[idx].set(stored.astype(arr.dtype)))
                return
            ct = sc.ctype(base)
            if isinstance(ct, _CType64):
                v64 = _to64(val)
                new = arr.at[idx].set(jnp.stack([v64.lo, v64.hi]))
                orig = sc.read_binding(base)
                if jnp.shape(new) != jnp.shape(orig):
                    # _array_path flattened a cursor view over a
                    # multi-dim 64-bit array to (-1, 2) limb rows;
                    # restore the canonical shape.
                    new = new.reshape(jnp.shape(orig))
                sc.write_binding(base, new)
                return
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            new = arr.at[idx].set(stored.astype(arr.dtype))
            orig = sc.read_binding(base)
            if jnp.shape(new) != jnp.shape(orig):
                # _array_path flattened a cursor view over a multi-dim
                # array; restore the canonical shape.
                new = new.reshape(jnp.shape(orig))
            # base is already alias-RESOLVED: write the binding
            # directly (re-resolving would mis-route when a parameter
            # shadows a global of the same name).
            sc.write_binding(base, new)
            return
        if isinstance(lhs, c_ast.UnaryOp) and lhs.op == "*":
            # Deref store (*p++ = c): C order -- the store targets the
            # pointer value BEFORE any ++/-- side effect, which
            # _ptr_parts implements (p++ yields the old offset).
            base, off = self._ptr_parts(lhs.expr, sc)
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                flat = self._union_read(sc, base)
                stored = (ct.store(val) if ct is not None
                          else jnp.asarray(val).astype(flat.dtype))
                self._union_write(
                    sc, base, flat.at[off].set(stored.astype(flat.dtype)))
                return
            arr = sc.g[base]
            ct = sc.ctypes.get(base)
            if isinstance(ct, _CType64):
                v64 = _to64(val)
                flat = arr.reshape(-1, 2).at[off].set(
                    jnp.stack([v64.lo, v64.hi]))
                sc.write_binding(base, flat.reshape(jnp.shape(arr)))
                return
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            if jnp.ndim(arr) > 1:           # cursors walk row-major memory
                flat = arr.reshape(-1).at[off].set(stored.astype(arr.dtype))
                sc.write_binding(base, flat.reshape(jnp.shape(arr)))
            else:
                sc.write_binding(base,
                                 arr.at[off].set(stored.astype(arr.dtype)))
            return
        raise CLiftError(
            f"unsupported assignment target {type(lhs).__name__}")

    def _assign(self, node, sc):
        op = node.op
        if (op == "=" and isinstance(node.lvalue, c_ast.ID)
                and node.lvalue.name in self.g_ptrs
                and node.lvalue.name not in sc.locals
                and node.lvalue.name not in sc.aliases):
            # GLOBAL pointer (re-)seating: static single base, runtime
            # cursor stored in the int32 cursor global.
            name = node.lvalue.name
            base, off = self._ptr_parts(node.rvalue, sc)
            prev = self.g_ptr_base.get(name)
            if prev is not None and prev != base:
                raise CLiftError(
                    f"global pointer {name!r} re-seated from {prev!r} "
                    f"to {base!r} at {node.coord}: a single static base "
                    "per global pointer is the modeled envelope")
            self.g_ptr_base[name] = base
            sc.write(name, jnp.asarray(off, jnp.int32))
            sc.consts.pop(name, None)
            return off
        if (op == "=" and isinstance(node.lvalue, c_ast.ID)
                and (node.lvalue.name in sc.ptrs
                     or node.lvalue.name in sc.aliases)):
            # Pointer (re-)seating: `p = arr`, `p = q`, `p = p + k`,
            # `p = (T*)s`, `p = &a[k]` -- resolve the RHS to
            # (array, offset) and re-bind the cursor.  An unresolvable
            # RHS refuses loudly in _ptr_parts (the round-3 advisor
            # found the old scalar path silently storing a whole array
            # into the cursor local).
            name = node.lvalue.name
            base, off = self._ptr_parts(node.rvalue, sc)
            union = self._union_bases(sc.aliases.get(name))
            if union is not None and not isinstance(base, tuple):
                # Union pointer: a seat on a member re-bases the cursor
                # into that member's segment of the concatenation.
                off = self._union_offset(sc, union, base) + jnp.asarray(
                    off, jnp.int32)
            else:
                sc.aliases[name] = base
            sc.locals[name] = jnp.asarray(off, jnp.int32)
            sc.consts.pop(name, None)
            return off
        if op == "=":
            const = (self._const_eval(node.rvalue, sc)
                     if isinstance(node.lvalue, c_ast.ID) else None)
            val = self.eval(node.rvalue, sc)
            self._store(node.lvalue, val, sc)
            if isinstance(node.lvalue, c_ast.ID):
                self._const_set(sc, node.lvalue.name, const,
                                sc.ctype(node.lvalue.name))
            return val
        # Compound assignment (+= <<= ...): the lvalue designates ONE
        # location, evaluated ONCE (C11 6.5.16.2) -- a side-effecting
        # lvalue like GSM's rescale `*s++ <<= scalauto` must advance the
        # cursor exactly once, with read and store hitting the SAME
        # element (the old fake-binop path re-evaluated it for the
        # store, double-stepping the cursor).
        bin_op = op[:-1]
        lhs = node.lvalue
        if isinstance(lhs, c_ast.UnaryOp) and lhs.op == "*":
            base, off = self._ptr_parts(lhs.expr, sc)   # effects, once
            if isinstance(base, tuple):          # union pointer
                ct = sc.ctypes.get(base[0])
                flat0 = self._union_read(sc, base)
                old = flat0[off]
                if ct is not None and ct.bits < 32:
                    old = ct.store(old)
                val = self._apply_binop(bin_op, old,
                                        self.eval(node.rvalue, sc), node)
                stored = (ct.store(val) if ct is not None
                          else jnp.asarray(val).astype(flat0.dtype))
                self._union_write(
                    sc, base,
                    flat0.at[off].set(stored.astype(flat0.dtype)))
                return val
            arr = sc.g[base]
            flat = arr.reshape(-1) if jnp.ndim(arr) > 1 else arr
            ct = sc.ctypes.get(base)
            old = flat[off]
            if ct is not None and ct.bits < 32:
                old = ct.store(old)
            val = self._apply_binop(bin_op, old,
                                    self.eval(node.rvalue, sc), node)
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            new = flat.at[off].set(stored.astype(arr.dtype))
            if jnp.ndim(arr) > 1:
                new = new.reshape(jnp.shape(arr))
            sc.write_binding(base, new)
            return val
        if isinstance(lhs, c_ast.ArrayRef):
            arr, idx, base = self._array_path(lhs, sc)  # subscripts, once
            ct = (sc.ctypes.get(base[0]) if isinstance(base, tuple)
                  else sc.ctype(base))
            old = arr[idx]
            if ct is not None and ct.bits < 32:
                old = ct.store(old)
            val = self._apply_binop(bin_op, old,
                                    self.eval(node.rvalue, sc), node)
            stored = (ct.store(val) if ct is not None
                      else jnp.asarray(val).astype(arr.dtype))
            new = arr.at[idx].set(stored.astype(arr.dtype))
            if isinstance(base, tuple):              # union pointer
                self._union_write(sc, base, new)
                return val
            orig = sc.read_binding(base)
            if jnp.shape(new) != jnp.shape(orig):
                new = new.reshape(jnp.shape(orig))
            sc.write_binding(base, new)
            return val
        # Plain identifier lvalue: no side effects to duplicate.
        fake = c_ast.BinaryOp(bin_op, node.lvalue, node.rvalue, node.coord)
        const = (self._const_eval(fake, sc)
                 if isinstance(node.lvalue, c_ast.ID) else None)
        val = self._binop(fake, sc)
        self._store(node.lvalue, val, sc)
        if isinstance(node.lvalue, c_ast.ID):
            self._const_set(sc, node.lvalue.name, const,
                            sc.ctype(node.lvalue.name))
        return val

    def _call(self, node, sc):
        if not isinstance(node.name, c_ast.ID):
            raise CLiftError(f"unsupported indirect call at {node.coord}")
        fname = node.name.name
        arg_nodes = node.args.exprs if node.args else []
        if fname == "printf":
            # The QEMU loop's observable: everything printed is output.
            # The format string itself is not evaluated (no string
            # model); a 64-bit value prints as its two limbs.
            vals = []
            for a in arg_nodes[1:]:
                v = self.eval(a, sc)
                if isinstance(v, _C64):
                    vals.extend([v.lo, v.hi])
                else:
                    vals.append(jnp.asarray(v))
            if (not vals and isinstance(sc.printed, _NoPrintList)
                    and "__print_buf" in sc.g and arg_nodes
                    and isinstance(arg_nodes[0], c_ast.Constant)
                    and arg_nodes[0].type == "string"):
                # String-only print at a dynamically-reached site: its
                # string-table id is the buffered word.
                text = (arg_nodes[0].value[1:-1]
                        .encode("utf-8").decode("unicode_escape"))
                if text in self.print_strings:
                    sid = self.print_strings.index(text)
                else:
                    self.print_strings.append(text)
                    sid = len(self.print_strings) - 1
                vals = [jnp.uint32(sid)]
            if (vals and isinstance(sc.printed, _NoPrintList)
                    and "__print_buf" in sc.g):
                # UART-buffer model: dynamically-reached prints append
                # into the bounded __print_buf observable (overflowing
                # words drop; __print_cnt keeps the true total).
                buf = sc.g["__print_buf"]
                cnt = sc.g["__print_cnt"]
                for v in vals:
                    idx = jnp.clip(cnt, 0, _PRINT_BUF_WORDS - 1)
                    keep = cnt < _PRINT_BUF_WORDS
                    buf = buf.at[idx].set(
                        jnp.where(keep, jnp.asarray(v).astype(jnp.uint32),
                                  buf[idx]))
                    cnt = cnt + 1
                sc.g["__print_buf"] = buf
                sc.g["__print_cnt"] = cnt
                return jnp.int32(0)
            sc.printed.extend(vals)
            return jnp.int32(0)
        # C array arguments are pointers: a bare ID naming a (possibly
        # already-aliased) global array binds the parameter to that global.
        args = []
        for a in arg_nodes:
            # A pointer CAST on an argument changes the static type only
            # ((unsigned char *)ivec): unwrap it and bind the underlying
            # array/pointer as usual.
            while (isinstance(a, c_ast.Cast)
                   and isinstance(a.to_type.type, c_ast.PtrDecl)):
                a = a.expr
            if isinstance(a, c_ast.UnaryOp) and a.op == "&":
                inner = a.expr
                if (isinstance(inner, c_ast.ID) and inner.name in sc.locals
                        and inner.name not in sc.aliases
                        and jnp.ndim(sc.locals[inner.name]) == 0):
                    # Scalar out-parameter (&num, blowfish's cfb64 state):
                    # copy-in/copy-out through a 1-word transient slot,
                    # like caller-local arrays.
                    args.append(("__alias_scalar_local__", inner.name))
                    continue
                if (isinstance(inner, c_ast.ID) and inner.name in sc.g
                        and jnp.ndim(sc.g[inner.name]) == 0):
                    # Address of a GLOBAL scalar (jpeg's
                    # &OutData_image_width): same slot model, copied
                    # back into the global when the callee returns
                    # (in-call aliasing with direct reads of the same
                    # global is outside the envelope).
                    args.append(("__alias_scalar_global__", inner.name))
                    continue
                # &localarr[k]: caller-LOCAL array element address
                # (motion's &PMV[0]) -- transient slot + cursor k.
                idxs, node2 = [], inner
                while isinstance(node2, c_ast.ArrayRef):
                    idxs.append(node2.subscript)
                    node2 = node2.name
                if (isinstance(node2, c_ast.ID) and node2.name in sc.locals
                        and node2.name not in sc.aliases
                        and jnp.ndim(sc.locals[node2.name]) >= 1):
                    shape = jnp.shape(sc.locals[node2.name])
                    flat = jnp.int32(0)
                    for d, ix in enumerate(reversed(idxs)):
                        stride = int(np.prod(shape[d + 1:],
                                             dtype=np.int64))
                        flat = flat + jnp.asarray(
                            self.eval(ix, sc), jnp.int32) * stride
                    args.append(("__alias_local_off__", node2.name, flat))
                    continue
                # &arr[k] / &glob: a pointer value -- forward base+offset.
                base, off = self._ptr_parts(a, sc)
                args.append(("__alias_off__", base,
                             jnp.asarray(off, jnp.int32)))
                continue
            if isinstance(a, c_ast.ID):
                if (a.name in sc.locals and a.name not in sc.aliases
                        and jnp.ndim(sc.locals[a.name]) >= 1):
                    # A caller-LOCAL array argument: C passes a pointer to
                    # it.  Modeled as copy-in/copy-out through a transient
                    # slot (run_function), sound because the subset has no
                    # overlapping aliases.
                    args.append(("__alias_local__", a.name))
                    continue
                tgt = sc.aliases.get(a.name, a.name)
                if isinstance(tgt, tuple):       # union pointer forwards
                    args.append(("__alias_off__", tgt,
                                 jnp.asarray(sc.locals.get(a.name, 0),
                                             jnp.int32)))
                    continue
                if tgt in sc.g and jnp.ndim(sc.g[tgt]) >= 1:
                    if a.name in sc.aliases and a.name in sc.locals:
                        # A WALKED/SEATED pointer forwards base AND
                        # cursor, so the callee continues from the
                        # caller's position (sha_stream passing
                        # &indata[j][0] onward to sha_update).
                        args.append(("__alias_off__", tgt,
                                     jnp.asarray(sc.locals[a.name],
                                                 jnp.int32)))
                        continue
                    args.append(("__alias__", tgt))
                    continue
            if isinstance(a, c_ast.ArrayRef):
                # PARTIAL indexing of a multi-dim array (motion.c's
                # motion_vector(PMV[0][s], ...)): C decays the sub-array
                # to a pointer -- forward base + flattened row offset so
                # callee writes land in the caller's array.  FULL
                # indexing stays a by-value element.
                idxs, node2 = [], a
                while isinstance(node2, c_ast.ArrayRef):
                    idxs.append(node2.subscript)
                    node2 = node2.name
                if isinstance(node2, c_ast.ID):
                    nm2 = node2.name
                    arrv = cur = None
                    basen, is_local = nm2, False
                    if nm2 in sc.aliases:
                        basen = sc.aliases[nm2]
                        arrv = sc.g.get(basen)
                        cur = sc.locals.get(nm2)
                    elif (nm2 in sc.locals
                            and jnp.ndim(sc.locals[nm2]) >= 1):
                        arrv, is_local = sc.locals[nm2], True
                    elif nm2 in sc.g and jnp.ndim(sc.g[nm2]) >= 1:
                        arrv = sc.g[nm2]
                    eff_nd = None
                    if arrv is not None:
                        eff_nd = jnp.ndim(arrv)
                        # The BASE array's element type decides the
                        # logical arity (a walked cursor's own ctype is
                        # deliberately None, so resolve the base).
                        ctn = (sc.ctype(nm2) if is_local
                               else sc.ctypes.get(basen))
                        if isinstance(ctn, _CType64):
                            eff_nd -= 1     # trailing dim is the limb pair
                    if arrv is not None and len(idxs) < eff_nd:
                        shape = jnp.shape(arrv)
                        flat = jnp.int32(0)
                        for d, ix in enumerate(reversed(idxs)):
                            stride = int(np.prod(shape[d + 1:],
                                                 dtype=np.int64))
                            flat = flat + jnp.asarray(
                                self.eval(ix, sc), jnp.int32) * stride
                        if cur is not None:
                            flat = flat + jnp.asarray(cur, jnp.int32)
                        if is_local:
                            args.append(("__alias_local_off__", nm2,
                                         flat))
                        else:
                            args.append(("__alias_off__", basen, flat))
                        continue
            args.append(self.eval(a, sc))
        if fname == "exit":
            # exit(n) on an error path (jpeg's "Not Jpeg File!"/huffman
            # read error): modeled as an OBSERVABLE poison -- the
            # synthetic global __exit_state records 1+n and joins the
            # output surface.  Fault-free runs never take these paths,
            # so the oracle is exact; under injection the poisoned flag
            # plus divergent outputs classify the run, though in-model
            # execution continues past the exit (documented fidelity
            # envelope -- the QEMU guest would stop).
            code = (args[0] if args else jnp.int32(0))
            # POSIX truncates the exit status to 8 bits; 1+(n & 0xFF)
            # is in [1, 256], never colliding with 0 = ran to end.
            sc.g["__exit_state"] = (
                (jnp.asarray(code, jnp.int32) & jnp.int32(0xFF))
                + jnp.int32(1))
            return jnp.int32(0)
        if fname == "abort":
            raise CLiftError(
                "abort() needs the abort/DUE machinery; model it via "
                "DWC (detect-only strategy) instead")
        fn = self.funcs.get(fname)
        if fn is None:
            raise CLiftError(f"call to undefined function {fname!r} "
                             f"at {node.coord}")
        arg_consts = [None if isinstance(v, tuple)
                      or self._has_effects(n2)
                      else self._const_eval(n2, sc)
                      for n2, v in zip(arg_nodes, args)]
        return self._run_function(fn, args, sc, arg_consts)

    def _walked_names(self, node) -> set:
        """Names subject to POINTER arithmetic: ++/--/assignment on the
        BARE identifier.  Element stores (``a[i] = v``) do not count --
        they write the pointee, not the pointer (mm.c's r_matrix vs
        crc16.c's data_p)."""
        names: set = set()

        class V(c_ast.NodeVisitor):
            def visit_UnaryOp(v, n):
                if (n.op in ("++", "p++", "--", "p--")
                        and isinstance(n.expr, c_ast.ID)):
                    names.add(n.expr.name)
                v.generic_visit(n)

            def visit_Assignment(v, n):
                if isinstance(n.lvalue, c_ast.ID):
                    names.add(n.lvalue.name)
                v.generic_visit(n)

        V().visit(node)
        return names

    # -- desugar pre-pass --------------------------------------------------
    @staticmethod
    def _string_only_printf(stmt) -> bool:
        return (isinstance(stmt, c_ast.FuncCall)
                and isinstance(stmt.name, c_ast.ID)
                and stmt.name.name == "printf"
                and stmt.args is not None
                and len(stmt.args.exprs) == 1
                and isinstance(stmt.args.exprs[0], c_ast.Constant)
                and stmt.args.exprs[0].type == "string")

