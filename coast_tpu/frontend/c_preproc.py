"""Minimal C preprocessor for the restricted frontend: comments,
object-/function-like #define (cpp substitution order, literal
masking, ## token paste), #ifdef conditionals, #include "..." and the
COAST.h annotation macros.  Split out of c_lifter.py (round 5).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.frontend.lifter import LiftError

try:
    from pycparser import c_ast, c_parser
    _HAVE_PYCPARSER = True
except Exception:  # pragma: no cover - pycparser ships with cffi
    _HAVE_PYCPARSER = False

from coast_tpu.frontend.c_types import CLiftError



# ---------------------------------------------------------------------------
# Minimal preprocessing: the subset needs no system headers.
# ---------------------------------------------------------------------------

_COAST_MACROS = ("__DEFAULT_NO_xMR", "__DEFAULT_xMR", "__xMR", "__NO_xMR",
                 "__xMR_FN", "__NO_xMR_FN")

# Further COAST.h attribute macros: recorded and stripped so annotated
# sources PARSE (the annotations expand to __attribute__ in the real
# header, COAST.h:11-67); behaviors already designed away (ISRs,
# malloc/printf wrappers) surface later as loud refusals on the
# construct itself, not as parse errors on the macro token.
_COAST_STRIP_TOKENS = ("__xMR_FN_CALL", "__SKIP_FN_CALL",
                       "__COAST_VOLATILE", "__ISR_FUNC", "__xMR_RET_VAL",
                       "__xMR_PROT_LIB", "__xMR_ALL_AFTER_CALL",
                       "__COAST_NO_INLINE")
# Function-like COAST macros whose whole invocation line is a no-op
# declaration in the real header (wrapper registration).
_COAST_STRIP_CALLS = ("PRINTF_WRAPPER_REGISTER", "MALLOC_WRAPPER_REGISTER",
                      "__COAST_IGNORE_GLOBAL")

_PRELUDE = """
typedef unsigned int uint32_t;
typedef int int32_t;
typedef unsigned short uint16_t;
typedef short int16_t;
typedef unsigned char uint8_t;
typedef signed char int8_t;
"""


def _strip_comments(text: str) -> str:
    """Remove //... and /*...*/ outside string literals (pycparser wants
    preprocessed input)."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"':
            j = i + 1
            while j < n and text[j] != '"':
                j += 2 if text[j] == "\\" else 1
            out.append(text[i:j + 1])
            i = j + 1
        elif text.startswith("//", i):
            i = text.find("\n", i)
            i = n if i < 0 else i
        elif text.startswith("/*", i):
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("\n" * text.count("\n", i, j))   # keep line numbers
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def preprocess(text: str, include_dirs: Sequence[str] = (),
               defines: Optional[Dict[str, str]] = None,
               name_flags: Optional[Dict[str, bool]] = None,
               fdefines: Optional[Dict[str, Tuple[List[str], str]]] = None,
               ) -> Tuple[str, Dict[str, str], List[str], Dict[str, bool]]:
    """Strip/resolve the tiny preprocessor surface the benchmarks use.

    Returns (source, defines, coast_macros, name_flags).  ``#include
    "local.c"`` is inlined from ``include_dirs`` (the mm_common.c
    pattern) and SHARES the including file's ``#define`` table, exactly
    like cpp textual inclusion; ``#include <...>`` system headers are
    dropped (the prelude supplies the stdint names); object-like AND
    function-like ``#define``s substitute (continuation lines joined;
    arguments are paren-wrapped on substitution, which the benchmark
    macros -- ROTRIGHT, DBL_INT_ADD -- are written to tolerate).
    ``name_flags`` collects per-declaration scope annotations:
    ``uint32_t __xMR results[..]`` records ``{"results": True}`` (and
    ``__NO_xMR`` False) -- the identifier FOLLOWING the macro, matching
    the reference's declaration style (tests/mm_common/mm_tmr.c).
    """
    text = _strip_comments(text).replace("\\\n", " ")
    defines = {} if defines is None else defines
    fdefines = {} if fdefines is None else fdefines
    name_flags = {} if name_flags is None else name_flags
    annotations: List[str] = []
    out: List[str] = []

    def expand_fn(line: str) -> str:
        """Expand function-like macro calls with balanced-paren args."""
        for _ in range(8):                       # bounded nesting
            changed = False
            for name, (params, body) in fdefines.items():
                m = re.search(rf"\b{re.escape(name)}\s*\(", line)
                if not m:
                    continue
                start, i = m.start(), m.end()
                depth, args, cur = 1, [], ""
                while i < len(line) and depth:
                    ch = line[i]
                    if ch == "(":
                        depth += 1
                    elif ch == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    if depth == 1 and ch == ",":
                        args.append(cur)
                        cur = ""
                    else:
                        cur += ch
                    i += 1
                if depth:
                    raise CLiftError(
                        f"unbalanced macro call {name}(... in: {line!r}")
                args.append(cur)
                if not params:
                    args = [a for a in args if a.strip()]
                if len(args) != len(params):
                    raise CLiftError(
                        f"macro {name} expects {len(params)} args, "
                        f"got {len(args)} in: {line!r}")
                # Token paste FIRST (cpp order): a parameter adjacent to
                # ## substitutes its RAW argument (no parens, no prior
                # expansion), then the operator splices the tokens --
                # CHStone sha's `f##n(B,C,D)` / `CONST##n`.
                raw = {p: a.strip() for p, a in zip(params, args)}

                def paste(m):
                    l, r2 = m.group(1), m.group(2)
                    return raw.get(l, l) + raw.get(r2, r2)

                while re.search(r"\w+\s*##\s*\w+", body):
                    body = re.sub(r"(\w+)\s*##\s*(\w+)", paste, body,
                                  count=1)
                # SIMULTANEOUS parameter substitution with a function
                # replacement: sequential re.sub would re-substitute an
                # argument that mentions a later parameter's name, and a
                # string template would reinterpret backslashes in the
                # argument ('\n' in a char constant).  An argument that
                # is already one parenthesized unit is not re-wrapped
                # (_ANSI_ARGS_((void)) must yield (void), not ((void))).
                def wrap_arg(s: str) -> str:
                    s = s.strip()
                    if s.startswith("(") and s.endswith(")"):
                        depth = 0
                        for k, ch in enumerate(s):
                            if ch == "(":
                                depth += 1
                            elif ch == ")":
                                depth -= 1
                                if depth == 0 and k != len(s) - 1:
                                    break
                        else:
                            return s
                    return f"({s})"

                amap = {p: wrap_arg(a) for p, a in zip(params, args)}
                if amap:
                    pat = "|".join(rf"\b{re.escape(p)}\b" for p in amap)
                    sub = re.sub(pat, lambda m: amap[m.group(0)], body)
                else:
                    sub = body
                line = line[:start] + sub + line[i + 1:]
                changed = True
            if not changed:
                return line
        return line

    _LIT_RE = re.compile(r'"(?:[^"\\]|\\.)*"|\'(?:[^\'\\]|\\.)*\'')

    def expand(line: str) -> str:
        # String/char literals are masked out before substitution (cpp
        # never substitutes inside them -- a macro name appearing in a
        # printf format must survive) and restored after; literals
        # introduced BY an expansion are masked on the next pass.
        lits: List[str] = []

        def mask(m):
            lits.append(m.group(0))
            return f"\x01{len(lits) - 1}\x02"

        for _ in range(8):                       # rescan until stable
            line = _LIT_RE.sub(mask, line)
            before = line
            for name, val in defines.items():
                # Function replacement: a value containing backslashes
                # must not be reinterpreted as a regex template.
                line = re.sub(rf"\b{re.escape(name)}\b", lambda m: val,
                              line)
            line = expand_fn(line)
            if line == before:
                break
        return re.sub(r"\x01(\d+)\x02", lambda m: lits[int(m.group(1))],
                      line)

    def _paren_balance(s: str) -> int:
        s = _LIT_RE.sub("", s)
        return s.count("(") - s.count(")")

    # Conditional-inclusion stack: [taking, evaluable, satisfied].
    # #ifdef/#ifndef evaluate against the defines tables (motion's
    # global.h selects the _ANSI_ARGS_ variant this way); other #if
    # forms keep the legacy include-everything behavior
    # (evaluable=False), their #else/#elif branches included too.
    cond_stack: List[List[bool]] = []

    lines_in = text.splitlines()
    li = 0
    while li < len(lines_in):
        raw = lines_in[li]
        li += 1
        # A function-like macro call spanning lines (motion's
        # _ANSI_ARGS_((int *PMV, ...) prototypes): join until balanced.
        if (any(re.search(rf"\b{re.escape(n)}\s*\(", raw)
                for n in fdefines)
                and not raw.lstrip().startswith("#")):
            guard = 0
            while (_paren_balance(raw) > 0 and li < len(lines_in)
                   and guard < 100):
                raw += " " + lines_in[li]
                li += 1
                guard += 1
        line = raw
        stripped = line.strip()
        if stripped.startswith("#"):
            # cpp allows whitespace between # and the directive name
            # (global.h's `#   define _ANSI_ARGS_(x) x`).
            stripped = re.sub(r"^#\s+", "#", stripped)
        if stripped.startswith("#ifdef") or stripped.startswith("#ifndef"):
            m = re.match(r"#ifn?def\s+(\w+)", stripped)
            if m:
                known = (m.group(1) in defines or m.group(1) in fdefines)
                taking = (known if stripped.startswith("#ifdef")
                          else not known)
                cond_stack.append([taking, True, taking])
            else:
                cond_stack.append([True, False, True])
            continue
        if stripped.startswith("#if"):
            cond_stack.append([True, False, True])
            continue
        if stripped.startswith("#elif"):
            if cond_stack and cond_stack[-1][1]:
                if cond_stack[-1][2]:        # a branch was taken: skip rest
                    cond_stack[-1][0] = False
                else:                        # unknown #elif: legacy include
                    cond_stack[-1] = [True, False, True]
            continue
        if stripped.startswith("#else"):
            if cond_stack and cond_stack[-1][1]:
                cond_stack[-1][0] = not cond_stack[-1][2]
            continue
        if stripped.startswith("#endif"):
            if cond_stack:
                cond_stack.pop()
            continue
        if not all(e[0] for e in cond_stack):
            continue                          # skipped conditional branch
        if stripped.startswith("#include"):
            m = re.match(r'#include\s+"([^"]+)"', stripped)
            if m:
                fname = m.group(1)
                for d in include_dirs:
                    path = os.path.join(d, fname)
                    if os.path.exists(path):
                        if fname.endswith("COAST.h") or fname == "COAST.h":
                            break
                        with open(path) as f:
                            sub, _, subann, _ = preprocess(
                                f.read(), include_dirs, defines,
                                name_flags, fdefines)
                        annotations.extend(subann)
                        out.append(sub)
                        break
                else:
                    if not fname.endswith("COAST.h"):
                        raise CLiftError(
                            f'#include "{fname}" not found in '
                            f"{list(include_dirs)}")
            continue
        if stripped.startswith("#define"):
            fm = re.match(r"#define\s+(\w+)\(([^)]*)\)\s+(.+?)\s*$",
                          stripped)
            if fm:
                params = [p.strip() for p in fm.group(2).split(",")
                          if p.strip()]
                fdefines[fm.group(1)] = (params, fm.group(3))
                continue
            m = re.match(r"#define\s+(\w+)\s+(.+?)\s*$", stripped)
            if m:
                defines[m.group(1)] = expand(m.group(2))
                continue
            m = re.match(r"#define\s+(\w+)\s*$", stripped)
            if m:
                # Valueless define (SPARC-GCC.h's `#define INLINE`):
                # substitutes to nothing, and flips #ifdef decisions.
                defines[m.group(1)] = ""
            continue
        if stripped.startswith("#"):
            continue                      # #ifdef guards etc.: benign here
        # Expand BEFORE the annotation passes: a source-local alias like
        # `#define FUNCTION_TAG __xMR` must be recorded and stripped the
        # same as a literal __xMR (load_store.c's style).
        line = expand(line)
        # Per-declaration scope annotations.  Styles the reference corpus
        # uses: mid-declaration ``uint32_t __xMR name[..]`` (the token
        # after the macro is the name), prefix ``__xMR uint32_t name``
        # (the SECOND token is; the first is a type and resolves to
        # nothing), and trailing ``int foo() __xMR``.
        for m in re.finditer(r"\b(__NO_xMR|__xMR)\s+(\w+)(?:\s+(\w+))?",
                             line):
            flag = m.group(1) == "__xMR"
            name_flags.setdefault(m.group(2), flag)
            if m.group(3):
                name_flags.setdefault(m.group(3), flag)
        for m in re.finditer(r"\b(\w+)\s*\([^()]*\)\s*(__NO_xMR|__xMR)\b",
                             line):
            name_flags.setdefault(m.group(1), m.group(2) == "__xMR")
        # Record + strip COAST annotation macros and GCC attributes.
        for mac in _COAST_MACROS + _COAST_STRIP_TOKENS:
            if re.search(rf"\b{mac}\b", line):
                annotations.append(mac)
                line = re.sub(rf"\b{mac}\b", "", line)
        for mac in _COAST_STRIP_CALLS:
            if re.search(rf"\b{mac}\s*\(", line):
                annotations.append(mac)
                line = re.sub(rf"\b{mac}\s*\([^)]*\)\s*;?", "", line)
        line = re.sub(r"__attribute__\s*\(\(.*?\)\)", "", line)
        out.append(line)
    return "\n".join(out), defines, annotations, name_flags
