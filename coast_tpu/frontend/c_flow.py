"""Statement/control-flow lowering for the restricted-C compiler:
the desugaring pre-pass (switch, deep breaks, run-once loops), the
forward-goto skip-flag rewrite, early returns, and the loop/branch
executors (scan/while/rotated-condition lowering).  Mixin methods of
_Compiler (c_lifter.py); split out in round 5.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.frontend.lifter import LiftError

try:
    from pycparser import c_ast, c_parser
    _HAVE_PYCPARSER = True
except Exception:  # pragma: no cover - pycparser ships with cffi
    _HAVE_PYCPARSER = False

from coast_tpu.frontend.c_types import (
    _PRINT_BUF_WORDS, CLiftError, _C64, _CType, _CType64, _NoPrintList, _Scope,
    _const_int, _to64)


class _FlowMixin:
    """Statement-execution half of _Compiler."""

    def _desugar_fn(self, fndef) -> None:
        """Memoized per-function AST pre-pass, run before execution and
        before the early-return rewrite:

        * ``switch`` -> evaluate-once + ``if``/``else if`` chain (the
          subset's switches are break/return-terminated, CHStone mips.c
          style; fallthrough refuses loudly);
        * ``do {B} while (C)`` -> ``B; while (C) {B}`` (the body AST is
          shared; execution is functional over it);
        * ``while (1)`` whose body always returns at its tail runs
          exactly once -> body inlined (mips.c's outer retry loop), so
          its printfs stay program outputs;
        * a string-only ``printf("...")`` under a branch/loop becomes a
          PRINT SLOT: ``__print_sel_k = <string id>`` with the slot
          initialized to -1 (never printed) and appended to the output
          surface when the function returns.  The reference's oracle IS
          stdout ("RESULT: PASS", unittest/cfg/full.yml) and which
          string prints is data -- a selected-constant output captures
          exactly that bit.  The id -> string table lands in
          ``region.meta['print_strings']``.  printf with VALUE arguments
          inside branches still refuses loudly (a traced per-iteration
          value cannot escape as a fixed output).
        """
        fid = id(fndef)
        if fid in self._desugared:
            return
        self._desugared.add(fid)
        slots = self._print_slots.setdefault(fid, [])
        temps = self._sw_temps.setdefault(fid, [])
        slot_by_node: Dict[int, Tuple[str, int]] = {}

        def as_items(node) -> list:
            if node is None:
                return []
            if isinstance(node, c_ast.Compound):
                return list(node.block_items or [])
            return [node]

        def ends_in_return(items) -> bool:
            if not items:
                return False
            last = items[-1]
            if isinstance(last, c_ast.Return):
                return True
            if isinstance(last, c_ast.Compound):
                return ends_in_return(as_items(last))
            if isinstance(last, c_ast.If) and last.iffalse is not None:
                return (ends_in_return(as_items(last.iftrue))
                        and ends_in_return(as_items(last.iffalse)))
            return False

        def loose_break(items) -> bool:
            """A break/continue that would bind to the statement being
            flattened (not to a nested loop of its own)."""
            for s in items:
                if isinstance(s, (c_ast.Break, c_ast.Continue)):
                    return True
                if isinstance(s, (c_ast.While, c_ast.For, c_ast.DoWhile,
                                  c_ast.Switch)):
                    continue
                if isinstance(s, c_ast.Compound):
                    if loose_break(as_items(s)):
                        return True
                elif isinstance(s, c_ast.If):
                    if (loose_break(as_items(s.iftrue))
                            or loose_break(as_items(s.iffalse))):
                        return True
            return False

        def slot_for(stmt) -> Tuple[str, int]:
            sid = id(stmt)
            if sid not in slot_by_node:
                text = stmt.args.exprs[0].value[1:-1]
                self.print_strings.append(
                    text.encode("utf-8").decode("unicode_escape"))
                k = len(self.print_strings) - 1
                slot_by_node[sid] = (f"__print_sel_{k}", k)
                slots.append(slot_by_node[sid])
            return slot_by_node[sid]

        def xform_block(node, in_branch: bool):
            items = []
            for s in as_items(node):
                items.extend(xform(s, in_branch))
            return c_ast.Compound(items, getattr(node, "coord", None))

        def desugar_switch(sw) -> list:
            body_items = as_items(sw.stmt)
            if isinstance(sw.cond, (c_ast.ID, c_ast.Constant)):
                ctrl, pre = sw.cond, []
            else:
                nm = f"__sw_{len(temps)}"
                temps.append(nm)
                ctrl = c_ast.ID(nm, sw.cond.coord)
                pre = [c_ast.Assignment("=", c_ast.ID(nm, sw.cond.coord),
                                        sw.cond, sw.cond.coord)]
            groups: list = []          # (conds | None-for-default, stmts)
            pending: list = []
            pending_default = False
            for it in body_items:
                if isinstance(it, c_ast.Case):
                    pending.append(it.expr)
                    stmts = list(it.stmts or [])
                elif isinstance(it, c_ast.Default):
                    pending_default = True
                    stmts = list(it.stmts or [])
                else:
                    raise CLiftError(
                        f"unsupported statement between switch cases at "
                        f"{getattr(it, 'coord', '?')}")
                if not stmts:
                    continue                      # label stacking
                if pending_default and pending:
                    raise CLiftError(
                        f"case labels stacked with default at {it.coord} "
                        "are not supported; restructure")
                groups.append((None if pending_default else list(pending),
                               stmts, it.coord))
                pending, pending_default = [], False
            # Validate break/return termination (fallthrough refuses);
            # the FINAL group may simply fall out of the switch.
            cleaned = []
            for gi, (conds, stmts, coord) in enumerate(groups):
                if isinstance(stmts[-1], c_ast.Break):
                    stmts = stmts[:-1]
                elif not ends_in_return(stmts) and gi != len(groups) - 1:
                    raise CLiftError(
                        f"switch case at {coord} falls through; add "
                        "break/return (fallthrough is outside the subset)")
                cleaned.append((conds, stmts, coord))
            default_body = None
            chain_groups = []
            for conds, stmts, coord in cleaned:
                body = xform_block(c_ast.Compound(stmts, coord), True)
                if conds is None:
                    default_body = body
                else:
                    chain_groups.append((conds, body))
            node = default_body
            for conds, body in reversed(chain_groups):
                cond_expr = None
                for cexpr in conds:
                    eq = c_ast.BinaryOp("==", ctrl, cexpr, sw.coord)
                    cond_expr = (eq if cond_expr is None else
                                 c_ast.BinaryOp("||", cond_expr, eq,
                                                sw.coord))
                node = c_ast.If(cond_expr, body, node, sw.coord)
            out_sw = pre + ([node] if node is not None else [])
            # MID-CASE breaks (beyond the stripped terminators) exit the
            # SWITCH, not any enclosing loop: lower them as a forward
            # goto to a label right after the if-chain, BEFORE any
            # enclosing loop's deep-break pass could misbind them.
            swend = None

            def rb(s):
                nonlocal swend
                if isinstance(s, c_ast.Break):
                    if swend is None:
                        swend = f"__swend{self._tmp}"
                        self._tmp += 1
                    return c_ast.Goto(swend, s.coord)
                if isinstance(s, (c_ast.While, c_ast.For, c_ast.DoWhile,
                                  c_ast.Switch)):
                    return s                     # inner construct's own
                if isinstance(s, c_ast.If):
                    return c_ast.If(
                        s.cond,
                        rb(s.iftrue) if s.iftrue is not None else None,
                        rb(s.iffalse) if s.iffalse is not None else None,
                        s.coord)
                if isinstance(s, c_ast.Compound):
                    return c_ast.Compound(
                        [rb(x) for x in (s.block_items or [])], s.coord)
                return s

            out_sw = [rb(s) for s in out_sw]
            if swend is not None:
                out_sw.append(c_ast.Label(
                    swend, c_ast.EmptyStatement(sw.coord), sw.coord))
            return out_sw

        def is_break_if(s) -> bool:
            if not isinstance(s, c_ast.If) or s.iffalse is not None:
                return False
            b = (s.iftrue.block_items or []
                 if isinstance(s.iftrue, c_ast.Compound) else [s.iftrue])
            return len(b) == 1 and isinstance(b[0], c_ast.Break)

        def lower_deep_breaks(loop) -> list:
            """Breaks beyond the `if (c) break;` idiom (jpeg's
            `if (s) { if ((k += n) >= 64) break; ... }`) lower through
            the goto machinery: break -> goto __brkN with the label
            right after the loop."""
            lbl = None

            def replace(s, top):
                nonlocal lbl
                if isinstance(s, c_ast.Break):
                    if top:
                        return s                 # the direct idiom's own
                    if lbl is None:
                        lbl = f"__brk{self._tmp}"
                        self._tmp += 1
                    return c_ast.Goto(lbl, s.coord)
                if isinstance(s, (c_ast.While, c_ast.For, c_ast.DoWhile,
                                  c_ast.Switch)):
                    return s                     # inner loop owns breaks
                if isinstance(s, c_ast.If):
                    if top and is_break_if(s):
                        return s
                    return c_ast.If(
                        s.cond,
                        replace(s.iftrue, False)
                        if s.iftrue is not None else None,
                        replace(s.iffalse, False)
                        if s.iffalse is not None else None, s.coord)
                if isinstance(s, c_ast.Compound):
                    return c_ast.Compound(
                        [replace(x, top) for x in as_items(s)], s.coord)
                return s

            items2 = as_items(loop.stmt)
            new_items = []
            for k, s in enumerate(items2):
                if isinstance(s, c_ast.Break) and k == len(items2) - 1:
                    new_items.append(s)          # run-once trailing break
                else:
                    new_items.append(replace(s, True))
            body2 = c_ast.Compound(new_items, loop.coord)
            if isinstance(loop, c_ast.For):
                new_loop = c_ast.For(loop.init, loop.cond, loop.next,
                                     body2, loop.coord)
            else:
                new_loop = c_ast.While(loop.cond, body2, loop.coord)
            if lbl is None:
                return [new_loop]
            return [new_loop,
                    c_ast.Label(lbl, c_ast.EmptyStatement(loop.coord),
                                loop.coord)]

        def xform(stmt, in_branch: bool) -> list:
            if isinstance(stmt, c_ast.Switch):
                return desugar_switch(stmt)
            if isinstance(stmt, c_ast.DoWhile):
                body = xform_block(stmt.stmt, True)
                if loose_break(as_items(body)):
                    raise CLiftError(
                        f"break/continue in do-while body at {stmt.coord} "
                        "is outside the subset; restructure")
                return [body, c_ast.While(stmt.cond, body, stmt.coord)]
            if isinstance(stmt, c_ast.While):
                body = xform_block(stmt.stmt, True)
                if (_const_int(stmt.cond) and ends_in_return(as_items(body))
                        and not loose_break(as_items(body))):
                    # while(1) whose body always returns: exactly one
                    # iteration -- inline it.
                    return as_items(body)
                return [c_ast.While(stmt.cond, body, stmt.coord)]
            if isinstance(stmt, c_ast.For):
                body = xform_block(stmt.stmt, True)
                return lower_deep_breaks(
                    c_ast.For(stmt.init, stmt.cond, stmt.next, body,
                              stmt.coord))
            if isinstance(stmt, c_ast.If):
                t = (xform_block(stmt.iftrue, True)
                     if stmt.iftrue is not None else None)
                f = (xform_block(stmt.iffalse, True)
                     if stmt.iffalse is not None else None)
                return [c_ast.If(stmt.cond, t, f, stmt.coord)]
            if isinstance(stmt, c_ast.Compound):
                return [xform_block(stmt, in_branch)]
            if in_branch and self._string_only_printf(stmt):
                nm, k = slot_for(stmt)
                return [c_ast.Assignment(
                    "=", c_ast.ID(nm, stmt.coord),
                    c_ast.Constant("int", str(k), stmt.coord), stmt.coord)]
            return [stmt]

        body = xform_block(fndef.body, False)
        fndef.body = self._rewrite_gotos(body, temps)

    def _rewrite_gotos(self, body, temps) -> "c_ast.Compound":
        """Lower FORWARD gotos into skip flags, per enclosing compound:

          goto L;   ->  __goto_L = 1;  (+ exit any FOR loops between)
          L: stmt   ->  __goto_L = 0; <stmt guarded like the rest>

        A label lives at the top level of SOME compound (the function
        body, a loop body, a branch); its gotos may sit anywhere below
        that compound, including inside nested FOR loops (jpeg's
        id_found search: the loop gains a flag-conditional break, and
        the in-loop statements after the jump run under the no-flags
        guard -- one masked partial iteration, no effects).  Statements
        of the label's compound between the goto point and the label
        run under ``if ((flagA | flagB | ...) == 0)`` -- the
        early-return discipline applied to jumps.  Refused loudly:
        backward gotos, gotos escaping while/do-while loops, unknown
        labels."""

        def goto_names(n) -> List[str]:
            out: List[str] = []

            class V(c_ast.NodeVisitor):
                def visit_Goto(v, nn):
                    out.append(nn.name)

            if n is not None:
                V().visit(n)
            return out

        if not goto_names(body):
            return body

        flag: Dict[str, str] = {}

        def flag_for(name: str) -> str:
            if name not in flag:
                flag[name] = f"__goto_{name}"
                temps.append(flag[name])
            return flag[name]

        def no_flags(names, coord):
            expr = None
            for L in names:
                e = c_ast.ID(flag_for(L), coord)
                expr = e if expr is None else c_ast.BinaryOp("|", expr, e,
                                                             coord)
            return c_ast.BinaryOp("==", expr, c_ast.Constant("int", "0"),
                                  coord)

        def as_items(node):
            if node is None:
                return []
            if isinstance(node, c_ast.Compound):
                return list(node.block_items or [])
            return [node]

        def rewrite(stmt, active):
            """Replace active gotos under ``stmt``; loops crossed by a
            jump gain guard+break discipline.  Returns the new stmt."""
            hit = [g for g in goto_names(stmt) if g in active]
            if not hit:
                return stmt
            if isinstance(stmt, c_ast.Goto):
                return c_ast.Assignment(
                    "=", c_ast.ID(flag_for(stmt.name), stmt.coord),
                    c_ast.Constant("int", "1", stmt.coord), stmt.coord)
            if isinstance(stmt, c_ast.Compound):
                return c_ast.Compound(
                    seq_guard(as_items(stmt), active, stmt.coord),
                    stmt.coord)
            if isinstance(stmt, c_ast.If):
                return c_ast.If(
                    stmt.cond,
                    rewrite(stmt.iftrue, active)
                    if stmt.iftrue is not None else None,
                    rewrite(stmt.iffalse, active)
                    if stmt.iffalse is not None else None,
                    stmt.coord)
            if isinstance(stmt, c_ast.For):
                items2 = seq_guard(as_items(stmt.stmt), active, stmt.coord)
                esc = sorted({g for g in goto_names(stmt.stmt)
                              if g in active})
                brk = c_ast.If(
                    c_ast.BinaryOp("==", no_flags(esc, stmt.coord),
                                   c_ast.Constant("int", "0", stmt.coord),
                                   stmt.coord),
                    c_ast.Break(stmt.coord), None, stmt.coord)
                return c_ast.For(stmt.init, stmt.cond, stmt.next,
                                 c_ast.Compound(items2 + [brk],
                                                stmt.coord), stmt.coord)
            if isinstance(stmt, (c_ast.While, c_ast.DoWhile)):
                raise CLiftError(
                    f"goto escaping a while/do-while at {stmt.coord} is "
                    "outside the modeled envelope; restructure")
            if isinstance(stmt, c_ast.Label):
                return c_ast.Label(stmt.name, rewrite(stmt.stmt, active),
                                   stmt.coord)
            raise CLiftError(
                f"goto in unsupported construct {type(stmt).__name__} at "
                f"{getattr(stmt, 'coord', '?')}")

        def seq_guard(stmts, active, coord):
            """Within a compound below the label level: statements after
            a goto point run under the no-flags guard."""
            out = []
            for k, s in enumerate(stmts):
                hit = [g for g in goto_names(s) if g in active]
                if not hit:
                    out.append(s)
                    continue
                out.append(rewrite(s, active))
                rest = seq_guard(stmts[k + 1:], active, coord)
                if rest:
                    wrap = c_ast.If(
                        no_flags(sorted(active), coord),
                        c_ast.Compound(rest, coord), None, coord)
                    self._synth_reason[id(wrap)] = "after a goto point"
                    out.append(wrap)
                return out
            return out

        def process(items, coord):
            """Handle labels at THIS compound level (recursing into
            nested compounds for deeper labels first)."""
            # Recurse structurally so deeper compounds resolve their own
            # label/goto pairs before this level's flags apply.
            def descend(s):
                if isinstance(s, c_ast.Compound):
                    return c_ast.Compound(
                        process(as_items(s), s.coord), s.coord)
                if isinstance(s, c_ast.If):
                    return c_ast.If(
                        s.cond,
                        descend(s.iftrue) if s.iftrue is not None
                        else None,
                        descend(s.iffalse) if s.iffalse is not None
                        else None, s.coord)
                if isinstance(s, (c_ast.For, c_ast.While, c_ast.DoWhile)):
                    body2 = c_ast.Compound(
                        process(as_items(s.stmt), s.coord), s.coord)
                    if isinstance(s, c_ast.For):
                        return c_ast.For(s.init, s.cond, s.next, body2,
                                         s.coord)
                    if isinstance(s, c_ast.While):
                        return c_ast.While(s.cond, body2, s.coord)
                    return c_ast.DoWhile(s.cond, body2, s.coord)
                if isinstance(s, c_ast.Label):
                    return c_ast.Label(s.name, descend(s.stmt), s.coord)
                return s

            items = [descend(s) for s in items]
            labels_here = {it.name: k for k, it in enumerate(items)
                           if isinstance(it, c_ast.Label)}
            if not labels_here:
                return items
            active = set(labels_here)
            # Forward check at this level.
            for k, it in enumerate(items):
                holder = it.stmt if isinstance(it, c_ast.Label) else it
                for g in goto_names(holder):
                    if g in labels_here and labels_here[g] <= k:
                        raise CLiftError(
                            f"backward goto {g!r} is outside the "
                            "modeled envelope (forward jumps only)")
            out: List[object] = []
            seen_goto = False
            for k_i, it in enumerate(items):
                if (seen_goto and isinstance(it, c_ast.Break)
                        and k_i == len(items) - 1):
                    # A trailing break (the run-once while(1) idiom) is
                    # reached on every path: forward-only jumps mean all
                    # this level's labels precede it, and each label
                    # resets its flag -- so by here every guard passes.
                    # It must also STAY a syntactic Break, or
                    # _exec_while no longer recognizes the idiom and the
                    # loop falls to the dynamic-while lowering.
                    out.append(it)
                    continue
                if isinstance(it, c_ast.Label) and it.name in active:
                    out.append(c_ast.Assignment(
                        "=", c_ast.ID(flag_for(it.name), it.coord),
                        c_ast.Constant("int", "0", it.coord), it.coord))
                    inner = rewrite(it.stmt, active)
                    wrap = c_ast.If(no_flags(sorted(active), it.coord),
                                    inner, None, it.coord)
                    self._synth_reason[id(wrap)] = "after a goto point"
                    out.append(wrap)
                    seen_goto = seen_goto or bool(
                        [g for g in goto_names(it.stmt) if g in active])
                    continue
                if seen_goto:
                    inner = rewrite(it, active)
                    wrap = c_ast.If(
                        no_flags(sorted(active),
                                 getattr(it, "coord", None)),
                        inner, None, getattr(it, "coord", None))
                    self._synth_reason[id(wrap)] = "after a goto point"
                    out.append(wrap)
                else:
                    out.append(rewrite(it, active))
                    seen_goto = seen_goto or bool(
                        [g for g in goto_names(it) if g in active])
            return out

        new_items = process(as_items(body), body.coord)
        stray = goto_names(c_ast.Compound(new_items, body.coord))
        if stray:
            raise CLiftError(
                f"goto to unknown/backward label(s) {sorted(set(stray))}; "
                "only forward jumps to a label in an enclosing compound "
                "are modeled")
        return c_ast.Compound(new_items, body.coord)


    @staticmethod
    def _has_return(node) -> bool:
        found = []

        class V(c_ast.NodeVisitor):
            def visit_Return(v, n):
                found.append(n)

        V().visit(node)
        return bool(found)

    def _rewrite_early_returns(self, fndef):
        """Lower structured early returns to a carried flag pair.

        ``return E`` anywhere becomes ``if (!__ret_set) { __ret_val = E;
        __ret_set = 1; }``; every statement after a return-containing
        one runs under ``if (!__ret_set)``; every loop whose subtree
        returns gains ``&& !__ret_set`` in its condition with the
        for-next moved into the body under the same guard (the exact
        discipline of the break lowering, applied function-wide) -- so
        ``if (hash[i] != golden[i]) return 1;`` inside a scan loop
        (checkGolden, sha256_common_tmr.c:191-198) exits with C's
        semantics.  Loop conditions become PURE carried variables primed
        before the loop and re-evaluated at the end of each body under
        the guard -- C's return exits WITHOUT re-testing the condition,
        so a side-effecting condition must not run on the returning
        exit.  Returns (new_body_items, set_name, val_name, synth_names)
        where synth_names are locals the caller must pre-create, or
        (None, None, None, None) when the body has no early return."""
        items = list(fndef.body.block_items or [])
        early = any(self._has_return(s) for s in items[:-1]) or (
            items and not isinstance(items[-1], c_ast.Return)
            and self._has_return(items[-1]))
        if not early:
            return None, None, None, None
        set_n = f"__ret_set{self._tmp}"
        val_n = f"__ret_val{self._tmp}"
        self._tmp += 1
        synth_names = [set_n, val_n]
        not_set = lambda coord: c_ast.BinaryOp(  # noqa: E731
            "==", c_ast.ID(set_n), c_ast.Constant("int", "0"), coord)

        def ret_to_set(n):
            expr = n.expr if n.expr is not None else c_ast.Constant(
                "int", "0")
            body = c_ast.Compound([
                c_ast.Assignment("=", c_ast.ID(val_n), expr, n.coord),
                c_ast.Assignment("=", c_ast.ID(set_n),
                                 c_ast.Constant("int", "1"), n.coord),
            ], n.coord)
            return c_ast.If(not_set(n.coord), body, None, n.coord)

        def xform(s):
            """Transform ONE statement in place-ish; returns new stmt."""
            if isinstance(s, c_ast.Return):
                return ret_to_set(s)
            if not self._has_return(s):
                return s
            if isinstance(s, c_ast.Compound):
                return c_ast.Compound(seq(list(s.block_items or [])),
                                      s.coord)
            if isinstance(s, c_ast.If):
                return c_ast.If(
                    s.cond,
                    xform(s.iftrue) if s.iftrue is not None else None,
                    xform(s.iffalse) if s.iffalse is not None else None,
                    s.coord)
            if isinstance(s, (c_ast.For, c_ast.While)):
                cond = getattr(s, "cond", None)
                guard = not_set(s.coord)
                body_items = (list(s.stmt.block_items or [])
                              if isinstance(s.stmt, c_ast.Compound)
                              else [s.stmt])
                body_items = seq(body_items)
                nxt = getattr(s, "next", None)
                if nxt is not None:
                    body_items.append(
                        c_ast.If(not_set(s.coord), nxt, None, s.coord))
                # Pure carried condition: primed before the loop,
                # re-evaluated (effects included) at the body end under
                # the !set guard so the returning exit never re-runs it.
                cnd = f"__cnd{self._tmp}"
                self._tmp += 1
                synth_names.append(cnd)
                pre = []
                init = getattr(s, "init", None)
                if init is not None:
                    pre.append(init)
                if cond is not None:
                    cond_val = c_ast.BinaryOp(
                        "!=", cond, c_ast.Constant("int", "0"), s.coord)
                    prime = c_ast.If(
                        guard,
                        c_ast.Assignment("=", c_ast.ID(cnd), cond_val,
                                         s.coord),
                        None, s.coord)
                    body_items.append(c_ast.Assignment(
                        "=", c_ast.ID(cnd), c_ast.Constant("int", "0"),
                        s.coord))
                    body_items.append(c_ast.If(
                        guard,
                        c_ast.Assignment("=", c_ast.ID(cnd), cond_val,
                                         s.coord),
                        None, s.coord))
                else:
                    prime = c_ast.Assignment(
                        "=", c_ast.ID(cnd), guard, s.coord)
                    body_items.append(c_ast.Assignment(
                        "=", c_ast.ID(cnd), guard, s.coord))
                pre.append(c_ast.Assignment(
                    "=", c_ast.ID(cnd), c_ast.Constant("int", "0"),
                    s.coord))
                pre.append(prime)
                new_body = c_ast.Compound(body_items, s.coord)
                loop = c_ast.For(None, c_ast.ID(cnd), None, new_body,
                                 s.coord)
                return c_ast.Compound(pre + [loop], s.coord)
            raise CLiftError(
                f"return in unsupported construct "
                f"{type(s).__name__} at {getattr(s, 'coord', '?')}")

        def seq(stmts):
            out = []
            for k, s in enumerate(stmts):
                if not self._has_return(s):
                    out.append(s)
                    continue
                out.append(xform(s))
                rest = seq(stmts[k + 1:])
                if rest:
                    wrap = c_ast.If(
                        not_set(getattr(s, "coord", None)),
                        c_ast.Compound(rest, getattr(s, "coord", None)),
                        None, getattr(s, "coord", None))
                    self._synth_reason[id(wrap)] = \
                        "after an early-return point"
                    out.append(wrap)
                return out
            return out

        return seq(items), set_n, val_n, synth_names

    def _rewrite_breaks(self, stmt, sc: _Scope):
        """Lower mid-loop conditional breaks (``if (c) break;``) to a
        carried break flag: the loop condition gains ``&& !brk`` and
        every statement after the break point runs under ``if (!brk)``,
        so the exit is exact -- same iteration count, same final state
        as the C program (sha256_tmr.c's for-100 early exit; the
        quicksort error-break idiom).  Returns a rewritten For (or the
        original when the body has no breaks).  Breaks in any other
        position refuse loudly; breaks inside NESTED loops belong to
        those loops and are left alone."""
        items = (list(stmt.stmt.block_items or [])
                 if isinstance(stmt.stmt, c_ast.Compound) else [stmt.stmt])
        if not any(self._count_breaks(s) for s in items
                   if not isinstance(s, (c_ast.While, c_ast.For))):
            return stmt
        brk = f"__brk{self._tmp}"
        self._tmp += 1
        sc.locals[brk] = jnp.int32(0)

        def is_break_if(s):
            """``if (c) break;`` / ``if (c) { break; }`` with no else."""
            if not isinstance(s, c_ast.If) or s.iffalse is not None:
                return False
            body = (s.iftrue.block_items or []
                    if isinstance(s.iftrue, c_ast.Compound) else [s.iftrue])
            return len(body) == 1 and isinstance(body[0], c_ast.Break)

        def rewrite(seq):
            out = []
            for k, s in enumerate(seq):
                if isinstance(s, (c_ast.While, c_ast.For)):
                    out.append(s)          # inner loop owns its breaks
                    continue
                if is_break_if(s):
                    set_brk = c_ast.Assignment(
                        "=", c_ast.ID(brk),
                        c_ast.Constant("int", "1"), s.coord)
                    out.append(c_ast.If(s.cond, set_brk, None, s.coord))
                    rest = rewrite(seq[k + 1:])
                    if rest:
                        guard = c_ast.BinaryOp(
                            "==", c_ast.ID(brk),
                            c_ast.Constant("int", "0"), s.coord)
                        wrap = c_ast.If(
                            guard, c_ast.Compound(rest, s.coord), None,
                            s.coord)
                        self._synth_reason[id(wrap)] = \
                            "after a mid-loop break point"
                        out.append(wrap)
                    return out
                if self._count_breaks(s):
                    raise CLiftError(
                        f"break in unsupported position at "
                        f"{getattr(s, 'coord', '?')}; only the "
                        "'if (cond) break;' idiom is lowered")
                out.append(s)
            return out

        body_stmts = rewrite(items)
        not_brk = c_ast.BinaryOp("==", c_ast.ID(brk),
                                 c_ast.Constant("int", "0"), stmt.coord)
        # C does not run the increment on the broken-out iteration: move
        # the next-expression into the body under the !brk guard (an If
        # STATEMENT, so its side effects are genuinely masked -- a
        # ternary would evaluate both arms under tracing).
        if stmt.next is not None:
            body_stmts.append(c_ast.If(not_brk, stmt.next, None,
                                       stmt.coord))
        # The loop condition becomes a PURE carried variable: C's break
        # exits WITHOUT re-testing the condition, so a side-effecting
        # condition (while (g--)) must not be evaluated on the
        # broken-out exit.  The variable is primed here (the pre-loop
        # test, effects apply once) and re-evaluated at the END of the
        # body under the !brk guard.
        cnd = f"__cnd{self._tmp}"
        self._tmp += 1
        sc.locals[cnd] = jnp.int32(0)
        if stmt.cond is not None:
            cond_val = c_ast.BinaryOp("!=", stmt.cond,
                                      c_ast.Constant("int", "0"),
                                      stmt.coord)
            self._exec_stmt(c_ast.Assignment("=", c_ast.ID(cnd),
                                             cond_val, stmt.coord), sc)
            body_stmts.append(c_ast.Assignment(
                "=", c_ast.ID(cnd), c_ast.Constant("int", "0"),
                stmt.coord))
            body_stmts.append(c_ast.If(
                not_brk,
                c_ast.Assignment("=", c_ast.ID(cnd), cond_val,
                                 stmt.coord),
                None, stmt.coord))
        else:
            self._exec_stmt(c_ast.Assignment(
                "=", c_ast.ID(cnd), c_ast.Constant("int", "1"),
                stmt.coord), sc)
            body_stmts.append(c_ast.Assignment(
                "=", c_ast.ID(cnd), not_brk, stmt.coord))
        new_body = c_ast.Compound(body_stmts, stmt.stmt.coord)
        return c_ast.For(None, c_ast.ID(cnd), None, new_body, stmt.coord)

    @staticmethod
    def _contains_printf(node) -> bool:
        found: List[object] = []

        class V(c_ast.NodeVisitor):
            def visit_FuncCall(v, n):
                if isinstance(n.name, c_ast.ID) and n.name.name == "printf":
                    found.append(n)
                v.generic_visit(n)

        V().visit(node)
        return bool(found)

    def _exec_for(self, stmt, sc: _Scope):
        if stmt.init is not None:
            self._exec_stmt(stmt.init, sc)
        # PRINT-ONLY loop (aes.c dumping the ciphertext bytes): a loop
        # whose body writes nothing (beyond print slots) but prints
        # per-iteration values.  Its observable IS the printed sequence,
        # so it unrolls at trace time under a concrete bound -- each
        # iteration's printf appends one program output.  A traced bound
        # refuses loudly (the output arity must be static).
        if (stmt.cond is not None and stmt.stmt is not None
                and self._contains_printf(stmt.stmt)
                and all(n.startswith("__print_sel_")
                        or n in ("__print_buf", "__print_cnt")
                        for n in self._assigned_names(stmt.stmt))):
            for _ in range(4096):
                live = (self._const_eval(stmt.cond, sc)
                        if not self._has_effects(stmt.cond) else None)
                if live is None:
                    raise CLiftError(
                        f"print-only loop at {stmt.coord} has a traced "
                        "bound; the number of printed outputs must be "
                        "static")
                if not live:
                    return None
                ret = self._exec_block(stmt.stmt, sc)
                if ret is not None:
                    raise CLiftError(
                        f"return inside a loop at {stmt.coord}; "
                        "restructure")
                if stmt.next is not None:
                    self.eval(stmt.next, sc)
            raise CLiftError(
                f"print-only loop at {stmt.coord} exceeds the 4096-"
                "iteration unroll bound")
        stmt = self._rewrite_breaks(stmt, sc)
        self._preseat(stmt, sc)
        carry_names = self._loop_carry(stmt, sc)

        def pack():
            return tuple(sc.read_binding(n) for n in carry_names)

        def unpack(sub_sc, vals):
            for n, v in zip(carry_names, vals):
                sub_sc.write_binding(n, v)
                sub_sc.consts.pop(n, None)   # traced write: value unknown

        trip = self._static_trip(stmt, sc)
        if trip is not None:
            def body(carry, _):
                sub = sc.fork(no_print_at=stmt.coord)
                # Per-iteration prints become STACKED scan outputs (one
                # [trip]-shaped observable per printed value, dfmul's
                # per-vector diagnostic line); the arity is fixed by the
                # single body trace.  Branch prints inside the body
                # still go through slots / loud refusals as usual.
                sub.printed = []
                unpack(sub, carry)
                ret = self._exec_block(stmt.stmt, sub)
                if ret is not None:
                    raise CLiftError(
                        f"return inside a loop at {stmt.coord}; restructure")
                if stmt.next is not None:
                    self.eval(stmt.next, sub)
                self._guard_reseat(sc, sub, stmt.coord)
                return (tuple(sub.read_binding(n) for n in carry_names),
                        tuple(jnp.asarray(p) for p in sub.printed))

            out, ys = jax.lax.scan(body, pack(), None, length=trip)
            unpack(sc, out)
            if ys:
                if (isinstance(sc.printed, _NoPrintList)
                        and "__print_buf" in sc.g
                        and all(jnp.ndim(y) == 1 for y in ys)):
                    # Stacked prints inside a DYNAMIC outer context flow
                    # into the UART buffer in true stdout order
                    # (iteration-major interleave).
                    flat = jnp.stack(
                        [y.astype(jnp.uint32) for y in ys],
                        axis=1).reshape(-1)
                    buf = sc.g["__print_buf"]
                    cnt = sc.g["__print_cnt"]
                    idx = cnt + jnp.arange(flat.size, dtype=jnp.int32)
                    # mode="drop" discards out-of-range writes outright:
                    # clipping them onto the last word would scatter
                    # duplicate indices with conflicting values, and JAX
                    # leaves duplicate-index order unspecified -- the
                    # legit final word could lose to a stale overflow row
                    # exactly when the buffer fills.
                    buf = buf.at[idx].set(flat, mode="drop")
                    sc.g["__print_buf"] = buf
                    sc.g["__print_cnt"] = cnt + flat.size
                else:
                    sc.printed.extend(list(ys))
            return None

        # A side-effecting condition (C's `while (length--)`) cannot be
        # evaluated in the while cond function -- writes made there are
        # discarded.  Rotate the loop instead: evaluate the condition once
        # up front (its effects apply), carry its truth value, and have
        # each iteration run body+next then re-evaluate the condition with
        # effects inside the body.  Exact C semantics, including the final
        # value of the side-effected variable after the failing test.
        if stmt.cond is not None and self._loop_carry(stmt.cond, sc):
            # int32 truth carry, not bool: every loop carry can become an
            # injectable region leaf, and the memory map is 32-bit words.
            t0 = self._truth(self.eval(stmt.cond, sc)).astype(jnp.int32)

            def cond_rot(carry):
                return jnp.not_equal(carry[-1], 0)

            def body_rot(carry):
                sub = sc.fork(no_print_at=stmt.coord)
                unpack(sub, carry[:-1])
                ret = self._exec_block(stmt.stmt, sub)
                if ret is not None:
                    raise CLiftError(
                        f"return inside a loop at {stmt.coord}; "
                        "restructure")
                if stmt.next is not None:
                    self.eval(stmt.next, sub)
                t = self._truth(self.eval(stmt.cond, sub)
                                ).astype(jnp.int32)
                self._guard_reseat(sc, sub, stmt.coord)
                return tuple(sub.read_binding(n) for n in carry_names) + (t,)

            out = jax.lax.while_loop(cond_rot, body_rot, pack() + (t0,))
            unpack(sc, out[:-1])
            return None

        # General for: lower as while with explicit cond/next.
        def cond_f(carry):
            sub = sc.fork(no_print_at=stmt.coord)
            unpack(sub, carry)
            c = (self.eval(stmt.cond, sub) if stmt.cond is not None
                 else jnp.int32(1))
            return self._truth(c)

        def body_f(carry):
            sub = sc.fork(no_print_at=stmt.coord)
            unpack(sub, carry)
            ret = self._exec_block(stmt.stmt, sub)
            if ret is not None:
                raise CLiftError(
                    f"return inside a loop at {stmt.coord}; restructure")
            if stmt.next is not None:
                self.eval(stmt.next, sub)
            self._guard_reseat(sc, sub, stmt.coord)
            return tuple(sub.read_binding(n) for n in carry_names)

        out = jax.lax.while_loop(cond_f, body_f, pack())
        unpack(sc, out)
        return None

    def _count_breaks(self, node) -> int:
        count = 0

        class V(c_ast.NodeVisitor):
            def visit_Break(v, n):
                nonlocal count
                count += 1

            def visit_While(v, n):      # breaks inside nested loops bind
                pass                    # to THOSE loops; don't descend

            def visit_For(v, n):
                pass

        V().visit(node)
        return count

    def _exec_while(self, stmt, sc: _Scope):
        # The run-once idiom ``while (1) { ...; break; }`` (sha256.c's
        # main): a body whose LAST top-level statement is the loop's only
        # break executes exactly once under the condition -- and with a
        # static-true condition it inlines into the enclosing scope, so
        # printf stays a program output.
        items = (stmt.stmt.block_items or []
                 if isinstance(stmt.stmt, c_ast.Compound) else [stmt.stmt])
        if items and isinstance(items[-1], c_ast.Break):
            body = c_ast.Compound(list(items[:-1]), stmt.stmt.coord)
            if self._count_breaks(body):
                raise CLiftError(
                    f"break before the tail of the loop at {stmt.coord}; "
                    "restructure")
            if _const_int(stmt.cond):
                return self._exec_block(body, sc)
            return self._exec_stmt(
                c_ast.If(stmt.cond, body, None, stmt.coord), sc)
        fake = c_ast.For(None, stmt.cond, None, stmt.stmt, stmt.coord)
        return self._exec_for(fake, sc)

    def _static_trip(self, stmt, sc) -> Optional[int]:
        """Trip count for the canonical `for (i = A; i < B; i++)` shape
        with literal A/B and the loop variable not written in the body."""
        init, cond, nxt = stmt.init, stmt.cond, stmt.next
        if init is None or cond is None or nxt is None:
            return None
        # init: i = A (assignment or single decl)
        if isinstance(init, c_ast.DeclList) and len(init.decls) == 1:
            var, a = init.decls[0].name, _const_int(init.decls[0].init)
        elif isinstance(init, c_ast.Assignment) and init.op == "=" \
                and isinstance(init.lvalue, c_ast.ID):
            var, a = init.lvalue.name, _const_int(init.rvalue)
        else:
            return None
        if a is None:
            return None
        if not (isinstance(cond, c_ast.BinaryOp) and cond.op in ("<", "<=")
                and isinstance(cond.left, c_ast.ID)
                and cond.left.name == var):
            return None
        b = _const_int(cond.right)
        if b is None:
            return None
        inc_ok = (isinstance(nxt, c_ast.UnaryOp)
                  and nxt.op in ("++", "p++")
                  and isinstance(nxt.expr, c_ast.ID)
                  and nxt.expr.name == var)
        if not inc_ok:
            return None
        # The loop variable must not be written inside the body (the scan
        # carries it via the next-expression only).
        if var in self._assigned_names(stmt.stmt):
            return None
        trip = (b - a) + (1 if cond.op == "<=" else 0)
        return max(0, trip)

    def _exec_if(self, stmt, sc: _Scope):
        self._preseat(stmt, sc)
        if not self._has_effects(stmt.cond):
            kc = self._const_eval(stmt.cond, sc)
            if kc is not None:
                # Statically-decided predicate: execute only the taken
                # branch INLINE (exact C semantics; keeps trace-time
                # constants known -- aes_enc.c's switch on a literal
                # `type` must yield a known nb for the ciphertext print
                # loop -- and keeps prints in statically-taken branches
                # legal program outputs).
                node = stmt.iftrue if kc else stmt.iffalse
                return (self._exec_block(node, sc)
                        if node is not None else None)
        cval = self.eval(stmt.cond, sc)      # cond effects apply once
        carry_names = self._loop_carry(stmt, sc)
        c = self._truth(cval)

        def branch(node):
            def run(vals):
                sub = sc.fork(
                    no_print_at=stmt.coord,
                    no_print_reason=self._synth_reason.get(id(stmt)))
                for n, v in zip(carry_names, vals):
                    sub.write_binding(n, v)
                if node is not None:
                    ret = self._exec_block(node, sub)
                    if ret is not None:
                        raise CLiftError(
                            f"return inside if at {stmt.coord}; restructure")
                self._guard_reseat(sc, sub, stmt.coord)
                return tuple(sub.read_binding(n) for n in carry_names)
            return run

        vals = tuple(sc.read_binding(n) for n in carry_names)
        out = jax.lax.cond(c, branch(stmt.iftrue), branch(stmt.iffalse),
                           vals)
        for n, v in zip(carry_names, out):
            sc.write_binding(n, v)
            sc.consts.pop(n, None)           # traced write: value unknown
        return None


# ---------------------------------------------------------------------------
# Translation-unit ingestion
# ---------------------------------------------------------------------------

