"""Fleet worker: claim queued campaigns, run them crash-safely, report.

One worker is one process in the fleet.  Its loop is the reference
supervisor's per-QEMU-worker thread (threadFunctions.py) rebuilt on the
PR 4-8 primitives:

  * it **claims** items from the :class:`~coast_tpu.fleet.queue
    .CampaignQueue` (atomic rename; lease renewed from the campaign's
    own progress heartbeat);
  * it **runs** each item through a cached
    :class:`~coast_tpu.inject.campaign.CampaignRunner`
    (:mod:`coast_tpu.fleet.compile_cache`) with the item's journal --
    every collected batch is fsync'd before the lease beat that
    acknowledges it, so the journal is always at least as complete as
    the queue believes;
  * it **survives SIGKILL by construction**: the worker holds no state
    the queue + journal do not.  A killed worker's lease expires (or the
    fleet supervisor requeues it on observing the death), the next
    claimant re-opens the same journal, and ``CampaignRunner.run``
    resumes at the first missing batch bit-for-bit -- the journal's
    exclusive flock guarantees the kill really is dead (a merely-slow
    worker still holds the lock, and the duplicate claimant backs off
    with :class:`~coast_tpu.inject.journal.JournalLockedError`).

Per item the worker lands a ``done`` record carrying the campaign
summary, the per-run ``codes`` sha256 (the fleet merge's parity pin),
and the compile-cache outcome.  Throughout, it mirrors a worker-status
doc (atomic JSON) into the queue's ``status/`` directory -- the fleet
aggregator's scrape surface (:mod:`coast_tpu.fleet.telemetry`).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import traceback
from typing import Dict, Optional

import numpy as np

from coast_tpu.fleet.compile_cache import CompileCache
from coast_tpu.fleet.queue import CampaignQueue, LostLeaseError, QueueItem
from coast_tpu.inject.journal import JournalError, JournalLockedError
from coast_tpu.obs import flightrec
from coast_tpu.obs.metrics import CampaignMetrics, atomic_write_json

__all__ = ["Worker", "codes_sha256"]


def codes_sha256(codes: np.ndarray) -> str:
    """Parity pin over a campaign's per-run class codes: bit-identical
    campaigns -- and nothing else -- share it."""
    return hashlib.sha256(
        np.ascontiguousarray(codes, dtype=np.int32).tobytes()).hexdigest()


class _LeaseKeeper:
    """Renew an item's lease from a background thread while the worker
    sits inside a long blocking phase with no progress beats -- the cold
    program build (trace + lower + XLA compile), which compile_cache
    documents as the dominant cold-start cost and which can easily
    outlast the lease.  Without this, every cold config's first attempt
    gets reaped mid-compile and the fleet pays N duplicate compiles.
    A renewal that fails with :class:`LostLeaseError` is parked in
    ``lost`` for the caller (raising on the keeper thread would vanish)."""

    def __init__(self, q: CampaignQueue, item_id: str, worker: str,
                 lease_s: float):
        self.q, self.item_id, self.worker = q, item_id, worker
        self.lease_s = float(lease_s)
        self.lost: Optional[LostLeaseError] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"lease-{item_id}", daemon=True)

    def __enter__(self) -> "_LeaseKeeper":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join()

    def _run(self) -> None:
        while not self._stop.wait(self.lease_s / 3.0):
            try:
                self.q.renew(self.item_id, self.worker, self.lease_s)
                flightrec.record("lease_renew", item=self.item_id,
                                 phase="compile")
            except LostLeaseError as e:
                flightrec.record("lease_lost", item=self.item_id,
                                 phase="compile")
                self.lost = e
                return


class Worker:
    """One fleet worker process (or an in-process drain loop in tests)."""

    def __init__(self, queue: "CampaignQueue | str", worker_id: str,
                 mesh_devices: Optional[int] = None,
                 lease_s: float = 60.0, poll_s: float = 0.25,
                 cache: Optional[CompileCache] = None,
                 metrics: Optional[CampaignMetrics] = None,
                 max_retries: int = 2, max_item_attempts: int = 3):
        self.q = (queue if isinstance(queue, CampaignQueue)
                  else CampaignQueue(queue))
        self.worker_id = str(worker_id)
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.max_item_attempts = int(max_item_attempts)
        self.cache = cache if cache is not None \
            else CompileCache(self.q.cache_dir)
        self.metrics = metrics if metrics is not None else CampaignMetrics()
        self._mesh = None
        if mesh_devices:
            from coast_tpu.parallel.mesh import make_mesh
            self._mesh = make_mesh(int(mesh_devices))
        self._retry = None
        if max_retries > 0:
            from coast_tpu.inject.resilience import RetryPolicy
            self._retry = RetryPolicy(max_attempts=int(max_retries) + 1)
        self.items_done = 0
        self.items_failed = 0
        self.items_yielded = 0            # journal-locked backoffs
        self._current_item: Optional[str] = None
        self._write_status("idle")

    # -- status doc ----------------------------------------------------------
    def _write_status(self, state: str) -> None:
        """Atomically mirror this worker's live state for the fleet
        aggregator.  The campaign block is the standard CampaignMetrics
        snapshot, included only while an item is actually running --
        completed items are counted from their durable done records, so
        the aggregate never double-counts a finished campaign."""
        doc: Dict[str, object] = {
            "format": "coast-fleet-worker", "version": 1,
            "worker": self.worker_id, "pid": os.getpid(),
            "state": state, "item": self._current_item,
            "items_done": self.items_done,
            "items_failed": self.items_failed,
            "items_yielded": self.items_yielded,
            "cache": self.cache.snapshot(),
            "updated_unix_s": round(time.time(), 6),
        }
        if state == "running":
            doc["campaign"] = self.metrics.snapshot()
        atomic_write_json(self.q.worker_status_path(self.worker_id), doc)

    # -- the drain loop ------------------------------------------------------
    def drain(self, idle_exit: bool = True,
              max_items: Optional[int] = None) -> int:
        """Claim-and-run until the queue is drained (``idle_exit``) or
        ``max_items`` items have been attempted.  Returns how many items
        this worker completed."""
        attempted = 0
        while max_items is None or attempted < max_items:
            self.q.requeue_expired()
            item = self.q.claim(self.worker_id, self.lease_s)
            if item is None:
                if idle_exit and self.q.drained():
                    break
                self._write_status("idle")
                time.sleep(self.poll_s)
                continue
            attempted += 1
            self.run_item(item)
        self._write_status("exited")
        return self.items_done

    # -- one item ------------------------------------------------------------
    def run_item(self, item: QueueItem) -> bool:
        """Run one claimed item to a terminal queue state.  Returns True
        if it completed (False: failed terminally or yielded)."""
        spec = item.spec
        self._current_item = item.id
        flightrec.record("lease_claim", item=item.id,
                         attempts=int(item.attempts))
        keeper = _LeaseKeeper(self.q, item.id, self.worker_id,
                              self.lease_s)
        try:
            with keeper:
                runner, strategy, cache_key, cache_event = \
                    self.cache.runner(spec, mesh=self._mesh,
                                      metrics=self.metrics,
                                      retry=self._retry)
        except (RuntimeError, ValueError) as e:
            # Deterministic build failure: any worker would fail the
            # same way, so the item is terminally failed, not requeued.
            self.items_failed += 1
            self._current_item = None
            self.q.fail(item.id, self.worker_id, f"build: {e}")
            self._write_status("idle")
            return False
        if keeper.lost is not None:
            # Our claim moved while we compiled.  The compile itself is
            # not wasted (the cache keeps it), but the item belongs to
            # another worker now -- stop touching it.
            flightrec.current().dump(
                "lease_lost", extra={"item": item.id,
                                     "worker": self.worker_id,
                                     "phase": "compile",
                                     "error": str(keeper.lost)})
            self.items_yielded += 1
            self._current_item = None
            self._write_status("idle")
            return False

        from coast_tpu.inject.spec import CampaignSpec
        cs = CampaignSpec.from_item(spec)
        state = {"last_renew": time.monotonic(), "marked": False}
        throttle = cs.throttle_s

        def progress(done: int, counts: Dict[str, int]) -> None:
            # First collected batch proves the compile happened: record
            # the key so a restarted worker's rebuild is a cache hit.
            if not state["marked"]:
                self.cache.mark_compiled(cache_key, spec)
                state["marked"] = True
            now = time.monotonic()
            if now - state["last_renew"] >= self.lease_s / 3.0:
                self.q.renew(item.id, self.worker_id, self.lease_s)
                flightrec.record("lease_renew", item=item.id,
                                 phase="campaign", done=int(done))
                state["last_renew"] = now
            self._write_status("running")
            if throttle > 0:
                time.sleep(throttle)

        stop_when = cs.stop_when_parsed()
        try:
            with runner.telemetry.activate():
                if cs.delta_from:
                    # Delta item (the protection-regression CI's work
                    # unit): re-inject only fingerprint-changed
                    # sections, splice the rest from the base journal,
                    # each section convergence-bounded by stop_when.
                    # The live campaign writes no journal (the spliced
                    # rows never ran), so the result is materialized as
                    # one afterwards -- the done record must still have
                    # a journal to parity-check against, and the CI
                    # refresh wants it as the next splice base.
                    res = runner.run_delta(
                        cs.n, cs.delta_from, seed=cs.seed,
                        batch_size=cs.batch_size,
                        start_num=cs.start_num,
                        progress=progress, stop_when=stop_when,
                        static_budget=cs.static_budget)
                    jpath = self.q.journal_path(item.id)
                    if os.path.exists(jpath):
                        os.unlink(jpath)       # a previous attempt's
                    runner.journal_result(res, jpath, n=cs.n,
                                          batch_size=cs.batch_size)
                else:
                    res = runner.run(
                        cs.n, seed=cs.seed,
                        batch_size=cs.batch_size,
                        start_num=cs.start_num,
                        journal=self.q.journal_path(item.id),
                        progress=progress, stop_when=stop_when)
        except JournalLockedError:
            # The previous holder of this item is still alive and
            # appending (our claim came from a wrongly-reaped lease).
            # Yield: put the item back and let the journal's owner
            # finish it -- complete() is idempotent either way.
            self.items_yielded += 1
            self._current_item = None
            self.q.requeue_worker(self.worker_id)
            self._write_status("idle")
            time.sleep(self.poll_s)
            return False
        except LostLeaseError as e:
            # Our lease was reaped mid-campaign and someone else owns
            # the item now; the journal we already appended is theirs to
            # resume.  Stop touching it -- but leave the blackbox behind:
            # a reaped lease on a worker that believed itself healthy is
            # exactly the "who stalled, us or the supervisor?" dispute
            # the forensic bundle adjudicates.
            flightrec.record("lease_lost", item=item.id, phase="campaign")
            flightrec.current().dump(
                "lease_lost", extra={"item": item.id,
                                     "worker": self.worker_id,
                                     "error": str(e)})
            self.items_yielded += 1
            self._current_item = None
            self._write_status("idle")
            return False
        except JournalError as e:
            # Deterministic journal failure -- a delta item's base does
            # not describe this campaign (JournalMismatchError), a
            # corrupt/poisoned journal, or a journal_result parity
            # failure: every worker would fail the same way, so the
            # item is terminally failed, not requeued.  (The LOCKED
            # case is transient and already handled above.)
            self.items_failed += 1
            self._current_item = None
            self.q.fail(item.id, self.worker_id, f"journal: {e}")
            self._write_status("idle")
            return False
        except Exception as e:          # noqa: BLE001
            self._current_item = None
            if item.attempts < self.max_item_attempts:
                # Possibly transient infrastructure beyond what the
                # RetryPolicy absorbed on THIS worker (device hiccup,
                # disk blip): the journal is intact and resumable, so
                # requeue for another attempt before declaring the item
                # poison -- fail() is for work that would fail
                # identically anywhere.
                self.items_yielded += 1
                self.q.requeue_worker(self.worker_id)
                self._write_status("idle")
                return False
            self.items_failed += 1
            self.q.fail(item.id, self.worker_id,
                        f"attempt {item.attempts}: "
                        f"{type(e).__name__}: {e}\n"
                        f"{traceback.format_exc(limit=3)}")
            self._write_status("idle")
            return False

        # The done record's shape is what merge_fleet parity-checks and
        # FleetTelemetry aggregates: counts as a dict (not the summary's
        # flattened keys), the codes sha as the parity pin, the full
        # summary alongside for humans and json_parser-style consumers.
        result = {
            "benchmark": res.benchmark,
            "strategy": res.strategy,
            "injections": int(res.n),
            "seconds": round(float(res.seconds), 6),
            "counts": {k: int(v) for k, v in res.counts.items()},
            "codes_sha256": codes_sha256(res.codes),
            "cache_event": cache_event,
            "worker": self.worker_id,
            "summary": res.summary(),
        }
        if res.physical_n is not None:
            result["physical_injections"] = int(res.physical_n)
        if res.delta is not None:
            result["delta"] = dict(res.delta)
        self.q.complete(item.id, self.worker_id, result)
        self.items_done += 1
        self._current_item = None
        self._write_status("idle")
        return True
