"""Crash-safe file-based campaign queue: the fleet's work ledger.

The reference platform ran ``-t N`` parallel QEMU workers inside ONE
supervisor process (supervisor.py:335); a fleet of *processes* needs the
work list itself to be durable and contention-safe.  This queue is a
directory of one-JSON-file-per-item with rename-based state transitions
-- ``os.rename`` within a filesystem is atomic, so a state change either
happened or it did not, with no locks, no daemons, and no database:

    <root>/pending/<id>.json    enqueued, claimable
    <root>/claimed/<id>.json    leased to a worker (worker + expiry inside)
    <root>/done/<id>.json       completed (result summary inside)
    <root>/failed/<id>.json     failed terminally (error inside)
    <root>/journals/<id>.journal  the item's CampaignJournal
    <root>/status/<worker>.json   per-worker live status (fleet telemetry)
    <root>/cache/               shared compile cache (fleet.compile_cache)

**Item identity is the journal's identity.**  An item spec is the
:class:`~coast_tpu.inject.spec.CampaignSpec` identity vocabulary in its
queue-item encoding -- benchmark, opt flags (the protection-config
source), section, seed/n/start_num, fault-model spec, equiv flag,
stop-when spec -- so the worker that claims an item can
regenerate the campaign and the journal header validates it, and a
*different* worker resuming after a SIGKILL regenerates the *same*
campaign bit-for-bit (the journal refuses anything else).

**Claim** is first-come-first-served over the sorted pending listing:
each claimant tries ``rename(pending/x, claimed/x)``; exactly one
succeeds per item (the losers get ``FileNotFoundError`` and move on).
**Lease**: the claimed file records the worker and an expiry; the worker
renews it from its progress heartbeat.  **Requeue**: an expired lease
(worker died, or was SIGKILL'd) renames the item back to pending --
the journal survives, so the next claimant resumes instead of
restarting.  A slow-but-alive worker whose lease was wrongly reaped is
harmless: the journal's exclusive flock (JournalLockedError) keeps the
duplicate claimant out of the append stream, and completion is
idempotent (``done`` is written atomically from the journal-backed
result, identical whichever attempt lands it).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Dict, List, Optional

from coast_tpu.inject.spec import CampaignSpec, SpecError
from coast_tpu.obs.metrics import atomic_write_json

__all__ = ["QueueError", "LostLeaseError", "QueueItem", "CampaignQueue",
           "item_spec"]

#: Item states, in directory form.  ``pending`` and ``claimed`` are the
#: live states; ``done`` and ``failed`` are terminal.
STATES = ("pending", "claimed", "done", "failed")

#: A claim's rename and its lease write are two steps (claim()'s
#: ms-scale window), and a requeued item still carries its previous
#: attempt's expired lease until the new claimant's write lands.  So
#: reapers never trust the recorded lease alone: the claim rename
#: refreshes the file's ctime, and anything whose ctime is within this
#: grace is a claim in progress, not an expired one -- reaping it would
#: leave the winner holding a claim the queue no longer records.
CLAIM_WRITE_GRACE_S = 5.0


class QueueError(RuntimeError):
    """Queue misuse or an unreadable/corrupt item file."""


class LostLeaseError(QueueError):
    """The worker's claim on an item vanished (lease reaped and the item
    requeued, or completed by another attempt) -- the worker must stop
    touching it."""


def item_spec(benchmark: str, n: int, seed: int = 0,
              opt_passes: str = "-TMR", section: str = "memory",
              batch_size: int = 4096, start_num: int = 0,
              fault_model: str = "single", equiv: bool = False,
              stop_when: Optional[str] = None, unroll: int = 1,
              throttle_s: float = 0.0,
              delta_from: Optional[str] = None,
              collect: str = "dense") -> Dict[str, object]:
    """One queued campaign, serialized through the shared
    :class:`~coast_tpu.inject.spec.CampaignSpec` identity vocabulary
    (``to_item`` is bit-compatible with this function's historical
    output, so enqueue ids and pre-existing queue directories keep
    their meaning).

    ``throttle_s`` sleeps that long after every collected batch -- an
    operator rate-limit knob (and what makes kill-mid-campaign tests
    deterministic on a fast CPU backend).  ``delta_from`` makes the item
    a DELTA campaign: the worker re-injects only the sections whose
    propagation fingerprint changed since that journal was written and
    splices the rest (the protection-regression CI's work unit).
    Validation happens here, at enqueue time, so a bad spec fails the
    *enqueuer*, not a worker an hour later."""
    spec = CampaignSpec(
        benchmark=benchmark, n=n, seed=seed, opt_passes=opt_passes,
        section=section, batch_size=batch_size, start_num=start_num,
        fault_model=fault_model, equiv=equiv, stop_when=stop_when,
        unroll=unroll, throttle_s=throttle_s, delta_from=delta_from,
        collect=collect)
    try:
        spec.validate()
    except SpecError as e:
        # Parser-typed errors (FaultModel's ValueError, StopWhenError)
        # pass through untouched; the spec-level rules keep the queue's
        # historical QueueError type.
        raise QueueError(str(e)) from e
    return spec.to_item()


@dataclasses.dataclass
class QueueItem:
    """One claimed work item: the spec plus its claim bookkeeping."""

    id: str
    spec: Dict[str, object]
    attempts: int
    worker: str
    lease_expires_unix: float


class CampaignQueue:
    """File-based multi-process campaign queue rooted at one directory."""

    def __init__(self, root: str):
        self.root = str(root)
        for sub in (*STATES, "journals", "status"):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)

    # -- paths ---------------------------------------------------------------
    def _item_path(self, state: str, item_id: str) -> str:
        return os.path.join(self.root, state, f"{item_id}.json")

    def journal_path(self, item_id: str) -> str:
        return os.path.join(self.root, "journals", f"{item_id}.journal")

    def worker_status_path(self, worker: str) -> str:
        return os.path.join(self.root, "status", f"{worker}.json")

    @property
    def cache_dir(self) -> str:
        return os.path.join(self.root, "cache")

    # -- enqueue -------------------------------------------------------------
    def enqueue(self, spec: Dict[str, object]) -> str:
        """Durably add one item; returns its id.

        Ids are ``<seq>-<sha8>``: a monotone sequence number for FIFO
        claim order plus a spec fingerprint for the humans reading the
        directory.  ``O_CREAT|O_EXCL`` arbitrates concurrent enqueuers
        of the *same* spec racing for one slot; concurrent enqueuers of
        different specs may land the same sequence number, in which case
        the sha tiebreaks their claim order -- they raced, so no
        meaningful FIFO order exists between them anyway."""
        sha = hashlib.sha256(
            json.dumps(spec, sort_keys=True).encode()).hexdigest()[:8]
        seq = self._next_seq()
        while True:
            item_id = f"{seq:06d}-{sha}"
            doc = {"format": "coast-fleet-item", "version": 1,
                   "id": item_id, "spec": dict(spec), "attempts": 0,
                   "enqueued_unix": time.time()}
            try:
                fd = os.open(self._item_path("pending", item_id),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                seq += 1
                continue
            with os.fdopen(fd, "w") as fh:
                json.dump(doc, fh, separators=(",", ":"), sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            return item_id

    def _next_seq(self) -> int:
        seq = 0
        for state in STATES:
            for name in os.listdir(os.path.join(self.root, state)):
                head = name.split("-", 1)[0]
                if head.isdigit():
                    seq = max(seq, int(head) + 1)
        return seq

    # -- claim / lease / requeue --------------------------------------------
    def claim(self, worker: str, lease_s: float = 60.0
              ) -> Optional[QueueItem]:
        """Atomically claim the oldest pending item, or None.

        The rename IS the claim: concurrent claimants racing for one
        item see exactly one winner.  The claimed file is then rewritten
        (atomically) with the worker and lease expiry."""
        pending = os.path.join(self.root, "pending")
        for name in sorted(os.listdir(pending)):
            if not name.endswith(".json"):
                continue
            item_id = name[:-len(".json")]
            if os.path.exists(self._item_path("done", item_id)):
                # A slow previous attempt already landed the result (see
                # complete()); this pending entry is a stale requeue.
                try:
                    os.unlink(os.path.join(pending, name))
                except FileNotFoundError:
                    pass
                continue
            dst = self._item_path("claimed", item_id)
            try:
                os.rename(os.path.join(pending, name), dst)
            except FileNotFoundError:
                continue                       # another claimant won
            try:
                doc = self._read(dst)
            except FileNotFoundError:
                # Our fresh claim was moved out from under us (a reaper
                # running with an artificial far-future ``now``): the
                # item is pending again and fair game for anyone.
                continue
            doc["worker"] = str(worker)
            doc["attempts"] = int(doc.get("attempts", 0)) + 1
            doc["claimed_unix"] = time.time()
            doc["lease_expires_unix"] = time.time() + float(lease_s)
            atomic_write_json(dst, doc)
            return QueueItem(id=item_id, spec=doc["spec"],
                             attempts=doc["attempts"], worker=str(worker),
                             lease_expires_unix=doc["lease_expires_unix"])
        return None

    def renew(self, item_id: str, worker: str, lease_s: float = 60.0
              ) -> None:
        """Extend the lease from the worker's heartbeat.  Raises
        :class:`LostLeaseError` if the claim vanished or moved to
        another worker -- the caller must abandon the item (its journal
        flock already keeps any replacement's appends safe)."""
        path = self._item_path("claimed", item_id)
        try:
            doc = self._read(path)
        except FileNotFoundError:
            raise LostLeaseError(
                f"item {item_id} is no longer claimed by {worker} "
                "(lease reaped or item completed)") from None
        if doc.get("worker") != worker:
            raise LostLeaseError(
                f"item {item_id} is now claimed by {doc.get('worker')!r}, "
                f"not {worker!r}")
        doc["lease_expires_unix"] = time.time() + float(lease_s)
        atomic_write_json(path, doc)

    def requeue_expired(self, now: Optional[float] = None) -> List[str]:
        """Move every expired-lease claimed item back to pending (its
        journal stays, so the next claimant resumes).  Returns the
        requeued ids.  ``now`` is injectable for tests."""
        now = time.time() if now is None else float(now)
        out: List[str] = []
        claimed = os.path.join(self.root, "claimed")
        for name in sorted(os.listdir(claimed)):
            if not name.endswith(".json"):
                continue
            item_id = name[:-len(".json")]
            path = os.path.join(claimed, name)
            try:
                doc = self._read(path)
            except FileNotFoundError:
                continue
            # The ctime floor covers the mid-claim window (see
            # CLAIM_WRITE_GRACE_S): a just-renamed claim whose doc still
            # shows the PREVIOUS attempt's expired lease (or none at
            # all) must not be reaped before its claimant's write lands.
            try:
                floor = os.stat(path).st_ctime + CLAIM_WRITE_GRACE_S
            except FileNotFoundError:
                continue
            expires = max(float(doc.get("lease_expires_unix") or 0.0),
                          floor)
            if expires > now:
                continue
            out.extend(self._requeue(item_id))
        return out

    def requeue_worker(self, worker: str) -> List[str]:
        """Requeue every item claimed by ``worker`` immediately -- the
        fleet supervisor's fast path when it *observed* the worker
        process die (no need to wait out the lease)."""
        out: List[str] = []
        claimed = os.path.join(self.root, "claimed")
        for name in sorted(os.listdir(claimed)):
            if not name.endswith(".json"):
                continue
            item_id = name[:-len(".json")]
            try:
                doc = self._read(os.path.join(claimed, name))
            except FileNotFoundError:
                continue
            if doc.get("worker") == worker:
                out.extend(self._requeue(item_id))
        return out

    def _requeue(self, item_id: str) -> List[str]:
        if os.path.exists(self._item_path("done", item_id)):
            # The "expired" worker actually finished (complete() is
            # journal-backed and idempotent): just drop the stale claim.
            try:
                os.unlink(self._item_path("claimed", item_id))
            except FileNotFoundError:
                pass
            return []
        try:
            os.rename(self._item_path("claimed", item_id),
                      self._item_path("pending", item_id))
        except FileNotFoundError:
            return []
        return [item_id]

    # -- terminal transitions ------------------------------------------------
    def complete(self, item_id: str, worker: str,
                 result: Dict[str, object]) -> None:
        """Land the item's result durably.  Written atomically (not
        renamed) so completion is idempotent: if the lease was wrongly
        reaped and two attempts finish, both derive the identical
        result from the same resumed journal, and last-writer-wins is
        bit-for-bit the same file.  The claim (and any stale pending
        requeue) is cleared afterwards."""
        doc = {"format": "coast-fleet-done", "version": 1, "id": item_id,
               "worker": str(worker), "completed_unix": time.time(),
               "result": dict(result)}
        try:
            claim = self._read(self._item_path("claimed", item_id))
            doc["spec"] = claim["spec"]
            doc["attempts"] = claim.get("attempts", 1)
            # Queue lifecycle timestamps ride into the done record so
            # the fleet trace federation (obs/federate.py) can plot the
            # claim->complete lease window without the claim doc, which
            # is unlinked below.
            for key in ("enqueued_unix", "claimed_unix",
                        "lease_expires_unix"):
                if claim.get(key) is not None:
                    doc[key] = claim[key]
        except FileNotFoundError:
            pass
        atomic_write_json(self._item_path("done", item_id), doc)
        for state in ("claimed", "pending"):
            try:
                os.unlink(self._item_path(state, item_id))
            except FileNotFoundError:
                pass

    def fail(self, item_id: str, worker: str, error: str) -> None:
        """Mark the item terminally failed (bad spec, fatal build error).
        Transient infrastructure failures should requeue instead -- this
        is for work that would fail identically on any worker."""
        path = self._item_path("claimed", item_id)
        try:
            doc = self._read(path)
        except FileNotFoundError:
            doc = {"id": item_id, "spec": {}}
        doc["format"] = "coast-fleet-failed"
        doc["worker"] = str(worker)
        doc["error"] = str(error)
        doc["failed_unix"] = time.time()
        atomic_write_json(self._item_path("failed", item_id), doc)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass

    # -- queries -------------------------------------------------------------
    def _read(self, path: str) -> Dict[str, object]:
        with open(path) as fh:
            try:
                return json.load(fh)
            except ValueError as e:
                raise QueueError(f"queue item {path!r} is corrupt: {e}") \
                    from e

    def stats(self) -> Dict[str, int]:
        """Item counts per state (the fleet telemetry's queue gauges)."""
        return {state: len([n for n in os.listdir(
                    os.path.join(self.root, state))
                    if n.endswith(".json")])
                for state in STATES}

    def items(self, state: str) -> List[Dict[str, object]]:
        """Every item doc in ``state``, sorted by id (enqueue order)."""
        if state not in STATES:
            raise QueueError(f"unknown queue state {state!r}; "
                             f"want one of {STATES}")
        dirname = os.path.join(self.root, state)
        out = []
        for name in sorted(os.listdir(dirname)):
            if not name.endswith(".json"):
                continue
            try:
                out.append(self._read(os.path.join(dirname, name)))
            except FileNotFoundError:
                continue                       # moved mid-listing
        return out

    def drained(self) -> bool:
        """True when no live (pending or claimed) work remains."""
        stats = self.stats()
        return stats["pending"] == 0 and stats["claimed"] == 0
