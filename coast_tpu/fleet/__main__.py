"""``python -m coast_tpu.fleet`` -- the fleet supervisor CLI."""

import sys

from coast_tpu.fleet.supervisor import main

if __name__ == "__main__":
    sys.exit(main())
