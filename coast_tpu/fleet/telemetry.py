"""Merged fleet telemetry: one /metrics + /status over many workers.

Per-campaign observability already exists (:mod:`coast_tpu.obs.metrics`
feeds one hub per runner); a fleet needs the *sum*.  The aggregation
topology is deliberately file-based, matching the queue: every worker
mirrors an atomic worker-status doc into ``<queue>/status/`` on each
batch, and completed items live as durable ``done`` records -- so the
aggregator is a pure *reader* with no RPC fabric, no worker
registration, and no extra failure mode.  A SIGKILL'd worker simply
goes stale (its last doc's age exceeds the staleness window) and its
completed work keeps counting, because completed work is counted from
``done`` records, never from worker memory.

:class:`FleetTelemetry` duck-types the hub interface
(:meth:`snapshot` / :meth:`prometheus`), so the stock
:class:`coast_tpu.obs.serve.MetricsServer` serves the fleet aggregate
unchanged -- one ``/metrics`` endpoint a Prometheus scraper reads for
the whole fleet, one ``/status`` JSON for dashboards.

Double-count hygiene: fleet per-class totals = (sum of ``done`` record
counts) + (live ``running`` workers' current-campaign counts).  Workers
drop the campaign block from their status doc the moment an item's
``done`` record lands, so an item is never in both terms (modulo one
in-flight beat, which the next scrape corrects).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Tuple

from coast_tpu.fleet.queue import CampaignQueue
from coast_tpu.obs.convergence import interval_table
from coast_tpu.obs.metrics import _esc

__all__ = ["FleetTelemetry", "merge_histogram"]

#: done-record summary profile key -> the canonical histogram name the
#: SLO engine's ``p<q>_<alias>`` objectives resolve to (the same
#: mapping obs/slo.evidence_from_summary applies to one campaign).
_SUMMARY_HISTS = (("device_seconds_histogram", "dispatch_device_seconds"),
                  ("host_gap_seconds_histogram",
                   "dispatch_host_gap_seconds"))


def merge_histogram(into: Dict[str, Dict[str, object]], name: str,
                    snap: Dict[str, object]) -> None:
    """Sum one histogram snapshot into the fleet accumulator under
    ``name``.  Snapshots with different bucket bounds are skipped --
    mixing bounds would corrupt every quantile read off the merge, and
    all shipped histograms share Histogram.DEFAULT_BOUNDS."""
    if not snap or not snap.get("count"):
        return
    acc = into.get(name)
    if acc is None:
        into[name] = {"le": list(snap.get("le") or ()),
                      "counts": [int(c) for c in snap.get("counts") or ()],
                      "count": int(snap["count"]),
                      "sum": float(snap.get("sum", 0.0))}
        return
    if list(snap.get("le") or ()) != acc["le"]:
        return
    acc["counts"] = [a + int(b) for a, b in
                     zip(acc["counts"], snap.get("counts") or ())]
    acc["count"] += int(snap["count"])
    acc["sum"] += float(snap.get("sum", 0.0))


class FleetTelemetry:
    """Read-side aggregate over one queue's workers + done records."""

    def __init__(self, queue: "CampaignQueue | str",
                 stale_s: float = 30.0, z: float = 1.96, slo=None):
        self.q = (queue if isinstance(queue, CampaignQueue)
                  else CampaignQueue(queue))
        self.stale_s = float(stale_s)
        self.z = float(z)
        if isinstance(slo, str):
            from coast_tpu.obs.slo import SLOSet
            slo = SLOSet.parse(slo)
        self.slo_set = slo
        self._done_cache: Dict[str, Tuple[int, Dict[str, object]]] = {}

    # -- readers -------------------------------------------------------------
    def _worker_docs(self) -> List[Dict[str, object]]:
        status_dir = os.path.join(self.q.root, "status")
        out: List[Dict[str, object]] = []
        for name in sorted(os.listdir(status_dir)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(status_dir, name)) as fh:
                    out.append(json.load(fh))
            except (OSError, ValueError):
                continue                   # torn/unreadable: skip a beat
        return out

    def _done_docs(self) -> List[Dict[str, object]]:
        """Done records, parsed once each.  They are immutable once
        ``atomic_write_json`` lands them (an idempotent re-complete
        rewrites the identical bytes but bumps mtime, which just
        re-parses that one file), and the aggregate runs per /metrics
        scrape, per /status hit, AND per supervisor poll -- a long
        fleet accumulates thousands of done files, so re-reading all of
        them every half-second is the one unbounded cost here."""
        done_dir = os.path.join(self.q.root, "done")
        out: List[Dict[str, object]] = []
        for name in sorted(os.listdir(done_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(done_dir, name)
            try:
                mtime = os.stat(path).st_mtime_ns
            except FileNotFoundError:
                continue
            hit = self._done_cache.get(path)
            if hit is None or hit[0] != mtime:
                try:
                    with open(path) as fh:
                        hit = (mtime, json.load(fh))
                except (OSError, ValueError):
                    continue               # torn/unreadable: skip a beat
                self._done_cache[path] = hit
            out.append(hit[1])
        return out

    def _aggregate(self) -> Dict[str, object]:
        now = time.time()
        queue_stats = self.q.stats()
        done = self._done_docs()
        counts: Dict[str, float] = {}
        injections = 0
        physical = 0
        seconds = 0.0
        cache: Dict[str, int] = {}
        # Federated dispatch-latency histograms, canonical names: the
        # evidence the p99_dispatch-style fleet SLOs read.  Done records
        # carry them under the summary profile keys; live workers'
        # campaign blocks already use the canonical names.
        histograms: Dict[str, Dict[str, object]] = {}
        for rec in done:
            result = rec.get("result") or {}
            for k, v in (result.get("counts") or {}).items():
                counts[k] = counts.get(k, 0.0) + float(v)
            injections += int(result.get("injections", 0))
            physical += int(result.get("physical_injections",
                                       result.get("injections", 0)))
            seconds += float(result.get("seconds", 0.0))
            profile = ((result.get("summary") or {}).get("profile")
                       or {})
            for summary_key, canonical in _SUMMARY_HISTS:
                merge_histogram(histograms, canonical,
                                profile.get(summary_key) or {})
            event = result.get("cache_event")
            if event:
                cache[event] = cache.get(event, 0) + 1
        workers: List[Dict[str, object]] = []
        live = 0
        inj_per_sec = 0.0
        for doc in self._worker_docs():
            age = max(0.0, now - float(doc.get("updated_unix_s", 0.0)))
            stale = age > self.stale_s or doc.get("state") == "exited"
            if not stale:
                live += 1
            campaign = doc.get("campaign") if doc.get("state") == "running" \
                else None
            if campaign and not stale:
                for k, v in (campaign.get("counts") or {}).items():
                    counts[k] = counts.get(k, 0.0) + float(v)
                inj_per_sec += float(campaign.get("inj_per_sec", 0.0))
                for name, snap in ((campaign.get("profile") or {})
                                   .get("histograms") or {}).items():
                    merge_histogram(histograms, name, snap)
            for k, v in (doc.get("cache") or {}).items():
                if k in ("warm_hit", "persistent_hit", "miss"):
                    # Live view of in-flight workers' cache traffic;
                    # the done-record sum above is the durable one, so
                    # keep them in separate keys.
                    cache[f"live_{k}"] = cache.get(f"live_{k}", 0) + int(v)
            workers.append({
                "worker": doc.get("worker"),
                "pid": doc.get("pid"),
                "state": "stale" if stale else doc.get("state"),
                "item": doc.get("item"),
                "items_done": doc.get("items_done", 0),
                "items_failed": doc.get("items_failed", 0),
                "age_s": round(age, 3),
                "inj_per_sec": (float(campaign.get("inj_per_sec", 0.0))
                                if campaign and not stale else 0.0),
            })
        return {
            "now": now, "queue": queue_stats, "workers": workers,
            "workers_live": live, "counts": counts,
            "injections_done": injections, "physical_done": physical,
            "seconds": seconds, "cache": cache,
            "inj_per_sec": inj_per_sec,
            "histograms": histograms,
        }

    def _slo_report(self, agg: Dict[str, object]):
        """Evaluate the configured SLO set against the fleet aggregate:
        the union of done-record counts, live campaigns, and the
        federated dispatch-latency histograms -- so ``p99_dispatch``-
        style latency objectives get a fleet-scope verdict from the
        same evidence shape a single campaign's evaluation reads."""
        if self.slo_set is None:
            return None
        from coast_tpu.obs.slo import evaluate
        rate = agg["inj_per_sec"] or None
        if rate is None and agg["seconds"] > 0:
            rate = agg["injections_done"] / agg["seconds"]
        return evaluate(self.slo_set, {
            "counts": {k: int(v) for k, v in agg["counts"].items()},
            "inj_per_sec": rate,
            "histograms": agg["histograms"],
        })

    # -- hub interface (MetricsServer duck-typing) ---------------------------
    def snapshot(self) -> Dict[str, object]:
        agg = self._aggregate()
        doc = {
            "format": "coast-fleet-status", "version": 1,
            "queue": agg["queue"],
            "workers": agg["workers"],
            "workers_live": agg["workers_live"],
            "counts": {k: v for k, v in sorted(agg["counts"].items())},
            "rates": interval_table(agg["counts"], self.z),
            "injections_done": agg["injections_done"],
            "physical_done": agg["physical_done"],
            "seconds": round(agg["seconds"], 6),
            "inj_per_sec": round(agg["inj_per_sec"], 3),
            "cache": agg["cache"],
            "updated_unix_s": round(agg["now"], 6),
        }
        if agg["histograms"]:
            # Same shape as a campaign snapshot's profile block, so the
            # evidence readers (and dashboards) share one vocabulary.
            doc["profile"] = {"histograms": agg["histograms"]}
        report = self._slo_report(agg)
        if report is not None:
            from coast_tpu.obs.slo import summary_block
            doc["slo"] = summary_block(report)
        return doc

    def prometheus(self) -> str:
        """Prometheus 0.0.4 text of the fleet aggregate -- the names
        docs/observability.md's fleet section pins."""
        agg = self._aggregate()
        lines: List[str] = []

        def metric(name: str, mtype: str, help_text: str,
                   samples: List[Tuple[str, float]]) -> None:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for label_str, value in samples:
                text = (f"{int(value)}" if float(value).is_integer()
                        else f"{value:.17g}")
                body = f"{{{label_str}}}" if label_str else ""
                lines.append(f"{name}{body} {text}")

        metric("coast_fleet_queue_items", "gauge",
               "Queue items per state.",
               [(f'state="{s}"', float(n))
                for s, n in sorted(agg["queue"].items())])
        states: Dict[str, int] = {}
        for w in agg["workers"]:
            states[str(w["state"])] = states.get(str(w["state"]), 0) + 1
        metric("coast_fleet_workers", "gauge",
               "Workers per observed state (stale = no fresh status).",
               [(f'state="{_esc(s)}"', float(n))
                for s, n in sorted(states.items())] or [("", 0.0)])
        metric("coast_fleet_class_total", "gauge",
               "Fleet-wide weighted count per classification class "
               "(done records + live campaigns).",
               [(f'class="{_esc(k)}"', float(v))
                for k, v in sorted(agg["counts"].items())]
               or [('class="success"', 0.0)])
        rates = interval_table(agg["counts"], self.z)
        if rates:
            metric("coast_fleet_class_rate", "gauge",
                   "Fleet-wide weighted per-class rate.",
                   [(f'class="{_esc(k)}"', v["rate"])
                    for k, v in rates.items()])
            metric("coast_fleet_class_ci_half_width", "gauge",
                   "Wilson CI half-width of the fleet per-class rate.",
                   [(f'class="{_esc(k)}"', v["half_width"])
                    for k, v in rates.items()])
        metric("coast_fleet_injections_done_total", "counter",
               "Effective injections in completed items.",
               [("", float(agg["injections_done"]))])
        metric("coast_fleet_inj_per_sec", "gauge",
               "Summed instantaneous inj/s over live running workers.",
               [("", float(agg["inj_per_sec"]))])
        metric("coast_fleet_compile_cache_events_total", "counter",
               "Compile-cache outcomes (done records; live_* = in-flight "
               "worker counters).",
               [(f'kind="{_esc(k)}"', float(v))
                for k, v in sorted(agg["cache"].items())]
               or [('kind="miss"', 0.0)])
        for hname, hist in sorted(agg["histograms"].items()):
            # Federated dispatch-latency histograms (done records +
            # live campaigns): the fleet-scope evidence behind the
            # latency SLO rows below.
            full = f"coast_fleet_{hname}"
            lines.append(f"# HELP {full} Federated per-dispatch "
                         "latency histogram (seconds).")
            lines.append(f"# TYPE {full} histogram")
            for bound, cum in zip(hist["le"], hist["counts"]):
                lines.append(
                    f'{full}_bucket{{le="{float(bound):g}"}} {cum}')
            lines.append(
                f'{full}_bucket{{le="+Inf"}} {hist["count"]}')
            lines.append(f'{full}_sum {float(hist["sum"]):.17g}')
            lines.append(f'{full}_count {hist["count"]}')
        report = self._slo_report(agg)
        if report is not None:
            rows = report.get("objectives") or []
            metric("coast_fleet_slo_burn_rate", "gauge",
                   "Fleet error-budget burn rate per SLO objective "
                   "(1.0 = consuming budget exactly at the allowed "
                   "pace).",
                   [(f'objective="{_esc(r["objective"])}"',
                     float(r["burn"]["long"]))
                    for r in rows
                    if (r.get("burn") or {}).get("long") is not None])
            metric("coast_fleet_slo_budget_remaining_frac", "gauge",
                   "Unconsumed fleet error-budget fraction per SLO "
                   "objective (negative = overspent).",
                   [(f'objective="{_esc(r["objective"])}"',
                     float(r["budget"]["remaining_frac"]))
                    for r in rows
                    if (r.get("budget") or {}).get("remaining_frac")
                    is not None])
            metric("coast_fleet_slo_verdict", "gauge",
                   "Fleet per-objective verdict (0=ok, 1=warn, 2=page).",
                   [(f'objective="{_esc(r["objective"])}"',
                     float(("ok", "warn",
                            "page").index(r["verdict"])))
                    for r in rows])
        return "\n".join(lines) + "\n"
