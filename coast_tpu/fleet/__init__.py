"""coast_tpu.fleet: campaign fleet -- many campaigns x many workers.

The scale-out layer above :class:`~coast_tpu.inject.campaign
.CampaignRunner` (ROADMAP item 3).  One process per worker, one
durable file-based queue per fleet, one journal per work item:

  * :mod:`coast_tpu.fleet.queue` -- crash-safe campaign queue with
    atomic claim / lease / requeue semantics (rename-based, lockless);
  * :mod:`coast_tpu.fleet.worker` -- SIGKILL-surviving worker loop: a
    restarted worker resumes the claimed item's journal bit-for-bit;
  * :mod:`coast_tpu.fleet.compile_cache` -- persistent compile cache
    keyed by the journal's config-sha + mesh geometry, so protected-
    program tracing/lowering is paid once per config across the fleet;
  * :mod:`coast_tpu.fleet.telemetry` -- merged fleet /metrics + /status
    served through the stock :class:`coast_tpu.obs.serve.MetricsServer`;
  * :mod:`coast_tpu.fleet.supervisor` -- the ``python -m coast_tpu.fleet``
    CLI (enqueue / run / worker / status / merge) with the
    parity-checked fleet merge.

See docs/fleet.md for the queue format, lease semantics, cache key, and
aggregation topology.
"""

from coast_tpu.fleet.compile_cache import CompileCache
from coast_tpu.fleet.queue import (CampaignQueue, LostLeaseError,
                                   QueueError, QueueItem, item_spec)
from coast_tpu.fleet.supervisor import FleetParityError, merge_fleet
from coast_tpu.fleet.telemetry import FleetTelemetry
from coast_tpu.fleet.worker import Worker, codes_sha256

__all__ = [
    "CampaignQueue", "QueueItem", "QueueError", "LostLeaseError",
    "item_spec", "Worker", "codes_sha256", "CompileCache",
    "FleetTelemetry", "FleetParityError", "merge_fleet",
]
