"""Persistent compile cache: pay the protected-program build once per
config across the fleet.

Per campaign.py's own accounting, the dominant cold-start cost of a
campaign is not the injections -- it is tracing + lowering + XLA-
compiling the protected step (and, for ``--equiv``, the partition
analysis riding the same traced jaxpr).  A fleet runs thousands of
campaigns over a *handful* of configs, so that cost must be paid once
per config, not once per campaign.  The host-side discipline is the TPU
CFD framework's (arXiv:2108.11076): keep the slices saturated by making
sure the host never stalls re-preparing work it has already prepared.

Three layers, cheapest first:

  1. **Warm (in-process)**: one :class:`~coast_tpu.inject.campaign
     .CampaignRunner` per cache key, memoized for the life of the worker
     -- a worker draining ten same-config items traces/compiles once and
     reuses the jitted batch program for the other nine (``warm_hit``).
  2. **Persistent (cross-process)**: jax's compilation cache is pointed
     at ``<root>/xla``, so a *different* worker process (or a restarted
     one) compiling the same HLO gets the XLA binary from disk instead
     of the compiler (best-effort: backends without persistent-cache
     support degrade silently to a plain re-compile).
  3. **Key ledger**: ``<root>/keys/<key>.json`` records which configs
     some fleet process has already compiled.  The key is the journal's
     identity vocabulary -- the protection ``config_sha`` (the same
     fingerprint the journal header pins) + mesh geometry + section /
     fault-model / equiv / unroll + jax version + backend -- so a cache
     hit can never hand back a program compiled for a different
     campaign identity.  A cold build under an existing key is counted
     as a ``persistent_hit`` (layer 2 serves it); a key never seen
     anywhere is a ``miss``.

Hit/miss counters feed the ambient obs telemetry (``compile_cache_*``
counts), the per-worker status doc, and the fleet-level /metrics
aggregate.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional, Tuple

from coast_tpu.obs.metrics import atomic_write_json

__all__ = ["CompileCache"]

#: Cache event vocabulary, in "best outcome first" order.
EVENTS = ("warm_hit", "persistent_hit", "miss")


class CompileCache:
    """Per-worker facade over the three cache layers, rooted at the
    queue's shared ``cache/`` directory."""

    def __init__(self, root: str, program_hook=None):
        """``program_hook(prog)`` is applied (in place) to every freshly
        built protected program before any runner/key is derived from
        it -- the seam the protection-regression CI's tests and smoke
        driver use to seed a weakened build (e.g. dropping a commit
        vote) into an otherwise stock worker.  None in production."""
        self.root = str(root)
        os.makedirs(os.path.join(self.root, "keys"), exist_ok=True)
        self.counters: Dict[str, int] = {name: 0 for name in EVENTS}
        self.last_event: Optional[str] = None
        self.program_hook = program_hook
        self._runners: Dict[str, Tuple[object, str]] = {}
        self._programs: Dict[Tuple[str, str], Tuple[object, str]] = {}
        self.persistent_enabled = self._enable_persistent()

    def _enable_persistent(self) -> bool:
        """Point jax's compilation cache at the shared directory -- but
        only if the process has not already configured one (a test
        harness or operator environment that set its own cache dir keeps
        it; the XLA cache is shared-state either way, and the key ledger
        and counters live in OUR root regardless).  Every knob is
        best-effort: older jax versions miss some of them, and backends
        without persistent-cache support simply recompile."""
        try:
            import jax
            if getattr(jax.config, "jax_compilation_cache_dir", None):
                return True
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(self.root, "xla"))
        except Exception:                    # noqa: BLE001 - degrade
            return False
        for knob, value in (
                ("jax_persistent_cache_min_entry_size_bytes", -1),
                ("jax_persistent_cache_min_compile_time_secs", 0.0)):
            try:
                jax.config.update(knob, value)
            except Exception:                # noqa: BLE001 - older jax
                pass
        return True

    # -- keys ----------------------------------------------------------------
    @staticmethod
    def _mesh_geometry(mesh) -> Optional[Dict[str, int]]:
        if mesh is None:
            return None
        return {str(name): int(size)
                for name, size in zip(mesh.axis_names, mesh.devices.shape)}

    def key(self, prog, spec: Dict[str, object], mesh=None) -> str:
        """Cache key = journal config-sha + mesh geometry + the
        :class:`~coast_tpu.inject.spec.CampaignSpec` fields that change
        what gets compiled.  ``delta_from``/``stop_when`` are
        deliberately absent: a delta or convergence-bounded item runs
        the same compiled program as its plain campaign."""
        import jax
        from coast_tpu.inject.journal import config_fingerprint
        from coast_tpu.inject.spec import CampaignSpec
        cs = CampaignSpec.from_item(spec)
        doc = {
            "benchmark": prog.region.name,
            "config_sha": config_fingerprint(prog.cfg),
            "section": cs.section,
            "fault_model": cs.fault_model,
            "equiv": cs.equiv,
            "unroll": cs.unroll,
            # Collection mode compiles a different batch program (the
            # sparse path's generation + compaction) AND fixes the
            # runner's collect at construction: a warm hit must never
            # serve a runner in the other mode.
            "collect": cs.collect,
            "mesh": self._mesh_geometry(mesh),
            "jax": jax.__version__,
            "backend": jax.default_backend(),
        }
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]

    def _key_path(self, key: str) -> str:
        return os.path.join(self.root, "keys", f"{key}.json")

    # -- build paths ---------------------------------------------------------
    def program(self, benchmark: str, opt_passes: str):
        """Memoized protected-program build (region resolve + protection
        transform), via the opt CLI's own flag parser so semantics
        cannot drift from ``python -m coast_tpu.opt``."""
        memo_key = (str(benchmark), str(opt_passes))
        if memo_key not in self._programs:
            from coast_tpu.inject.supervisor import build_program
            try:
                prog, strategy = build_program(benchmark, opt_passes)
                if self.program_hook is not None:
                    self.program_hook(prog)
                self._programs[memo_key] = (prog, strategy)
            except SystemExit as e:
                # build_program is a CLI helper: it reports to stderr and
                # exits.  A fleet worker must fail the ITEM, not itself.
                raise RuntimeError(
                    f"protected-program build failed for "
                    f"benchmark={benchmark!r} opt_passes={opt_passes!r} "
                    f"(exit {e.code}; see the worker's stderr)") from e
        return self._programs[memo_key]

    def runner(self, spec: Dict[str, object], mesh=None,
               metrics=None, retry=None):
        """The cached-runner entry point: returns ``(runner, strategy,
        key, event)`` where ``event`` is this call's cache outcome.

        The runner is fully constructed for the spec's campaign identity
        (sections, fault model, equiv partition, mesh backend); a warm
        hit returns the SAME object, jitted program and all.  ``metrics``
        is re-pointed per call -- the live hub belongs to the worker,
        not the cache entry."""
        from coast_tpu import obs
        from coast_tpu.inject.campaign import CampaignRunner
        from coast_tpu.inject.spec import CampaignSpec
        cs = CampaignSpec.from_item(spec)
        prog, strategy = self.program(cs.benchmark, cs.opt_passes)
        key = self.key(prog, spec, mesh)
        if key in self._runners:
            event = "warm_hit"
            runner, strategy = self._runners[key]
        else:
            event = ("persistent_hit"
                     if os.path.exists(self._key_path(key)) else "miss")
            from coast_tpu.inject.supervisor import section_filter
            try:
                sections = section_filter(prog, cs.section)
            except SystemExit as e:
                raise RuntimeError(
                    f"section {cs.section!r} has no injectable "
                    f"leaves in {prog.region.name} (exit {e.code})") from e
            runner = CampaignRunner(
                prog, sections=sections, strategy_name=strategy,
                unroll=cs.unroll,
                fault_model=cs.fault_model_parsed(),
                equiv=cs.equiv,
                collect=cs.collect,
                mesh=mesh, retry=retry)
            self._runners[key] = (runner, strategy)
        runner.metrics = metrics
        runner.retry = retry if retry is not None else runner.retry
        self.counters[event] += 1
        self.last_event = event
        obs.count(f"compile_cache_{event}", key=key)
        from coast_tpu.obs import flightrec
        flightrec.record("compile_cache", outcome=event, key=key)
        return runner, strategy, key, event

    def mark_compiled(self, key: str, spec: Dict[str, object]) -> None:
        """Record that ``key``'s program compiled (first collected batch
        proves it): a later cold build under this key -- a restarted
        worker, another process -- is a persistent hit, served by the
        XLA disk cache rather than the compiler.  Idempotent."""
        path = self._key_path(key)
        if os.path.exists(path):
            return
        atomic_write_json(path, {
            "format": "coast-fleet-compile-key", "version": 1,
            "key": key,
            "benchmark": spec.get("benchmark"),
            "opt_passes": spec.get("opt_passes"),
            "section": spec.get("section"),
            "persistent_xla_cache": self.persistent_enabled,
        })

    # -- accounting ----------------------------------------------------------
    @property
    def hits(self) -> int:
        return self.counters["warm_hit"] + self.counters["persistent_hit"]

    @property
    def misses(self) -> int:
        return self.counters["miss"]

    def snapshot(self) -> Dict[str, object]:
        return {**self.counters, "hits": self.hits, "misses": self.misses,
                "persistent_enabled": self.persistent_enabled}
