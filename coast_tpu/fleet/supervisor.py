"""Fleet supervisor CLI: many campaigns x many workers, one command.

    # queue work (the journal-identity vocabulary, one item per campaign)
    python -m coast_tpu.fleet enqueue --queue /tmp/q -f matrixMultiply \\
        -O -TMR -t 4096 --seed 0 --count 8

    # drain it: N worker processes, merged live telemetry, crash babysit
    python -m coast_tpu.fleet run --queue /tmp/q --workers 4 --mesh 8 \\
        --metrics-port 9100

    # observe / merge later
    python -m coast_tpu.fleet status --queue /tmp/q
    python -m coast_tpu.fleet merge  --queue /tmp/q

``run`` is the zero-to-aha path: it launches the workers, requeues
expired leases, restarts dead workers (requeueing their claimed items
immediately -- no need to wait out a lease it *watched* die), serves the
fleet-aggregate ``/metrics``+``/status`` endpoint while they work, and
finishes with the **parity-checked merge**: every merged count is
re-derived from the item's durable journal (codes sha + final
cumulative counts must match what the worker reported), the same
trust-the-device-not-the-messenger discipline as the mesh backend's
single-device-identical classification pin.  The merged artifact lands
atomically at ``<queue>/fleet_result.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from coast_tpu.fleet.queue import CampaignQueue, QueueError, item_spec
from coast_tpu.obs.metrics import atomic_write_json

__all__ = ["FleetParityError", "merge_fleet", "main"]


class FleetParityError(RuntimeError):
    """A done record disagrees with its own journal: the merge refuses
    to publish counts it cannot re-derive from the durable batch
    stream."""


# -- parity-checked merge ----------------------------------------------------

def _journal_columns(path: str):
    """(codes, last_cumulative_counts) re-derived from a journal's batch
    records: sorted by row offset, deduped (a resumed journal never
    duplicates, but the merge does not *trust* that), contiguity
    checked.  Parsing -- torn-tail tolerance included -- is
    ``CampaignJournal._load``, the one reader of the format; anything
    it refuses, the merge refuses as a parity failure."""
    from coast_tpu.inject.journal import CampaignJournal, JournalError
    try:
        _header, records, _valid = CampaignJournal._load(path)
    except JournalError as e:
        raise FleetParityError(
            f"journal {path!r} is unreadable: {e}") from e
    batches: Dict[int, Dict[str, object]] = {}
    for rec in records:
        if rec.get("kind") == "batch":
            lo = int(rec["lo"])
            prev = batches.get(lo)
            if prev is not None and prev != rec:
                raise FleetParityError(
                    f"journal {path!r} has two CONFLICTING batch "
                    f"records at row {lo}; refusing to pick one")
            batches[lo] = rec
    if not batches:
        raise FleetParityError(f"journal {path!r} has no batch records")
    codes: List[int] = []
    expected = min(batches)
    last = None
    for lo in sorted(batches):
        rec = batches[lo]
        if lo != expected:
            raise FleetParityError(
                f"journal {path!r} has a gap: batch at row {expected} "
                f"missing (next record starts at {lo})")
        codes.extend(int(c) for c in rec["codes"])
        expected = lo + int(rec["n"])
        last = rec
    return np.asarray(codes, dtype=np.int32), dict(last["counts"])


def merge_fleet(queue: "CampaignQueue | str") -> Dict[str, object]:
    """Merge every completed item into one fleet-level artifact, parity-
    checking each against its journal.  Raises
    :class:`FleetParityError` on any disagreement."""
    from coast_tpu.fleet.worker import codes_sha256
    q = (queue if isinstance(queue, CampaignQueue)
         else CampaignQueue(queue))
    items_out: List[Dict[str, object]] = []
    totals: Dict[str, int] = {}
    cache_events: Dict[str, int] = {}
    injections = 0
    physical = 0
    for rec in sorted(q.items("done"), key=lambda r: str(r.get("id"))):
        item_id = str(rec["id"])
        result = rec.get("result") or {}
        codes, last_counts = _journal_columns(q.journal_path(item_id))
        sha = codes_sha256(codes)
        if sha != result.get("codes_sha256"):
            raise FleetParityError(
                f"item {item_id}: journal codes sha {sha[:12]} != "
                f"reported {str(result.get('codes_sha256'))[:12]}; the "
                "done record does not describe its own journal")
        reported = {k: int(v)
                    for k, v in (result.get("counts") or {}).items()}
        derived = {k: int(v) for k, v in last_counts.items()}
        if reported != derived:
            raise FleetParityError(
                f"item {item_id}: journal cumulative counts {derived} "
                f"!= reported {reported}")
        for k, v in reported.items():
            totals[k] = totals.get(k, 0) + v
        injections += int(result.get("injections", 0))
        physical += int(result.get("physical_injections",
                                   result.get("injections", 0)))
        event = result.get("cache_event")
        if event:
            cache_events[event] = cache_events.get(event, 0) + 1
        items_out.append({
            "id": item_id,
            "benchmark": result.get("benchmark"),
            "strategy": result.get("strategy"),
            "injections": int(result.get("injections", 0)),
            "counts": reported,
            "codes_sha256": sha,
            "cache_event": event,
            "worker": result.get("worker"),
            "attempts": int(rec.get("attempts", 1)),
        })
    failed = [{"id": r.get("id"), "error": r.get("error")}
              for r in q.items("failed")]
    return {
        "format": "coast-fleet-result", "version": 1,
        "items": items_out, "failed": failed,
        "totals": totals, "injections": injections,
        "physical_injections": physical,
        "cache": {**cache_events,
                  "hits": sum(v for k, v in cache_events.items()
                              if k.endswith("hit")),
                  "misses": cache_events.get("miss", 0)},
        "queue": q.stats(),
        "parity": "ok",
    }


# -- CLI ---------------------------------------------------------------------

def _add_queue(p: argparse.ArgumentParser) -> None:
    p.add_argument("--queue", "-Q", required=True, metavar="DIR",
                   help="fleet queue root directory (created if absent)")


def parse_command_line(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        prog="python -m coast_tpu.fleet",
        description="Campaign fleet: schedule many campaigns across many "
                    "worker processes with crash-kill-resume and merged "
                    "parity-checked results")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("enqueue", help="queue one (or --count) campaigns")
    _add_queue(p)
    p.add_argument("--filename", "-f", required=True,
                   help="benchmark registry name or restricted-C path")
    p.add_argument("--opt-passes", "-O", default="-TMR",
                   help="protection flags (opt CLI string)")
    p.add_argument("--section", "-s", default="memory")
    p.add_argument("-t", metavar="N", type=int, required=True,
                   help="injections per campaign")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--start-num", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=4096)
    p.add_argument("--fault-model", default="single")
    p.add_argument("--equiv", action="store_true")
    p.add_argument("--stop-when", default=None)
    p.add_argument("--delta-from", default=None, metavar="JOURNAL",
                   help="make the item a DELTA campaign: re-inject only "
                   "the sections whose propagation fingerprint changed "
                   "since JOURNAL (a completed --equiv run of the same "
                   "campaign), splicing the rest; implies --equiv.  "
                   "Combined with --stop-when, each re-injected section "
                   "is convergence-bounded on its own (the CI work "
                   "unit)")
    p.add_argument("--unroll", type=int, default=1)
    p.add_argument("--collect", default="dense",
                   choices=["dense", "sparse"],
                   help="result-collection mode for the item's worker: "
                   "'sparse' keeps the campaign loop device-resident "
                   "(on-device flip generation + histogram accounting, "
                   "only interesting rows fetched); counts identical, "
                   "journal records sparse-shaped")
    p.add_argument("--throttle", type=float, default=0.0, metavar="S",
                   help="sleep S seconds per collected batch (operator "
                   "rate limit)")
    p.add_argument("--count", type=int, default=1, metavar="K",
                   help="enqueue K copies with seeds seed..seed+K-1")

    p = sub.add_parser("run", help="launch workers and drain the queue")
    _add_queue(p)
    p.add_argument("--workers", "-w", type=int, default=2, metavar="N")
    p.add_argument("--mesh", type=int, default=None, metavar="M",
                   help="each worker shards its batch over the first M "
                   "devices (CampaignRunner mesh backend)")
    p.add_argument("--lease", type=float, default=30.0, metavar="S",
                   help="work-item lease seconds (renewed per batch; an "
                   "expired lease requeues the item)")
    p.add_argument("--poll", type=float, default=0.5, metavar="S")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="per-worker-slot restart budget for crashed "
                   "workers")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve the merged fleet /metrics + /status here "
                   "(0 = ephemeral, printed; conflicts fall back to "
                   "ephemeral with a warning)")
    p.add_argument("--bind", default="127.0.0.1", metavar="ADDR",
                   help="aggregate endpoint bind address")
    p.add_argument("--status-json", default=None, metavar="PATH",
                   help="mirror the fleet status JSON here (atomic "
                   "replace) every poll")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write ONE federated Perfetto trace here after "
                   "the drain: every item's journal span timeline "
                   "(clock-skew corrected, SIGKILL'd+resumed workers' "
                   "batches exactly once) plus the queue's "
                   "claim/lease/complete events (obs/federate.py)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="evaluate this reliability SLO spec live against "
                   "the fleet aggregate (obs/slo.py grammar); verdicts "
                   "ride /status, /metrics and the spawned workers' "
                   "own status docs")

    p = sub.add_parser("worker", help="run ONE worker process (what "
                       "`run` spawns)")
    _add_queue(p)
    p.add_argument("--worker-id", required=True)
    p.add_argument("--mesh", type=int, default=None)
    p.add_argument("--lease", type=float, default=30.0)
    p.add_argument("--poll", type=float, default=0.25)
    p.add_argument("--max-retries", type=int, default=2)
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve this worker's own live campaign metrics "
                   "(port conflicts fall back to an ephemeral port, so "
                   "per-worker servers coexist on one host)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="evaluate this reliability SLO spec live against "
                   "the worker's campaign metrics (obs/slo.py grammar, "
                   "e.g. 'sdc_rate<=0.002;min=4096'); the verdict rides "
                   "the worker status doc and /metrics")

    p = sub.add_parser("status", help="print the fleet status document")
    _add_queue(p)

    p = sub.add_parser("merge", help="parity-checked merge of completed "
                       "items into fleet_result.json")
    _add_queue(p)
    p.add_argument("--out", default=None, metavar="PATH",
                   help="artifact path (default <queue>/fleet_result.json)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="also write the federated Perfetto trace of "
                   "every item's journal timeline + queue events")

    # `-O -TMR` ergonomics, exactly as the inject supervisor CLI: argparse
    # eats a bare `-TMR` as an unknown option, so pre-join the pass flags
    # following -O/--opt-passes into `-O=<flags>`.  Tokens that ARE fleet
    # options stop the join.
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    known = {"-h", "--help"}
    for sp in (parser, *sub.choices.values()):
        known.update(s for a in sp._actions for s in a.option_strings)
    joined, i = [], 0
    while i < len(argv):
        tok = argv[i]
        if tok in ("-O", "--opt-passes") and i + 1 < len(argv):
            passes, j = [], i + 1
            while (j < len(argv) and argv[j].startswith("-")
                   and argv[j] not in known):
                passes.append(argv[j])
                j += 1
            if passes:
                joined.append(tok + "=" + " ".join(passes))
                i = j
                continue
        joined.append(tok)
        i += 1
    return parser.parse_args(joined)


def cmd_enqueue(args) -> int:
    q = CampaignQueue(args.queue)
    if args.delta_from and args.count > 1:
        # --count varies the seed per item, and a delta base journal
        # records ONE seed: items 2..K would deterministically fail
        # at claim time with DeltaMismatchError.  Refuse the enqueuer.
        print("Error, --delta-from cannot be combined with --count > 1: "
              "the delta base journal records one seed, and --count "
              "enqueues seed-varied copies that can never splice from "
              "it", file=sys.stderr)
        return 1
    try:
        specs = [item_spec(args.filename, args.t,
                           seed=args.seed + i,
                           opt_passes=args.opt_passes,
                           section=args.section,
                           batch_size=args.batch_size,
                           start_num=args.start_num,
                           fault_model=args.fault_model,
                           equiv=args.equiv or bool(args.delta_from),
                           stop_when=args.stop_when,
                           unroll=args.unroll, throttle_s=args.throttle,
                           delta_from=args.delta_from,
                           collect=args.collect)
                 for i in range(max(1, args.count))]
    except (QueueError, ValueError) as e:
        print(f"Error, bad item spec: {e}", file=sys.stderr)
        return 1
    for spec in specs:
        print(q.enqueue(spec))
    return 0


def _spawn_worker(args, wid: str) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "coast_tpu.fleet", "worker",
           "--queue", args.queue, "--worker-id", wid,
           "--lease", str(args.lease)]
    if args.mesh:
        cmd += ["--mesh", str(args.mesh)]
    if getattr(args, "slo", None):
        cmd += ["--slo", args.slo]
    # The package may be run from a source checkout rather than an
    # installed dist: make sure the child resolves the same coast_tpu
    # this supervisor is running.
    import coast_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(coast_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(cmd, env=env)


def cmd_run(args) -> int:
    from coast_tpu.fleet.telemetry import FleetTelemetry
    q = CampaignQueue(args.queue)
    if q.drained():
        print("Error, the queue has no live work; enqueue items first",
              file=sys.stderr)
        return 1
    try:
        telemetry = FleetTelemetry(q, stale_s=max(10.0, 2.0 * args.lease),
                                   slo=args.slo)
    except Exception as e:              # noqa: BLE001 - bad --slo spec
        print(f"Error, bad --slo spec: {e}", file=sys.stderr)
        return 2
    server = None
    if args.metrics_port is not None:
        from coast_tpu.obs.serve import MetricsServer
        server = MetricsServer(telemetry, port=args.metrics_port,
                               bind=args.bind)
        port = server.start()
        print(f"# fleet metrics: http://{args.bind}:{port}/metrics  "
              f"status: http://{args.bind}:{port}/status",
              file=sys.stderr, flush=True)
    ids = [f"w{i}" for i in range(max(1, args.workers))]
    procs: Dict[str, Optional[subprocess.Popen]] = {
        wid: _spawn_worker(args, wid) for wid in ids}
    restarts = {wid: 0 for wid in ids}
    rc = 0
    try:
        while True:
            q.requeue_expired()
            if args.status_json:
                atomic_write_json(args.status_json, telemetry.snapshot())
            if q.drained():
                break
            alive = 0
            for wid in ids:
                proc = procs[wid]
                if proc is None:
                    continue
                code = proc.poll()
                if code is None:
                    alive += 1
                    continue
                # The worker died (or drained and exited while work was
                # requeued behind its back).  Reclaim anything it held
                # NOW -- the supervisor watched it exit, no lease wait
                # needed -- and restart the slot if budget remains.
                requeued = q.requeue_worker(wid)
                if code != 0 or requeued:
                    print(f"# worker {wid} exited rc={code}; requeued "
                          f"{len(requeued)} item(s)",
                          file=sys.stderr, flush=True)
                if q.drained():
                    procs[wid] = None
                    continue
                if restarts[wid] < args.max_restarts:
                    restarts[wid] += 1
                    procs[wid] = _spawn_worker(args, wid)
                    alive += 1
                else:
                    procs[wid] = None
            if alive == 0 and not q.drained():
                print("Error, all workers exhausted their restart "
                      "budget with work remaining", file=sys.stderr)
                rc = 1
                break
            time.sleep(args.poll)
    finally:
        for proc in procs.values():
            if proc is not None and proc.poll() is None:
                try:
                    proc.wait(timeout=max(5.0, 2.0 * args.poll))
                except subprocess.TimeoutExpired:
                    proc.terminate()
                    proc.wait(timeout=5.0)
        if args.status_json:
            # Terminal snapshot: the workers have exited, so a headless
            # consumer polling this file must see the drained state.
            atomic_write_json(args.status_json, telemetry.snapshot())
        if server is not None:
            server.stop()
    try:
        result = merge_fleet(q)
    except FleetParityError as e:
        print(f"Error, fleet merge parity check failed: {e}",
              file=sys.stderr)
        return 1
    out = os.path.join(q.root, "fleet_result.json")
    atomic_write_json(out, result)
    if args.trace_out:
        from coast_tpu.obs.federate import write_merged_trace
        write_merged_trace(q, args.trace_out)
        print(f"wrote federated trace {args.trace_out}")
    totals = ", ".join(f"{k}={v}" for k, v in sorted(
        result["totals"].items()) if v)
    print(f"fleet: {len(result['items'])} campaigns merged "
          f"({result['injections']} injections; {totals}); "
          f"cache hits={result['cache']['hits']} "
          f"misses={result['cache']['misses']}; parity ok")
    print(f"wrote {out}")
    if result["failed"]:
        for rec in result["failed"]:
            print(f"FAILED item {rec['id']}: {rec['error']}",
                  file=sys.stderr)
        return 1
    return rc


def cmd_worker(args) -> int:
    from coast_tpu.fleet.worker import Worker
    from coast_tpu.obs import flightrec
    from coast_tpu.obs.metrics import CampaignMetrics
    from coast_tpu.obs.slo import SLOError
    # Process-lifetime blackbox: lease/journal/dispatch events land in
    # one ring, bundles land under the queue root (the supervisor's and
    # the tests' harvest surface), SIGUSR1 dumps on demand.
    rec = flightrec.install(dump_dir=os.environ.get(
        "COAST_FLIGHTREC_DIR") or os.path.join(args.queue, "flightrec"),
        source=f"fleet-worker:{args.worker_id}")
    rec.install_signal_handler()
    try:
        metrics = CampaignMetrics(slo=args.slo)
    except SLOError as e:
        print(f"Error, bad --slo spec: {e}", file=sys.stderr)
        return 2
    server = None
    if args.metrics_port is not None:
        from coast_tpu.obs.serve import MetricsServer
        server = MetricsServer(metrics, port=args.metrics_port)
        port = server.start()
        print(f"# worker {args.worker_id} metrics: "
              f"http://127.0.0.1:{port}/metrics",
              file=sys.stderr, flush=True)
    try:
        worker = Worker(args.queue, args.worker_id,
                        mesh_devices=args.mesh, lease_s=args.lease,
                        poll_s=args.poll, metrics=metrics,
                        max_retries=args.max_retries)
        done = worker.drain()
    finally:
        if server is not None:
            server.stop()
    print(f"# worker {args.worker_id} drained: {done} item(s) completed",
          file=sys.stderr, flush=True)
    return 0


def cmd_status(args) -> int:
    from coast_tpu.fleet.telemetry import FleetTelemetry
    print(json.dumps(FleetTelemetry(args.queue).snapshot(), indent=2,
                     sort_keys=True))
    return 0


def cmd_merge(args) -> int:
    q = CampaignQueue(args.queue)
    try:
        result = merge_fleet(q)
    except FleetParityError as e:
        print(f"Error, fleet merge parity check failed: {e}",
              file=sys.stderr)
        return 1
    out = args.out or os.path.join(q.root, "fleet_result.json")
    atomic_write_json(out, result)
    print(f"wrote {out} ({len(result['items'])} items, "
          f"{result['injections']} injections, parity ok)")
    if args.trace_out:
        from coast_tpu.obs.federate import write_merged_trace
        write_merged_trace(q, args.trace_out)
        print(f"wrote federated trace {args.trace_out}")
    return 1 if result["failed"] else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_command_line(argv)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    return {"enqueue": cmd_enqueue, "run": cmd_run, "worker": cmd_worker,
            "status": cmd_status, "merge": cmd_merge}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
