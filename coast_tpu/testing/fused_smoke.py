"""Fused protected-step smoke driver (unittest/cfg/fast.yml row).

The fused engine's two-sided contract (ROADMAP item 1, the PR 15
attribution's 20x in-step overhead), regression-checked every CI run on
CPU in under a minute:

  * **Byte parity**: a dense mm x TMR campaign at one seed produces the
    IDENTICAL classification counts and a byte-identical dense ndjson
    log (sha256 over the file with the wall-clock timestamp normalized
    -- the one legitimately time-varying token) whether the program runs
    the unfused interpreter loop or the fused engine.  Fusion is a
    schedule change, never a semantics change.
  * **It actually wins**: the restructured-scan path's measured program
    op count (obs/roofline.py over the real jaxpr, pallas_call-aware)
    cuts `flops_overhead` by >= 2x for TMR -- the acceptance floor of
    the fused-step issue -- and strictly improves DWC too.
  * **Campaign identity**: a journal written under one engine refuses
    the other with the typed FuseStepMismatchError, both directions.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile
from typing import List, Optional


def _norm_sha(path: str) -> str:
    """sha256 of an ndjson log with the campaign timestamp normalized:
    every line embeds the ONE per-campaign wall-clock string (logs.py
    write_ndjson), which two sequential writes legitimately differ on."""
    with open(path, "rb") as f:
        text = f.read().decode()
    text = re.sub(r'"timestamp": "[^"]*"', '"timestamp": "TS"', text)
    # The summary line's wall-clock measurements (seconds, rate, stage
    # timings) describe THIS run's scheduling, not campaign semantics.
    text = re.sub(r'"stages": \{[^}]*\}(, )?', '', text)
    text = re.sub(r'"(seconds|injections_per_sec)": [0-9.eE+-]+(, )?',
                  '', text)
    return hashlib.sha256(text.encode()).hexdigest()


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR, DWC
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.journal import FuseStepMismatchError
    from coast_tpu.inject.logs import write_ndjson
    from coast_tpu.models import resolve_region
    from coast_tpu.obs import roofline

    region = resolve_region("matrixMultiply")
    n, seed, batch = 512, 2026, 256

    # -- byte parity: fused vs unfused dense ndjson at one seed ----------
    shas, counts = {}, {}
    with tempfile.TemporaryDirectory() as d:
        for mode, fused in (("unfused", False), ("fused", True)):
            prog = TMR(region, fuse_step=fused)
            runner = CampaignRunner(prog, strategy_name="TMR")
            res = runner.run(n, seed=seed, batch_size=batch)
            path = os.path.join(d, f"{mode}.ndjson")
            write_ndjson(res, runner.mmap, path)
            shas[mode] = _norm_sha(path)
            counts[mode] = dict(res.counts)
        if counts["fused"] != counts["unfused"]:
            print(f"Error, fused campaign changed classification counts: "
                  f"{counts['unfused']} -> {counts['fused']}")
            return 1
        if shas["fused"] != shas["unfused"]:
            print(f"Error, fused dense ndjson is not byte-identical "
                  f"(sha {shas['unfused'][:16]} vs {shas['fused'][:16]})")
            return 1
        print(f"byte parity: dense ndjson sha {shas['fused'][:16]} "
              f"identical across engines ({counts['fused']})")

        # -- the fused engine must WIN: measured op-count overhead -------
        for name, make, floor in (("TMR", TMR, 2.0), ("DWC", DWC, 1.5)):
            base = roofline.flops_overhead(make(region))
            fused = roofline.flops_overhead(make(region, fuse_step=True))
            red = base / fused
            print(f"{name}: flops_overhead {base:.3f}x -> {fused:.3f}x "
                  f"({red:.2f}x reduction)")
            if red < floor:
                print(f"Error, {name} fused overhead reduction "
                      f"{red:.2f}x below the {floor}x floor")
                return 1

        # -- journal fuse identity: typed refusal, both directions -------
        for first, second in ((False, True), (True, False)):
            jpath = os.path.join(d, f"j_{int(first)}.ndjson")
            CampaignRunner(TMR(region, fuse_step=first),
                           strategy_name="TMR").run(
                16, seed=1, batch_size=16, journal=jpath)
            try:
                CampaignRunner(TMR(region, fuse_step=second),
                               strategy_name="TMR").run(
                    16, seed=1, batch_size=16, journal=jpath)
                print(f"Error, fuse={second} runner resumed a "
                      f"fuse={first} journal")
                return 1
            except FuseStepMismatchError:
                pass
        print("journal identity: cross-engine resume refused typed "
              "(both directions)")

    print("Success!")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
