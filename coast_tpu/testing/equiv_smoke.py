"""Equivalence-reduction smoke driver (unittest/cfg/fast.yml row).

Regression-checks the FastFlip/FuzzyFlow contract every CI run, on CPU
in a few seconds (prints ``Success!`` for the harness driver oracle,
coast_tpu.testing.harness.run_drivers):

  1. **Differential parity** -- the equivalence-reduced campaign's
     weighted classification distribution EXACTLY equals the exhaustive
     one on a seeded TMR and a seeded DWC target, while physically
     dispatching strictly fewer runs.
  2. **Journal identity** -- an interrupted equiv campaign resumes
     bit-for-bit, and resuming its journal without the partition (or
     vice versa) is refused with the typed JournalMismatchError.
  3. **Delta campaigns** -- a no-op rebuild re-injects zero rows; a
     pre-equiv journal (no fingerprint block) is refused with the typed
     DeltaMismatchError.
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

import numpy as np


class _Kill(Exception):
    """SIGKILL stand-in raised from a progress beat after the preceding
    batches' journal records are already fsync'd."""


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import DWC, TMR
    from coast_tpu.analysis.equiv import DeltaMismatchError
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.journal import JournalMismatchError
    from coast_tpu.models import crc16, mm

    # 1. differential parity on two strategies / two targets
    checks = ((TMR, "TMR", mm.make_region()),
              (DWC, "DWC", crc16.make_region()))
    for maker, strat, region in checks:
        prog = maker(region)
        exhaustive = CampaignRunner(prog, strategy_name=strat)
        reduced = CampaignRunner(prog, strategy_name=strat, equiv=True)
        a = exhaustive.run(1500, seed=23, batch_size=500)
        b = reduced.run(1500, seed=23, batch_size=500)
        if a.counts != b.counts:
            print(f"differential parity FAILED on {region.name} {strat}: "
                  f"{a.counts} != {b.counts}")
            return 1
        if b.physical_n is None or b.physical_n >= a.n:
            print(f"no reduction on {region.name} {strat}: "
                  f"physical={b.physical_n}")
            return 1
        print(f"{region.name} {strat}: distribution identical at "
              f"{b.physical_n}/{a.n} physical injections "
              f"({a.n / b.physical_n:.1f}x)")

    # 2. journaled equiv campaign: interrupt, resume, identity checks
    prog = TMR(mm.make_region())
    runner = CampaignRunner(prog, strategy_name="TMR", equiv=True)
    baseline = runner.run(1200, seed=23, batch_size=300)
    with tempfile.TemporaryDirectory() as d:
        jpath = os.path.join(d, "equiv.journal")
        beats = {"n": 0}

        def kill_on_second(done, counts):
            beats["n"] += 1
            if beats["n"] >= 2:
                raise _Kill

        try:
            runner.run(1200, seed=23, batch_size=300, journal=jpath,
                       progress=kill_on_second)
            print("campaign was not interrupted; smoke setup broken")
            return 1
        except _Kill:
            pass
        resumed = runner.run(1200, seed=23, batch_size=300, journal=jpath)
        if not np.array_equal(resumed.codes, baseline.codes) \
                or resumed.counts != baseline.counts:
            print("equiv resume parity FAILED")
            return 1
        try:
            CampaignRunner(prog, strategy_name="TMR").run(
                1200, seed=23, batch_size=300, journal=jpath)
            print("partition mismatch was NOT refused")
            return 1
        except JournalMismatchError:
            pass
        print("equiv campaign interrupted, resumed bit-for-bit; "
              "partitionless resume refused")

        # 3. delta: no-op rebuild reuses everything; pre-equiv refused
        base_j = os.path.join(d, "delta_base.journal")
        runner.run(1200, seed=23, batch_size=300, journal=base_j)
        rebuilt = CampaignRunner(TMR(mm.make_region()),
                                 strategy_name="TMR", equiv=True)
        delta = rebuilt.run_delta(1200, base_j, seed=23, batch_size=300)
        if delta.delta["reinjected_rows"] != 0 \
                or delta.delta["changed_sections"]:
            print(f"no-op delta re-injected: {delta.delta}")
            return 1
        if delta.counts != baseline.counts:
            print("delta splice distribution FAILED")
            return 1
        plain_j = os.path.join(d, "plain.journal")
        CampaignRunner(prog, strategy_name="TMR").run(
            600, seed=23, batch_size=300, journal=plain_j)
        try:
            rebuilt.run_delta(600, plain_j, seed=23, batch_size=300)
            print("pre-equiv delta base was NOT refused")
            return 1
        except DeltaMismatchError:
            pass
        print("no-op delta re-injected 0 rows; pre-equiv base refused")
    print("Success!")
    return 0


if __name__ == "__main__":
    import sys

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
