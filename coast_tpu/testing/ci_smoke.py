"""Protection-regression-CI smoke driver (unittest/cfg/fast.yml row).

Regression-checks the ``python -m coast_tpu ci`` contract every CI run,
on CPU in under a minute (prints ``Success!`` for the harness driver
oracle, coast_tpu.testing.harness.run_drivers):

  1. **baseline** -- two targets (mm x TMR, crc16 x DWC) run as full
     equivalence-reduced fleet campaigns into a baseline artifact.
  2. **no-op check** -- re-checking the unchanged tree re-injects ZERO
     rows on every target and passes (exit 0), and the refreshed
     artifact it produces is itself checkable.
  3. **weakened build** -- the seeded protection-weakening edit (the
     lint sweep's dropped-commit-vote regression seed:
     ``prog.step_sync["results"] = False`` on the TMR build) must
     change section fingerprints, re-inject only the affected target's
     sections, and FAIL the check with a per-class drift verdict
     (exit 1) while the untouched DWC target stays consistent.
"""

from __future__ import annotations

import os
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu.ci import engine
    from coast_tpu.inject.spec import CampaignSpec

    specs = [
        CampaignSpec("matrixMultiply", 512, seed=7, opt_passes="-TMR",
                     batch_size=256, equiv=True),
        CampaignSpec("crc16", 512, seed=7, opt_passes="-DWC",
                     batch_size=256, equiv=True),
    ]

    # 1. baseline
    doc = engine.build_baseline(specs)
    if len(doc["targets"]) != 2:
        print(f"baseline has {len(doc['targets'])} targets; want 2")
        return 1
    for tid, block in doc["targets"].items():
        if not block["section_fingerprints"]:
            print(f"{tid}: baseline carries no section fingerprints")
            return 1
    print(f"baseline built: {sorted(doc['targets'])}")

    # 2. no-op check: zero rows re-injected, exit 0
    report = engine.check_baseline(doc)
    if report.exit_code != engine.EXIT_PASS:
        print(f"no-op check FAILED:\n{report.format()}")
        return 1
    for t in report.targets:
        if t.reinjected_rows != 0 or t.changed_sections:
            print(f"no-op check re-injected rows: {t.target} "
                  f"{t.reinjected_rows} ({t.changed_sections})")
            return 1
    print("no-op check: 0 rows re-injected on every target; PASS")

    # ... and the refreshed artifact is itself a valid splice base.
    report2 = engine.check_baseline(report.refreshed)
    if report2.exit_code != engine.EXIT_PASS or any(
            t.reinjected_rows for t in report2.targets):
        print(f"refreshed-baseline check FAILED:\n{report2.format()}")
        return 1
    print("refreshed baseline checks clean")

    # 3. weakened TMR build must drift (and only it)
    def weaken(prog):
        if prog.region.name == "matrixMultiply" \
                and prog.step_sync.get("results"):
            prog.step_sync["results"] = False

    weak = engine.check_baseline(doc, program_hook=weaken)
    if weak.exit_code != engine.EXIT_DRIFT:
        print(f"weakened build did NOT drift:\n{weak.format()}")
        return 1
    by_target = {t.target: t for t in weak.targets}
    mm_t = next(t for tid, t in by_target.items()
                if tid.startswith("matrixMultiply|"))
    crc_t = next(t for tid, t in by_target.items()
                 if tid.startswith("crc16|"))
    if not mm_t.drift or not mm_t.changed_sections \
            or not mm_t.reinjected_rows:
        print(f"weakened mm target did not re-inject/drift: "
              f"{mm_t}")
        return 1
    if crc_t.drift or crc_t.reinjected_rows:
        print(f"untouched crc16 target drifted: {crc_t}")
        return 1
    print(f"weakened build: DRIFT on {mm_t.target} "
          f"(sections {mm_t.changed_sections}, "
          f"{mm_t.reinjected_rows} rows re-injected); "
          "crc16 stayed consistent")
    print("Success!")
    return 0


if __name__ == "__main__":
    import sys

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
