"""Live-observability smoke driver (unittest/cfg/fast.yml row).

The live-metrics guarantees regression-checked every CI run, on CPU in
a few seconds:

  1. **Live surfaces track a running campaign**: while batches are
     still dispatching, the HTTP endpoint's /status JSON and /metrics
     Prometheus text (and the atomic --status-json file) report the
     exact cumulative progress the campaign loop has reached.
  2. **Statistical early stop is sound**: a loose ``stop_when``
     condition stops the campaign mid-schedule, and the stopped
     campaign's per-class rates agree with the exhaustive run's within
     the reported Wilson intervals (the FastFlip stop-when-converged
     contract).
  3. **The stop is a first-class journal record**: rerunning the same
     journaled call replays the prefix and stops at the same batch
     bit-for-bit without growing the journal; resuming under a
     different (or no) condition refuses with the typed error.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request
from typing import List, Optional

import numpy as np


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode("utf-8")


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR, obs
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.journal import JournalMismatchError
    from coast_tpu.models import mm

    with tempfile.TemporaryDirectory() as d:
        status_path = os.path.join(d, "status.json")
        metrics = obs.CampaignMetrics(status_path=status_path)
        server = obs.MetricsServer(metrics, port=0)
        port = server.start()
        runner = CampaignRunner(TMR(mm.make_region()), strategy_name="TMR",
                                metrics=metrics)

        # 1. Live tracking: probe the HTTP surfaces from the progress
        # callback, i.e. strictly WHILE the campaign is running.
        live_ok = []

        def probe(done, counts):
            doc = json.loads(_get(f"http://127.0.0.1:{port}/status"))
            file_doc = json.loads(open(status_path).read())
            live_ok.append(
                doc["state"] == "running"
                and doc["done_rows"] == done
                and file_doc["done_rows"] == done
                and doc["counts"].get("sdc", 0) == counts.get("sdc", 0))

        full = runner.run(1500, seed=11, batch_size=128, progress=probe)
        prom = _get(f"http://127.0.0.1:{port}/metrics")
        server.stop()
        if not (live_ok and all(live_ok)):
            print(f"live tracking FAILED: probes {live_ok}")
            return 1
        if "coast_campaign_class_total" not in prom \
                or 'strategy="TMR"' not in prom:
            print("prometheus exposition FAILED: expected metrics missing")
            return 1
        final_doc = json.loads(open(status_path).read())
        if final_doc["state"] != "finished" \
                or final_doc["done_rows"] != 1500:
            print(f"status file FAILED: terminal state {final_doc['state']}"
                  f" done {final_doc['done_rows']}")
            return 1

        # 2. Early stop: loose target, must trip before the full 1500.
        stop = obs.StopWhen.parse("sdc:0.05;min=256")
        jpath = os.path.join(d, "stop.journal")
        stopped = runner.run(1500, seed=11, batch_size=128,
                             stop_when=stop, journal=jpath)
        conv = stopped.convergence
        if not conv["stopped"] or stopped.n >= full.n:
            print(f"early stop FAILED: {conv}")
            return 1
        for cls_name in ("sdc", "corrected", "success"):
            ci = conv["intervals"][cls_name]
            exact = full.counts[cls_name] / full.n
            if not (ci["lo"] <= exact <= ci["hi"]):
                print(f"convergence soundness FAILED: exhaustive "
                      f"{cls_name} rate {exact:.4f} outside the stopped "
                      f"campaign's CI [{ci['lo']:.4f}, {ci['hi']:.4f}]")
                return 1

        # 3. First-class terminal record: resume replays and stops at
        # the same batch, bit-for-bit, appending nothing.
        size_before = os.path.getsize(jpath)
        resumed = runner.run(1500, seed=11, batch_size=128,
                             stop_when=stop, journal=jpath)
        if not np.array_equal(resumed.codes, stopped.codes) \
                or os.path.getsize(jpath) != size_before:
            print("early-stop resume FAILED: codes or journal changed")
            return 1
        try:
            runner.run(1500, seed=11, batch_size=128, journal=jpath)
            print("early-stop identity FAILED: resume without stop_when "
                  "was not refused")
            return 1
        except JournalMismatchError:
            pass

    print(f"live surfaces tracked {len(live_ok)} batches; early stop at "
          f"{stopped.n}/{full.n} with exhaustive rates inside every CI; "
          "journaled stop resumed bit-for-bit and refused a mismatched "
          "condition")
    print("Success!")
    return 0


if __name__ == "__main__":
    sys.exit(main())
