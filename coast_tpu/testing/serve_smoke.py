"""Continuous-protection serving smoke driver (fast.yml row).

The PR 18 serving contract, regression-checked every CI run on CPU in
a few seconds:

  * the lane-isolation prover gates construction: both strategy
    programs HOLD, and a seeded voter bypass makes ``ServeEngine``
    refuse to serve (``IsolationRefusedError``) instead of running an
    unproved program under live traffic;
  * a request burst over the live engine is served within SLA while
    injection lanes run in the same compiled dispatches, the runtime
    lane-leak assert stays at zero violations, and the ``serving``
    block carries a live Wilson-CI'd SDC rate next to the campaign
    hub's SLO verdicts;
  * the differential contract: the same request stream serialises
    byte-identically with the injection lanes on and off -- the
    measurement arm must not perturb responses;
  * the HTTP front answers ``POST /v1/infer`` deterministically and
    exports ``/status`` (``coast-serve-status``) + ``/metrics``
    (``coast_serve_*`` rows);
  * ``json_parser`` renders the recorded ``serving`` block from the
    run artifact.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional

_BENCH = "matrixMultiply"
_BATCH = 16
_INJECT_N = 64


def _serve_burst(engine, n_requests: int) -> List[dict]:
    """Submit a burst, wait each request out, return the responses."""
    reqs = [engine.submit(f"req-{i:03d}", sla_s=30.0)
            for i in range(n_requests)]
    responses = []
    for req in reqs:
        assert req.done.wait(60.0), f"request {req.rid} never completed"
        assert req.response is not None, (req.rid, req.error)
        responses.append(req.response)
    return responses


def _check_live_engine(tmp: str) -> dict:
    """Prover-gated engine serves a burst while self-measuring."""
    from coast_tpu.serve import ServeEngine, ServeMetrics

    metrics = ServeMetrics(slo="sdc_rate<=0.9;min=8")
    with ServeEngine(_BENCH, batch_size=_BATCH, inject_share=0.5,
                     seed=11, inject_n=_INJECT_N, metrics=metrics,
                     journal_dir=tmp) as engine:
        for lane in engine._lanes.values():
            assert lane.proof.holds and not lane.proof.vacuous, \
                lane.proof.summary()
        responses = _serve_burst(engine, 12)
        assert engine.drain_injection(timeout_s=120.0), \
            f"standing injection never drained: {engine.error}"
        doc = engine.summary()
    assert all(r["class"] == "success" for r in responses), responses
    srv = doc["serving"]
    assert srv["requests"]["served"] == 12, srv["requests"]
    assert srv["lane_leak"]["violations"] == 0, srv["lane_leak"]
    assert srv["lane_leak"]["checks"] > 0, "lane-leak assert never ran"
    inj = srv["inject"]
    # Both standing campaigns fully injected; the CI is live Wilson.
    assert inj["lanes_done"] == 2 * _INJECT_N, inj
    ci = inj["sdc_ci"]
    assert 0.0 <= ci["lo"] <= inj["sdc_rate"] <= ci["hi"] <= 1.0, inj
    assert doc["slo"]["verdict"] == "ok", doc.get("slo")
    # Wilson consistency: the serving CI is obs/convergence's interval.
    from coast_tpu.obs.convergence import wilson_interval
    lo, hi = wilson_interval(inj["sdc"], inj["lanes_done"], 1.96)
    assert abs(ci["lo"] - round(lo, 8)) < 1e-9, (ci, lo)
    assert abs(ci["hi"] - round(hi, 8)) < 1e-9, (ci, hi)
    print(f"# live serve: 12 served, {inj['lanes_done']} injection "
          f"lanes, sdc {inj['sdc_rate']:.4g} "
          f"[{ci['lo']:.4g}, {ci['hi']:.4g}], slo "
          f"{doc['slo']['verdict']}")
    return doc


def _check_byte_identity() -> None:
    """Responses byte-identical with injection lanes on and off."""
    from coast_tpu.serve import ServeEngine

    streams = []
    for share in (0.5, 0.0):
        with ServeEngine(_BENCH, batch_size=_BATCH,
                         inject_share=share, seed=11,
                         inject_n=_INJECT_N) as engine:
            responses = _serve_burst(engine, 10)
        streams.append(json.dumps(responses, sort_keys=True))
    assert streams[0] == streams[1], \
        "injection lanes perturbed the response stream"
    print("# differential: 10-request stream byte-identical, "
          "inject_share 0.5 vs 0.0")


def _check_prover_refusal() -> None:
    """A seeded voter bypass must refuse to serve, not serve unproved."""
    from coast_tpu.analysis.propagation import seeded_voter_bypass
    from coast_tpu.serve import IsolationRefusedError, ServeEngine

    try:
        with seeded_voter_bypass():
            ServeEngine(_BENCH, batch_size=_BATCH, inject_share=0.0,
                        inject_n=0, strategies=("TMR",))
        raise AssertionError("bypassed voter served anyway")
    except IsolationRefusedError as e:
        assert "REFUTED" in str(e), str(e)
    print("# prover gate: seeded voter bypass refused at construction")


def _check_http_front(tmp: str) -> dict:
    """The HTTP plane: infer + status + metrics off one live front."""
    import urllib.request

    from coast_tpu.serve import ServeEngine, ServeFront, ServeMetrics

    metrics = ServeMetrics(slo="sdc_rate<=0.9;min=8")
    engine = ServeEngine(_BENCH, batch_size=_BATCH, inject_share=0.5,
                         seed=11, inject_n=_INJECT_N, metrics=metrics)
    with ServeFront(engine, port=0) as front:
        body = json.dumps({"payload": "http-req", "sla_s": 30.0})
        req = urllib.request.Request(
            front.url + "/v1/infer", data=body.encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60.0) as resp:
            assert resp.status == 200, resp.status
            answer = json.loads(resp.read())
        assert answer["payload"] == "http-req", answer
        assert answer["class"] == "success", answer
        with urllib.request.urlopen(front.url + "/status",
                                    timeout=10.0) as resp:
            status = json.loads(resp.read())
        assert status["format"] == "coast-serve-status", \
            status.get("format")
        assert status["serving"]["requests"]["served"] >= 1, \
            status["serving"]
        with urllib.request.urlopen(front.url + "/metrics",
                                    timeout=10.0) as resp:
            prom = resp.read().decode()
        for row in ("coast_serve_served_total",
                    "coast_serve_lane_leak_violations_total 0",
                    "coast_serve_request_latency_seconds_count"):
            assert row in prom, f"missing metrics row: {row}"
    print(f"# http front: infer 200 ({answer['strategy']}), status + "
          "metrics export")
    return answer


def _check_json_parser(tmp: str, doc: dict) -> None:
    """The recorded serving block renders in the analysis CLI."""
    from coast_tpu.analysis.json_parser import summarize_path

    artifact = os.path.join(tmp, "serve_run.json")
    with open(artifact, "w") as fh:
        head = {"format": "ndjson", "injections": 0,
                "benchmark": doc["benchmark"], "counts": doc["counts"],
                "serving": doc["serving"], "slo": doc.get("slo")}
        json.dump({"summary": head, "runs": []}, fh)
    summary = summarize_path(artifact)
    assert summary.serving is not None, "serving block dropped"
    text = summary.format()
    assert "--- serving ---" in text and "live sdc" in text, text
    print("# json_parser: serving block renders "
          f"({summary.serving['requests']['served']} served)")


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    with tempfile.TemporaryDirectory() as tmp:
        doc = _check_live_engine(tmp)
        _check_byte_identity()
        _check_prover_refusal()
        _check_http_front(tmp)
        _check_json_parser(tmp, doc)
    print("Success!")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
