"""Interrupt-and-resume STREAMING smoke driver (unittest/cfg/fast.yml row).

The streaming-serialization guarantee regression-checked every CI run:
a journaled campaign with a streaming log writer, killed after k
collected batches and relaunched, produces a final log file whose rows
are bit-for-bit the uninterrupted streamed run's -- which are in turn
bit-for-bit the one-shot ``write_ndjson`` rows.  (The summary header
line carries wall-clock seconds, so the comparison is: header parses
with identical counts, every row byte-identical.)  Runs on CPU in a few
seconds; prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import List, Optional


class _Kill(Exception):
    """SIGKILL stand-in: aborts the campaign from a progress beat, after
    the preceding batches' journal records are already fsync'd."""


def _read_lines(path: str) -> List[bytes]:
    with open(path, "rb") as f:
        return f.read().splitlines()


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR
    from coast_tpu.inject import logs
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.models import mm

    # Every writer stamps rows with its own wall-clock timestamp; pin it
    # so the comparison sees serialization differences, not clock ones.
    logs._timestamp = lambda: "2026-01-01 00:00:00.000000"

    runner = CampaignRunner(TMR(mm.make_region()), strategy_name="TMR")

    with tempfile.TemporaryDirectory() as d:
        # Uninterrupted baseline: streamed and one-shot writers.
        base = runner.run(120, seed=17, batch_size=40)
        logs.write_ndjson(base, runner.mmap, os.path.join(d, "oneshot.json"))
        w = logs.StreamLogWriter(os.path.join(d, "stream.json"),
                                 runner.mmap, fmt="ndjson")
        full = runner.run(120, seed=17, batch_size=40, stream=w)
        w.finish(full)

        # Interrupted + resumed streamed run against a journal.
        jpath = os.path.join(d, "smoke.journal")
        beats = {"n": 0}

        def kill_on_second(done, counts):
            beats["n"] += 1
            if beats["n"] >= 2:
                raise _Kill
        w2 = logs.StreamLogWriter(os.path.join(d, "resumed.json"),
                                  runner.mmap, fmt="ndjson")
        try:
            runner.run(120, seed=17, batch_size=40, journal=jpath,
                       progress=kill_on_second, stream=w2)
            print("campaign was not interrupted; smoke setup broken")
            return 1
        except _Kill:
            w2.abort()            # the kill also takes the temp stream
        w3 = logs.StreamLogWriter(os.path.join(d, "resumed.json"),
                                  runner.mmap, fmt="ndjson")
        resumed = runner.run(120, seed=17, batch_size=40, journal=jpath,
                             stream=w3)
        w3.finish(resumed)

        files = {name: _read_lines(os.path.join(d, f"{name}.json"))
                 for name in ("oneshot", "stream", "resumed")}
        rows = {name: lines[1:] for name, lines in files.items()}
        if not (rows["oneshot"] == rows["stream"] == rows["resumed"]):
            print("stream parity FAILED: rows differ between one-shot, "
                  "streamed, and resumed-streamed logs")
            return 1
        counts = {name: json.loads(lines[0])["summary"]["sdc"]
                  for name, lines in files.items()}
        if len(set(counts.values())) != 1:
            print(f"stream parity FAILED: summary sdc counts differ "
                  f"({counts})")
            return 1
        if "overlap" not in full.stages:
            print("stream accounting FAILED: no overlap fraction recorded")
            return 1

    print(f"interrupted after {beats['n']} batches; resumed streamed log "
          f"== uninterrupted streamed log == one-shot log "
          f"({len(rows['oneshot'])} rows); overlap="
          f"{full.stages['overlap']:.2f}")
    print("Success!")
    return 0


if __name__ == "__main__":
    sys.exit(main())
