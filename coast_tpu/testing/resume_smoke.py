"""Interrupt-and-resume smoke driver (unittest/cfg/fast.yml row).

The resume guarantee regression-checked every CI run: a campaign killed
after k collected batches and relaunched against its journal completes
with ``codes`` and ``counts`` bit-for-bit identical to the uninterrupted
run.  Runs on CPU in a few seconds; prints ``Success!`` for the harness
driver oracle (coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import os
import sys
import tempfile
from typing import List, Optional

import numpy as np


class _Kill(Exception):
    """SIGKILL stand-in: aborts the campaign from a progress beat, after
    the preceding batches' journal records are already fsync'd."""


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.models import mm

    runner = CampaignRunner(TMR(mm.make_region()), strategy_name="TMR")
    baseline = runner.run(120, seed=17, batch_size=40)

    with tempfile.TemporaryDirectory() as d:
        jpath = os.path.join(d, "smoke.journal")
        beats = {"n": 0}

        def kill_on_second(done, counts):
            beats["n"] += 1
            if beats["n"] >= 2:
                raise _Kill
        try:
            runner.run(120, seed=17, batch_size=40, journal=jpath,
                       progress=kill_on_second)
            print("campaign was not interrupted; smoke setup broken")
            return 1
        except _Kill:
            pass
        resumed = runner.run(120, seed=17, batch_size=40, journal=jpath)

    if not np.array_equal(resumed.codes, baseline.codes):
        print("resume parity FAILED: codes differ")
        return 1
    if resumed.counts != baseline.counts:
        print(f"resume parity FAILED: counts differ "
              f"({resumed.counts} vs {baseline.counts})")
        return 1
    print(f"interrupted after {beats['n']} batches, resumed to "
          f"{resumed.n} injections, codes bit-for-bit identical")
    print("Success!")
    return 0


if __name__ == "__main__":
    sys.exit(main())
