"""Campaign-profiler smoke driver (unittest/cfg/fast.yml row).

The device-time attribution layer's contract, regression-checked every
CI run on CPU in a few seconds:

  * a profiled campaign's attribution sums exactly: device_busy +
    host_gap + host_other == wall clock (the profile_mm.json
    acceptance identity), with one histogram observation per dispatch;
  * campaign OUTPUTS are byte-identical with the profiler on or off
    (codes, counts) -- the profiler only observes timing;
  * the ``python -m coast_tpu profile`` verb produces the attribution
    artifact (profile + mfu blocks per target) and exits 0;
  * the roofline accounting is sane: the protected program's analytic
    op count exceeds the unprotected region's (flops overhead > lanes
    is expected for bookkeeping-heavy toy kernels);
  * fleet trace federation merges a journaled campaign's span timeline
    with the queue's claim/complete events, exactly once per batch.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional

import numpy as np


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.models import mm

    region = mm.make_region()
    plain = CampaignRunner(TMR(region), strategy_name="TMR")
    profiled = CampaignRunner(TMR(region), strategy_name="TMR",
                              profile=True)

    a = plain.run(240, seed=17, batch_size=48)
    profiled.run(48, seed=1, batch_size=48)            # warm compile
    b = profiled.run(240, seed=17, batch_size=48)
    assert a.counts == b.counts, (a.counts, b.counts)
    assert np.array_equal(a.codes, b.codes), \
        "profiler changed campaign outputs"
    prof = b.profile
    assert prof is not None and prof["dispatches"] == 5, prof
    total = (prof["device_busy_s"] + prof["host_gap_s"]
             + prof["host_other_s"])
    assert abs(total - prof["wall_s"]) < 1e-3, (total, prof["wall_s"])
    hist = prof["device_seconds_histogram"]
    assert hist["count"] == prof["dispatches"], hist
    mfu = prof["mfu"]
    assert mfu["program_ops_per_run"] > mfu["useful_ops_per_run"], mfu
    assert mfu["flops_overhead"] > 2.0, mfu  # 3 lanes + bookkeeping
    print(f"# attribution: device {prof['device_busy_s']:.4f}s + gap "
          f"{prof['host_gap_s']:.4f}s + other {prof['host_other_s']:.4f}s"
          f" == wall {prof['wall_s']:.4f}s; overhead "
          f"{mfu['flops_overhead']}x")

    with tempfile.TemporaryDirectory() as tmp:
        # The CLI verb end-to-end: artifact with profile+mfu per target.
        from coast_tpu.obs.profile_cli import main as profile_main
        out = os.path.join(tmp, "profile.json")
        trace = os.path.join(tmp, "profile.trace.json")
        rc = profile_main(["--target", "matrixMultiply|-TMR",
                           "-t", "512", "--batch-size", "128",
                           "--out", out, "--trace-out", trace])
        assert rc == 0, rc
        with open(out) as fh:
            doc = json.load(fh)
        blk = doc["targets"]["matrixMultiply|-TMR"]
        assert blk["profile"]["dispatches"] == 4, blk["profile"]
        assert blk["mfu"]["flops_overhead"] > 2.0
        with open(trace) as fh:
            tdoc = json.load(fh)
        assert any(e.get("cat") == "device"
                   for e in tdoc["traceEvents"]), \
            "no device-track spans in the exported trace"

        # Fleet federation over a journaled campaign: every batch's
        # spans exactly once, queue claim/complete events present.
        from coast_tpu.fleet.queue import CampaignQueue, item_spec
        from coast_tpu.obs.federate import merge_traces
        q = CampaignQueue(os.path.join(tmp, "queue"))
        item_id = q.enqueue(item_spec("matrixMultiply", 240, seed=17,
                                      batch_size=48))
        item = q.claim("w0", lease_s=60.0)
        assert item is not None and item.id == item_id
        res = plain.run(240, seed=17, batch_size=48,
                        journal=q.journal_path(item_id))
        q.complete(item_id, "w0", {"benchmark": res.benchmark,
                                   "strategy": res.strategy,
                                   "counts": dict(res.counts),
                                   "worker": "w0"})
        doc = merge_traces(q)
        spans = [e for e in doc["traceEvents"]
                 if e.get("cat") == "journal"]
        los = sorted(e["args"]["lo"] for e in spans
                     if e["name"] == "dispatch")
        assert los == [0, 48, 96, 144, 192], los
        marks = {e["name"].split(" ", 1)[0]
                 for e in doc["traceEvents"] if e.get("cat") == "queue"}
        assert {"enqueue", "claim", "complete"} <= marks, marks
    print("Success!")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
