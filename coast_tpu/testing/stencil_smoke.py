"""Sharded halo-exchange stencil smoke driver (unittest/cfg/fast.yml row).

Regression-checks the cross-chip protected stencil every CI run, on CPU
in under a minute (prints ``Success!`` for the harness driver oracle):

  1. **2-shard campaign parity, both placements** -- a sharded sparse
     campaign over a 2-device mesh classifies bit-identically (codes AND
     counts) to the single-device runner at the same schedule, under
     vote-then-exchange (``compute``) and exchange-then-vote (``link``)
     voter placements, and the sharded summary carries the mesh ledger.
  2. **Link-model row** -- the measured containment duality: under
     vote-then-exchange every in-flight halo flip escapes as SDC (the
     collective is the blind spot), under exchange-then-vote the
     receiver's majority repairs every one of the same draws.
  3. **Walker-prediction spot check** -- the propagation walker's
     cross-``shard_map`` reach closure matches the measured truth:
     compute placement bounds each grid's influence to its own shard
     (``cross_shard`` false), link placement lets grid corruption cross
     (``cross_shard`` true); and a live campaign shows no SDC outside
     the statically sdc-possible sections.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import ProtectionConfig, protect
    from coast_tpu.analysis.propagation import (analyze_propagation,
                                                crossvalidate_counts)
    from coast_tpu.inject import classify as cls
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.schedule import FaultModel, generate
    from coast_tpu.models import resolve_region
    from coast_tpu.parallel.mesh import ShardedCampaignRunner, make_mesh

    mesh = make_mesh(2)
    n, seed = 96, 7
    link_sdc = {}
    for placement in ("compute", "link"):
        region = resolve_region("stencil", placement=placement)
        prog = protect(region, ProtectionConfig(num_clones=3))

        # 1. sharded-vs-single parity per fault model, sparse collect
        for model in (FaultModel.single(), FaultModel.link()):
            sh = ShardedCampaignRunner(prog, mesh, strategy_name="TMR",
                                       fault_model=model, collect="sparse")
            sched = generate(sh.mmap, n, seed, region.nominal_steps,
                             model=sh.fault_model)
            sres = sh.run_schedule(sched, batch_size=48)
            bres = CampaignRunner(prog, strategy_name="TMR",
                                  fault_model=model, collect="sparse"
                                  ).run_schedule(sched, batch_size=48)
            if not (np.array_equal(bres.codes, sres.codes)
                    and bres.counts == sres.counts):
                print(f"{placement}/{model.spec()}: sharded campaign "
                      f"diverges from single-device: {sres.counts} vs "
                      f"{bres.counts}")
                return 1
            mesh_block = sres.summary().get("mesh") or {}
            if (mesh_block.get("devices") != 2
                    or sum(mesh_block.get("per_shard_interesting", []))
                    != len(sres.interesting_rows)):
                print(f"{placement}/{model.spec()}: bad mesh ledger "
                      f"{mesh_block}")
                return 1
            if model.kind == "link":
                link_sdc[placement] = bres.counts["sdc"]

        # 3a. walker reach closure vs the placement's measured semantics
        vmap = analyze_propagation(prog)
        reach = vmap.shard_reach or {}
        grid_cross = {name: (reach.get(name) or {}).get("cross_shard")
                      for name in ("grid0", "grid1")}
        want_cross = placement == "link"
        if any(v != want_cross for v in grid_cross.values()):
            print(f"{placement}: walker grid reach {grid_cross} != "
                  f"cross_shard={want_cross}")
            return 1

        # 3b. live soundness: every SDC inside sdc-possible sections
        dense = CampaignRunner(prog, strategy_name="TMR")
        res = dense.run(n, seed=seed, batch_size=48)
        lids = np.asarray(res.schedule.leaf_id)
        section_counts = {}
        for sec in dense.mmap.sections:
            binc = np.bincount(res.codes[lids == sec.leaf_id],
                               minlength=cls.NUM_CLASSES)
            section_counts[sec.name] = {
                k: int(c) for k, c in zip(cls.CLASS_NAMES, binc) if c}
        violations = crossvalidate_counts(vmap, section_counts)
        if violations:
            print(f"{placement}: soundness violations: {violations}")
            return 1
        print(f"{placement}: 2-shard parity OK (single+link models), "
              f"walker cross_shard={want_cross} as measured, "
              "no SDC outside sdc-possible sections")

    # 2. the containment duality on the SAME link-model draw stream
    if not (link_sdc["compute"] > 0 and link_sdc["link"] == 0):
        print(f"link-model containment broken: vote-then-exchange "
              f"sdc={link_sdc['compute']} (want >0, the blind spot), "
              f"exchange-then-vote sdc={link_sdc['link']} (want 0)")
        return 1
    print(f"link fault model: vote-then-exchange leaks "
          f"{link_sdc['compute']}/{n} in-flight flips as SDC; "
          "exchange-then-vote repairs all of them")

    print("Success!")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
