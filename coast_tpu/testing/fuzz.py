"""Region fuzzing: the llvm-stress tier (unittest/llvm-stress.py:27-77).

The reference generates random IR modules with ``llvm-stress-7`` and checks
the protection passes survive compiling them to assembly (no run, no main).
The TPU analogue generates random *stepped regions* -- random uint32
dataflow over randomly-kinded state leaves with a loop-carried program
counter -- and holds a stronger oracle than "it compiled":

  1. every strategy (unprotected / TMR / DWC / TMR+CFCSS / segmented TMR)
     builds, jit-compiles and runs to completion (the compile-survival bar);
  2. protection does not change semantics: every strategy's output equals
     the unprotected output (the tier-1 golden rule applied to random
     programs);
  3. a single bit flip in one replica lane under TMR is voted away (the
     zero-to-aha property holds on arbitrary dataflow, not just the
     curated benchmarks).

Deterministic per seed, so any failure is replayable:
``python -m coast_tpu.testing.fuzz -seed 12345 -n 1``.
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List, Optional

import numpy as np

W = 8           # words per vector leaf (static shapes throughout)
MAX_OPS = 12    # random ops per step


def random_region(seed: int):
    """Build a random region from a seed.  Mirrors llvm-stress's role:
    random op mix over random operands, but shaped as a stepped region."""
    import jax.numpy as jnp

    from coast_tpu.ir.graph import BlockGraph
    from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                     LeafSpec, Region)

    rng = np.random.RandomState(seed)
    n_mem = rng.randint(1, 4)
    n_reg = rng.randint(1, 3)
    steps = int(rng.randint(8, 33))

    leaves: Dict[str, LeafSpec] = {"pc": LeafSpec(KIND_CTRL)}
    init_vals: Dict[str, np.ndarray] = {"pc": np.int32(0)}
    for i in range(n_mem):
        leaves[f"m{i}"] = LeafSpec(KIND_MEM)
        init_vals[f"m{i}"] = rng.randint(0, 2**32, W, np.uint32)
    for i in range(n_reg):
        leaves[f"r{i}"] = LeafSpec(KIND_REG)
        init_vals[f"r{i}"] = rng.randint(0, 2**32, W, np.uint32)
    leaves["ro"] = LeafSpec(KIND_RO)
    init_vals["ro"] = rng.randint(0, 2**32, W, np.uint32)

    data_leaves = [n for n in leaves if n != "pc"]
    writable = [n for n in data_leaves if n != "ro"]

    # A random straight-line op list, chosen once at build time (the
    # program is fixed; the *data* flows through it every step).
    ops: List[tuple] = []
    for _ in range(rng.randint(3, MAX_OPS + 1)):
        kind = rng.choice(["add", "sub", "mul", "xor", "and", "or",
                           "shl", "shr", "rot", "sel", "gather", "scatter"])
        dst = rng.choice(writable)
        srcs = [rng.choice(data_leaves) for _ in range(3)]
        k = int(rng.randint(0, 32))
        ops.append((kind, dst, srcs, k))

    def init():
        return {k: jnp.asarray(v) for k, v in init_vals.items()}

    def step(state, t):
        s = dict(state)
        for kind, dst, (a, b, c), k in ops:
            va, vb, vc = s[a], s[b], s[c]
            if kind == "add":
                out = va + vb
            elif kind == "sub":
                out = va - vb
            elif kind == "mul":
                out = va * vb
            elif kind == "xor":
                out = va ^ vb
            elif kind == "and":
                out = va & vb
            elif kind == "or":
                out = va | vb
            elif kind == "shl":
                out = va << np.uint32(k % 31 + 1)
            elif kind == "shr":
                out = va >> np.uint32(k % 31 + 1)
            elif kind == "rot":
                r = k % 31 + 1
                out = (va << np.uint32(r)) | (va >> np.uint32(32 - r))
            elif kind == "sel":
                out = jnp.where((va & 1) == 1, vb, vc)
            elif kind == "gather":
                idx = (jnp.arange(W) + s["pc"] + k) % W
                out = vb[idx]
            else:  # scatter
                slot = (s["pc"] + k) % W
                out = s[dst].at[slot].set(vb[k % W])
            s[dst] = out.astype(jnp.uint32)
        s["pc"] = state["pc"] + 1
        return s

    def done(state):
        return state["pc"] >= steps

    def check(state):
        # The fuzz oracle is cross-strategy output equality (held by the
        # driver), not an in-region golden value.
        return jnp.int32(0)

    def output(state):
        return jnp.concatenate(
            [state[n].reshape(-1) for n in sorted(data_leaves)]
            + [state["pc"].reshape(1).astype(jnp.uint32)])

    graph = BlockGraph(
        names=["entry", "body", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["pc"] >= steps,
                                     jnp.int32(2), jnp.int32(1)))

    return Region(
        name=f"fuzz{seed}",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=steps,
        max_steps=2 * steps,
        spec=leaves,
        default_xmr=True,
        graph=graph,
    )


def fuzz_one(seed: int) -> None:
    """Run the full oracle for one seed; raises AssertionError on any
    divergence."""
    import jax
    import jax.numpy as jnp

    from coast_tpu import DWC, TMR, unprotected

    region = random_region(seed)
    region.validate()

    golden = np.asarray(jax.device_get(
        jax.jit(unprotected(region).run)()["output"]))

    progs = {
        "TMR": TMR(region),
        "DWC": DWC(region),
        "TMR-s": TMR(region, segmented=True),
        "TMR+CFCSS": TMR(region, cfcss=True),
        "TMR-noMem": TMR(region, no_mem_replication=True),
    }
    for name, prog in progs.items():
        rec = jax.device_get(jax.jit(prog.run)())
        assert bool(rec["done"]), f"seed {seed}: {name} did not terminate"
        assert not bool(rec["dwc_fault"]), f"seed {seed}: {name} false DWC"
        assert not bool(rec["cfc_fault"]), f"seed {seed}: {name} false CFC"
        got = np.asarray(rec["output"])
        assert (got == golden).all(), (
            f"seed {seed}: {name} changed semantics "
            f"(first diff at {int(np.argmax(got != golden))})")

    # Single-lane flip under TMR must be voted away.
    _assert_flip_masked(progs["TMR"], region, golden,
                        np.random.RandomState(seed ^ 0x5EED), seed)


def _assert_flip_masked(prog, region, golden, rng, seed) -> None:
    """Random single-lane flip into a replicated leaf under TMR: the
    output must still equal the fault-free golden image."""
    import jax
    import jax.numpy as jnp

    repl = [n for n in prog.leaf_order if prog.replicated.get(n)]
    leaf = repl[rng.randint(len(repl))]
    words = int(np.prod(jax.eval_shape(region.init)[leaf].shape)) or 1
    fault = {"leaf_id": jnp.int32(prog.leaf_order.index(leaf)),
             "lane": jnp.int32(rng.randint(1, 3)),
             "word": jnp.int32(rng.randint(words)),
             "bit": jnp.int32(rng.randint(32)),
             "t": jnp.int32(rng.randint(region.nominal_steps))}
    rec = jax.device_get(jax.jit(prog.run)(fault))
    got = np.asarray(rec["output"])
    assert (got == golden).all(), (
        f"seed {seed}: TMR failed to mask a single-lane flip in {leaf}")


# ---------------------------------------------------------------------------
# Lifter fuzzing: random whole functions through lift_fn, and the random
# regions above re-derived by lift_step with NO hand-written spec.  The
# soundness bar: the lifted region's unprotected output equals jit(fn)'s,
# every strategy preserves it, and TMR still masks a single-lane flip --
# whatever leaf kinds the lifter inferred.
# ---------------------------------------------------------------------------

_FN_OPS = ("add", "xor", "mul", "or", "and", "shl", "shr", "sub")


def random_fn(seed: int):
    """A random jittable function with a lax.scan main loop: random uint32
    dataflow over loop carries (+ optional scanned inputs), random stacked
    outputs, and a post-loop epilogue.  Returns (fn, example_args)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed ^ 0x11F7E4)
    n_carry = int(rng.randint(1, 4))
    n_xs = int(rng.randint(0, 3))
    length = int(rng.randint(4, 25))
    n_ops = int(rng.randint(3, MAX_OPS))
    # Concrete op program, fixed at build time (deterministic per seed).
    prog = []
    n_vals = n_carry + n_xs
    for _ in range(n_ops):
        op = _FN_OPS[rng.randint(len(_FN_OPS))]
        a = int(rng.randint(n_vals))
        b = int(rng.randint(n_vals))
        sh = int(rng.randint(1, 31))
        prog.append((op, a, b, sh))
        n_vals += 1
    carry_picks = [int(rng.randint(n_vals)) for _ in range(n_carry)]
    y_pick = int(rng.randint(n_vals))

    def fn(*args):
        c0 = args[:n_carry]
        xs = args[n_carry:]

        def body(carry, x):
            vals = list(carry) + ([] if x is None else list(x))
            for op, a, b, sh in prog:
                va, vb = vals[a], vals[b]
                if op == "add":
                    vals.append(va + vb)
                elif op == "sub":
                    vals.append(va - vb)
                elif op == "xor":
                    vals.append(va ^ vb)
                elif op == "mul":
                    vals.append(va * vb)
                elif op == "or":
                    vals.append(va | vb)
                elif op == "and":
                    vals.append(va & vb)
                elif op == "shl":
                    vals.append(va << jnp.uint32(sh))
                else:
                    vals.append(va >> jnp.uint32(sh))
            new_carry = tuple(vals[i] for i in carry_picks)
            return new_carry, vals[y_pick]

        final, ys = jax.lax.scan(
            body, c0, tuple(xs) if xs else None,
            length=length if not xs else None)
        # Epilogue: fold the stacked outputs into the result.
        return tuple(f ^ jnp.uint32(0xA5A5A5A5) for f in final) + (ys[-1],)

    args = tuple(jnp.uint32(v)
                 for v in rng.randint(0, 2**32, n_carry, np.uint32))
    args += tuple(
        jnp.asarray(rng.randint(0, 2**32, length, np.uint32))
        for _ in range(n_xs))
    return fn, args


def fuzz_lifter_one(seed: int) -> None:
    """lift_fn + lift_step soundness for one seed."""
    import jax
    import jax.numpy as jnp

    from coast_tpu import DWC, TMR, unprotected
    from coast_tpu.frontend import lift_fn, lift_step

    # -- whole-function lifting --------------------------------------------
    fn, args = random_fn(seed)
    want = jax.device_get(jax.jit(fn)(*args))
    flat_want = np.concatenate([
        np.asarray(w).reshape(-1).view(np.uint32) for w in want])
    region = lift_fn(f"fuzzfn{seed}", fn, *args)
    got = np.asarray(jax.device_get(region.output(region.run_unprotected())))
    assert (got == flat_want).all(), (
        f"seed {seed}: lift_fn changed the function's result")

    for name, prog in (("TMR", TMR(region)), ("DWC", DWC(region))):
        rec = jax.device_get(jax.jit(prog.run)())
        assert int(rec["errors"]) == 0, f"seed {seed}: lift_fn {name} broke"
        assert bool(rec["done"])

    # -- step lifting with no hand-written spec ----------------------------
    hand = random_region(seed)
    lifted = lift_step(f"fuzzstep{seed}", hand.step, hand.init,
                       done=hand.done)
    golden = np.asarray(jax.device_get(
        jax.jit(unprotected(lifted).run)()["output"]))
    prog = TMR(lifted)
    rec = jax.device_get(jax.jit(prog.run)())
    assert int(rec["errors"]) == 0, f"seed {seed}: lift_step TMR broke"

    _assert_flip_masked(prog, lifted, golden,
                        np.random.RandomState(seed ^ 0x11F7), seed)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="random-region fuzzing")
    parser.add_argument("-n", type=int, default=10, help="number of seeds")
    parser.add_argument("-seed", type=int, default=0, help="first seed")
    parser.add_argument("-mode", choices=("region", "lifter", "all"),
                        default="all")
    args = parser.parse_args(argv)

    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The TPU site hook sets the platform programmatically; env var
        # alone is not enough (see tests/conftest.py).
        import jax
        jax.config.update("jax_platforms", "cpu")

    for seed in range(args.seed, args.seed + args.n):
        try:
            if args.mode in ("region", "all"):
                fuzz_one(seed)
            if args.mode in ("lifter", "all"):
                fuzz_lifter_one(seed)
        except AssertionError as e:
            print(f"FAILED: {e}")
            return 1
        print(f"seed {seed}: ok")
    print("Success!")
    return 0


if __name__ == "__main__":
    sys.exit(main())
