"""Reliability-SLO + flight-recorder smoke driver (fast.yml row).

The PR 16 observability contract, regression-checked every CI run on
CPU in a few seconds:

  * a live campaign with an attached SLO set lands verdicts in
    ``CampaignResult.slo`` / ``summary()["slo"]``, the hub snapshot,
    and the heartbeat/console status line;
  * the SLO engine's Wilson math is the one in ``obs/convergence``
    (same interval, same z) -- no second implementation to drift;
  * ``python -m coast_tpu slo check`` reproduces the live verdicts
    from the RECORDED run artifact and exits 1 on a seeded budget
    burn, 0 on an attained spec (the ``make ci_protection`` gate
    shape);
  * the flight recorder dumps a parseable forensic bundle on watchdog
    wedge (``CampaignWedgedError``) and on SIGUSR1, with all-thread
    stacks and the event ring; the disabled path records nothing and
    costs one attribute test;
  * ``json_parser`` renders the recorded ``slo`` block alongside
    convergence.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
from typing import List, Optional


def _check_live_slo(tmp: str) -> dict:
    """Live campaign with an SLO set: verdicts on every surface."""
    from coast_tpu import TMR
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.models import mm
    from coast_tpu.obs.slo import status_line

    region = mm.make_region()
    # Generous ceiling: the toy TMR campaign's SDC rate is far below
    # 90%, so the budget must read as attained/ok.
    runner = CampaignRunner(TMR(region), strategy_name="TMR",
                            slo="sdc_rate<=0.9;min=8")
    res = runner.run(240, seed=17, batch_size=48)
    assert res.slo is not None, "no slo block on the result"
    assert res.slo["verdict"] == "ok", res.slo
    row = res.slo["objectives"]["sdc_rate"]
    assert row["attained"] is True, row
    assert res.summary()["slo"]["verdict"] == "ok"

    # The hub carries the same report, and the live status fragment
    # reads ok.
    report = runner.metrics.slo_status()
    assert report is not None and report["verdict"] == "ok"
    assert status_line(report) == "slo ok"
    snap = runner.metrics.snapshot()
    assert snap["slo"]["verdict"] == "ok", snap.get("slo")

    # Wilson consistency: the engine's interval IS obs/convergence's.
    from coast_tpu.obs.convergence import wilson_interval
    live_row = next(r for r in report["objectives"]
                    if r["objective"] == "sdc_rate")
    lo, hi = wilson_interval(live_row["bad"], live_row["effective_n"],
                             1.96)
    assert abs(live_row["wilson"]["lo"] - lo) < 1e-12
    assert abs(live_row["wilson"]["hi"] - hi) < 1e-12

    # Heartbeat + console each carry one SLO status line.
    from coast_tpu.obs.console import Console
    from coast_tpu.obs.heartbeat import Heartbeat
    beats: List[str] = []
    hb = Heartbeat(240, interval_s=0.0, metrics=runner.metrics,
                   emit=beats.append)
    hb.update(240, res.counts)
    assert beats and "slo ok" in beats[0], beats
    panels: List[str] = []
    con = Console(240, interval_s=0.0, metrics=runner.metrics,
                  emit=panels.append)
    con.final(240, res.counts)
    assert "slo ok" in panels[-1], panels[-1]

    # Record the run artifact the CLI gate will replay.
    artifact = os.path.join(tmp, "run.json")
    with open(artifact, "w") as fh:
        # The campaign-log doc shape (summary head + runs) so both the
        # slo CLI and json_parser accept the same recorded artifact.
        json.dump({"summary": res.summary(), "runs": []}, fh)
    print(f"# live slo: {status_line(report)} "
          f"(observed sdc_rate {live_row['observed']:.4g})")
    return {"artifact": artifact, "counts": dict(res.counts),
            "n": res.n}


def _check_slo_gate(tmp: str, live: dict) -> None:
    """``python -m coast_tpu slo`` reproduces the pinned verdicts from
    the recorded artifact: generous spec passes, seeded burn exits 1."""
    from coast_tpu.__main__ import main as coast_main
    from coast_tpu.inject.classify import SDC_CLASSES

    artifact = live["artifact"]
    out = os.path.join(tmp, "slo_report.json")
    rc = coast_main(["slo", "check", "--spec", "sdc_rate<=0.9;min=8",
                     "--input", artifact, "--out", out])
    assert rc == 0, f"attained spec gated: rc={rc}"
    with open(out) as fh:
        doc = json.load(fh)
    assert doc["format"] == "coast-slo" and doc["verdict"] == "ok", doc

    # The recorded evidence must re-derive the live counts exactly.
    row = next(r for r in doc["objectives"]
               if r["objective"] == "sdc_rate")
    bad = sum(live["counts"].get(k, 0) for k in SDC_CLASSES)
    assert row["bad"] == bad and row["effective_n"] == live["n"], \
        (row, bad, live["n"])

    # Seeded budget burn: a ceiling below the observed rate must page
    # the gate (nonzero exit) -- unless the campaign truly saw zero
    # SDCs, in which case availability against an impossible floor
    # burns instead.
    burn_spec = ("sdc_rate<=0.000001;min=8" if bad
                 else "availability>=0.999999;z=0.1;min=8")
    if not bad:
        # With zero SDCs the sdc ceiling cannot burn; force a DUE-based
        # burn only if the campaign saw DUEs.  The mm-TMR seed 17
        # campaign reliably produces SDC+DUE outcomes, so reaching here
        # means the seed's distribution changed -- fail loudly.
        raise AssertionError(
            f"seed 17 campaign produced no SDCs: {live['counts']}")
    rc = coast_main(["slo", "check", "--spec", burn_spec,
                     "--input", artifact])
    assert rc == 1, f"burning budget passed the gate: rc={rc}"
    print(f"# slo gate: attained rc=0, seeded burn rc=1 ({bad} sdc)")


def _check_json_parser(live: dict) -> None:
    """The recorded slo block renders alongside convergence."""
    from coast_tpu.analysis.json_parser import summarize_path
    summary = summarize_path(live["artifact"])
    assert summary.slo is not None and summary.slo["verdict"] == "ok"
    text = summary.format()
    assert "--- slo ---" in text and "sdc_rate" in text, text


def _check_flightrec(tmp: str) -> None:
    """Forensic bundles: watchdog wedge, SIGUSR1, disabled path."""
    from coast_tpu.inject.resilience import (CampaignWedgedError,
                                             watchdog_collect)
    from coast_tpu.obs import flightrec

    dump_dir = os.path.join(tmp, "flightrec")
    with flightrec.activate(dump_dir=dump_dir, source="slo_smoke") as rec:
        rec.record("dispatch", lo=0, n=48)
        rec.record("retry", lo=0, attempt=1, kind="transient")

        # Watchdog wedge: the hung collect dumps a bundle BEFORE the
        # CampaignWedgedError propagates, stacks included.
        import threading
        hang = threading.Event()
        try:
            try:
                watchdog_collect(lambda: hang.wait(30.0), timeout=0.2)
                raise AssertionError("watchdog did not fire")
            except CampaignWedgedError:
                pass
        finally:
            hang.set()
        assert rec.dumps, "watchdog wedge wrote no bundle"
        doc = flightrec.read_bundle(rec.dumps[-1])
        assert doc["reason"] == "watchdog_wedge", doc["reason"]
        assert doc["extra"]["timeout_s"] == 0.2, doc["extra"]
        events = {e["event"] for e in doc["events"]}
        assert {"dispatch", "retry", "watchdog_fired"} <= events, events
        assert "coast-collect-watchdog" in doc["stacks"], \
            "hung collect thread missing from the stack dump"

        # SIGUSR1: the bench parent's "give me your blackbox" channel.
        n_before = len(rec.dumps)
        assert rec.install_signal_handler()
        os.kill(os.getpid(), signal.SIGUSR1)
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)
        assert len(rec.dumps) == n_before + 1, "SIGUSR1 wrote no bundle"
        doc = flightrec.read_bundle(rec.dumps[-1])
        assert doc["reason"].startswith("signal:"), doc["reason"]
        assert flightrec.newest_bundle(dump_dir) == rec.dumps[-1]

    # Disabled path: nothing installed -> the NULL recorder absorbs
    # both records and dumps without touching the filesystem.
    assert flightrec.current() is flightrec.NULL
    flightrec.record("orphan_event", x=1)
    assert flightrec.current().dump("nothing") is None
    assert not flightrec.NULL.events and not flightrec.NULL.dumps
    print(f"# flightrec: watchdog + SIGUSR1 bundles parse "
          f"({len(doc['events'])} ring events)")


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    with tempfile.TemporaryDirectory() as tmp:
        live = _check_live_slo(tmp)
        _check_slo_gate(tmp, live)
        _check_json_parser(live)
        _check_flightrec(tmp)
    print("Success!")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
