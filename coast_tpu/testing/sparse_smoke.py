"""Sparse-collect smoke driver (unittest/cfg/fast.yml row).

The device-resident campaign loop's contract, regression-checked every
CI run on CPU in a few seconds:

  * dense and sparse collection at the same seed produce IDENTICAL
    classification counts and the identical interesting-row set (rows
    whose class is outside success/corrected), with the on-device flip
    generation bit-parity-checked against the host schedule;
  * the measured host<->device transfer bytes shrink (the mode's whole
    point);
  * a journaled sparse campaign killed mid-run resumes bit-for-bit,
    and a dense runner refuses the sparse journal (collection mode is
    campaign identity);
  * a tiny interesting-row buffer capacity falls back to dense fetch
    for overflowing batches without changing any result.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

import numpy as np


class _Kill(Exception):
    """SIGKILL stand-in: aborts the campaign from a progress beat, after
    the preceding batches' journal records are already fsync'd."""


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.journal import JournalMismatchError
    from coast_tpu.models import mm

    region = mm.make_region()
    dense = CampaignRunner(TMR(region), strategy_name="TMR")
    sparse = CampaignRunner(TMR(region), strategy_name="TMR",
                            collect="sparse")

    a = dense.run(240, seed=17, batch_size=48)
    b = sparse.run(240, seed=17, batch_size=48)
    assert a.counts == b.counts, (a.counts, b.counts)
    interesting = np.flatnonzero(a.codes > 1)
    assert np.array_equal(interesting, b.interesting_rows), \
        "sparse interesting-row set diverged from dense"
    for col in ("codes", "errors", "corrected", "steps"):
        assert np.array_equal(getattr(a, col)[interesting],
                              getattr(b, col)), col
    dense_bytes = a.transfer["up"] + a.transfer["down"]
    sparse_bytes = b.transfer["up"] + b.transfer["down"]
    assert sparse_bytes < dense_bytes, (dense_bytes, sparse_bytes)
    print(f"# host bytes: dense {dense_bytes} -> sparse {sparse_bytes} "
          f"({dense_bytes / max(sparse_bytes, 1):.1f}x)")

    # Overflow fallback: a 2-row buffer cannot hold the interesting rows
    # of any batch here, so every batch takes the dense-fetch fallback --
    # and nothing about the result may change.
    tiny = CampaignRunner(TMR(region), collect="sparse",
                          sparse_capacity=2)
    c = tiny.run(240, seed=17, batch_size=48)
    assert c.counts == a.counts
    assert np.array_equal(c.interesting_rows, interesting)

    # Kill + resume, bit-for-bit; dense refuses the sparse journal.
    with tempfile.TemporaryDirectory() as d:
        jpath = os.path.join(d, "sparse.journal")
        beats = {"n": 0}

        def kill_on_second(done, counts):
            beats["n"] += 1
            if beats["n"] == 2:
                raise _Kill()

        try:
            CampaignRunner(TMR(region), collect="sparse").run(
                240, seed=17, batch_size=48, journal=jpath,
                progress=kill_on_second)
            raise AssertionError("kill hook never fired")
        except _Kill:
            pass
        resumed = CampaignRunner(TMR(region), collect="sparse").run(
            240, seed=17, batch_size=48, journal=jpath)
        assert resumed.counts == b.counts
        assert np.array_equal(resumed.interesting_rows,
                              b.interesting_rows)
        for col in ("codes", "errors", "corrected", "steps"):
            assert np.array_equal(getattr(resumed, col),
                                  getattr(b, col)), col
        try:
            CampaignRunner(TMR(region)).run(240, seed=17, batch_size=48,
                                            journal=jpath)
            raise AssertionError("dense resume of a sparse journal "
                                 "must refuse")
        except JournalMismatchError:
            pass

    print("Success!")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
