"""Differential fuzzing of the restricted-C frontend against gcc.

The hand-picked reference sources (mm.c, crc16.c, ...) pin the frontend
to four real programs; this tier pins its SEMANTICS broadly: a seeded
generator emits random programs inside the documented envelope
(frontend/c_lifter.py) -- 32-bit and narrow integer globals, for loops
over arrays, if/else, ternaries, compound assignment, helper-function
calls, pointer walks with ``*p++`` and ``while (length--)`` -- and each
program is

  1. compiled NATIVELY with gcc and executed (the ground-truth C
     implementation; the reference's own guests are gcc/llvm-compiled),
  2. ingested with ``lift_c`` and stepped to completion,

and every printf'd value must match bit-for-bit.  The generated
programs end by printing each written global's checksum plus every
scalar accumulator, so the whole observable state is compared, not just
a final value.

gcc flags pin the implementation-defined corners to the model's
semantics (which follow the reference's ARM targets): ``-fwrapv``
(signed wraparound mod 2^32 -- the 32-bit lane model) and
``-funsigned-char`` (plain char is unsigned on ARM AAPCS).

Deterministic per seed: ``python -m coast_tpu.testing.c_fuzz -seed 7``
replays a failure; the failing source is printed.
"""

from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys
import tempfile
from typing import List, Tuple

import numpy as np

_TYPES = [
    ("unsigned int", "uint32", False),
    ("int", "int32", False),
    ("uint8_t", "uint8", True),
    ("uint16_t", "uint16", True),
    ("short", "int16", True),
    ("int8_t", "int8", True),
]


class _Gen:
    def __init__(self, seed: int):
        self.r = random.Random(seed)
        self.arrays: List[Tuple[str, str, int]] = []   # (name, ctype, size)
        self.lines: List[str] = []
        self.printed = 0

    def _expr(self, depth, names):
        """Random integer expression over ``names`` (all promoted reads)."""
        r = self.r
        if depth <= 0 or r.random() < 0.3:
            if names and r.random() < 0.7:
                return r.choice(names)
            return str(r.randrange(0, 2**31 - 1)) + "u"
        a = self._expr(depth - 1, names)
        b = self._expr(depth - 1, names)
        op = r.choice(["+", "-", "*", "^", "&", "|", "<<", "?"])
        if op == "<<":
            # Shift only on an unsigned operand by a literal amount:
            # everything else is UB or sign-implementation territory.
            return f"((unsigned int)({a}) << {r.randrange(0, 8)})"
        if op == "?":
            c = self._expr(depth - 1, names)
            return f"(({a}) > ({b}) ? ({c}) : ({b}))"
        return f"(({a}) {op} ({b}))"

    def source(self) -> str:
        r = self.r
        g: List[str] = ["#include <stdio.h>",
                        "typedef unsigned char uint8_t;",
                        "typedef unsigned short uint16_t;",
                        "typedef unsigned int uint32_t;",
                        "typedef signed char int8_t;"]
        n_arrays = r.randrange(2, 4)
        for ai in range(n_arrays):
            ctype, _, _ = r.choice(_TYPES)
            size = r.randrange(4, 11)
            init = ", ".join(str(r.randrange(-100, 1000))
                             for _ in range(r.randrange(1, size + 1)))
            self.arrays.append((f"a{ai}", ctype, size))
            g.append(f"{ctype} a{ai}[{size}] = {{{init}}};")
        g.append("unsigned int acc0 = 0;")
        g.append("unsigned int acc1 = 1;")
        # A global array for pointer re-seating (local pointers may only
        # seat on globals; seating on a LOCAL array refuses by design).
        g.append("unsigned int rs[6] = {"
                 + ", ".join(str(r.randrange(1, 500))
                             for _ in range(6)) + "};")
        # Same-shaped partner for the union-pointer block (a pointer
        # seated on rs OR ua per traced branch).
        g.append("unsigned int ua[6] = {"
                 + ", ".join(str(r.randrange(1, 500))
                             for _ in range(6)) + "};")
        # Named 'b' ON PURPOSE: it collides with MIXM's second parameter,
        # so passing it as the FIRST argument pins simultaneous (non-
        # sequential) macro substitution.
        g.append(f"unsigned int b = {r.randrange(1, 10000)}u;")

        # A function-like macro used in expressions (simultaneous,
        # escape-safe substitution; the second parameter's name 'b'
        # deliberately collides with common argument text).
        mk = r.randrange(0, 6)
        g.append(f"#define MIXM(a, b) (((a) ^ ((unsigned int)(b) "
                 f"<< {mk})) + {r.randrange(1, 999)}u)")

        # A mix helper (exercises call inlining + promotions).
        k, c = r.randrange(0, 8), r.randrange(1, 99999)
        g.append(f"unsigned int mix(unsigned int a, unsigned int b) "
                 f"{{ return (a ^ ((unsigned int)(b) << {k})) + {c}u; }}")
        # A writer helper taking an array by reference and storing
        # through a walked pointer (deref stores + copy-in/out when the
        # caller passes a LOCAL array).
        g.append("void scale(unsigned int *p, uint8_t length, "
                 "unsigned int v) { while (length--) { "
                 "*p = (*p ^ v) + (unsigned int)sizeof(length); p++; } }")
        # Early-return helper over a walked pointer: the returning
        # iteration's tail (the mutation after the return point) must be
        # masked exactly as C does.
        g.append("unsigned int seek(unsigned int *p, uint8_t n, "
                 "unsigned int v) { uint8_t i; "
                 "for (i = 0; i < n; i++) { "
                 "if ((p[i] & 7u) == (v & 7u)) return v + (unsigned int)i; "
                 "p[i] = p[i] + 11u; } "
                 "return v ^ 21u; }")
        # A pointer-walk helper per array element type in use (exercises
        # *p++ / while (length--) / narrow deref promotion).
        walked_types = sorted({t for _, t, _ in self.arrays}
                              | {"unsigned int"})
        for t in walked_types:
            g.append(
                f"unsigned int walk_{t.replace(' ', '_')}"
                f"({t} *p, uint8_t length) {{ unsigned int s = 0; "
                f"while (length--) {{ s += (unsigned int)*p++; }} "
                f"return s; }}")

        body: List[str] = ["  int i;"]
        # A local array filled in a loop then passed BY REFERENCE to the
        # walker and the deref-store writer (copy-in/copy-out path).
        lsize = r.randrange(3, 8)
        body.append(f"  unsigned int lbuf[{lsize}] = "
                    f"{{{r.randrange(1, 50)}}};")
        body.append(f"  for (i = 0; i < {lsize}; i++) "
                    f"{{ lbuf[i] = lbuf[i] + (unsigned int)i * 3u; }}")
        body.append(f"  scale(lbuf, {r.randrange(1, lsize + 1)}, "
                    f"{r.randrange(1, 1000)}u);")
        body.append(f"  acc1 += walk_unsigned_int(lbuf, "
                    f"{r.randrange(1, lsize + 1)}) + "
                    f"(unsigned int)sizeof(lbuf) + (unsigned int)'A';")
        # Guaranteed macro-hazard exercise each seed: first argument is
        # the identifier 'b' (collides with the second parameter), the
        # second is a comma-bearing nested call into mix().
        body.append(f"  acc0 ^= MIXM(b, mix(acc1, "
                    f"{r.randrange(0, 99)}u));")
        # Early return through a walked pointer (data-dependent exit).
        body.append(f"  acc1 += seek(lbuf, {lsize}, acc0);")
        # Mid-loop conditional break with a data-dependent threshold and
        # work after the break point (both must be masked on the broken
        # iteration, incl. the i++).
        body.append(f"  for (i = 0; i < {lsize}; i++) {{ "
                    f"acc0 += lbuf[i]; "
                    f"if ((acc0 & {r.randrange(3, 31)}u) == 1u) break; "
                    f"acc1 ^= acc0 + (unsigned int)i; }}")
        body.append("  acc1 += (unsigned int)i;")
        for name, ctype, size in self.arrays:
            names = [f"{name}[i]", "(unsigned int)i", "acc0", "acc1"]
            stmts = []
            if r.random() < 0.8:
                stmts.append(f"{name}[i] = {self._expr(2, names)};")
            aop = r.choice(["+=", "^=", "|=", "&="])
            stmts.append(f"acc0 {aop} (unsigned int)({self._expr(1, names)});")
            if r.random() < 0.5:
                stmts.append(f"if (({name}[i] & 1) == 1) "
                             f"{{ acc1 += {self._expr(1, names)}; }} "
                             f"else {{ acc1 ^= acc0; }}")
            body.append(f"  for (i = 0; i < {size}; i++) {{ "
                        + " ".join(stmts) + " }")
            body.append(f"  acc1 += walk_{ctype.replace(' ', '_')}"
                        f"({name}, {r.randrange(1, size + 1)});")
            if r.random() < 0.5:
                body.append(f"  acc0 ^= MIXM(acc1, {r.randrange(0, 99)});")
        # switch dispatch in a loop: stacked labels, a default, and
        # break-terminated cases -- the desugared if-chain must match
        # C's dispatch exactly, including the evaluate-once control.
        mask = r.choice([3, 7])
        body.append(f"  for (i = 0; i < {lsize}; i++) {{ "
                    f"switch (lbuf[i] & {mask}u) {{ "
                    f"case 0: case 1: acc0 += {r.randrange(1, 99)}u; break; "
                    f"case 2: acc1 ^= acc0 + (unsigned int)i; break; "
                    f"case 3: acc0 ^= acc1 >> {r.randrange(1, 5)}; break; "
                    f"default: acc1 += 3u; break; }} }}")
        # do..while: body-first execution, side-effected counter.
        body.append(f"  {{ unsigned int dwc = {r.randrange(1, 6)}u; "
                    f"do {{ acc0 += dwc * 7u; dwc--; }} "
                    f"while (dwc != 0u); }}")
        # long long round trip: signed and unsigned 32x32->64 products
        # with both halves extracted (the limb-pair model vs gcc's
        # native 64-bit arithmetic).
        body.append(f"  {{ long long h; unsigned long long u; "
                    f"h = (long long)(int)acc0 * "
                    f"(long long)(int)(acc1 ^ {r.randrange(1, 999)}u); "
                    f"acc0 ^= (unsigned int)(h & 0x00000000ffffffffULL); "
                    f"acc1 += (unsigned int)(h >> 32); "
                    f"u = (unsigned long long)acc0 * "
                    f"(unsigned long long)b; "
                    f"acc0 += (unsigned int)(u >> 32); "
                    f"acc1 ^= (unsigned int)(u & 0xffffffffULL); }}")
        # Pointer re-seating on a global: seat, walk, re-seat, index.
        body.append(f"  {{ unsigned int *rp; rp = rs; "
                    f"acc0 += *rp++; rp = rp + {r.randrange(1, 3)}; "
                    f"acc1 ^= *rp; rp = rs; acc0 += rp[{r.randrange(0, 5)}]"
                    f" + rp[1]; *rp = acc0 & 1023u; }}")
        # Forward goto over live work (the CHStone adpcm/dfdiv shape):
        # the skipped statements must be masked exactly per the
        # data-dependent predicate, including a skipped array store.
        body.append(f"  if ((acc0 & {r.choice([3, 7, 15])}u) == "
                    f"{r.randrange(0, 3)}u) goto fskip; "
                    f"acc1 += {r.randrange(1, 999)}u; "
                    f"rs[{r.randrange(0, 6)}] ^= acc1; "
                    f"acc0 = acc0 * 5u + 1u; "
                    f"fskip: acc0 ^= {r.randrange(1, 99)}u;")
        # Union pointer: seated on DIFFERENT same-shaped globals per
        # traced branch (jpeg huffman-table shape); writes through the
        # branch-seated pointer must split back to the right member.
        body.append(f"  {{ unsigned int *up; int ui; "
                    f"for (ui = 0; ui < {lsize}; ui++) {{ "
                    f"if ((lbuf[ui] & {r.choice([1, 3])}u) == 0u) "
                    f"{{ up = rs; }} else {{ up = ua; }} "
                    f"up[ui % 6] = up[ui % 6] * 3u + (unsigned int)ui; }} }}")
        # 64-bit limb ARITHMETIC chain (not just one product): a long
        # long accumulator looped over an array with add/sub/shift and
        # a 64-bit comparison driving control flow -- the limb-pair
        # carry/borrow/shift model vs gcc's native 64-bit.
        body.append(f"  {{ long long s64; int li; s64 = 0; "
                    f"for (li = 0; li < {lsize}; li++) {{ "
                    f"s64 += (long long)(int)lbuf[li] * "
                    f"(long long)({r.randrange(3, 1000)} - (int)(li * 7)); "
                    f"s64 -= (long long)(int)acc0; }} "
                    # Shift through unsigned: s64 << k on a negative
                    # value is UB in ISO C; the round-trip is the
                    # defined spelling of the same bit pattern (and what
                    # the limb model computes).
                    f"s64 = (long long)((unsigned long long)s64 "
                    f"<< {r.randrange(1, 5)}); "
                    f"if (s64 > (long long){r.randrange(100, 100000)}) "
                    f"{{ acc0 ^= 77u; }} "
                    f"acc0 += (unsigned int)(s64 & 0xffffffffULL); "
                    f"acc1 ^= (unsigned int)((unsigned long long)s64 >> 32);"
                    f" }}")
        # Checksums: the whole written state becomes observable output
        # (rs/ua included -- the re-seating and union-pointer blocks
        # deref-store into them).
        self.arrays.append(("rs", "unsigned int", 6))
        self.arrays.append(("ua", "unsigned int", 6))
        for name, _, size in self.arrays:
            body.append(f"  {{ unsigned int chk = 0; "
                        f"for (i = 0; i < {size}; i++) "
                        f"{{ chk ^= (unsigned int){name}[i]; }} "
                        f'printf("%u\\n", chk); }}')
            self.printed += 1
        # lbuf's FULL checksum: scale()'s deref-store tail must be
        # observable even where the walk length is shorter.
        body.append(f"  {{ unsigned int lchk = 0; "
                    f"for (i = 0; i < {lsize}; i++) "
                    f"{{ lchk ^= lbuf[i]; }} "
                    f'printf("%u\\n", lchk); }}')
        body.append('  printf("%u\\n", acc0);')
        body.append('  printf("%u\\n", acc1);')
        self.printed += 3
        g.append("int main() {")
        if r.random() < 0.5:
            # Run-once loop idiom (sha256.c main): the body -- prints
            # included -- inlines into the enclosing scope.
            g.append("  while (1) {")
            g.extend(body)
            g.append("  break;")
            g.append("  }")
        else:
            g.extend(body)
        g.append("  return 0;")
        g.append("}")
        return "\n".join(g) + "\n"


def run_native(src_path: str, workdir: str) -> List[int]:
    exe = os.path.join(workdir, "native")
    subprocess.run(
        ["gcc", "-O1", "-fwrapv", "-funsigned-char", "-o", exe, src_path],
        check=True, capture_output=True)
    out = subprocess.run([exe], check=True, capture_output=True,
                         text=True, timeout=30)
    return [int(line) for line in out.stdout.split()]


def run_lifted(src_path: str, n_printed: int) -> List[int]:
    import jax.numpy as jnp

    from coast_tpu.frontend.c_lifter import lift_c

    region = lift_c("fuzz", [src_path])
    st = region.init()
    for t in range(region.max_steps):
        st = region.step(st, jnp.int32(t))
        if bool(region.done(st)):
            break
    out = np.asarray(region.output(st)).astype(np.uint32)
    return [int(v) for v in out[-n_printed:]]


def check_seed(seed: int, keep: bool = False) -> None:
    """Raises AssertionError (with the source) on any divergence."""
    gen = _Gen(seed)
    src = gen.source()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, f"fuzz_{seed}.c")
        with open(path, "w") as f:
            f.write(src)
        native = run_native(path, d)
        lifted = run_lifted(path, gen.printed)
    if native != lifted:
        raise AssertionError(
            f"seed {seed}: gcc {native} != lifted {lifted}\n--- source ---\n"
            + src)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-n", type=int, default=20)
    args = ap.parse_args(argv)
    import jax
    jax.config.update("jax_platforms", "cpu")
    for s in range(args.seed, args.seed + args.n):
        check_seed(s)
        print(f"seed {s}: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
