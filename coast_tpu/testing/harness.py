"""Flag-matrix regression harness: the unittest/unittest.py equivalent.

The reference's tier-1 functional tests build every benchmark with every
``OPT_PASSES`` combo for BOARD=x86 and regex-check its self-check output
(unittest/unittest.py:28-88; configs unittest/cfg/{fast,full,full_tmr}.yml).
Here the "build + run" of one combo is one in-process invocation of the opt
CLI (coast_tpu.opt) -- the jit compile is the build, the CPU backend is the
x86 board -- so a 17-combo matrix over the registry runs in one python
process instead of one subprocess per (combo, benchmark).

Config format is the reference's, unchanged:

    benchmarks:
      - path: matrixMultiply         # registry name, or a suite name
        re: "Number of errors: 0"    # optional stdout regex oracle
        passes: ["-TMR", "-DWC"]     # optional: OVERRIDES the global
                                     # OPT_PASSES column for this entry
                                     # (reduced combos for heavy
                                     # programs, e.g. CHStone jpeg)
    OPT_PASSES:
      - ""
      - "-DWC"
      - "-TMR -noMemReplication"

A combo string is split on whitespace and handed to coast_tpu.opt.main
verbatim.  Exit status must be 0 and the regex (if any) must match stdout,
else the harness stops with a nonzero exit, exactly like the reference's
error() (unittest.py:24-26).
"""

from __future__ import annotations

import argparse
import contextlib
import io
import re
import sys
from typing import Dict, List, Optional, Tuple

import yaml


class _Colors:
    HEADER = "\033[95m"
    OKBLUE = "\033[94m"
    FAIL = "\033[91m"
    ENDC = "\033[0m"


class HarnessError(Exception):
    pass


def expand_benchmarks(
        cfg: dict) -> List[Tuple[str, Optional[str], Optional[List[str]]]]:
    """Resolve cfg benchmark entries to (registry_name, regex,
    passes_override) rows; passes_override is None for benchmarks using
    the global OPT_PASSES column.

    ``path`` may name one region or a suite ('chstone' expands to the
    CHSTONE tuple; 'all' to the whole registry), the analogue of the
    directory-walk discovery of unittest.py:91-102.
    """
    from coast_tpu.models import CHSTONE, REGISTRY
    rows: List[Tuple[str, Optional[str]]] = []
    for entry in cfg["benchmarks"]:
        path = entry["path"]
        regex = entry.get("re")
        if path == "all":
            names = sorted(REGISTRY)
        elif path == "chstone":
            names = list(CHSTONE)
        elif path in REGISTRY:
            names = [path]
        elif path.endswith(".c"):
            # C source paths ('+'-joined for multi-TU programs) run
            # through the same ingestion path as `opt ... file.c` -- the
            # reference's harness likewise builds its tests from source.
            from coast_tpu.models import c_source_paths
            try:
                c_source_paths(path)
            except FileNotFoundError as e:
                raise HarnessError(
                    f"No benchmark source at {e.args[0]!r}") from e
            names = [path]
        else:
            raise HarnessError(f"No benchmarks found at {path!r}")
        passes = entry.get("passes")
        if passes is not None and (not isinstance(passes, list)
                                   or not passes):
            # An empty list would silently exclude the benchmark from
            # every run (skipped in the global matrix, zero own combos).
            raise HarnessError(
                f"'passes' for {path!r} must be a non-empty list of "
                "combo strings (omit it to use the global OPT_PASSES)")
        rows.extend((n, regex, passes) for n in names)
    return rows


def run_combo(bench: str, opt_passes: str) -> Tuple[int, str]:
    """One (benchmark, OPT_PASSES) cell: returns (exit_status, stdout)."""
    from coast_tpu.opt import main as opt_main
    argv = opt_passes.split() + [bench]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = opt_main(argv)
    return rc, buf.getvalue()


def run_config(cfg: dict, quiet: bool = False) -> int:
    """The unittest.py main loop: every combo x every benchmark.  Returns
    the number of cells run; raises HarnessError on the first failure.
    Benchmarks with a ``passes`` override run their own (reduced) combo
    column after the global matrix."""
    benches = expand_benchmarks(cfg)
    cells = 0

    def one_cell(bench, regex, opt_pass):
        rc, out = run_combo(bench, opt_pass)
        if rc != 0:
            print(out)
            raise HarnessError(
                f"Could not run {bench} with OPT_PASSES='{opt_pass}' "
                f"(exit {rc})")
        if regex is not None and not re.search(regex, out):
            print(out)
            raise HarnessError(
                f"Could not match stdout of {bench} using re "
                f"expression: {regex}")

    for opt_pass in cfg["OPT_PASSES"]:
        if not quiet:
            print(f"{_Colors.HEADER}OPT_PASSES: {opt_pass}{_Colors.ENDC}")
        for bench, regex, passes in benches:
            if passes is not None:
                continue                 # own column below
            if not quiet:
                print(f"  {_Colors.OKBLUE}{bench}{_Colors.ENDC}")
            one_cell(bench, regex, opt_pass)
            cells += 1
    for bench, regex, passes in benches:
        if passes is None:
            continue
        for opt_pass in passes:
            if not quiet:
                print(f"{_Colors.HEADER}OPT_PASSES: {opt_pass}"
                      f"{_Colors.ENDC}  {_Colors.OKBLUE}{bench}"
                      f"{_Colors.ENDC}")
            one_cell(bench, regex, opt_pass)
            cells += 1
    return cells


def run_drivers(cfg: dict, quiet: bool = False) -> int:
    """The pyDriver.py layer (unittest/pyDriver.py:1-88): run specialized
    drivers over pass combos; each must print 'Success!'.

        drivers:
          - module: fuzz          # coast_tpu.testing.<module>.main(argv)
            args: ["-n", "5"]
    """
    import importlib
    ran = 0
    for drv in cfg.get("drivers", ()):
        mod = importlib.import_module(f"coast_tpu.testing.{drv['module']}")
        argv = [str(a) for a in drv.get("args", ())]
        if not quiet:
            print(f"{_Colors.HEADER}driver: {drv['module']} "
                  f"{' '.join(argv)}{_Colors.ENDC}")
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = mod.main(argv)
        out = buf.getvalue()
        if rc != 0 or not re.search(r"Success!", out):
            print(out)
            raise HarnessError(f"driver {drv['module']} failed (exit {rc})")
        ran += 1
    return ran


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="yml-driven flag-matrix regression harness")
    parser.add_argument("config_yml")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The TPU environment's site hook sets the platform
        # programmatically, so the env var alone is not enough (see
        # tests/conftest.py); pin before the first backend init.
        import jax
        jax.config.update("jax_platforms", "cpu")

    try:
        with open(args.config_yml) as fh:
            cfg = yaml.safe_load(fh)
    except OSError:
        print(f"!!!! ERROR: Config file {args.config_yml} does not exist.")
        return 1
    except yaml.YAMLError as exc:
        print(f"!!!! ERROR: invalid YAML in {args.config_yml}: {exc}")
        return 1
    if not isinstance(cfg, dict):
        print(f"!!!! ERROR: Config file {args.config_yml} is empty or not "
              "a mapping.")
        return 1
    if "OPT_PASSES" in cfg and "benchmarks" not in cfg:
        print(f"!!!! ERROR: Config file {args.config_yml} has OPT_PASSES "
              "but no benchmarks section.")
        return 1

    try:
        cells = run_config(cfg, quiet=args.quiet) if "OPT_PASSES" in cfg else 0
        cells += run_drivers(cfg, quiet=args.quiet)
    except HarnessError as e:
        print(f"{_Colors.FAIL}!!!! ERROR: {e}{_Colors.ENDC}")
        return 1
    print(f"{cells} cells passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
