"""Protected-training smoke driver (unittest/cfg/fast.yml row).

Regression-checks the train subsystem's contract every CI run, on CPU in
well under a minute (prints ``Success!`` for the harness driver oracle,
coast_tpu.testing.harness.run_drivers):

  1. **FuzzyFlow differential parity** -- the fault-free training
     trajectory (final weights, bit-for-bit) is identical across
     unprotected / DWC / selective-xMR / full-TMR builds of
     ``train_mlp`` (arXiv:2306.16178's validation idiom: divergence
     under a campaign is attributable to the fault, never the
     transform).
  2. **Outcome buckets** -- a tiny seeded unprotected campaign
     populates BOTH silent-training-corruption classes
     (``train_self_heal`` and ``train_sdc``), with the raw ``sdc``
     bucket fully refined away.
  3. **Selective coverage** -- the selective-xMR campaign's commit
     votes repair (corrected > 0) and its persistent-SDC count sits
     strictly below the unprotected one.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import DWC, TMR, unprotected
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.ops.bitflip import noop_fault
    from coast_tpu.train import make_train_region, selective_xmr

    region = make_train_region("sgd")
    progs = {"unprotected": unprotected(region), "DWC": DWC(region),
             "selective-xMR": selective_xmr(region), "TMR": TMR(region)}

    # 1. fault-free trajectory parity, bit-for-bit
    outs = {}
    for name, prog in progs.items():
        rec = prog.run(noop_fault())
        if int(rec["errors"]) or not bool(rec["done"]) \
                or int(rec["train_probe"]):
            print(f"fault-free {name} run is not clean")
            return 1
        outs[name] = np.asarray(rec["output"])
    for name, out in outs.items():
        if not np.array_equal(out, outs["unprotected"]):
            print(f"fault-free trajectory parity FAILED for {name}")
            return 1
    print("fault-free trajectory bit-identical across all 4 strategies")

    # 2. both train outcome buckets populated
    unprot = CampaignRunner(progs["unprotected"],
                            strategy_name="unprotected").run(
        512, seed=11, batch_size=256)
    heals = unprot.counts["train_self_heal"]
    sdcs = unprot.counts["train_sdc"]
    if not (heals and sdcs):
        print(f"train bucket empty: self_heal={heals} train_sdc={sdcs}")
        return 1
    if unprot.counts["sdc"]:
        print(f"raw sdc not refined: {unprot.counts['sdc']}")
        return 1
    print(f"unprotected n=512: self_heal={heals} persistent_sdc={sdcs}")

    # 3. selective xMR: commit votes repair, persistent SDCs shrink
    selx = CampaignRunner(progs["selective-xMR"],
                          strategy_name="selective-xMR").run(
        512, seed=11, batch_size=256)
    if not selx.counts["corrected"]:
        print("selective-xMR campaign recorded no commit-vote repairs")
        return 1
    if selx.counts["train_sdc"] >= sdcs:
        print(f"selective-xMR did not reduce persistent SDCs "
              f"({selx.counts['train_sdc']} >= {sdcs})")
        return 1
    print(f"selective-xMR n=512: corrected={selx.counts['corrected']} "
          f"persistent_sdc={selx.counts['train_sdc']}")
    print("Success!")
    return 0


if __name__ == "__main__":
    import os
    import sys

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
