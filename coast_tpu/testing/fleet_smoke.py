"""Campaign-fleet smoke driver (unittest/cfg/fast.yml row).

The fleet guarantees regression-checked every CI run, on CPU:

  1. **Fleet drains a queue across worker processes**: 2 workers x 2
     tiny queued campaigns (same protection config, distinct seeds).
  2. **Kill/resume convergence**: one worker is SIGKILL'd mid-campaign;
     its item is requeued and a replacement worker resumes the claimed
     journal -- the fleet still converges, and the merged
     parity-checked result's per-item codes AND counts are
     bit-identical to the same campaigns run sequentially in one
     process.
  3. **Compile cache pays off**: the replacement's rebuild of the
     killed config is recorded as a cache hit (>=1 hit fleet-wide).
  4. **Live fleet telemetry**: the aggregate /metrics endpoint serves
     fleet-wide per-class rates over HTTP while workers are still
     running.

Prints ``Success!`` for the harness driver oracle
(coast_tpu.testing.harness.run_drivers).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request
from typing import List, Optional


def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode("utf-8")


def _spawn_worker(queue_root: str, worker_id: str) -> subprocess.Popen:
    import coast_tpu
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(coast_tpu.__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "coast_tpu.fleet", "worker",
         "--queue", queue_root, "--worker-id", worker_id,
         "--lease", "60"],
        env=env)


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu.fleet import (CampaignQueue, CompileCache,
                                 FleetTelemetry, codes_sha256, item_spec,
                                 merge_fleet)
    from coast_tpu.obs.serve import MetricsServer

    with tempfile.TemporaryDirectory() as d:
        q = CampaignQueue(os.path.join(d, "q"))
        # Throttled batches make "mid-campaign" a wide, deterministic
        # window: 300 rows / 50-row batches x 0.2 s.
        specs = [item_spec("matrixMultiply", 300, seed=3, batch_size=50,
                           throttle_s=0.2),
                 item_spec("matrixMultiply", 300, seed=4, batch_size=50,
                           throttle_s=0.2)]
        ids = [q.enqueue(spec) for spec in specs]

        server = MetricsServer(FleetTelemetry(q, stale_s=120.0), port=0)
        port = server.start()

        procs = {wid: _spawn_worker(q.root, wid) for wid in ("w0", "w1")}
        live_rates_seen = False
        victim_id = None
        victim_item = None
        deadline = time.time() + 240
        try:
            # Wait until some item's journal has collected batches but
            # is still far from its last (so the kill really lands
            # mid-campaign, not in a complete() race), probing the live
            # aggregate endpoint only until it has answered -- the HTTP
            # round-trip must not widen the selection-to-kill gap.
            while time.time() < deadline and victim_item is None:
                if not live_rates_seen:
                    prom = _get(f"http://127.0.0.1:{port}/metrics")
                    if "coast_fleet_class_rate" in prom \
                            and not q.drained():
                        live_rates_seen = True
                for rec in q.items("claimed"):
                    jpath = q.journal_path(str(rec["id"]))
                    if not os.path.exists(jpath):
                        continue
                    batches = sum(1 for line in open(jpath, "rb")
                                  if b'"kind":"batch"' in line)
                    if 1 <= batches <= 4:          # of 6: >=2 to go
                        victim_item = str(rec["id"])
                        victim_id = str(rec["worker"])
                        break
                time.sleep(0.05)
            if victim_item is None:
                print("no worker journaled a batch in time")
                return 1
            # SIGKILL the worker mid-campaign; requeue what it held and
            # start a replacement -- the fleet must converge anyway.
            victim = procs.pop(victim_id)
            victim.kill()
            victim.wait(timeout=30)
            requeued = q.requeue_worker(victim_id)
            size_at_kill = os.path.getsize(q.journal_path(victim_item))
            procs[f"{victim_id}r"] = _spawn_worker(q.root,
                                                   f"{victim_id}r")
            while time.time() < deadline and not q.drained():
                if not live_rates_seen:
                    prom = _get(f"http://127.0.0.1:{port}/metrics")
                    if "coast_fleet_class_rate" in prom \
                            and not q.drained():
                        live_rates_seen = True
                time.sleep(0.05)
            for proc in procs.values():
                proc.wait(timeout=60)
        finally:
            for proc in procs.values():
                if proc.poll() is None:
                    proc.terminate()
            server.stop()

        if not q.drained() or q.stats()["done"] != 2:
            print(f"fleet never converged: {q.stats()}")
            return 1
        if victim_item not in requeued:
            print(f"kill/requeue FAILED: {victim_item} not in {requeued}")
            return 1
        if os.path.getsize(q.journal_path(victim_item)) <= size_at_kill:
            print("resume FAILED: the killed item's journal never grew "
                  "(item was redone, not resumed?)")
            return 1
        if not live_rates_seen:
            print("live telemetry FAILED: /metrics never served fleet "
                  "per-class rates while workers ran")
            return 1

        result = merge_fleet(q)         # raises FleetParityError itself
        by_id = {item["id"]: item for item in result["items"]}
        if by_id[victim_item]["attempts"] != 2:
            print(f"expected 2 attempts on the killed item, got "
                  f"{by_id[victim_item]['attempts']}")
            return 1
        hits = result["cache"]["hits"]
        if hits < 1:
            print(f"compile cache FAILED: {result['cache']} (want >=1 "
                  "hit from the replacement worker's rebuild)")
            return 1

        # Merged-parity pin: fleet == the same campaigns sequentially
        # in ONE process (codes AND counts, per item and in total).
        ref_cache = CompileCache(os.path.join(d, "refcache"))
        ref_totals = {}
        for item_id, spec in zip(ids, specs):
            runner, _, _, _ = ref_cache.runner(spec)
            ref = runner.run(spec["n"], seed=spec["seed"],
                             batch_size=spec["batch_size"])
            if by_id[item_id]["codes_sha256"] != codes_sha256(ref.codes):
                print(f"parity FAILED: item {item_id} codes differ from "
                      "the sequential run")
                return 1
            if by_id[item_id]["counts"] != {k: int(v) for k, v
                                            in ref.counts.items()}:
                print(f"parity FAILED: item {item_id} counts "
                      f"{by_id[item_id]['counts']} != sequential "
                      f"{ref.counts}")
                return 1
            for k, v in ref.counts.items():
                ref_totals[k] = ref_totals.get(k, 0) + int(v)
        if result["totals"] != ref_totals:
            print(f"parity FAILED: merged totals {result['totals']} != "
                  f"sequential {ref_totals}")
            return 1

    print(f"fleet drained 2 campaigns over 2 workers with {victim_id} "
          f"SIGKILL'd mid-campaign and resumed by a replacement; merged "
          f"counts bit-identical to the sequential run; cache hits="
          f"{hits}; live /metrics served fleet rates")
    print("Success!")
    return 0


if __name__ == "__main__":
    sys.exit(main())
