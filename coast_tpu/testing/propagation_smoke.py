"""Static fault-propagation smoke driver (unittest/cfg/fast.yml row).

Regression-checks the propagation pass every CI run, on CPU in a few
seconds (prints ``Success!`` for the harness driver oracle,
coast_tpu.testing.harness.run_drivers):

  1. **Vulnerability-map verdicts** -- mm under TMR: the check oracle
     (``golden``) and the value-fed predicate word (``phase``) are
     ``sdc-possible`` with witness paths, every structurally-routed
     replicated leaf is ``detected-bounded``, and a tiny seeded campaign
     confirms the soundness direction live: no flip into a
     detected-bounded section classifies SDC.
  2. **Isolation prover** -- noninterference HOLDS on the clean TMR and
     DWC builds (with discharged voted-commit obligations), and the
     seeded voter bypass (lane 0 routed around every vote) is refuted
     with a non-empty counterexample path on both strategies.
  3. **Static budget** -- a delta campaign under ``--stop-when`` with
     ``static_budget=True`` re-injects the sdc-possible sections first
     and spends no MORE physical injections than the unseeded delta at
     the same stop condition.
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

import numpy as np


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import DWC, TMR
    from coast_tpu.analysis.propagation import (VERDICT_DETECTED,
                                                VERDICT_SDC,
                                                analyze_propagation,
                                                crossvalidate_counts,
                                                prove_isolation,
                                                seeded_voter_bypass)
    from coast_tpu.inject import classify as cls
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.models import crc16, mm
    from coast_tpu.obs.convergence import StopWhen

    # 1. vulnerability-map verdicts + live soundness spot-check
    prog = TMR(mm.make_region())
    vmap = analyze_propagation(prog)
    verdicts = vmap.section_verdicts()
    want_sdc = {"golden", "phase"}
    got_sdc = {n for n, v in verdicts.items() if v == VERDICT_SDC}
    if got_sdc != want_sdc:
        print(f"mm TMR sdc-possible set {sorted(got_sdc)} != "
              f"{sorted(want_sdc)}")
        return 1
    if any(v != VERDICT_DETECTED for n, v in verdicts.items()
           if n not in want_sdc):
        print(f"mm TMR non-sdc sections not detected-bounded: {verdicts}")
        return 1
    if not any(r.witness for r in vmap.rows["phase"]):
        print("sdc-possible verdict for 'phase' carries no witness path")
        return 1
    runner = CampaignRunner(prog, strategy_name="TMR")
    res = runner.run(1500, seed=23, batch_size=500)
    lids = np.asarray(res.schedule.leaf_id)
    section_counts = {}
    for sec in runner.mmap.sections:
        binc = np.bincount(res.codes[lids == sec.leaf_id],
                           minlength=cls.NUM_CLASSES)
        section_counts[sec.name] = {
            k: int(c) for k, c in zip(cls.CLASS_NAMES, binc) if c}
    violations = crossvalidate_counts(vmap, section_counts)
    if violations:
        print("soundness violations:", violations)
        return 1
    print(f"mm TMR map: {vmap.counts()} -- no detected-bounded section "
          "shows SDC in a live 1500-injection campaign")

    # 2. isolation prover: clean holds, seeded bypass refuted, both
    #    strategies
    for maker, strat in ((TMR, "TMR"), (DWC, "DWC")):
        proof = prove_isolation(maker(mm.make_region()), strategy=strat)
        if not proof.holds or proof.vacuous or not proof.voted_commits:
            print(f"clean {strat} isolation proof broken: "
                  f"{proof.format()}")
            return 1
        with seeded_voter_bypass():
            bad = maker(crc16.make_region())
            leak = prove_isolation(bad, strategy=strat)
        if leak.holds or not leak.leaks or not leak.leaks[0].path:
            print(f"seeded voter bypass NOT caught under {strat}")
            return 1
        print(f"{strat}: clean proof holds "
              f"({len(proof.voted_commits)} voted commits); seeded "
              f"bypass refuted with a {len(leak.leaks[0].path)}-step "
              "counterexample path")

    # 3. static-budget delta: sdc-possible first, no extra spend
    eq = CampaignRunner(prog, strategy_name="TMR", equiv=True)
    with tempfile.TemporaryDirectory() as d:
        base = eq.run(1500, seed=23, batch_size=500)
        jpath = os.path.join(d, "base.journal")
        eq.journal_result(base, jpath, n=1500, batch_size=500)
        # Rebuild-with-change stand-in: re-inject everything by planting
        # a fresh partition is overkill for a smoke; a no-op delta plus
        # verdict recording exercises the full allocator path.
        sw = StopWhen.parse("sdc:0.05;min=128")
        plain = eq.run_delta(1500, jpath, seed=23, batch_size=500,
                             stop_when=sw)
        seeded = eq.run_delta(1500, jpath, seed=23, batch_size=500,
                              stop_when=sw, static_budget=True)
        sb = (seeded.delta or {}).get("static_budget") or {}
        if sb.get("verdicts", {}).get("golden") != VERDICT_SDC:
            print(f"static_budget verdicts missing/wrong: {sb}")
            return 1
        if seeded.physical_n > plain.physical_n:
            print(f"static budget spent MORE physical injections "
                  f"({seeded.physical_n} > {plain.physical_n})")
            return 1
        print(f"static-budget delta: verdicts recorded, physical spend "
              f"{seeded.physical_n} <= plain {plain.physical_n}")

    print("Success!")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
