"""Fault-model smoke driver (unittest/cfg/fast.yml row).

Regression-checks the three fault-model guarantees every CI run, on CPU
in a few seconds (prints ``Success!`` for the harness driver oracle,
coast_tpu.testing.harness.run_drivers):

  1. **Legacy parity** -- a ``FaultModel.single`` campaign classifies
     bit-for-bit identically to the default (model-less) runner, and its
     log summary carries no fault-model key.
  2. **Expansion parity** -- the native ``coast_fault_expand`` and the
     numpy fallback produce identical flip-group streams for every
     model kind (skipped per-kind when the native core is unavailable;
     the numpy path is then the only path, so parity is vacuous).
  3. **Model identity** -- a journaled multi-site campaign interrupted
     after k batches resumes bit-for-bit, and resume under a DIFFERENT
     model is refused with the typed FaultModelMismatchError.
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

import numpy as np


class _Kill(Exception):
    """SIGKILL stand-in, raised from a progress beat after the preceding
    batches' journal records are already fsync'd."""


def main(argv: Optional[List[str]] = None) -> int:
    del argv
    from coast_tpu import TMR, native
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.journal import FaultModelMismatchError
    from coast_tpu.inject.schedule import FaultModel, generate
    from coast_tpu.models import mm

    region = mm.make_region()
    prog = TMR(region)

    # 1. legacy parity: explicit single == default, no summary key
    default = CampaignRunner(prog, strategy_name="TMR")
    single = CampaignRunner(prog, strategy_name="TMR",
                            fault_model=FaultModel.single())
    a = default.run(120, seed=17, batch_size=40)
    b = single.run(120, seed=17, batch_size=40)
    if not np.array_equal(a.codes, b.codes) or "fault_model" in b.summary():
        print("single-model parity FAILED")
        return 1
    print("single-model campaign identical to the legacy path")

    # 2. native/numpy expansion parity per kind
    models = [FaultModel.multibit(k=4), FaultModel.cluster(span=4, k=3),
              FaultModel.burst(window=8, rate=0.5)]
    mmap = default.mmap
    if native.native_available():
        base_sched = generate(mmap, 200, 17, region.nominal_steps)
        base = {k: getattr(base_sched, k)
                for k in ("leaf_id", "lane", "word", "bit", "t",
                          "section_idx")}
        tables = mmap.section_tables()
        for m in models:
            args = (17, m.kind, m.sites, m.span, m.window,
                    region.nominal_steps, base, tables)
            nat = native.fault_expand(*args)
            py = native.fault_expand(*args, force_python=True)
            if not all(np.array_equal(x, y) for x, y in zip(nat, py)):
                print(f"expansion parity FAILED for {m.spec()}")
                return 1
        print(f"native/numpy expansion parity over {len(models)} kinds")
    else:
        print("native core unavailable; numpy expansion is the only path")

    # 3. journaled multi-site resume + typed model-mismatch refusal
    model = FaultModel.cluster(span=4, k=3)
    runner = CampaignRunner(prog, strategy_name="TMR", fault_model=model)
    baseline = runner.run(120, seed=17, batch_size=40)
    with tempfile.TemporaryDirectory() as d:
        jpath = os.path.join(d, "fm.journal")
        beats = {"n": 0}

        def kill_on_second(done, counts):
            beats["n"] += 1
            if beats["n"] >= 2:
                raise _Kill
        try:
            runner.run(120, seed=17, batch_size=40, journal=jpath,
                       progress=kill_on_second)
            print("campaign was not interrupted; smoke setup broken")
            return 1
        except _Kill:
            pass
        resumed = runner.run(120, seed=17, batch_size=40, journal=jpath)
        if not np.array_equal(resumed.codes, baseline.codes):
            print("multi-site resume parity FAILED: codes differ")
            return 1
        try:
            CampaignRunner(prog, strategy_name="TMR",
                           fault_model=FaultModel.multibit(k=4)).run(
                120, seed=17, batch_size=40, journal=jpath)
            print("model mismatch was NOT refused")
            return 1
        except FaultModelMismatchError:
            pass
    print(f"{model.spec()} campaign interrupted after {beats['n']} "
          "batches, resumed bit-for-bit; mismatched model refused")
    print("Success!")
    return 0


if __name__ == "__main__":
    import sys

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
