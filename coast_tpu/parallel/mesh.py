"""Campaign scale-out over a device mesh: the distributed backend.

The reference's only scale-out axis is running multiple supervisor processes
side-by-side on disjoint localhost port ranges (supervisor.py:335, 386-391)
-- its "communication backend" is POSIX sockets between QEMU/GDB/python
(SURVEY.md §5).  None of that survives on TPU: replicas live inside one XLA
program, so the *campaign batch* is the distributed axis.  We shard it over
a ``jax.sharding.Mesh`` with ``shard_map``.

The sharded runner is a first-class campaign backend: spell it
``CampaignRunner(prog, mesh=make_mesh(8))`` and the whole campaign
surface -- seeded runs, journals, retry policies, streaming log writers,
the supervisor CLI's ``--mesh`` -- rides the sharded dispatch unchanged,
with classification counts identical to single-device at the same
seed/schedule.

Two result paths:
  * ``run`` / ``run_schedule``: per-run records come back (codes, E, F, T)
    -- one device_get of 4xB int32 per batch.
  * ``run_histogram``: only the per-class counts come back -- the histogram
    is one-hot-reduced on each shard and ``psum``'d over every mesh axis
    (ICI within a host, DCN across hosts), so the host transfer is 6 ints
    per batch regardless of campaign size.  This is the high-throughput
    campaign mode, replacing the reference's per-injection socket
    round-trips with one collective per batch.

The mesh may be any rank; the batch is sharded over the product of all axes
(``P(axis_names)``), so a 2D (host, chip) mesh works unchanged.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from coast_tpu.inject import classify as cls
from coast_tpu.inject.campaign import (CampaignResult, CampaignRunner,
                                       _sparse_device_outputs)
from coast_tpu.inject.schedule import generate
from coast_tpu.passes.dataflow_protection import ProtectedProgram

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("data",),
              shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Mesh over the first n devices.  Default 1D 'data'; pass shape +
    axis_names for multi-axis layouts (e.g. (hosts, chips))."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if shape is None:
        shape = (n,)
    devices = np.array(devs[:n]).reshape(shape)
    return Mesh(devices, axis_names=tuple(axis_names))


def _shard_mapped(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map with the varying-manual-axes check off: the campaign scan
    carry starts from unvarying init() constants and becomes axis-varying
    after the flip, which the VMA analysis rejects."""
    try:
        return shard_map(fn, mesh=mesh, check_vma=False,
                         in_specs=in_specs, out_specs=out_specs)
    except TypeError:  # pragma: no cover - older jax spelling
        return shard_map(fn, mesh=mesh, check_rep=False,
                         in_specs=in_specs, out_specs=out_specs)


_FAULT_KEYS = ("leaf_id", "lane", "word", "bit", "t")


class ShardedCampaignRunner(CampaignRunner):
    """CampaignRunner whose batch axis is sharded over a mesh.

    First-class campaign backend, reachable as ``CampaignRunner(prog,
    mesh=...)``: every CampaignRunner surface -- ``run`` /
    ``run_schedule`` / ``run_until_errors`` / journals / retry policies /
    streaming log writers -- works unchanged on top of the sharded
    dispatch, and classification is seed-stable: identical counts (and
    codes) to the single-device runner at the same schedule
    (tests/test_parallel.py, the multichip harness parity assert).
    """

    def __init__(self, prog: ProtectedProgram, mesh: Optional[Mesh] = None,
                 **kw):
        if not isinstance(mesh, Mesh):
            raise TypeError(
                f"ShardedCampaignRunner needs a jax.sharding.Mesh, got "
                f"{type(mesh).__name__}; build one with make_mesh(n)")
        super().__init__(prog, **kw)
        self.mesh = mesh
        # Geometry on the record: every campaign artifact's trace names
        # the mesh it ran on and the per-device batch rounding in force.
        self.telemetry.instant(
            "mesh_geometry",
            devices=int(np.prod(mesh.devices.shape)),
            axes={name: int(n) for name, n
                  in zip(mesh.axis_names, mesh.devices.shape)})
        axes = tuple(mesh.axis_names)
        batch_spec = P(axes)   # batch sharded over the product of all axes
        fault_specs = {k: batch_spec for k in _FAULT_KEYS}

        def records_fn(fault):
            return jax.vmap(self._run_one)(fault)

        self._records_sharded = jax.jit(_shard_mapped(
            records_fn, mesh,
            in_specs=(fault_specs,),
            out_specs={k: batch_spec for k in
                       ("code", "errors", "corrected", "steps")}))

        def hist_fn(fault, valid):
            out = jax.vmap(self._run_one)(fault)
            onehot = jax.nn.one_hot(out["code"], cls.NUM_CLASSES,
                                    dtype=jnp.int32)
            hist = jnp.sum(onehot * valid[:, None].astype(jnp.int32), axis=0)
            for ax in axes:
                hist = jax.lax.psum(hist, ax)
            return hist

        self._hist_sharded = jax.jit(_shard_mapped(
            hist_fn, mesh,
            in_specs=(fault_specs, batch_spec),
            out_specs=P()))

    @property
    def n_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    # -- per-shard interesting-row ledger ------------------------------------
    # The batch splits contiguously over the mesh (row r of a batch runs on
    # device r // per), so attributing each collected batch's interesting
    # rows back to physical shards is pure host arithmetic -- no extra
    # device traffic.  Journal-replayed batches are not re-attributed: the
    # ledger accounts for what *this* process ran.
    def _ledger_reset(self) -> None:
        self._shard_ledger = np.zeros(self.n_devices, np.int64)

    def _ledger_rows(self, rows: np.ndarray, per: int) -> None:
        ledger = getattr(self, "_shard_ledger", None)
        if ledger is None or not len(rows):
            return
        shard = np.minimum(rows // max(int(per), 1), self.n_devices - 1)
        np.add.at(ledger, shard, 1)

    def _ledger_dense(self, out: Dict[str, np.ndarray],
                      batch_size: int) -> None:
        rows = np.flatnonzero(np.asarray(out["code"]) > cls.CORRECTED)
        self._ledger_rows(rows.astype(np.int64),
                          max(1, batch_size // self.n_devices))

    def _mesh_block(self) -> Dict[str, object]:
        ledger = getattr(self, "_shard_ledger", None)
        if ledger is None:
            ledger = np.zeros(self.n_devices, np.int64)
        return {
            "devices": self.n_devices,
            "axes": {name: int(n) for name, n
                     in zip(self.mesh.axis_names, self.mesh.devices.shape)},
            "per_shard_interesting": [int(v) for v in ledger],
        }

    # -- hooks into the base batching loop ---------------------------------
    def _round_batch(self, batch_size: int) -> int:
        nd = self.n_devices
        rounded = max(nd, (batch_size // nd) * nd)
        if rounded != batch_size:
            # Device-count rounding is a geometry decision worth a mark:
            # the edge-padding it forces shows up in pad_waste_rows, and
            # this instant explains where the shape came from.
            self.telemetry.instant("batch_rounded", requested=batch_size,
                                   rounded=rounded, devices=nd)
        return rounded

    def _dispatch(self, fault: Dict[str, jax.Array]):
        return self._records_sharded(fault)

    # -- sparse (device-resident) collection, sharded -----------------------
    def _sparse_shards(self) -> int:
        return self.n_devices

    def _make_sparse_fn(self, batch_size: int, mode: str, cap: int,
                        gen):
        """Sharded sparse batch program: each shard regenerates (or
        slices) its contiguous block of the batch, classifies it, and
        compacts its own interesting rows into per-shard buffers; the
        class histogram is the one cross-shard collective (psum).  The
        host extraction is the base runner's -- per-shard buffer
        segments are exactly the [shards, ...] leading-axis layout it
        already consumes."""
        pack = self._sparse_pack()
        axes = tuple(self.mesh.axis_names)
        sizes = tuple(int(n) for n in self.mesh.devices.shape)
        nd = self.n_devices
        per = max(1, batch_size // nd)
        run_one = self._run_one
        batch_spec = P(axes)
        out_specs = {"hist": P(), "n_int": batch_spec,
                     "n_exact": batch_spec, "mask": batch_spec,
                     "packed": batch_spec, "exact": batch_spec,
                     "full": {k: batch_spec for k in
                              ("code", "errors", "corrected", "steps")}}

        def shard_base():
            idx = jnp.int32(0)
            for ax, size in zip(axes, sizes):
                idx = idx * size + jax.lax.axis_index(ax)
            return idx * per

        def finish(out, base, n_valid):
            pos = base + jnp.arange(per, dtype=jnp.int32)
            valid = pos < n_valid
            return out, valid

        def wrap(out, count_w, valid):
            o = _sparse_device_outputs(out, count_w, valid, cap, pack)
            hist = o["hist"]
            for ax in axes:
                hist = jax.lax.psum(hist, ax)
            wrapped = {k: v[None] for k, v in o.items() if k != "hist"}
            wrapped["hist"] = hist
            wrapped["full"] = out
            return wrapped

        if mode == "gen":
            def body(seed_hi, seed_lo, stream_n, offset, n_valid):
                base = shard_base()
                rows = (offset + base.astype(jnp.uint32)
                        + jnp.arange(per, dtype=jnp.uint32))
                fault = gen.columns((seed_hi, seed_lo), stream_n, rows)
                out = jax.vmap(run_one)(fault)
                out, valid = finish(out, base, n_valid)
                return wrap(out, valid.astype(jnp.int32), valid)

            fn = _shard_mapped(body, self.mesh,
                               in_specs=(P(), P(), P(), P(), P()),
                               out_specs=out_specs)
        else:
            def body(fault, count_w, n_valid):
                out = jax.vmap(run_one)(fault)
                out, valid = finish(out, shard_base(), n_valid)
                return wrap(out, count_w, valid)

            fn = _shard_mapped(
                body, self.mesh,
                in_specs=({k: batch_spec for k in _FAULT_KEYS},
                          batch_spec, P()),
                out_specs=out_specs)
        return jax.jit(fn)

    # -- counts-only campaign mode ------------------------------------------
    def run_histogram(self, n: int, seed: int = 0,
                      batch_size: int = 4096) -> Dict[str, int]:
        """Classification counts for n seeded injections; per-run records
        never leave the devices (padding masked out of the histogram)."""
        tel = self.telemetry
        with tel.activate():        # generate() records its schedule span
            sched = generate(self.mmap, n, seed,
                             self.prog.region.nominal_steps,
                             model=self.fault_model)
        # One-shot campaign drawn here: clamp the batch to the schedule so
        # a small n does not pay for padding rows (the clamp happens
        # before device rounding, which floors at one row per device).
        batch_size = self._round_batch(min(batch_size, len(sched)))
        total = np.zeros(cls.NUM_CLASSES, np.int64)
        for lo in range(0, len(sched), batch_size):
            with tel.span("pad", lo=lo):
                part = sched.slice(lo, min(lo + batch_size, len(sched)))
                fault, n_part = self._padded_fault(part, batch_size)
                valid = jnp.asarray(np.arange(batch_size) < n_part)
            if batch_size - n_part:
                tel.count("pad_waste_rows", batch_size - n_part)
            with tel.span("dispatch", n=n_part):
                pending = self._hist_sharded(fault, valid)
            # One collective per batch: the device_get of 6 ints is the
            # only blocking point, so device execution bills here.
            with tel.span("collect", n=n_part):
                total += np.asarray(jax.device_get(pending), np.int64)
        counts = cls.counts_dict(total, self._train)
        # Parity with run_schedule's counts: never-fired draws (t < 0; none
        # from generate(), which only emits in-footprint faults, but the
        # key must match) are their own bucket, not success.  On-device
        # such rows classify success, so the host-side re-bucketing is a
        # plain subtraction.
        n_invalid = int((np.asarray(sched.t) < 0).sum())
        counts["success"] -= n_invalid
        counts["cache_invalid"] = n_invalid
        return counts
