"""Multi-host (DCN) campaign scale-out.

The reference scales campaigns across machines by running supervisors
side-by-side on disjoint port ranges (supervisor.py:335,386-391) -- its
"distributed backend" is POSIX processes + localhost TCP (SURVEY.md §5).
The TPU-native equivalent is a multi-process JAX program: every host
calls :func:`init_multihost`, contributes its local chips to one global
``Mesh``, and the sharded campaign histogram (parallel/mesh.py) reduces
with ``psum`` -- XLA routes the collective over ICI within a slice and
DCN across hosts.  Each process sees the identical, fully-replicated
classification counts; per-run records never cross hosts.

On a real TPU pod slice ``jax.distributed.initialize()`` auto-detects
the topology; the explicit coordinator arguments exist for CPU rehearsal
(two localhost processes over Gloo stand in for the DCN boundary -- the
same rehearsal role QEMU plays for the reference's boards) and for
non-auto-provisioned clusters.

Worker CLI (one invocation per host/process)::

    python -m coast_tpu.parallel.multihost matrixMultiply \
        --coordinator HOST:PORT --num-processes 2 --process-id 0 \
        -e 4096 --seed 21

Every process prints the same global counts; exit code 0 on success.
"""

from __future__ import annotations

import os
import sys
from typing import Optional


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Join (or auto-detect) the multi-process JAX runtime.

    With no arguments this defers entirely to
    ``jax.distributed.initialize()`` auto-detection (TPU pods).  Passing
    the coordinator triple runs the explicit bootstrap used by the CPU
    rehearsal and by clusters without an auto-provisioner.
    """
    import jax

    if coordinator_address is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


def main(argv=None) -> int:
    import argparse

    from coast_tpu.models import REGISTRY

    ap = argparse.ArgumentParser(
        prog="coast_tpu.parallel.multihost",
        description="one worker of a multi-host sharded fault-injection "
                    "campaign; run once per host/process")
    ap.add_argument("benchmark", choices=sorted(REGISTRY))
    ap.add_argument("--coordinator", metavar="HOST:PORT",
                    help="coordinator address (omit on TPU pods: "
                         "auto-detected)")
    ap.add_argument("--num-processes", type=int)
    ap.add_argument("--process-id", type=int)
    ap.add_argument("--local-devices", type=int, default=0,
                    help="force N virtual CPU devices per process "
                         "(rehearsal mode; 0 = real devices)")
    ap.add_argument("-e", type=int, default=4096, metavar="N",
                    help="total injections across all hosts")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--strategy", default="TMR", choices=("TMR", "DWC"))
    args = ap.parse_args(argv)

    if args.local_devices:
        import re
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{args.local_devices}").strip()

    import jax

    if args.local_devices:
        # Rehearsal runs on the CPU backend regardless of the site hook's
        # platform selection (see opt.py:174-179).
        jax.config.update("jax_platforms", "cpu")
    init_multihost(args.coordinator, args.num_processes, args.process_id)

    from coast_tpu.parallel.mesh import ShardedCampaignRunner, make_mesh
    from coast_tpu.passes.strategies import DWC, TMR

    region = REGISTRY[args.benchmark]()
    prog = (TMR if args.strategy == "TMR" else DWC)(region)
    mesh = make_mesh(len(jax.devices()))
    runner = ShardedCampaignRunner(prog, mesh,
                                   strategy_name=args.strategy)
    counts = runner.run_histogram(args.e, seed=args.seed,
                                  batch_size=args.batch_size)
    # Every process holds the identical psum'd histogram; print with the
    # process id so a launcher can assert cross-host agreement.
    print(f"[proc {jax.process_index()}/{jax.process_count()}] "
          f"devices={len(jax.devices())} counts={counts}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
