"""``python -m coast_tpu.opt``: the ``opt -load DataflowProtection.so``
command-line surface, TPU-native.

Flag names are the reference's verbatim (dataflowProtection.cpp:14-47;
docs/source/passes.rst:30-140): single-dash long flags, ``-flag=v1,v2``
comma lists.  Instead of an LLVM module, the positional argument names a
benchmark region from the registry (the analogue of the .bc input), and the
protected program is *run*; stdout ends with the guest UART line

    C: 0 E: <errors> F: <corrected> T: <steps>

exactly as resources/decoder.py:66 parses it, so the reference's campaign
tooling conventions carry over.  Exit status = error count (the benchmark
main()'s return convention).

    python -m coast_tpu.opt -TMR -countErrors matrixMultiply
    python -m coast_tpu.opt -DWC -s -ignoreGlbls=golden matrixMultiply
    python -m coast_tpu.opt -TMR -CFCSS -dumpModule sha256
    python -m coast_tpu.opt -TMR -inject=results:1:0:20:5 matrixMultiply

``-dumpModule`` prints the jaxpr of the protected step -- the analogue of
dumping the transformed LLVM module (utils.cpp:909-929);
``-dumpModule=hlo`` prints the *optimized* HLO instead (the module the
redundancy-survival lint pass analyzes).  ``-inject`` is the
forced-injection debug hook (--forceBreak, injector.py:59-68).

Every protected build runs the replication-integrity linter's static
rules first (analysis/lint; the ``verifyCloningSuccess`` analogue) and
refuses to run on an error finding; ``-noCloneOpsCheck`` bypasses the
gate and ``-lintOut=<path>`` writes the JSON findings next to whatever
``-dumpModule`` dumps.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

_BOOL_FLAGS = {
    "TMR", "DWC", "EDDI", "CFCSS",
    "noMemReplication", "noLoadSync", "noStoreDataSync", "noStoreAddrSync",
    "storeDataSync", "countErrors", "reportErrors", "countSyncs",
    "i", "s", "verbose", "noMain", "noCloneOpsCheck",
    "protectStack", "pallasVoters", "noPallasVoters",
    "fuseStep", "noFuseStep",
    # Utility passes (SURVEY.md §2.1 #6-#8), stackable with any strategy:
    # -DebugStatements (block trace), -SmallProfile (+ -noPrint), -ExitMarker.
    "DebugStatements", "SmallProfile", "noPrint", "ExitMarker",
}
_LIST_FLAGS = {
    "ignoreFns", "ignoreGlbls", "skipLibCalls", "replicateFnCalls",
    "isrFunctions", "cloneFns", "cloneGlbls", "cloneReturn",
    "cloneAfterCall", "protectedLibFn", "runtimeInitGlobals",
    "fnPrintList",  # -DebugStatements block-name filter
}
# List flags that feed the scope config (ScopeConfig.merge_cl); fnPrintList
# is instrumentation-only.
_SCOPE_LIST_FLAGS = _LIST_FLAGS - {"fnPrintList"}
_STR_FLAGS = {"configFile", "inject", "printFnName", "lintOut", "propOut"}
# Flags accepted bare (-dumpModule, today's jaxpr behavior) or with a
# value (-dumpModule=jaxpr|hlo).
_OPT_VALUE_FLAGS = {"dumpModule"}


class UsageError(Exception):
    pass


def parse_argv(argv: List[str]) -> Tuple[Dict[str, object], List[str]]:
    flags: Dict[str, object] = {}
    positional: List[str] = []
    for arg in argv:
        if not arg.startswith("-"):
            positional.append(arg)
            continue
        name, sep, value = arg[1:].partition("=")
        if name in _OPT_VALUE_FLAGS:
            flags[name] = value if sep else True
        elif name in _BOOL_FLAGS:
            if sep:
                raise UsageError(f"flag -{name} takes no value")
            flags[name] = True
        elif name in _LIST_FLAGS:
            if not sep:
                raise UsageError(f"flag -{name} needs =name,name,...")
            flags.setdefault(name, [])
            flags[name].extend(v for v in value.split(",") if v)  # type: ignore
        elif name in _STR_FLAGS:
            if not sep:
                raise UsageError(f"flag -{name} needs =value")
            flags[name] = value
        else:
            raise UsageError(f"unknown flag -{name}")
    return flags, positional


def _parse_inject(spec: str, prog) -> Dict[str, object]:
    import jax.numpy as jnp
    parts = spec.split(":")
    if len(parts) != 5:
        raise UsageError("-inject=leaf:lane:word:bit:t")
    leaf, lane, word, bit, t = parts
    if leaf not in prog.leaf_order:
        raise UsageError(f"-inject: no injectable leaf '{leaf}' "
                         f"(have: {', '.join(prog.leaf_order)})")
    lane, word, bit, t = int(lane), int(word), int(bit), int(t)
    # Range-check against the leaf's geometry: the flipper clamps indices
    # (a clamped flip would land somewhere the user never named) and a
    # bit >= 32 shifts to a silent no-op.
    rows = {name: (lanes, words)
            for name, _, lanes, words in prog.injectable_sections()}
    lanes, words = rows[leaf]
    if not 0 <= lane < lanes:
        raise UsageError(f"-inject: lane {lane} out of range for '{leaf}' "
                         f"(has {lanes} lane(s))")
    if not 0 <= word < words:
        raise UsageError(f"-inject: word {word} out of range for '{leaf}' "
                         f"(has {words} word(s) per lane)")
    if not 0 <= bit < 32:
        raise UsageError(f"-inject: bit {bit} out of range (32-bit words)")
    if t < 0:
        raise UsageError(f"-inject: step {t} must be >= 0")
    return {"leaf_id": jnp.int32(prog.leaf_order.index(leaf)),
            "lane": jnp.int32(lane), "word": jnp.int32(word),
            "bit": jnp.int32(bit), "t": jnp.int32(t)}


def build_overrides(flags: Dict[str, object]) -> Dict[str, object]:
    """Parsed flags -> ProtectionConfig overrides (incl. the scope lists
    from config file + CL merging).  Shared by the opt CLI and the
    campaign supervisor so the flag semantics cannot drift."""
    from coast_tpu.interface.config import parse_config_file
    scope = parse_config_file(flags.get("configFile"),
                              required="configFile" in flags)
    scope.merge_cl({k: v for k, v in flags.items()
                    if k in _SCOPE_LIST_FLAGS})
    overrides = dict(scope.protection_overrides())
    overrides["no_mem_replication"] = bool(flags.get("noMemReplication"))
    overrides["no_store_data_sync"] = bool(flags.get("noStoreDataSync"))
    overrides["no_load_sync"] = bool(flags.get("noLoadSync"))
    overrides["no_store_addr_sync"] = bool(flags.get("noStoreAddrSync"))
    overrides["count_errors"] = bool(flags.get("countErrors"))
    overrides["count_syncs"] = bool(flags.get("countSyncs"))
    overrides["segmented"] = bool(flags.get("s"))
    overrides["cfcss"] = bool(flags.get("CFCSS"))
    overrides["protect_stack"] = bool(flags.get("protectStack"))
    # Only force the Pallas voters when a flag is present; absence keeps
    # the config's auto default (on when the backend is the TPU).
    # -noPallasVoters makes the jnp-voter baseline reachable from the CLI
    # on TPU (bisecting a suspected kernel miscompare needs it).
    if flags.get("pallasVoters") and flags.get("noPallasVoters"):
        raise UsageError(
            "-pallasVoters and -noPallasVoters are mutually exclusive")
    if flags.get("pallasVoters"):
        overrides["pallas_voters"] = True
    elif flags.get("noPallasVoters"):
        overrides["pallas_voters"] = False
    # Fused protected step: default off (the unfused interpreter loop is
    # the reference program); -noFuseStep exists so schedules that set
    # fuse_step by config can be bisected back to the baseline.
    if flags.get("fuseStep") and flags.get("noFuseStep"):
        raise UsageError(
            "-fuseStep and -noFuseStep are mutually exclusive")
    if flags.get("fuseStep"):
        overrides["fuse_step"] = True
    elif flags.get("noFuseStep"):
        overrides["fuse_step"] = False
    return overrides


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        flags, positional = parse_argv(argv)
    except UsageError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2

    from coast_tpu.models import REGISTRY
    is_c_source = len(positional) == 1 and positional[0].endswith(".c")
    if is_c_source:
        from coast_tpu.models import c_source_paths
        try:
            c_source_paths(positional[0])
        except FileNotFoundError as e:
            print(f"ERROR: file {e.args[0]} does not exist",
                  file=sys.stderr)
            return 2
    if not is_c_source and (len(positional) != 1
                            or positional[0] not in REGISTRY):
        print("usage: python -m coast_tpu.opt [-TMR|-DWC|-EDDI] [flags] "
              "<benchmark | program.c>\n"
              f"benchmarks: {', '.join(sorted(REGISTRY))}\n"
              "or a C source file (restricted subset; docs/lifter.md)",
              file=sys.stderr)
        return 2
    bench = positional[0]

    strategies = [s for s in ("TMR", "DWC", "EDDI") if flags.get(s)]
    if len(strategies) > 1:
        print(f"ERROR: choose one of -TMR/-DWC/-EDDI, got {strategies}",
              file=sys.stderr)
        return 2
    if flags.get("i") and flags.get("s"):
        # The reference errors when both scheduling flags are given
        # (processCommandLine, interface.cpp:244-362).
        print("ERROR: -i and -s are mutually exclusive", file=sys.stderr)
        return 2

    from coast_tpu.interface.config import ConfigError
    try:
        overrides = build_overrides(flags)
    except UsageError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    except ConfigError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The axon site hook registers its PJRT plugin and *programmatically*
        # selects jax_platforms="axon,cpu" at interpreter start, overriding
        # the env var; honor the user's CPU request explicitly (the 'x86
        # board' path of the test harness; see testing/harness.py:145-150).
        jax.config.update("jax_platforms", "cpu")

    from coast_tpu import DWC, EDDI, TMR, unprotected
    from coast_tpu.passes.verification import SoRViolation

    # The reference's opt consumes a program file, not a name
    # (clang-emitted IR; here registry names or the restricted-C
    # frontend): opt -TMR mm.c protects the program the file defines.
    from coast_tpu.frontend import LiftError
    from coast_tpu.models import resolve_region
    try:
        region = resolve_region(bench)
    except LiftError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1

    strategy = strategies[0] if strategies else None
    try:
        if strategy == "TMR":
            prog = TMR(region, **overrides)
        elif strategy == "DWC":
            prog = DWC(region, **overrides)
        elif strategy == "EDDI":
            EDDI(region)           # raises: deprecated, switch to DWC
            return 1
        else:
            prog = unprotected(region, **{
                k: v for k, v in overrides.items()
                if k not in ("ignore_globals", "xmr_globals")})
    except SoRViolation as e:
        print(str(e), file=sys.stderr)
        return 1
    except NotImplementedError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1

    if flags.get("verbose"):
        for name in sorted(region.spec):
            print(f"# leaf {name}: kind={region.spec[name].kind} "
                  f"replicated={prog.replicated[name]}", file=sys.stderr)

    # Replication-integrity check (verifyCloningSuccess analogue): the
    # static lane-provenance/coverage rules AND the lane-isolation
    # noninterference prover (analysis/propagation) run on every
    # protected build and refuse to run the program on an error, exactly
    # as the reference refuses to emit; -noCloneOpsCheck disables the
    # gate (its reference meaning), -lintOut=<path> writes the JSON
    # findings either way.  The heavier post-XLA survival pass stays
    # with the lint CLI / campaign pre-flight (python -m
    # coast_tpu.analysis.lint).
    step_jaxpr = None          # shared: lint trace doubles as the dump
    if "lintOut" in flags or "propOut" in flags \
            or (strategy in ("TMR", "DWC")
                and not flags.get("noCloneOpsCheck")):
        from coast_tpu.analysis import lint as lint_mod
        from coast_tpu.analysis.propagation import analyze_step
        step_jaxpr = lint_mod.trace_step(prog)
        # ONE shared walk feeds the gate's isolation prover and (when
        # requested) the vulnerability map -- witness paths only when
        # the map will report them.
        step_facts = analyze_step(prog, closed=step_jaxpr,
                                  track_paths="propOut" in flags)
        lint_report = lint_mod.lint_program(
            prog, survival=False, strategy=strategy or "unprotected",
            closed=step_jaxpr, propagation=True, facts=step_facts)
        if "lintOut" in flags:
            # Honored for every build (an unprotected report is trivially
            # clean, but the requested file must exist).
            lint_report.write_json(flags["lintOut"])    # type: ignore
        if "propOut" in flags:
            # The full static fault-propagation artifact: the
            # per-section x bit-class vulnerability map (one compiled
            # fault-free run bounds the live flip window) plus the
            # isolation proof.  Honored for every build, like -lintOut.
            import json as _json
            from coast_tpu.analysis.propagation import (
                analyze_propagation, prove_isolation)
            vmap = analyze_propagation(prog, facts=step_facts)
            proof = prove_isolation(prog, facts=step_facts,
                                    strategy=strategy or "unprotected")
            with open(flags["propOut"], "w") as fh:   # type: ignore
                _json.dump({"vulnerability_map": vmap.summary(),
                            "isolation": proof.summary()},
                           fh, indent=1, sort_keys=True)
                fh.write("\n")
        if (strategy in ("TMR", "DWC")
                and not flags.get("noCloneOpsCheck")
                and not lint_report.ok):
            print(lint_report.format(include_notes=False), file=sys.stderr)
            print("ERROR: replication-integrity check failed; rerun with "
                  "-noCloneOpsCheck to bypass", file=sys.stderr)
            return 1

    if "dumpModule" in flags:
        dump = flags["dumpModule"]
        import jax.numpy as jnp
        pstate, fl = jax.eval_shape(prog.init_pstate)
        if dump is True or dump == "jaxpr":
            if step_jaxpr is None:
                step_jaxpr = jax.make_jaxpr(prog.step)(pstate, fl,
                                                       jnp.int32(0))
            print(step_jaxpr)
        elif dump == "hlo":
            # The optimized HLO the redundancy-survival pass analyzes
            # (analysis/lint/survival.py) -- the transformed module as
            # the compiler will actually run it.
            print(jax.jit(prog.step)
                  .lower(pstate, fl, jax.ShapeDtypeStruct((), jnp.int32))
                  .compile().as_text())
        else:
            print(f"ERROR: -dumpModule={dump}: format must be jaxpr or "
                  "hlo", file=sys.stderr)
            return 2

    fault = None
    if "inject" in flags:
        try:
            fault = _parse_inject(flags["inject"], prog)   # type: ignore
        except (UsageError, ValueError) as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 2

    if "printFnName" in flags:
        # Accepted for CLI compatibility (smallProfile.cpp:26); the TPU
        # target always prints host-side, there is no guest print symbol to
        # redirect.
        print("WARNING: -printFnName has no effect on the TPU target "
              "(profile stats print host-side)", file=sys.stderr)
    want_trace = bool(flags.get("DebugStatements")
                      or (flags.get("SmallProfile")
                          and not flags.get("noPrint")))
    want_state = bool(flags.get("ExitMarker"))
    runner = lambda f: prog.run(f, trace=want_trace, return_state=want_state)
    if fault is None:
        # Armed-but-inert, not a zero-argument program: campaigns always
        # run fault-armed, and a fully-constant run lets XLA fold/fuse
        # the step differently -- on the training regions' f32 optimizer
        # arithmetic that drifts an ulp from the armed program and fails
        # the golden bit-exact check (ops.bitflip.noop_fault's rationale,
        # applied to correctness rather than timing).
        from coast_tpu.ops.bitflip import noop_fault
        fault = noop_fault()
    rec = jax.jit(runner)(fault)

    if want_trace or want_state:
        from coast_tpu.passes import instrument
        if flags.get("DebugStatements"):
            for line in instrument.format_trace(
                    prog, rec, tuple(flags.get("fnPrintList", ()))):
                print(line)
        if flags.get("SmallProfile") and not flags.get("noPrint"):
            # PRINT_PROFILE_STATS before main returns
            # (insertProfilePrintFunction, smallProfile.cpp:184-253).
            for line in instrument.format_profile_stats(
                    instrument.profile_counts(prog, rec)):
                print(line)
        if want_state:
            digest = instrument.state_digest(rec["final_state"])
            print("EXIT_MARKER: " + " ".join(
                f"{k}={v:#010x}" for k, v in digest.items()))

    errors = int(rec["errors"])
    if bool(rec.get("stack_fault", False)):
        # The FreeRTOS stack-overflow hook line the decoder recognises
        # (decoder.py:69): the kernel's canary/watermark check tripped.
        print("HALT: stack overflow in task <kernel>", file=sys.stderr)
        return 134
    if bool(rec.get("assert_fault", False)):
        # configASSERT class (decoder.py:67): assert() calls abort().
        print("ASSERT FAILED: kernel invariant", file=sys.stderr)
        return 134
    if bool(rec["dwc_fault"]):
        # FAULT_DETECTED_DWC -> abort(): no UART success line is printed
        # (decoder.py classifies the absence as abort/DUE).
        print("FAULT_DETECTED_DWC: abort()", file=sys.stderr)
        return 134                       # SIGABRT convention
    if bool(rec["cfc_fault"]):
        print("FAULT_DETECTED_CFC: abort()", file=sys.stderr)
        return 134
    if not bool(rec["done"]):
        print("TIMEOUT: watchdog expired", file=sys.stderr)
        return 124                       # timeout(1) convention
    if flags.get("countSyncs"):
        print(f"__SYNC_COUNT: {int(rec['sync_count'])}")
    print(f"C: 0 E: {errors} F: {int(rec['corrected'])} "
          f"T: {int(rec['steps'])}")
    # Clamp below the 124/134 sentinels (and the mod-256 wrap): a large
    # error count must stay distinguishable from timeout/abort/success.
    return min(errors, 100)


if __name__ == "__main__":
    sys.exit(main())
