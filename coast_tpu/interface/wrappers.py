"""Signature-rewrite features as JAX function transforms.

The reference implements these by rewriting function signatures in IR --
adding cloned arguments (cloneFunctionArguments, cloning.cpp:493-1113),
out-pointer returns (.RR functions, :1128-1225), COAST_WRAPPER renames
(utils.cpp:716-830).  On TPU the same contracts become function
*transforms* over jittable callables: the lane axis is explicit, and the
caller picks the boundary semantics.

  protected_lib      -- "replicate body, keep signature"
                        (__xMR_PROT_LIB, cloning.cpp:562-564): single-copy
                        in/out; internally N lanes + vote; miscompare info
                        is returned so the caller can latch DWC faults.
  replicated_return  -- ".RR" (cloneFunctionReturnVals :1128-1225): the
                        caller passes per-lane arguments and receives
                        per-lane returns, no boundary sync.
  clone_after_call   -- (cloning.cpp:1700-1768, e.g. scanf): call ONCE on
                        the single-copy arguments, then fan the result out
                        to N lanes -- for functions that must not or cannot
                        be replicated.
  no_xmr_arg         -- __NO_xMR_ARG(n) (interface.cpp noXmrArgList):
                        listed argument positions stay single-copy
                        (shared across lanes) in replicated_return.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from coast_tpu.ops import voters


def protected_lib(fn: Callable, num_clones: int = 3,
                  static_argnums: Sequence[int] = ()) -> Callable:
    """Wrap ``fn(*args) -> pytree``: unreplicated signature, replicated
    body, boundary vote.  Returns ``(voted_out, miscompare)`` where
    miscompare is a scalar bool (any lane disagreed) -- the caller's DWC
    error-block hook / TMR correction count source.

    The redundancy is over *replicated argument copies*: each array
    argument is broadcast to N lane copies and the body is vmapped over the
    lane axis, so every lane computes from its own independently
    corruptible data (exactly how cloned globals occupy distinct addresses
    in the reference).  A fault model must flip bits in a lane's argument
    copy (or in per-lane intermediate state) for lanes to diverge --
    vmapping a closure over ignored lane indices would let XLA compute the
    body once and broadcast, yielding zero redundancy (the de-duplication
    hazard of SURVEY.md §7).

    ``static_argnums`` names positions that stay concrete Python values
    (axis numbers, shape parameters): they are passed through unreplicated
    and untraced, like non-pointer immediate arguments the reference leaves
    unchanged when it rewrites the signature."""
    if num_clones < 2:
        raise ValueError("protected_lib needs num_clones >= 2")
    static_set = frozenset(static_argnums)

    def wrapper(*args):
        dyn = [a for i, a in enumerate(args) if i not in static_set]
        laned = jax.tree.map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x), (num_clones,) + jnp.shape(x)), tuple(dyn))

        def one_lane(lane_args):
            it = iter(lane_args)
            full = [args[i] if i in static_set else next(it)
                    for i in range(len(args))]
            return fn(*full)

        lanes = jax.vmap(one_lane)(laned)
        flat, tree = jax.tree.flatten(lanes)
        mis = jnp.bool_(False)
        voted = []
        for leaf in flat:
            v, m = voters.vote(leaf, num_clones)
            voted.append(v)
            mis = jnp.logical_or(mis, m)
        return jax.tree.unflatten(tree, voted), mis

    wrapper.__name__ = f"{getattr(fn, '__name__', 'fn')}_COAST_WRAPPER"
    return wrapper


def replicated_return(fn: Callable, num_clones: int = 3,
                      no_xmr_args: Sequence[int] = ()) -> Callable:
    """Wrap ``fn`` as its .RR form: arguments carry a leading lane axis
    (except positions in ``no_xmr_args``, shared single-copy), and the
    return is per-lane with no sync -- divergence is the caller's to
    resolve at its own sync points."""

    def wrapper(*args):
        in_axes = tuple(None if i in no_xmr_args else 0
                        for i in range(len(args)))
        for i, a in enumerate(args):
            if i in no_xmr_args:
                continue
            shapes = [jnp.shape(x) for x in jax.tree.leaves(a)]
            bad = [s for s in shapes if len(s) == 0 or s[0] != num_clones]
            if bad:
                raise ValueError(
                    f"{wrapper.__name__}: argument {i} has leaf shape(s) "
                    f"{bad} without a leading lane axis of "
                    f"{num_clones} replicas")
        return jax.vmap(fn, in_axes=in_axes)(*args)

    wrapper.__name__ = f"{getattr(fn, '__name__', 'fn')}.RR"
    return wrapper


def no_xmr_arg(*argnums: int):
    """Annotation helper: ``replicated_return(fn, n, no_xmr_args=...)``
    sugar matching the __NO_xMR_ARG(n) macro shape."""
    def apply(fn: Callable, num_clones: int = 3) -> Callable:
        return replicated_return(fn, num_clones, no_xmr_args=argnums)
    return apply


def clone_after_call(fn: Callable, num_clones: int = 3) -> Callable:
    """Wrap ``fn``: call once on single-copy args, broadcast the result to
    a leading lane axis so each replica owns an (initially identical,
    independently corruptible) copy."""

    def wrapper(*args):
        out = fn(*args)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(
                x, (num_clones,) + jnp.shape(x)), out)

    wrapper.__name__ = (
        f"{getattr(fn, '__name__', 'fn')}_CLONE_AFTER_CALL_1_2")
    return wrapper


# ---------------------------------------------------------------------------
# In-lane scope wrappers: the same boundary contracts, applied *inside* the
# engine's vmapped lane trace.
#
# The transforms above take explicit lane axes and are used at the region
# boundary.  When a region's step calls named sub-functions
# (Region.functions), the engine runs the step under ``vmap(...,
# axis_name=LANE_AXIS)`` and rewraps each function per its scope class
# using cross-lane collectives over the named lane axis: ``all_gather``
# reconstructs the replica set inside a single lane's trace, so the
# call-boundary vote (processCallSync, synchronization.cpp:563-738) can
# run exactly at the call site.  Miscompare results are appended to the
# FnNamespace log and latched by the engine (DWC abort / TMR_ERROR_CNT).
# ---------------------------------------------------------------------------

LANE_AXIS = "lane"


def _gather_args(args):
    return jax.tree.map(
        lambda x: jax.lax.all_gather(jnp.asarray(x), LANE_AXIS), tuple(args))


def _vote_tree(tree, num_clones, log, fn_name: str = "-"):
    flat, treedef = jax.tree.flatten(tree)
    voted = []
    for leaf in flat:
        # Classified for the replication-integrity linter: one
        # call-boundary vote per crossing argument/return leaf
        # (processCallSync, synchronization.cpp:563-738).
        leaf = voters.sync_tag(leaf, "call_boundary", fn_name)
        v, m = voters.vote(leaf, num_clones)
        log.append(m)
        voted.append(v)
    return jax.tree.unflatten(treedef, voted)


def lane_ignored(fn: Callable, num_clones: int, log,
                 name: str = None) -> Callable:
    """-ignoreFns: the function is *outside* the sphere of replication --
    one logical call with synchronized arguments.  Every crossing argument
    is voted across lanes (the forced call-boundary sync of
    verification.cpp:587,676), the body runs once on the voted copies, and
    the single result re-enters every lane identically."""
    fname = name or getattr(fn, "__name__", "fn")

    def wrapper(*args):
        voted = _vote_tree(_gather_args(args), num_clones, log, fname)
        return fn(*voted)

    wrapper.__name__ = f"{fname}_IGNORED"
    return wrapper


def _call_on_lane0(fn: Callable, spof_name: str) -> Callable:
    """Single unsynced call on lane 0's arguments (shared by -skipLibCalls
    and -cloneAfterCall, whose mechanics coincide under the lane axis).
    The lane-0 read is tagged ``coast:spof:<fn>`` so the linter's SPOF
    report can match it against the accepted allowlist instead of
    flagging an unexplained single point of failure."""
    from jax.ad_checkpoint import checkpoint_name

    def wrapper(*args):
        gathered = _gather_args(args)
        lane0 = jax.tree.map(
            lambda g: checkpoint_name(g, voters.TAG_SPOF + spof_name)[0],
            gathered)
        return fn(*lane0)

    return wrapper


def lane_skip_lib(fn: Callable, num_clones: int,
                  name: str = None) -> Callable:
    """-skipLibCalls: single call, *no* argument sync -- lane 0's arguments
    are used verbatim (the reference simply does not clone or sync the
    call, interface.cpp:82-100).  A fault in lane 0's arguments therefore
    corrupts every replica: the single point of failure the flag
    deliberately accepts for cheap library calls."""
    fname = name or getattr(fn, "__name__", "fn")
    wrapper = _call_on_lane0(fn, fname)
    wrapper.__name__ = f"{fname}_SKIPLIB"
    return wrapper


def lane_protected_lib(fn: Callable, num_clones: int, log,
                       name: str = None) -> Callable:
    """-protectedLibFn (__xMR_PROT_LIB): replicated body behind a
    single-copy signature (cloning.cpp:562-564).  Arguments are voted in,
    the body runs per lane, and the return is voted out -- both boundary
    syncs are logged."""
    fname = name or getattr(fn, "__name__", "fn")

    def wrapper(*args):
        voted_in = _vote_tree(_gather_args(args), num_clones, log, fname)
        out = fn(*voted_in)
        (gathered_out,) = _gather_args((out,))
        return _vote_tree(gathered_out, num_clones, log, fname)

    wrapper.__name__ = f"{fname}_COAST_WRAPPER"
    return wrapper


def lane_clone_after_call(fn: Callable, num_clones: int,
                          name: str = None) -> Callable:
    """-cloneAfterCall: call once on lane 0's (single-copy) arguments and
    fan the result out -- each lane receives an identical copy that then
    lives and corrupts independently (cloning.cpp:1700-1768, the scanf
    pattern).  Under the lane axis the returned value is already per-lane;
    the fan-out is the identity."""
    fname = name or getattr(fn, "__name__", "fn")
    wrapper = _call_on_lane0(fn, fname)
    wrapper.__name__ = f"{fname}_CLONE_AFTER_CALL_1_2"
    return wrapper
