"""Scope-configuration ingestion: config file + command-line lists.

File format is byte-compatible with the reference's functions.config
(parsed at interface.cpp:172-241): ``key = name, name, ...`` lines, ``#``
comments, blank lines skipped, all whitespace stripped, unknown keys are a
hard error.  Default location: ``$COAST_TPU_ROOT/functions.config`` falling
back to ``./functions.config`` (the reference uses ``$COAST_ROOT/...``).

Merging follows getFunctionsFromCL (interface.cpp:82-164): command-line
lists are appended after the config file's, and the clone lists remove
matching names from the ignore lists ("pretty much reverse priority").
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

# The six keys the reference config parser accepts (interface.cpp:211-228).
FILE_KEYS = ("skipLibCalls", "ignoreFns", "replicateFnCalls", "ignoreGlbls",
             "runtimeInitGlobals", "isrFunctions")


class ConfigError(Exception):
    """Unknown option / unreadable file (the reference prints and returns
    nonzero, failing the pass; interface.cpp:187-191, 224-228)."""


@dataclasses.dataclass
class ScopeConfig:
    """All scope lists, mirroring the reference's internal editable lists
    (interface.cpp:40-61)."""

    skip_lib_calls: List[str] = dataclasses.field(default_factory=list)
    ignore_fns: List[str] = dataclasses.field(default_factory=list)
    replicate_fn_calls: List[str] = dataclasses.field(default_factory=list)
    ignore_glbls: List[str] = dataclasses.field(default_factory=list)
    runtime_init_globals: List[str] = dataclasses.field(default_factory=list)
    isr_functions: List[str] = dataclasses.field(default_factory=list)
    clone_fns: List[str] = dataclasses.field(default_factory=list)
    clone_glbls: List[str] = dataclasses.field(default_factory=list)
    clone_return: List[str] = dataclasses.field(default_factory=list)
    clone_after_call: List[str] = dataclasses.field(default_factory=list)
    protected_lib_fns: List[str] = dataclasses.field(default_factory=list)

    _FIELD_OF_KEY = {
        "skipLibCalls": "skip_lib_calls",
        "ignoreFns": "ignore_fns",
        "replicateFnCalls": "replicate_fn_calls",
        "ignoreGlbls": "ignore_glbls",
        "runtimeInitGlobals": "runtime_init_globals",
        "isrFunctions": "isr_functions",
        "cloneFns": "clone_fns",
        "cloneGlbls": "clone_glbls",
        "cloneReturn": "clone_return",
        "cloneAfterCall": "clone_after_call",
        "protectedLibFn": "protected_lib_fns",
    }

    def merge_cl(self, cl_lists: Dict[str, List[str]]) -> None:
        """Append command-line lists with the reference's override rules:
        cloneFns removes from ignoreFns, cloneGlbls from ignoreGlbls,
        replicateFnCalls from skipLibCalls, and cloneAfterCall implies
        skipLibCalls+ignoreFns (interface.cpp:88-164)."""
        for key, values in cl_lists.items():
            field = self._FIELD_OF_KEY.get(key)
            if field is None:
                raise ConfigError(f"unrecognized option '{key}'")
            getattr(self, field).extend(values)
        for x in cl_lists.get("replicateFnCalls", ()):
            while x in self.skip_lib_calls:
                self.skip_lib_calls.remove(x)
        for x in cl_lists.get("cloneFns", ()):
            while x in self.ignore_fns:
                self.ignore_fns.remove(x)
        for x in cl_lists.get("cloneGlbls", ()):
            while x in self.ignore_glbls:
                self.ignore_glbls.remove(x)
        for x in cl_lists.get("cloneAfterCall", ()):
            self.skip_lib_calls.append(x)
            self.ignore_fns.append(x)

    def protection_overrides(self) -> Dict[str, Tuple[str, ...]]:
        """The engine-facing knobs: every scope list, forwarded to
        ProtectionConfig.  Function-scope lists rewrap the region's named
        sub-functions per class (dataflow_protection.fn_scope_of); names
        that don't exist and flags with no tpu semantics are hard errors
        in verify_options, never silently inert."""
        u = lambda xs: tuple(dict.fromkeys(xs))  # noqa: E731 - dedupe, keep order
        return {
            "ignore_globals": u(self.ignore_glbls),
            "xmr_globals": u(self.clone_glbls),
            "ignore_fns": u(self.ignore_fns),
            "skip_lib_calls": u(self.skip_lib_calls),
            "replicate_fn_calls": u(self.replicate_fn_calls),
            "clone_fns": u(self.clone_fns),
            "clone_return_fns": u(self.clone_return),
            "clone_after_call_fns": u(self.clone_after_call),
            "protected_lib_fns": u(self.protected_lib_fns),
            "isr_functions": u(self.isr_functions),
            "runtime_init_globals": u(self.runtime_init_globals),
        }


def default_config_path() -> str:
    root = os.environ.get("COAST_TPU_ROOT")
    if root:
        return os.path.join(root, "functions.config")
    return "functions.config"


def parse_config_file(path: Optional[str] = None,
                      required: bool = False) -> ScopeConfig:
    """Parse a functions.config-format file into a ScopeConfig.

    Missing file: error only if ``required`` (the reference always errors,
    but ships a default file; we default to empty scope so the CLI works
    without one unless -configFile was given explicitly)."""
    filename = path or default_config_path()
    cfg = ScopeConfig()
    try:
        fh = open(filename, "r")
    except OSError:
        if required:
            raise ConfigError(
                f"No configuration file found at '{filename}'. "
                "Please pass one in using -configFile")
        return cfg
    with fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            line = line.replace(" ", "").replace("\t", "")
            key, sep, rest = line.partition("=")
            if not sep:
                raise ConfigError(f"malformed line (no '=') in '{filename}': "
                                  f"{line!r}")
            if key not in FILE_KEYS:
                raise ConfigError(f"unrecognized option '{key}' in "
                                  f"configuration file '{filename}'")
            field = getattr(cfg, ScopeConfig._FIELD_OF_KEY[key])
            field.extend(v for v in rest.split(",") if v)
    return cfg
