"""User-facing interface layer: replication-scope configuration.

The reference ingests scope through three layers with defined precedence --
config file, overridden by command line, refined by in-code annotations
(interface.cpp:82-362; SURVEY.md §5 "Config / flag system").  Here:

  * config file  -> :mod:`coast_tpu.interface.config` (same key=value
    format as projects/dataflowProtection/functions.config)
  * command line -> :mod:`coast_tpu.opt` (same flag names as
    dataflowProtection.cpp:14-47)
  * annotations  -> :class:`~coast_tpu.ir.region.LeafSpec` fields on the
    region itself (the COAST.h macro analogue)
  * signature-rewrite features (protected lib, replicated returns,
    clone-after-call, per-arg exclusion) -> :mod:`coast_tpu.interface.wrappers`
"""

from coast_tpu.interface.config import ScopeConfig, parse_config_file
from coast_tpu.interface.wrappers import (clone_after_call, protected_lib,
                                          replicated_return)

__all__ = ["ScopeConfig", "parse_config_file",
           "protected_lib", "replicated_return", "clone_after_call"]
