"""simd + scalarize: vector-dataflow benchmarks (reference:
tests/TMRregression/unitTests/simd.c and tests/scalarize/).

The reference's simd.c exercises the SIMD path of the voters (vector
compares + CreateAddReduce in synchronization.cpp:1136-1177, 1469-1530);
tests/scalarize checks vector code that must be scalarised before
replication.  The TPU analogue: regions whose leaves are whole vectors
updated per step, so every voter is an elementwise vector compare with a
reduction -- the natural TPU form of the reference's SIMD voter.

* ``simd``      : uint32x16 integer lanes (add/rot/xor mix)
* ``scalarize`` : float32x8 axpy-style chain
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_RO, LeafSpec,
                                 Region)

W = 16
N_STEPS = 64
FW = 8
F_STEPS = 48


def _simd_golden() -> np.ndarray:
    v = np.arange(W, dtype=np.uint64) * 2654435761 % (1 << 32)
    k = np.uint64(0x9E3779B9)
    for t in range(N_STEPS):
        v = (v + np.roll(v, 1) + k) % (1 << 32)
        v = ((v << np.uint64(7)) | (v >> np.uint64(25))) % (1 << 32)
        v = v ^ np.uint64(t)
    return v.astype(np.uint32)


def make_simd_region() -> Region:
    golden = _simd_golden()
    init_v = (np.arange(W, dtype=np.uint64) * 2654435761
              % (1 << 32)).astype(np.uint32)

    def init():
        return {"v": jnp.asarray(init_v), "i": jnp.int32(0)}

    def step(state, t):
        v = state["v"]
        v = v + jnp.roll(v, 1) + np.uint32(0x9E3779B9)
        v = (v << np.uint32(7)) | (v >> np.uint32(25))
        v = v ^ t.astype(jnp.uint32)
        return {"v": v, "i": state["i"] + 1}

    def done(state):
        return state["i"] >= N_STEPS

    def check(state):
        return jnp.sum(state["v"] != jnp.asarray(golden)).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "vloop", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["i"] >= N_STEPS,
                                     jnp.int32(2), jnp.int32(1)))

    return Region(
        name="simd",
        init=init,
        step=step,
        done=done,
        check=check,
        output=lambda s: s["v"],
        nominal_steps=N_STEPS,
        max_steps=N_STEPS + 8,
        spec={"v": LeafSpec(KIND_MEM), "i": LeafSpec(KIND_CTRL)},
        default_xmr=True,
        graph=graph,
        meta={},
    )


def _scalarize_golden() -> np.ndarray:
    x = np.linspace(0.1, 1.0, FW).astype(np.float32)
    y = np.ones(FW, np.float32)
    a = np.float32(1.0009765625)           # exactly representable
    for _ in range(F_STEPS):
        y = np.float32(a) * x + y
        x = np.float32(0.75) * x
    return np.concatenate([x, y])


def make_scalarize_region() -> Region:
    golden = _scalarize_golden()

    def init():
        return {
            "x": jnp.linspace(0.1, 1.0, FW, dtype=jnp.float32),
            "y": jnp.ones(FW, jnp.float32),
            "i": jnp.int32(0),
        }

    def step(state, t):
        y = jnp.float32(1.0009765625) * state["x"] + state["y"]
        x = jnp.float32(0.75) * state["x"]
        return {"x": x, "y": y, "i": state["i"] + 1}

    def done(state):
        return state["i"] >= F_STEPS

    def check(state):
        # Tolerance, not bit-equality: XLA may contract a*x+y into an FMA,
        # and whether it does differs between the plain and the vmapped
        # (replicated) lowering of the same step -- bit-exactness across
        # compilations is not an IEEE guarantee once contraction is legal.
        # A real fault perturbs exponent/sign bits and blows far past this.
        got = jnp.concatenate([state["x"], state["y"]])
        want = jnp.asarray(golden)
        return jnp.sum(jnp.abs(got - want) > 1e-4).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "axpy", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["i"] >= F_STEPS,
                                     jnp.int32(2), jnp.int32(1)))

    return Region(
        name="scalarize",
        init=init,
        step=step,
        done=done,
        check=check,
        output=lambda s: jax.lax.bitcast_convert_type(
            jnp.concatenate([s["x"], s["y"]]), jnp.uint32),
        nominal_steps=F_STEPS,
        max_steps=F_STEPS + 8,
        spec={"x": LeafSpec(KIND_MEM), "y": LeafSpec(KIND_MEM),
              "i": LeafSpec(KIND_CTRL)},
        default_xmr=True,
        graph=graph,
        meta={},
    )
