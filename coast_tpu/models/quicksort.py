"""quicksort: the LANL sort benchmark as a TPU region (BASELINE config 3, -DWC).

Semantics follow tests/quicksort/quicksort.c: 580 pseudo-random ints, sorted
forward twice then in reverse twice (init_array + the fwd/fwd/rev/rev main
loop), each result compared against golden sorted arrays with a running
``local_errors`` count.

TPU-native re-expression: recursive quicksort is hostile to XLA (dynamic
ranges, data-dependent recursion); the in-place sort becomes an
**odd-even transposition sort** -- one region step per phase, each phase a
580-wide vectorised compare-exchange, which maps onto the VPU and keeps the
step shape static.  The sorting-network phase index and pass counter are the
control state; a corrupted phase/pass mis-orders exchanges exactly as a
corrupted loop variable mis-orders the reference's partitions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)
from coast_tpu.models.common import lcg_words

ELEMS = 580            # array_elements, quicksort.c:84
PASSES = 4             # fwd, fwd, rev, rev
SEED = 7


def make_region() -> Region:
    vals = lcg_words(SEED, ELEMS, bits=15)
    golden_asc = jnp.asarray(np.sort(vals), dtype=jnp.int32)
    golden_desc = jnp.asarray(np.sort(vals)[::-1].copy(), dtype=jnp.int32)
    arr0 = jnp.asarray(vals, dtype=jnp.int32)

    def exchange(a, offset, ascending):
        """One transposition phase over pairs (offset+2k, offset+2k+1)."""
        m = ((ELEMS - offset) // 2) * 2
        body = jax.lax.slice_in_dim(a, offset, offset + m)
        left = body[0::2]
        right = body[1::2]
        lo = jnp.minimum(left, right)
        hi = jnp.maximum(left, right)
        new_left = jnp.where(ascending, lo, hi)
        new_right = jnp.where(ascending, hi, lo)
        merged = jnp.stack([new_left, new_right], axis=1).reshape(-1)
        return jnp.concatenate([a[:offset], merged, a[offset + m:]])

    def init():
        return {
            "array": arr0,
            "golden": golden_asc,
            "golden_rev": golden_desc,
            "pass_": jnp.int32(0),
            "phase": jnp.int32(0),
            "errs": jnp.int32(0),
        }

    def step(state, t):
        a = state["array"]
        p = state["pass_"]
        phase = state["phase"]
        active = p < PASSES
        ascending = p < 2
        even = exchange(a, 0, ascending)
        odd = exchange(a, 1, ascending)
        new_a = jnp.where((phase % 2) == 0, even, odd)
        last_phase = phase >= ELEMS - 1
        # End of the ascending passes: check against golden (the reference
        # checks after every sort; the final state is checked in check()).
        asc_done = jnp.logical_and(last_phase, p == 1)
        asc_errs = jnp.sum(new_a != state["golden"]).astype(jnp.int32)
        return {
            **state,
            "array": jnp.where(active, new_a, a),
            "phase": jnp.where(active, jnp.where(last_phase, 0, phase + 1),
                               phase),
            "pass_": jnp.where(active & last_phase, p + 1, p),
            "errs": state["errs"] + jnp.where(active & asc_done, asc_errs, 0),
        }

    def done(state):
        return state["pass_"] >= PASSES

    def check(state):
        final_errs = jnp.sum(state["array"] != state["golden_rev"])
        return (state["errs"] + final_errs).astype(jnp.int32)

    def output(state):
        return state["array"].astype(jnp.uint32)

    def block_of(state):
        p = state["pass_"]
        return jnp.where(p >= PASSES, jnp.int32(3),
                         jnp.where(p < 2, jnp.int32(1),
                                   jnp.int32(2))).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "sort_fwd", "sort_rev", "exit"],
        edges=[(0, 1), (1, 1), (1, 2), (2, 2), (2, 3)],
        block_of=block_of,
    )

    return Region(
        name="quicksort",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=PASSES * ELEMS,
        max_steps=PASSES * ELEMS + ELEMS,
        spec={
            "array": LeafSpec(KIND_MEM),
            # golden arrays: __NO_xMR in spirit -- the reference's golden
            # globals live outside the protected compute (mm.c pattern) and
            # are never written, so they are read-only (still injectable).
            "golden": LeafSpec(KIND_RO),
            "golden_rev": LeafSpec(KIND_RO),
            "pass_": LeafSpec(KIND_CTRL),
            "phase": LeafSpec(KIND_CTRL),
            "errs": LeafSpec(KIND_REG),
        },
        default_xmr=True,
        graph=graph,
        meta={"oracle": "local_errors == 0"},
    )
