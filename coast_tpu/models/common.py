"""Shared helpers for the benchmark regions.

The reference benchmarks seed C ``rand()`` and compare against golden
values captured at startup (e.g. tests/quicksort/quicksort.c init_array,
tests/mm_common/mm.c).  We use one deterministic LCG across all regions so
inputs are reproducible without glibc.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def lcg_words(seed: int, n: int, bits: int = 15) -> np.ndarray:
    """n deterministic pseudo-random values of `bits` width (numpy host-side,
    stands in for the reference's srand/rand input generation)."""
    out = np.empty(n, dtype=np.int64)
    x = seed & 0x7FFFFFFF
    for i in range(n):
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out[i] = (x >> 16) & ((1 << bits) - 1)
    return out


def lcg_fill(seed: int, n: int, bits: int = 15) -> jnp.ndarray:
    return jnp.asarray(lcg_words(seed, n, bits), dtype=jnp.int32)
