"""Shared helpers for the benchmark regions.

The reference benchmarks seed C ``rand()`` and compare against golden
values captured at startup (e.g. tests/quicksort/quicksort.c init_array,
tests/mm_common/mm.c).  We use one deterministic LCG across all regions so
inputs are reproducible without glibc.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


_LCG_A, _LCG_C, _LCG_MASK = 1103515245, 12345, 0x7FFFFFFF


@functools.lru_cache(maxsize=64)
def _lcg_state_stream(seed: int, n: int) -> np.ndarray:
    """The raw LCG state sequence, cached and read-only.

    The flagship regions draw 2x1M words per build; a pure-Python
    recurrence costs tens of seconds.  Affine maps compose, so after
    generating one stride sequentially the rest is vectorised numpy:
    x[i+s] = (A^s x[i] + C_s) mod 2^31, with A^s and C_s built by
    composing (a, c) -> (A a, A c + C) s times.  int64 holds the
    products exactly (a_s, x < 2^31 so a_s * x < 2^62)."""
    out = np.empty(n, dtype=np.int64)
    stride = min(n, 4096)
    x = seed & _LCG_MASK
    for i in range(stride):
        x = (_LCG_A * x + _LCG_C) & _LCG_MASK
        out[i] = x
    a_s, c_s = 1, 0
    for _ in range(stride):
        a_s, c_s = (_LCG_A * a_s) & _LCG_MASK, (_LCG_A * c_s + _LCG_C) & _LCG_MASK
    filled = stride
    while filled < n:
        m = min(stride, n - filled)
        out[filled:filled + m] = (
            a_s * out[filled - stride:filled - stride + m] + c_s) & _LCG_MASK
        filled += m
    out.setflags(write=False)
    return out


def lcg_words(seed: int, n: int, bits: int = 15) -> np.ndarray:
    """n deterministic pseudo-random values of `bits` width (numpy host-side,
    stands in for the reference's srand/rand input generation)."""
    return (_lcg_state_stream(seed, n) >> 16) & ((1 << bits) - 1)


def lcg_fill(seed: int, n: int, bits: int = 15) -> jnp.ndarray:
    return jnp.asarray(lcg_words(seed, n, bits), dtype=jnp.int32)
