"""CHStone mips: a MIPS ISA interpreter as a TPU region (BASELINE config 4).

Semantics follow tests/chstone/mips/mips.c + imem.h: interpret a 44-word
MIPS text segment (a bubble sort over A[8]) one instruction per region step
until pc==0, then check ``main_result`` = (n_inst==611) + 8 matches of
dmem against outData; RESULT: PASS iff main_result==9 (mips.c:297-305).

This is the richest injection target in the corpus: a 32-entry register
file, 64-word data memory, pc / Hi / Lo -- the direct analogue of the
reference's register-section injections (resources/registers.py).

TPU-native notes: the do-while dispatch loop becomes one step per
instruction; the switch over opcodes becomes masked selects (every op class
computed, one committed) -- branchless, static-shape, vmap-friendly.  C
quirks kept: ``reg`` is int, so SRL/SRLV compile to *arithmetic* shifts
(mips.c:199-207); shift amounts are masked to 5 bits (MIPS semantics);
IADDR/DADDR clamp-gather instead of trapping on wild addresses.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)

# The 44-word text segment of imem.h (SPIM assembly of main + compare_swap
# bubble sort; see imem.h:40-123 for the disassembly).
IMEM = [
    0x8fa40000, 0x27a50004, 0x24a60004, 0x00041080, 0x00c23021, 0x0c100016,
    0x00000000, 0x3402000a, 0x0000000c, 0x3c011001, 0x34280000, 0x00044880,
    0x01094821, 0x8d2a0000, 0x00055880, 0x010b5821, 0x8d6c0000, 0x018a682a,
    0x11a00003, 0xad2c0000, 0xad6a0000, 0x03e00008, 0x27bdfff4, 0xafbf0008,
    0xafb10004, 0xafb00000, 0x24100000, 0x2a080008, 0x1100000b, 0x26110001,
    0x2a280008, 0x11000006, 0x26040000, 0x26250000, 0x0c100009, 0x26310001,
    0x0810001e, 0x26100001, 0x0810001b, 0x8fbf0008, 0x8fb10004, 0x8fb00000,
    0x27bd000c, 0x03e00008,
]

A_IN = [22, 5, -9, 3, -17, 38, 0, 11]
OUT_DATA = [-17, -9, 0, 3, 5, 11, 22, 38]
N_INST_GOLDEN = 611      # mips.c:297


def _sra(x, n):
    """C `int >> n` (arithmetic); n already masked to [0,31]."""
    return jnp.right_shift(x, n)


def _srl_u(x, n):
    return jnp.right_shift(x.astype(jnp.uint32), n.astype(jnp.uint32)
                           ).astype(jnp.int32)


def _umulhi(a, b):
    """High 32 bits of the unsigned 64-bit product, in 32-bit ops."""
    au, bu = a.astype(jnp.uint32), b.astype(jnp.uint32)
    al, ah = au & 0xFFFF, au >> 16
    bl, bh = bu & 0xFFFF, bu >> 16
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = lh + (ll >> 16)
    mid_lo = mid & 0xFFFF
    carry = mid >> 16
    mid2 = hl + mid_lo
    return (hh + carry + (mid2 >> 16)).astype(jnp.int32)


def make_region() -> Region:
    imem0 = jnp.asarray(np.asarray(IMEM, dtype=np.uint32).view(np.int32))
    a_in = jnp.asarray(A_IN, dtype=jnp.int32)
    out_data = jnp.asarray(OUT_DATA, dtype=jnp.int32)

    def init():
        regs = jnp.zeros(32, jnp.int32).at[29].set(0x7FFFEFFC)
        dmem = jnp.zeros(64, jnp.int32).at[:8].set(a_in)
        return {
            "imem": imem0,
            "regs": regs,
            "dmem": dmem,
            "a_in": a_in,
            "out_data": out_data,
            "pc": jnp.int32(0x00400000),
            "hi": jnp.int32(0),
            "lo": jnp.int32(0),
            "n_inst": jnp.int32(0),
        }

    def step(state, t):
        pc = state["pc"]
        regs = state["regs"]
        dmem = state["dmem"]
        running = pc != 0

        iaddr = _srl_u(pc & 0xFF, jnp.int32(2))
        ins = jnp.take(state["imem"], iaddr, mode="clip")
        insu = ins.astype(jnp.uint32)
        op = (insu >> 26).astype(jnp.int32)
        funct = ins & 0x3F
        shamt = (ins >> 6) & 0x1F
        rd = (ins >> 11) & 0x1F
        rt = (ins >> 16) & 0x1F
        rs = (ins >> 21) & 0x1F
        addr_u = ins & 0xFFFF                       # zero-extended
        addr_s = (addr_u ^ 0x8000) - 0x8000         # sign-extended short
        vrs = jnp.take(regs, rs, mode="clip")
        vrt = jnp.take(regs, rt, mode="clip")
        pc1 = pc + 4

        # ---- R-type (op == 0) ----
        sh_s = shamt & 31
        sh_r = vrs & 31
        r_vals = [
            (33, vrs + vrt),                        # ADDU
            (35, vrs - vrt),                        # SUBU
            (16, state["hi"]),                      # MFHI
            (18, state["lo"]),                      # MFLO
            (36, vrs & vrt),                        # AND
            (37, vrs | vrt),                        # OR
            (38, vrs ^ vrt),                        # XOR
            (0, vrt << sh_s),                       # SLL
            (2, _sra(vrt, sh_s)),                   # SRL (C int >>)
            (4, vrt << sh_r),                       # SLLV
            (6, _sra(vrt, sh_r)),                   # SRLV (C int >>)
            (42, (vrs < vrt).astype(jnp.int32)),    # SLT
            (43, (vrs.astype(jnp.uint32)
                  < vrt.astype(jnp.uint32)).astype(jnp.int32)),  # SLTU
        ]
        r_writes = jnp.stack([funct == f for f, _ in r_vals])
        r_val = jnp.select([funct == f for f, _ in r_vals],
                           [v for _, v in r_vals], jnp.int32(0))
        r_reg_write = jnp.any(r_writes)
        is_mult = jnp.logical_or(funct == 24, funct == 25)
        lo_new = (vrs.astype(jnp.uint32) * vrt.astype(jnp.uint32)
                  ).astype(jnp.int32)
        hi_signed = (_umulhi(vrs, vrt)
                     - jnp.where(vrs < 0, vrt, 0)
                     - jnp.where(vrt < 0, vrs, 0))
        hi_new = jnp.where(funct == 24, hi_signed, _umulhi(vrs, vrt))
        is_jr = funct == 8
        r_known = jnp.logical_or(jnp.logical_or(r_reg_write, is_mult), is_jr)
        r_pc = jnp.where(is_jr, vrs, jnp.where(r_known, pc1, 0))

        # ---- J / JAL (op 2, 3) ----
        tgt = (ins & 0x3FFFFFF) << 2
        # ---- I-type ----
        daddr = _srl_u((vrs + addr_s) & 0xFF, jnp.int32(2))
        lw_val = jnp.take(dmem, daddr, mode="clip")
        i_vals = [
            (9, vrs + addr_s),                       # ADDIU
            (12, vrs & addr_u),                      # ANDI
            (13, vrs | addr_u),                      # ORI
            (14, vrs ^ addr_u),                      # XORI
            (35, lw_val),                            # LW
            (15, addr_u << 16),                      # LUI
            (10, (vrs < addr_s).astype(jnp.int32)),  # SLTI
            (11, (vrs.astype(jnp.uint32)
                  < addr_u.astype(jnp.uint32)).astype(jnp.int32)),  # SLTIU
        ]
        i_reg_write = jnp.any(jnp.stack([op == o for o, _ in i_vals]))
        i_val = jnp.select([op == o for o, _ in i_vals],
                           [v for _, v in i_vals], jnp.int32(0))
        is_sw = op == 43
        btaken = jnp.select(
            [op == 4, op == 5, op == 1],
            [vrs == vrt, vrs != vrt, vrs >= 0], jnp.bool_(False))
        is_branch = jnp.logical_or(jnp.logical_or(op == 4, op == 5), op == 1)
        i_known = jnp.logical_or(jnp.logical_or(i_reg_write, is_sw), is_branch)
        i_pc = jnp.where(jnp.logical_and(is_branch, btaken),
                         pc1 - 4 + (addr_s << 2),
                         jnp.where(i_known, pc1, 0))

        is_r = op == 0
        is_j = op == 2
        is_jal = op == 3

        # register write: rd for R-type, rt for I-type, $31 for JAL
        wr_en = jnp.where(is_r, r_reg_write,
                          jnp.where(is_jal, True,
                                    jnp.logical_and(~is_j, i_reg_write)))
        wr_idx = jnp.where(is_r, rd, jnp.where(is_jal, 31, rt))
        wr_val = jnp.where(is_r, r_val, jnp.where(is_jal, pc1, i_val))
        regs_w = regs.at[wr_idx].set(wr_val, mode="drop")
        new_regs = jnp.where(jnp.logical_and(running, wr_en), regs_w, regs)
        new_regs = new_regs.at[0].set(0)             # reg[0]=0, mips.c:292

        dmem_w = dmem.at[daddr].set(vrt, mode="drop")
        new_dmem = jnp.where(
            jnp.logical_and(running, jnp.logical_and(~is_r, is_sw)),
            dmem_w, dmem)

        new_hi = jnp.where(jnp.logical_and(is_r, is_mult),
                           hi_new, state["hi"])
        new_lo = jnp.where(jnp.logical_and(is_r, is_mult),
                           lo_new, state["lo"])
        new_pc = jnp.where(is_r, r_pc,
                           jnp.where(jnp.logical_or(is_j, is_jal), tgt, i_pc))

        return {
            **state,
            "regs": new_regs,
            "dmem": new_dmem,
            "hi": jnp.where(running, new_hi, state["hi"]),
            "lo": jnp.where(running, new_lo, state["lo"]),
            "pc": jnp.where(running, new_pc, pc),
            "n_inst": state["n_inst"] + jnp.where(running, 1, 0),
        }

    def done(state):
        return state["pc"] == 0

    def check(state):
        main_result = ((state["n_inst"] == N_INST_GOLDEN).astype(jnp.int32)
                       + jnp.sum(state["dmem"][:8] == state["out_data"]
                                 ).astype(jnp.int32))
        return jnp.int32(9) - main_result

    def output(state):
        return jnp.concatenate(
            [state["dmem"][:8], state["n_inst"].reshape(1)]).astype(jnp.uint32)

    # True per-basic-block graph of the guest text (the granularity of
    # populateGraph, CFCSS.cpp:149-185): leaders are branch/jump targets and
    # fall-throughs of imem.h's 44 instructions.  Block -> instruction-index
    # ranges:
    #   1 startup     0-5    (arg setup, jal main)
    #   2 exit_seq    6-8    (li $v0,10; syscall -> halt)
    #   3 cs_head     9-18   (compare_swap: load A[i],A[j], slt, beq)
    #   4 cs_swap     19-20  (the two sw of the swap-taken path)
    #   5 cs_ret      21     (jr $ra)
    #   6 main_pro    22-26  (prologue, s0=0)
    #   7 outer_head  27-28  (slti s0<8, beq -> epilogue)
    #   8 outer_body  29     (s1 init increment)
    #   9 inner_head  30-31  (slti s1<8, beq -> outer_inc)
    #  10 call_cs     32-34  (arg moves, jal compare_swap)
    #  11 after_call  35-36  (s1++, j inner_head)
    #  12 outer_inc   37-38  (s0++, j outer_head)
    #  13 main_epi    39-43  (epilogue, jr $ra)
    #  14 exit        pc==0
    _BLK_OF_IDX = jnp.asarray(
        [1] * 6 + [2] * 3 + [3] * 10 + [4] * 2 + [5] + [6] * 5 + [7] * 2
        + [8] + [9] * 2 + [10] * 3 + [11] * 2 + [12] * 2 + [13] * 5,
        dtype=jnp.int32)

    def block_of(state):
        pc = state["pc"]
        idx = _srl_u(pc & 0xFF, jnp.int32(2))
        return jnp.where(pc == 0, jnp.int32(14),
                         jnp.take(_BLK_OF_IDX, idx, mode="clip")
                         ).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "startup", "exit_seq", "cs_head", "cs_swap",
               "cs_ret", "main_pro", "outer_head", "outer_body",
               "inner_head", "call_cs", "after_call", "outer_inc",
               "main_epi", "exit"],
        edges=[(0, 1), (1, 6),                     # jal main
               (6, 7), (7, 8), (7, 13),            # outer loop head
               (8, 9), (9, 10), (9, 12),           # inner loop head
               (10, 3), (3, 4), (3, 5), (4, 5),    # compare_swap body
               (5, 11), (11, 9),                   # jr $ra -> after jal
               (12, 7), (13, 2), (2, 14),          # epilogue -> syscall halt
               # One step = one instruction: staying inside a
               # multi-instruction block is the self-transition.
               (1, 1), (2, 2), (3, 3), (4, 4), (6, 6), (7, 7), (9, 9),
               (10, 10), (11, 11), (12, 12), (13, 13)],
        block_of=block_of,
    )

    return Region(
        name="chstone_mips",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=N_INST_GOLDEN,
        max_steps=1536,
        spec={
            "imem": LeafSpec(KIND_RO),
            "a_in": LeafSpec(KIND_RO),
            "out_data": LeafSpec(KIND_RO),
            # regs/dmem are in-SoR local arrays: stores to them are store
            # sync points in the reference (populateSyncPoints).
            "regs": LeafSpec(KIND_MEM),
            "dmem": LeafSpec(KIND_MEM),
            "pc": LeafSpec(KIND_CTRL),
            "n_inst": LeafSpec(KIND_CTRL),
            "hi": LeafSpec(KIND_REG),
            "lo": LeafSpec(KIND_REG),
        },
        default_xmr=True,
        graph=graph,
        meta={"oracle": "RESULT: PASS"},
    )
