"""matrixMultiply: the reference's zero-to-aha benchmark as a TPU region.

Semantics follow tests/matrixMultiply/matrixMultiply.c (9x9 matrix product,
golden copy generated at startup, self-check counts mismatching words) and
tests/mm_common/mm_common.c.  Values are seeded pseudo-randomly (seed 42 like
the reference's ``seed_value``); we use our own LCG rather than glibc
``rand()``, so the golden XOR constant differs from the reference's
2802879457 but plays the same role (meta["golden_xor"]).

Execution is stepped at two micro-steps per output row:

    phase 0: acc  <- first[i,:] . second          (live in a register leaf)
    phase 1: results[i,:] <- acc ; i += 1

so a fault can land in the live accumulator between compute and store --
the closest analogue of the reference's register-section injections
(resources/registers.py A9Register) -- as well as in any memory word.

Scope annotations mirror the C source: ``results_matrix`` is ``__xMR``,
``golden`` is ``__NO_xMR`` (matrixMultiply.c globals), and the self-check
runs unprotected on the voted view (checkGolden is ``__NO_xMR``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ops.indexing import row_select, row_update
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)

SIDE = 9
SEED = 42


def _lcg_fill(seed: int, n: int) -> jnp.ndarray:
    """Deterministic 15-bit pseudo-random values (stands in for rand())."""
    out = []
    x = seed & 0x7FFFFFFF
    for _ in range(n):
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out.append((x >> 16) & 0x7FFF)
    return jnp.array(out, dtype=jnp.int32)


def _matmul_u32(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """9x9 product in mod-2^32 arithmetic (C unsigned semantics)."""
    au = a.astype(jnp.uint32)
    bu = b.astype(jnp.uint32)
    return jnp.einsum("ik,kj->ij", au, bu)


def make_region() -> Region:
    first = _lcg_fill(SEED, SIDE * SIDE).reshape(SIDE, SIDE)
    second = _lcg_fill(SEED + 1, SIDE * SIDE).reshape(SIDE, SIDE)
    golden = _matmul_u32(first, second)
    golden_xor = int(jnp.bitwise_xor.reduce(golden.reshape(-1)))

    def init():
        return {
            "first": first,
            "second": second,
            "results": jnp.zeros((SIDE, SIDE), jnp.uint32),
            "golden": golden,
            "acc": jnp.zeros((SIDE,), jnp.uint32),
            "i": jnp.int32(0),
            "phase": jnp.int32(0),
        }

    def step(state, t):
        i, phase = state["i"], state["phase"]
        # Row i access: OOB (corrupted i) clamps, i.e. reads the wrong
        # row rather than trapping -- documented fidelity envelope vs the
        # A9's data aborts (SURVEY.md §7 "Hard parts").  On TPU the
        # select/update lower densely (ops/indexing.py) so the vmapped
        # campaign never pays batched gather/scatter.
        row_a = row_select(state["first"], i).astype(jnp.uint32)
        computed = jnp.sum(row_a[:, None] * state["second"].astype(jnp.uint32),
                           axis=0)
        compute_phase = phase == 0
        acc = jnp.where(compute_phase, computed, state["acc"])
        stored = row_update(state["results"], state["acc"], i)
        results = jnp.where(compute_phase, state["results"], stored)
        return {
            **state,
            "acc": acc,
            "results": results,
            "i": jnp.where(compute_phase, i, i + 1),
            "phase": jnp.where(compute_phase, 1, 0),
        }

    def done(state):
        return state["i"] >= SIDE

    def check(state):
        return jnp.sum(state["golden"] != state["results"]).astype(jnp.int32)

    def output(state):
        return state["results"].reshape(-1)

    def block_of(state):
        """Post-step program label: the loop-exit test lives in the store/
        latch block (the C for-loop tests after the increment), so 'exit' is
        only reachable from a post-store state (phase back at 0)."""
        compute_pending = state["phase"] == 0
        return jnp.where(
            compute_pending,
            jnp.where(state["i"] >= SIDE, jnp.int32(3), jnp.int32(1)),
            jnp.int32(2)).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "compute", "store", "exit"],
        edges=[(0, 1), (1, 2), (2, 1), (2, 3)],
        block_of=block_of,
    )

    return Region(
        name="matrixMultiply",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=2 * SIDE,
        max_steps=6 * SIDE,
        spec={
            # first/second are filled by the protected initialize() in the
            # reference (an __xMR function writing cloned globals), so they
            # sit inside the sphere of replication: replicated + voted.
            "first": LeafSpec(KIND_MEM),
            "second": LeafSpec(KIND_MEM),
            "results": LeafSpec(KIND_MEM, xmr=True),
            # Never written after init -> read-only (still injectable).
            "golden": LeafSpec(KIND_RO),
            "acc": LeafSpec(KIND_REG),
            "i": LeafSpec(KIND_CTRL),
            "phase": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={"golden_xor": golden_xor, "oracle": "Number of errors: 0"},
    )
