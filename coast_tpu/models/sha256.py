"""sha256: the SHA-256 benchmark as a TPU region (BASELINE config 2, -TMR).

Semantics follow tests/sha256_common/sha256_common.c: hash a fixed message,
compare the digest against a golden digest (``hashGlbl`` vs ``golden``,
sha256_common.c:208).  The golden digest here comes from Python's hashlib --
an independent oracle, like the reference's precomputed ``sha_data.inc``.

TPU-native re-expression, stepped at round granularity so faults land
mid-compression (the analogue of register-section injections into the
a..h working variables):

    phase 0 (48 steps): message-schedule expansion  w[16+i] = ...
    phase 1 (64 steps): one compression round per step on regs a..h
    phase 2 (1 step):   state += regs; done

All words are uint32 (mod-2^32 add semantics for free).
"""

from __future__ import annotations

import hashlib

import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)

MESSAGE = b"coast_tpu sha256 benchmark: Automated TMR"

_K = [
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2]

_H0 = [0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
       0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19]


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _pad_block(msg: bytes) -> np.ndarray:
    assert len(msg) <= 55, "single-block region: message must fit 55 bytes"
    buf = bytearray(64)
    buf[:len(msg)] = msg
    buf[len(msg)] = 0x80
    bitlen = len(msg) * 8
    buf[56:64] = bitlen.to_bytes(8, "big")
    return np.frombuffer(bytes(buf), dtype=">u4").astype(np.uint32)


def make_region() -> Region:
    w16 = _pad_block(MESSAGE)
    golden = np.frombuffer(hashlib.sha256(MESSAGE).digest(),
                           dtype=">u4").astype(np.uint32)
    golden_a = jnp.asarray(golden, dtype=jnp.uint32)
    k_a = jnp.asarray(np.asarray(_K, dtype=np.uint32))

    def init():
        w0 = jnp.zeros(64, jnp.uint32).at[:16].set(jnp.asarray(w16))
        return {
            "w": w0,
            "h": jnp.asarray(np.asarray(_H0, dtype=np.uint32)),
            "regs": jnp.asarray(np.asarray(_H0, dtype=np.uint32)),
            "k": k_a,
            "golden": golden_a,
            "round": jnp.int32(0),
            "phase": jnp.int32(0),
        }

    def step(state, t):
        w = state["w"]
        regs = state["regs"]
        rnd = state["round"]
        phase = state["phase"]

        # --- phase 0: schedule expansion: w[16+rnd] ---
        j = jnp.clip(rnd, 0, 47) + 16
        s1w = jnp.take(w, j - 2, mode="clip")
        s0w = jnp.take(w, j - 15, mode="clip")
        sig1 = _rotr(s1w, 17) ^ _rotr(s1w, 19) ^ (s1w >> 10)
        sig0 = _rotr(s0w, 7) ^ _rotr(s0w, 18) ^ (s0w >> 3)
        new_w_val = (sig1 + jnp.take(w, j - 7, mode="clip")
                     + sig0 + jnp.take(w, j - 16, mode="clip"))
        w_expanded = w.at[j].set(new_w_val, mode="drop")

        # --- phase 1: compression round rnd ---
        a, b, c, d, e, f, g, h = [regs[i] for i in range(8)]
        i = jnp.clip(rnd, 0, 63)
        ep1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + ep1 + ch + jnp.take(state["k"], i, mode="clip") \
            + jnp.take(w, i, mode="clip")
        ep0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = ep0 + maj
        regs_next = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g])

        # --- phase 2: finalize ---
        h_final = state["h"] + regs

        p0 = phase == 0
        p1 = phase == 1
        p2 = phase == 2
        active = phase < 3
        last0 = jnp.logical_and(p0, rnd >= 47)
        last1 = jnp.logical_and(p1, rnd >= 63)
        new_w = jnp.where(p0, w_expanded, w)
        new_regs = jnp.where(p1, regs_next, regs)
        new_h = jnp.where(p2, h_final, state["h"])
        new_round = jnp.where(p0, jnp.where(last0, 0, rnd + 1),
                              jnp.where(p1, jnp.where(last1, 0, rnd + 1),
                                        rnd))
        new_phase = jnp.where(last0, 1,
                              jnp.where(last1, 2,
                                        jnp.where(p2, 3, phase)))
        return {
            **state,
            "w": jnp.where(active, new_w, w),
            "regs": jnp.where(active, new_regs, regs),
            "h": jnp.where(active, new_h, state["h"]),
            "round": jnp.where(active, new_round, rnd),
            "phase": jnp.where(active, new_phase, phase),
        }

    def done(state):
        return state["phase"] >= 3

    def check(state):
        return jnp.sum(state["h"] != state["golden"]).astype(jnp.int32)

    def output(state):
        return state["h"]

    def block_of(state):
        p = state["phase"]
        return jnp.clip(p + 1, 1, 4).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "schedule", "compress", "finalize", "exit"],
        edges=[(0, 1), (1, 1), (1, 2), (2, 2), (2, 3), (3, 4)],
        block_of=block_of,
    )

    return Region(
        name="sha256",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=48 + 64 + 1,
        max_steps=3 * (48 + 64 + 1),
        spec={
            "w": LeafSpec(KIND_MEM),
            "h": LeafSpec(KIND_MEM),
            "regs": LeafSpec(KIND_REG),
            "k": LeafSpec(KIND_RO),
            # hashGlbl-vs-golden compare runs outside the SoR (__NO_xMR);
            # never written -> read-only (still injectable).
            "golden": LeafSpec(KIND_RO),
            "round": LeafSpec(KIND_CTRL),
            "phase": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={"oracle": "Number of errors: 0",
              "golden_hex": hashlib.sha256(MESSAGE).hexdigest()},
    )
