"""Smoke-tier benchmarks: trivial, helloWorld, simpleTMR (reference:
tests/trivial/, tests/helloWorld/, tests/simpleTMR/).

The reference keeps a few near-empty programs in the matrix so the build
pipeline itself is tested on degenerate inputs (no loops, tiny loops,
string output).  Same role here: minimal regions that still satisfy the
full Region contract, so every strategy and the campaign machinery can be
exercised at near-zero cost.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)


def _linear_graph(name: str, n_steps: int):
    return BlockGraph(
        names=["entry", name, "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["i"] >= n_steps,
                                     jnp.int32(2), jnp.int32(1)))


def make_trivial_region() -> Region:
    """tests/trivial: main returns 0."""
    def init():
        return {"ret": jnp.int32(0), "i": jnp.int32(0)}

    def step(state, t):
        return {"ret": state["ret"], "i": state["i"] + 1}

    return Region(
        name="trivial",
        init=init,
        step=step,
        done=lambda s: s["i"] >= 1,
        check=lambda s: (s["ret"] != 0).astype(jnp.int32),
        output=lambda s: s["ret"].reshape(1).astype(jnp.uint32),
        nominal_steps=1,
        max_steps=4,
        spec={"ret": LeafSpec(KIND_REG), "i": LeafSpec(KIND_CTRL)},
        default_xmr=True,
        graph=_linear_graph("main", 1),
        meta={},
    )


_HELLO = b"Hello world!"


def make_hello_region() -> Region:
    """tests/helloWorld: emit the string, one character per step (the
    closest analogue of a putchar loop over UART)."""
    msg = np.frombuffer(_HELLO + b"\x00" * (-len(_HELLO) % 4),
                        dtype=np.uint8).astype(np.uint32)
    n = len(msg)

    def init():
        return {
            "text": jnp.asarray(msg),
            "out": jnp.zeros(n, jnp.uint32),
            "i": jnp.int32(0),
        }

    def step(state, t):
        i = jnp.clip(state["i"], 0, n - 1)
        ch = jnp.take(state["text"], i, mode="clip")
        return {"text": state["text"],
                "out": state["out"].at[i].set(ch, mode="drop"),
                "i": state["i"] + 1}

    return Region(
        name="helloWorld",
        init=init,
        step=step,
        done=lambda s: s["i"] >= n,
        check=lambda s: jnp.sum(s["out"] != jnp.asarray(msg)).astype(
            jnp.int32),
        output=lambda s: s["out"],
        nominal_steps=n,
        max_steps=n + 4,
        spec={"text": LeafSpec(KIND_RO), "out": LeafSpec(KIND_MEM),
              "i": LeafSpec(KIND_CTRL)},
        default_xmr=True,
        graph=_linear_graph("puts", n),
        meta={"message": _HELLO.decode()},
    )


N_ACC = 32


def make_simple_tmr_region() -> Region:
    """tests/simpleTMR: the minimal accumulate loop used as the TMR demo."""
    golden = sum(range(N_ACC)) * 3 + 7

    def init():
        return {"acc": jnp.int32(7), "i": jnp.int32(0)}

    def step(state, t):
        return {"acc": state["acc"] + 3 * jnp.clip(state["i"], 0, N_ACC - 1),
                "i": state["i"] + 1}

    return Region(
        name="simpleTMR",
        init=init,
        step=step,
        done=lambda s: s["i"] >= N_ACC,
        check=lambda s: (s["acc"] != golden).astype(jnp.int32),
        output=lambda s: s["acc"].reshape(1).astype(jnp.uint32),
        nominal_steps=N_ACC,
        max_steps=N_ACC + 8,
        spec={"acc": LeafSpec(KIND_REG), "i": LeafSpec(KIND_CTRL)},
        default_xmr=True,
        graph=_linear_graph("accumulate", N_ACC),
        meta={"golden": golden},
    )
