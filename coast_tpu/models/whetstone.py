"""whetstone: the classic floating-point synthetic benchmark (reference:
tests/TMRregression/unitTests/{whetstone.c,whets.c}).

The reference runs the Whetstone modules (array arithmetic, trig-free
polynomial chains, conditional jumps) to exercise FP dataflow under
replication.  The TPU region runs a compact float32 Whetstone: each step
is one iteration updating the classic 4-element working set through the
module-1 elementary arithmetic and a module-6-style integer/float mix.
State leaves are float32 words -- the flipper bitcasts, so a campaign
flips real IEEE bits (sign/exponent/mantissa) like a register-file upset.

Golden: the identical float32 sequence in numpy (one rounding per op),
compared with a small tolerance -- XLA FMA contraction may differ between
the plain and replicated lowerings, so exact-ulp equality across
compilations is not an IEEE guarantee.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, LeafSpec,
                                 Region)

N_ITER = 128
T = np.float32(0.499975)
T1 = np.float32(0.50025)
T2 = np.float32(2.0)


def golden_reference() -> np.ndarray:
    e = np.array([1.0, -1.0, -1.0, -1.0], np.float32)
    for _ in range(N_ITER):
        # Module 1: simple identifiers (whets.c N1 body).
        e0 = np.float32((e[0] + e[1] + e[2] - e[3]) * T)
        e1 = np.float32((e0 + e[1] - e[2] + e[3]) * T)
        e2 = np.float32((e0 - e1 + e[2] + e[3]) * T)
        e3 = np.float32((-e0 + e1 + e2 + e[3]) * T)
        e = np.array([e0, e1, e2, np.float32(e3 / T2)], np.float32)
    return e


def make_region() -> Region:
    golden = golden_reference()

    def init():
        return {
            "e": jnp.asarray([1.0, -1.0, -1.0, -1.0], jnp.float32),
            "i": jnp.int32(0),
        }

    def step(state, t):
        e = state["e"]
        e0 = (e[0] + e[1] + e[2] - e[3]) * T
        e1 = (e0 + e[1] - e[2] + e[3]) * T
        e2 = (e0 - e1 + e[2] + e[3]) * T
        e3 = (-e0 + e1 + e2 + e[3]) * T
        new_e = jnp.stack([e0, e1, e2, e3 / T2])
        return {"e": new_e, "i": state["i"] + 1}

    def done(state):
        return state["i"] >= N_ITER

    def check(state):
        # Tolerant compare: XLA's FMA contraction may differ between the
        # plain and replicated lowerings (see models/vector.py check), so
        # ulp-exact equality across compilations is not guaranteed.  Faults
        # that matter (sign/exponent flips) exceed this by orders of
        # magnitude.
        want = jnp.asarray(golden)
        return jnp.sum(jnp.abs(state["e"] - want) > 1e-4).astype(jnp.int32)

    def output(state):
        return jax.lax.bitcast_convert_type(state["e"], jnp.uint32)

    graph = BlockGraph(
        names=["entry", "module1", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["i"] >= N_ITER,
                                     jnp.int32(2), jnp.int32(1)))

    return Region(
        name="whetstone",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=N_ITER,
        max_steps=N_ITER + 8,
        spec={
            "e": LeafSpec(KIND_REG),
            "i": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={"golden_bits": [hex(int(x)) for x in
                              golden.view(np.uint32)]},
    )
