"""matrixMultiply256: the TPU-shaped flagship benchmark (>= 1 MiB state).

The reference's flagship is a 9x9 integer matrixMultiply
(tests/matrixMultiply/matrixMultiply.c) -- ~160 words of state, sized for a
Cortex-A9 guest under QEMU.  A TPU's fault-injection value proposition is
the opposite regime: large replica tensors resident in HBM, compute on the
MXU, thousands of campaigns batched per dispatch.  This region is the same
*program* as matrixMultiply -- golden generated at startup, triple loop,
self-check counts mismatching words -- scaled to that regime:

  * 256x256 operands/results/golden: 4 x 256 KiB = 1.0 MiB of region
    state; under TMR the replicated leaves alone hold 3.75 MiB in HBM
    (first/second/results x 3 lanes + shared golden).
  * one step = one 32-row output block: a (32x256)@(256x256) matmul the
    XLA compiler tiles onto the MXU -- per protected step that is
    3 lanes x 4.2 MFLOP of systolic work, vs the scalar adds of the 9x9.
  * entries are integer-valued floats sized per side (_entry_bits) so
    every product and row sum stays below 2^24: float32 matmul is *exact* and
    the golden compare is bitwise-stable under any op order or fusion
    XLA picks (the mm.c golden-XOR oracle, tests/mm_common/mm.c:31,
    without depending on float rounding).

Two micro-steps per block (compute into the live ``acc`` register leaf,
then commit), so register-class injections land between compute and store
exactly as in the small mm (resources/registers.py analogue).

meta carries the FLOP/byte footprint so the bench can report achieved
utilization alongside injections/sec.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)
from coast_tpu.models.common import lcg_words
from coast_tpu.ops.indexing import row_select, row_update

SIDE = 256
BLOCK = 32
SEED = 42


def _fill(seed: int, n: int, bits: int) -> np.ndarray:
    """Deterministic integer-valued entries in [0, 2^bits)."""
    return lcg_words(seed, n, bits=bits).astype(np.float32)


def _entry_bits(side: int, bf16_matmul: bool) -> int:
    """Largest entry width keeping every row sum exactly representable:
    side * (2^bits - 1)^2 < 2^24, so f32 accumulation never rounds and
    the golden compare is bitwise regardless of op order (256 -> 8 bits;
    1024 -> 7 bits, a 16.52M vs 16.78M margin).  bf16 operands
    additionally cap entries below 2^8 so the bfloat16 cast is exact."""
    bits = 1
    while side * (2 ** (bits + 1) - 1) ** 2 < 2 ** 24:
        bits += 1
    return min(bits, 8) if bf16_matmul else bits


def make_region(side: int = SIDE, block: int = BLOCK,
                bf16_matmul: bool = False,
                name: "str | None" = None) -> Region:
    """The flagship family: ``side``x``side`` blocked matmul.

    ``bf16_matmul=True`` feeds the MXU at bf16 rate: operands are cast to
    bfloat16 inside the step (state stays 32-bit for the word-addressed
    injection map).  Entries are integer-valued below 2^8 (exactly
    representable in bf16; see _entry_bits) and accumulation happens in
    f32 (preferred_element_type), so the result -- and therefore the
    golden compare -- stays exact.
    Injected mantissa flips in the f32 operands can land below bf16
    precision; SDC statistics of this variant reflect the reduced-
    precision datapath, exactly as a bf16 deployment would."""
    n_blocks = side // block
    bits = _entry_bits(side, bf16_matmul)
    first = jnp.asarray(_fill(SEED, side * side, bits).reshape(side, side))
    second = jnp.asarray(
        _fill(SEED + 1, side * side, bits).reshape(side, side))
    # Exact in f32 (sums < 2^24), so host float64 rounds to the same values.
    golden = jnp.asarray(
        (np.asarray(first, np.float64) @ np.asarray(second, np.float64)
         ).astype(np.float32))

    def init():
        return {
            "first": first,
            "second": second,
            "results": jnp.zeros((side, side), jnp.float32),
            "golden": golden,
            "acc": jnp.zeros((block, side), jnp.float32),
            "i": jnp.int32(0),
            "phase": jnp.int32(0),
        }

    def step(state, t):
        i, phase = state["i"], state["phase"]
        # Block-row access goes through ops/indexing.py over a
        # (n_blocks, block, side) view: a corrupted ``i`` clamps into
        # range (same fidelity envelope as the toy mm), and routing
        # through indexing.py makes the lowering of the batch-varying
        # index *selectable* (COAST_INDEXING_MODE / the mode arg), so
        # slice vs one-hot can be A/B'd on-chip
        # (scripts/flagship_indexing_ab.py).  Note "auto" currently
        # stays on the ``slice`` lowering here: a flagship block row is
        # a whole (block, side) panel, far above the
        # ONEHOT_MAX_ROW_BYTES=4096 cutoff the toy-scale sweep
        # (artifacts/unroll_sweep.json) justified for one-hot.  The
        # leaves keep their (side, side) shapes, so the word-addressed
        # injection map is unchanged.
        blk_i = jnp.clip(i, 0, n_blocks - 1)
        block_a = row_select(
            state["first"].reshape(n_blocks, block, side), blk_i)
        if bf16_matmul:
            computed = jnp.dot(block_a.astype(jnp.bfloat16),
                               state["second"].astype(jnp.bfloat16),
                               preferred_element_type=jnp.float32)
        else:
            computed = block_a @ state["second"]    # MXU, f32
        compute_phase = phase == 0
        acc = jnp.where(compute_phase, computed, state["acc"])
        stored = row_update(
            state["results"].reshape(n_blocks, block, side),
            state["acc"], blk_i).reshape(side, side)
        results = jnp.where(compute_phase, state["results"], stored)
        return {
            **state,
            "acc": acc,
            "results": results,
            "i": jnp.where(compute_phase, i, i + 1),
            "phase": jnp.where(compute_phase, 1, 0),
        }

    def done(state):
        return state["i"] >= n_blocks

    def check(state):
        return jnp.sum(state["golden"] != state["results"]).astype(jnp.int32)

    def output(state):
        return jax.lax.bitcast_convert_type(state["results"],
                                            jnp.uint32).reshape(-1)

    def block_of(state):
        compute_pending = state["phase"] == 0
        return jnp.where(
            compute_pending,
            jnp.where(state["i"] >= n_blocks, jnp.int32(3), jnp.int32(1)),
            jnp.int32(2)).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "compute", "store", "exit"],
        edges=[(0, 1), (1, 2), (2, 1), (2, 3)],
        block_of=block_of,
    )

    flops_per_run = 2 * side * side * side          # one full matmul
    state_bytes = 4 * (4 * side * side + block * side + 2)

    return Region(
        name=name or f"matrixMultiply{side}",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=2 * n_blocks,
        max_steps=6 * n_blocks,
        spec={
            "first": LeafSpec(KIND_MEM),
            "second": LeafSpec(KIND_MEM),
            "results": LeafSpec(KIND_MEM, xmr=True),
            "golden": LeafSpec(KIND_RO),
            "acc": LeafSpec(KIND_REG),
            "i": LeafSpec(KIND_CTRL),
            "phase": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={"oracle": "Number of errors: 0",
              "flops_per_run": flops_per_run,
              "state_bytes": state_bytes,
              "bf16_matmul": bf16_matmul,
              # Store-slice hint: each step stores at most the current
              # row block of `results`, so the store sync needs to vote
              # only those rows (the stored VALUE, syncStoreInst) -- the
              # voter's HBM traffic per run drops from O(steps * side^2)
              # to O(side^2).  Divergence in earlier rows is caught by
              # the region-boundary sync.
              "store_slice": {
                  "results": lambda view, t: (
                      (jnp.clip(view["i"], 0, n_blocks - 1) * block,
                       jnp.int32(0)),
                      (block, side),
                      view["phase"] == 1),   # only the commit micro-step
                                             # stores; compute steps skip
              }},
    )


def make_region_1024() -> Region:
    """The MXU-rate flagship: 1024x1024 with bf16 operands (4 MiB result
    state; ~2.1 GFLOP per run per lane)."""
    return make_region(side=1024, block=128, bf16_matmul=True)


def make_region_1024_b512() -> Region:
    """The high-MFU flagship variant: block=512 trades injection-window
    granularity (4 steps instead of 16) for a 4x cut in per-run voter
    HBM traffic -- the ~22%-of-peak row of the docs/perf.md roofline.
    Same program, same oracle; the campaign's cycle resolution coarsens."""
    return make_region(side=1024, block=512, bf16_matmul=True,
                       name="matrixMultiply1024b512")
