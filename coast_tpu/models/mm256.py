"""matrixMultiply256: the TPU-shaped flagship benchmark (>= 1 MiB state).

The reference's flagship is a 9x9 integer matrixMultiply
(tests/matrixMultiply/matrixMultiply.c) -- ~160 words of state, sized for a
Cortex-A9 guest under QEMU.  A TPU's fault-injection value proposition is
the opposite regime: large replica tensors resident in HBM, compute on the
MXU, thousands of campaigns batched per dispatch.  This region is the same
*program* as matrixMultiply -- golden generated at startup, triple loop,
self-check counts mismatching words -- scaled to that regime:

  * 256x256 operands/results/golden: 4 x 256 KiB = 1.0 MiB of region
    state; under TMR the replicated leaves alone hold 3.75 MiB in HBM
    (first/second/results x 3 lanes + shared golden).
  * one step = one 32-row output block: a (32x256)@(256x256) matmul the
    XLA compiler tiles onto the MXU -- per protected step that is
    3 lanes x 4.2 MFLOP of systolic work, vs the scalar adds of the 9x9.
  * entries are integer-valued floats in [0, 256): every product and
    256-term row sum stays below 2^24, so float32 matmul is *exact* and
    the golden compare is bitwise-stable under any op order or fusion
    XLA picks (the mm.c golden-XOR oracle, tests/mm_common/mm.c:31,
    without depending on float rounding).

Two micro-steps per block (compute into the live ``acc`` register leaf,
then commit), so register-class injections land between compute and store
exactly as in the small mm (resources/registers.py analogue).

meta carries the FLOP/byte footprint so the bench can report achieved
utilization alongside injections/sec.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 LeafSpec, Region)
from coast_tpu.models.common import lcg_words

SIDE = 256
BLOCK = 32
N_BLOCKS = SIDE // BLOCK
SEED = 42


def _fill(seed: int, n: int) -> np.ndarray:
    """Deterministic entries in [0, 256): integer-valued, f32-exact."""
    return lcg_words(seed, n, bits=8).astype(np.float32)


def make_region() -> Region:
    first = jnp.asarray(_fill(SEED, SIDE * SIDE).reshape(SIDE, SIDE))
    second = jnp.asarray(_fill(SEED + 1, SIDE * SIDE).reshape(SIDE, SIDE))
    # Exact in f32 (sums < 2^24), so host float64 rounds to the same values.
    golden = jnp.asarray(
        (np.asarray(first, np.float64) @ np.asarray(second, np.float64)
         ).astype(np.float32))

    def init():
        return {
            "first": first,
            "second": second,
            "results": jnp.zeros((SIDE, SIDE), jnp.float32),
            "golden": golden,
            "acc": jnp.zeros((BLOCK, SIDE), jnp.float32),
            "i": jnp.int32(0),
            "phase": jnp.int32(0),
        }

    def step(state, t):
        i, phase = state["i"], state["phase"]
        row0 = jnp.clip(i, 0, N_BLOCKS - 1) * BLOCK
        block_a = jax.lax.dynamic_slice(state["first"], (row0, 0),
                                        (BLOCK, SIDE))
        computed = block_a @ state["second"]        # MXU: (32,256)@(256,256)
        compute_phase = phase == 0
        acc = jnp.where(compute_phase, computed, state["acc"])
        stored = jax.lax.dynamic_update_slice(state["results"], state["acc"],
                                              (row0, 0))
        results = jnp.where(compute_phase, state["results"], stored)
        return {
            **state,
            "acc": acc,
            "results": results,
            "i": jnp.where(compute_phase, i, i + 1),
            "phase": jnp.where(compute_phase, 1, 0),
        }

    def done(state):
        return state["i"] >= N_BLOCKS

    def check(state):
        return jnp.sum(state["golden"] != state["results"]).astype(jnp.int32)

    def output(state):
        return jax.lax.bitcast_convert_type(state["results"],
                                            jnp.uint32).reshape(-1)

    def block_of(state):
        compute_pending = state["phase"] == 0
        return jnp.where(
            compute_pending,
            jnp.where(state["i"] >= N_BLOCKS, jnp.int32(3), jnp.int32(1)),
            jnp.int32(2)).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "compute", "store", "exit"],
        edges=[(0, 1), (1, 2), (2, 1), (2, 3)],
        block_of=block_of,
    )

    flops_per_run = 2 * SIDE * SIDE * SIDE          # one full matmul
    state_bytes = 4 * (4 * SIDE * SIDE + BLOCK * SIDE + 2)

    return Region(
        name="matrixMultiply256",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=2 * N_BLOCKS,
        max_steps=6 * N_BLOCKS,
        spec={
            "first": LeafSpec(KIND_MEM),
            "second": LeafSpec(KIND_MEM),
            "results": LeafSpec(KIND_MEM, xmr=True),
            "golden": LeafSpec(KIND_RO),
            "acc": LeafSpec(KIND_REG),
            "i": LeafSpec(KIND_CTRL),
            "phase": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={"oracle": "Number of errors: 0",
              "flops_per_run": flops_per_run,
              "state_bytes": state_bytes},
    )
