"""crc16: CRC-16/CCITT benchmark as a TPU region (BASELINE config 3, -DWC).

Semantics follow tests/crc16/crc16.c: reflected CCITT polynomial 0x8408,
init 0xFFFF, over the 13-byte message "Automated TMR"; one region step per
message byte (the while loop body).  The reference program just prints the
final CRC and the harness regex-checks it (unittest/unittest.py:74-88); here
``check`` compares against the build-time golden CRC and ``output`` is the
CRC word.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import KIND_CTRL, KIND_MEM, KIND_REG, LeafSpec, Region

MESSAGE = b"Automated TMR"
POLY = 0x8408


def _crc16_host(data: bytes) -> int:
    """Host-side golden model (independent oracle, mirrors crc16.c:21-31)."""
    crc = 0xFFFF
    for byte in data:
        x = ((crc >> 8) ^ byte) & 0xFF
        x ^= x >> 4
        crc = ((crc << 8) ^ (x << 12) ^ (x << 5) ^ x) & 0xFFFF
    return crc


GOLDEN = _crc16_host(MESSAGE)


def make_region() -> Region:
    msg = jnp.asarray(np.frombuffer(MESSAGE, dtype=np.uint8).astype(np.int32))
    n = len(MESSAGE)

    def init():
        return {
            "msg": msg,
            "crc": jnp.int32(0xFFFF),
            "i": jnp.int32(0),
        }

    def step(state, t):
        i = state["i"]
        crc = state["crc"]
        # Clamped gather on a corrupted index reads the wrong byte instead
        # of trapping (fidelity envelope vs the A9 data abort, SURVEY.md §7).
        byte = jnp.take(state["msg"], i, mode="clip") & 0xFF
        x = ((crc >> 8) ^ byte) & 0xFF
        x = x ^ (x >> 4)
        new_crc = (((crc << 8) ^ (x << 12) ^ (x << 5) ^ x)) & 0xFFFF
        active = i < n
        return {
            **state,
            "crc": jnp.where(active, new_crc, crc),
            "i": jnp.where(active, i + 1, i),
        }

    def done(state):
        return state["i"] >= n

    def check(state):
        return (state["crc"] != GOLDEN).astype(jnp.int32)

    def output(state):
        return state["crc"].reshape(1)

    def block_of(state):
        return jnp.where(state["i"] >= n, jnp.int32(2), jnp.int32(1))

    graph = BlockGraph(
        names=["entry", "loop", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=block_of,
    )

    return Region(
        name="crc16",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=n,
        max_steps=4 * n,
        spec={
            # The message is a global string; COAST clones in-scope globals
            # (cloning.cpp:2417-2462), so it sits inside the SoR by default.
            "msg": LeafSpec(KIND_MEM),
            "crc": LeafSpec(KIND_REG),
            "i": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={"golden": GOLDEN, "oracle": f"result: {GOLDEN:x}"},
    )
