"""towersOfHanoi: the recursion/control-flow stress benchmark as a TPU
region (tests/towersOfHanoi/towers.c).

The reference is pure recursion with no data output (its value is deep call
stacks and branching -- the stackProtection scenario,
synchronization.cpp:1579-1812).  The TPU-native re-expression runs the
recursion as an explicit stack machine, one frame visit per step, which
gives the fault injector a real in-memory call stack to corrupt: frames
(num, from, to, aux, stage) live in injectable memory leaves, and a flipped
frame word mis-routes the recursion exactly as a smashed stack does.

The reference uses num=32 (2^31 calls -- a pure burn); we run NUM_DISKS=8
and add a semantic oracle the reference lacks: every move is applied to a
disk-position array, and the check requires exactly 2^n - 1 moves with all
disks on the target peg.
"""

from __future__ import annotations

import jax.numpy as jnp

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, LeafSpec,
                                 Region)

NUM_DISKS = 8
DEPTH = NUM_DISKS + 1
PEG_FROM, PEG_TO, PEG_AUX = 0, 1, 2       # towers(num, 'A', 'C', 'B')
TOTAL_MOVES = (1 << NUM_DISKS) - 1
# Frame visits: non-leaf frames 3 (stages 0,1,2), leaves 1.
NOMINAL = 3 * ((1 << (NUM_DISKS - 1)) - 1) + (1 << (NUM_DISKS - 1))


def make_region() -> Region:

    def init():
        z = jnp.zeros(DEPTH, jnp.int32)
        return {
            "st_num": z.at[0].set(NUM_DISKS),
            "st_f": z.at[0].set(PEG_FROM),
            "st_t": z.at[0].set(PEG_TO),
            "st_a": z.at[0].set(PEG_AUX),
            "st_stage": z,
            "sp": jnp.int32(1),
            "disk_pos": jnp.full(NUM_DISKS, PEG_FROM, jnp.int32),
            "moves": jnp.int32(0),
        }

    def step(state, t):
        sp = state["sp"]
        running = sp > 0
        top = jnp.clip(sp - 1, 0, DEPTH - 1)
        num = jnp.take(state["st_num"], top, mode="clip")
        f = jnp.take(state["st_f"], top, mode="clip")
        to = jnp.take(state["st_t"], top, mode="clip")
        aux = jnp.take(state["st_a"], top, mode="clip")
        stage = jnp.take(state["st_stage"], top, mode="clip")

        leaf = num <= 1
        s0 = stage == 0
        s1 = stage == 1

        # stage 0, leaf: move disk 1 (index 0), pop.
        # stage 0, non-leaf: stage<-1, push (num-1, f, aux, to).
        # stage 1: move disk num (index num-1), stage<-2, push (num-1, aux, to, f).
        # stage >=2: pop.
        do_move = jnp.logical_and(running,
                                  jnp.logical_or(jnp.logical_and(s0, leaf), s1))
        moved_disk = jnp.where(jnp.logical_and(s0, leaf), 0,
                               jnp.clip(num - 1, 0, NUM_DISKS - 1))
        disk_pos = jnp.where(
            do_move,
            state["disk_pos"].at[moved_disk].set(to, mode="drop"),
            state["disk_pos"])

        push = jnp.logical_and(running,
                               jnp.logical_or(jnp.logical_and(s0, ~leaf), s1))
        pop = jnp.logical_and(running, ~push)

        # stage bump on the current frame before pushing the child.
        new_stage_top = jnp.where(s0, 1, 2)
        st_stage = jnp.where(
            push, state["st_stage"].at[top].set(new_stage_top, mode="drop"),
            state["st_stage"])

        child = jnp.clip(sp, 0, DEPTH - 1)
        cf = jnp.where(s0, f, aux)
        ct = jnp.where(s0, aux, to)
        ca = jnp.where(s0, to, f)
        st_num = jnp.where(push, state["st_num"].at[child].set(num - 1,
                                                               mode="drop"),
                           state["st_num"])
        st_f = jnp.where(push, state["st_f"].at[child].set(cf, mode="drop"),
                         state["st_f"])
        st_t = jnp.where(push, state["st_t"].at[child].set(ct, mode="drop"),
                         state["st_t"])
        st_a = jnp.where(push, state["st_a"].at[child].set(ca, mode="drop"),
                         state["st_a"])
        st_stage = jnp.where(push, st_stage.at[child].set(0, mode="drop"),
                             st_stage)

        new_sp = jnp.where(push, sp + 1, jnp.where(pop, sp - 1, sp))
        return {
            "st_num": st_num,
            "st_f": st_f,
            "st_t": st_t,
            "st_a": st_a,
            "st_stage": st_stage,
            "sp": new_sp,
            "disk_pos": disk_pos,
            "moves": state["moves"] + jnp.where(do_move, 1, 0),
        }

    def done(state):
        return state["sp"] <= 0

    def check(state):
        wrong_moves = (state["moves"] != TOTAL_MOVES).astype(jnp.int32)
        off_peg = jnp.sum(state["disk_pos"] != PEG_TO).astype(jnp.int32)
        return wrong_moves + off_peg

    def output(state):
        return jnp.concatenate(
            [state["disk_pos"], state["moves"].reshape(1)]).astype(jnp.uint32)

    def block_of(state):
        return jnp.where(state["sp"] <= 0, jnp.int32(2),
                         jnp.int32(1)).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "towers", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=block_of,
    )

    return Region(
        name="towersOfHanoi",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=NOMINAL,
        max_steps=3 * NOMINAL,
        spec={
            # The frame stack is the region's call stack: the target of
            # -protectStack (stackProtect.c / stackAttack.c scenarios).
            "st_num": LeafSpec(KIND_MEM, stack=True),
            "st_f": LeafSpec(KIND_MEM, stack=True),
            "st_t": LeafSpec(KIND_MEM, stack=True),
            "st_a": LeafSpec(KIND_MEM, stack=True),
            "st_stage": LeafSpec(KIND_MEM, stack=True),
            "sp": LeafSpec(KIND_CTRL, stack=True),
            "disk_pos": LeafSpec(KIND_MEM),
            "moves": LeafSpec(KIND_REG),
        },
        default_xmr=True,
        graph=graph,
        meta={"oracle": "all disks on peg C in 2^n-1 moves"},
    )
