"""cache_test: strided memory-walk benchmark (reference: tests/cache_test/
-- access patterns sized to the A9 cache hierarchy, the natural target of
the plugin's cache-section injections).

The TPU region walks a 1024-word table (4 KiB, one L1 way's worth in the
reference geometry) with three co-prime strides, read-modify-writing each
visited word.  Under ``-s dcache`` campaigns the hierarchy overlay
(coast_tpu.inject.hierarchy) maps cache lines onto exactly this leaf, so
flipped "cache lines" surface as corrupted table words mid-walk.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import KIND_CTRL, KIND_MEM, LeafSpec, Region

WORDS = 1024
PASSES = 3
STRIDES = (1, 17, 257)                # co-prime with 1024? 17,257 are; 1 too
N_STEPS = PASSES * WORDS


def golden_reference() -> np.ndarray:
    mem = (np.arange(WORDS, dtype=np.uint64) * 2246822519) % (1 << 32)
    for p in range(PASSES):
        stride = STRIDES[p]
        idx = 0
        for k in range(WORDS):
            mem[idx] = (mem[idx] * 5 + k + p) % (1 << 32)
            idx = (idx + stride) % WORDS
    return mem.astype(np.uint32)


def make_region() -> Region:
    golden = golden_reference()
    init_mem = ((np.arange(WORDS, dtype=np.uint64) * 2246822519)
                % (1 << 32)).astype(np.uint32)
    strides = jnp.asarray(STRIDES, jnp.int32)

    def init():
        return {
            "table": jnp.asarray(init_mem),
            "idx": jnp.int32(0),
            "i": jnp.int32(0),
        }

    def step(state, t):
        i = state["i"]
        p = jnp.clip(i // WORDS, 0, PASSES - 1)
        k = i % WORDS
        idx = state["idx"]
        v = jnp.take(state["table"], idx, mode="clip")
        v = v * np.uint32(5) + k.astype(jnp.uint32) + p.astype(jnp.uint32)
        table = state["table"].at[idx].set(v, mode="drop")
        # Pass boundary resets the cursor to 0 for the next stride.
        next_idx = (idx + jnp.take(strides, p, mode="clip")) % WORDS
        next_idx = jnp.where(k == WORDS - 1, 0, next_idx)
        return {"table": table, "idx": next_idx, "i": i + 1}

    def done(state):
        return state["i"] >= N_STEPS

    def check(state):
        return jnp.sum(state["table"]
                       != jnp.asarray(golden)).astype(jnp.int32)

    graph = BlockGraph(
        names=["entry", "walk", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["i"] >= N_STEPS,
                                     jnp.int32(2), jnp.int32(1)))

    return Region(
        name="cache_test",
        init=init,
        step=step,
        done=done,
        check=check,
        output=lambda s: s["table"],
        nominal_steps=N_STEPS,
        max_steps=N_STEPS + 8,
        spec={
            "table": LeafSpec(KIND_MEM),
            "idx": LeafSpec(KIND_CTRL),
            "i": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        meta={},
    )
