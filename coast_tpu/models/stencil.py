"""Sharded halo-exchange stencil: the first region whose fault surface
includes the interconnect.

A 2D Jacobi-style five-point relaxation in mod-2^32 integer arithmetic
(every flipped bit propagates; nothing is absorbed by rounding), sharded
into two column blocks the way the TPU CFD framework shards its grids
(arXiv:2108.11076): each super-step packs the shard-interface edge
columns into an exchange buffer (the words "on the wire" of a
``ppermute``), then integrates the received halo and relaxes.  The
region models the distributed program on one device -- per-shard grid
leaves plus an explicit ``link``-kind leaf for the in-flight halo -- so
single-device campaigns, the sharded mesh runner, and the static
propagation walker all see the same program; ``run_distributed`` is the
genuinely distributed ``shard_map`` + ``ppermute`` executor, kept
bit-identical as a FuzzyFlow-style differential pin (arXiv:2306.16178).

Two protection schedules, selected by ``placement``:

* ``compute`` -- **vote-then-exchange.**  The halo buffer is a plain
  shared leaf: the engine's SoR-crossing vote fires on the PACK commit,
  before the value travels.  A compute flip in one replica's edge cell
  is repaired before it can leave the shard (blast radius: one shard,
  measured zero cross-shard SDC), but a flip on the link itself -- after
  the vote, before the receive -- is integrated by every replica of the
  neighbor identically and votes cannot catch it (the honest blind
  spot; 1x halo bandwidth).
* ``link`` -- **exchange-then-vote.**  The halo buffer carries ``R=3``
  copies and is declared ``unvoted_crossing``: the engine commits the
  buffer raw (lane 0's pack, replicated into all three slots) and the
  RECEIVER bitwise-majority votes the copies after the collective.  A
  link flip hits one of three in-flight copies and is repaired (the
  placement's win), but the unvoted pack is a single point of failure:
  a lane-0 compute flip in an edge cell at a pack step ships corrupted
  data in ALL three copies, the receive vote passes it, and the
  neighbor shard silently integrates it -- measured cross-shard SDC
  (3x halo bandwidth).  The isolation prover honestly refutes this
  build; campaigns measure exactly the leak it names.

The ``link`` fault model (inject/schedule.py) targets the halo leaf in
its receive window (``meta['link_window'] = (1, 2)``: odd steps, after
the pack committed and before the receive reads), which is what makes
"interconnect upset" a distinct campaign axis from "compute upset".
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from coast_tpu.ir.region import (KIND_CTRL, KIND_LINK, KIND_MEM, KIND_RO,
                                 LeafSpec, Region)

H = 8            # rows per shard (vertical axis is periodic, unsharded)
W = 6            # interior columns per shard
SHARDS = 2       # column blocks (grid0 | grid1)
R_LINK = 3       # in-flight halo copies under exchange-then-vote
N_ITERS = 6      # relaxation iterations (2 micro-steps each)
SEED = 1234

PLACEMENTS = ("compute", "link")


def _fill(seed: int, n: int) -> np.ndarray:
    """Deterministic full-width uint32 pseudo-random fill (splitmix-like
    finalizer): the initial field, dense in every bit position."""
    x = np.arange(1, n + 1, dtype=np.uint64) + np.uint64(seed)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    return (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def _relax_full(u: np.ndarray) -> np.ndarray:
    """One five-point relaxation of the FULL logical grid (numpy truth):
    u' = u + N + S + E + W mod 2^32, rows periodic, zero side boundary."""
    up = np.roll(u, 1, axis=0)
    down = np.roll(u, -1, axis=0)
    z = np.zeros((u.shape[0], 1), np.uint32)
    left = np.concatenate([z, u[:, :-1]], axis=1)
    right = np.concatenate([u[:, 1:], z], axis=1)
    return u + up + down + left + right


def golden_trajectory(n_iters: int = N_ITERS) -> np.ndarray:
    """The exhaustive single-device truth: the full (H, SHARDS*W) grid
    after ``n_iters`` relaxations.  Both the region and the distributed
    shard_map executor are pinned against this array bit-for-bit."""
    u = _fill(SEED, H * SHARDS * W).reshape(H, SHARDS * W)
    for _ in range(n_iters):
        u = _relax_full(u)
    return u


def _relax_block(u: jnp.ndarray) -> jnp.ndarray:
    """Relax one (H, W+2) shard block in place: halo columns 0 / W+1 are
    already loaded; only interior columns 1..W update."""
    up = jnp.roll(u, 1, axis=0)
    down = jnp.roll(u, -1, axis=0)
    left = jnp.concatenate([u[:, :1] * 0, u[:, :-1]], axis=1)
    right = jnp.concatenate([u[:, 1:], u[:, -1:] * 0], axis=1)
    relaxed = u + up + down + left + right
    keep = jnp.concatenate(
        [u[:, :1], relaxed[:, 1:-1], u[:, -1:]], axis=1)
    return keep


def make_region(placement: str = "compute") -> Region:
    """Build the stencil region under one of the two voter placements.

    ``compute``: vote-then-exchange (the registry default).
    ``link``:    exchange-then-vote.
    """
    if placement not in PLACEMENTS:
        raise ValueError(
            f"unknown stencil placement {placement!r}; one of {PLACEMENTS}")
    xv = placement == "link"

    full0 = _fill(SEED, H * SHARDS * W).reshape(H, SHARDS * W)
    golden_full = golden_trajectory(N_ITERS)
    # Shard s holds logical columns [s*W, (s+1)*W) plus two halo columns.
    init_blocks = []
    golden_blocks = []
    for s in range(SHARDS):
        blk = np.zeros((H, W + 2), np.uint32)
        blk[:, 1:-1] = full0[:, s * W:(s + 1) * W]
        init_blocks.append(jnp.asarray(blk))
        golden_blocks.append(
            jnp.asarray(golden_full[:, s * W:(s + 1) * W].copy()))

    halo_shape = (R_LINK, SHARDS, H) if xv else (SHARDS, H)

    def init():
        return {
            "grid0": init_blocks[0],
            "grid1": init_blocks[1],
            "golden0": golden_blocks[0],
            "golden1": golden_blocks[1],
            "halo": jnp.zeros(halo_shape, jnp.uint32),
            "it": jnp.int32(0),
        }

    def _pack(g0, g1):
        """Edge columns onto the wire: row 0 = eastbound (shard0's last
        interior column -> shard1's left halo), row 1 = westbound."""
        return jnp.stack([g0[:, W], g1[:, 1]])

    def step(state, t):
        g0, g1 = state["grid0"], state["grid1"]
        recv_phase = (t % 2) == 1

        if xv:
            packed = jnp.broadcast_to(_pack(g0, g1)[None],
                                      (R_LINK, SHARDS, H))
            a, b, c = state["halo"][0], state["halo"][1], state["halo"][2]
            wire = (a & b) | (b & c) | (a & c)
        else:
            packed = _pack(g0, g1)
            wire = state["halo"]

        # Receive: load the interface halos (outer side halos stay the
        # zero boundary), then relax the interiors.
        r0 = g0.at[:, W + 1].set(wire[1]).at[:, 0].set(0)
        r1 = g1.at[:, 0].set(wire[0]).at[:, W + 1].set(0)
        n0 = _relax_block(r0)
        n1 = _relax_block(r1)

        return {
            **state,
            "grid0": jnp.where(recv_phase, n0, g0),
            "grid1": jnp.where(recv_phase, n1, g1),
            "halo": jnp.where(recv_phase, state["halo"], packed),
            "it": jnp.where(recv_phase, state["it"] + 1, state["it"]),
        }

    def done(state):
        return state["it"] >= N_ITERS

    def check(state):
        return (jnp.sum(state["golden0"] != state["grid0"][:, 1:-1])
                + jnp.sum(state["golden1"] != state["grid1"][:, 1:-1])
                ).astype(jnp.int32)

    def output(state):
        return jnp.concatenate([state["grid0"][:, 1:-1].reshape(-1),
                                state["grid1"][:, 1:-1].reshape(-1)])

    return Region(
        name=f"stencil[{placement}]",
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=2 * N_ITERS,
        max_steps=6 * N_ITERS,
        spec={
            "grid0": LeafSpec(KIND_MEM, xmr=True),
            "grid1": LeafSpec(KIND_MEM, xmr=True),
            "golden0": LeafSpec(KIND_RO),
            "golden1": LeafSpec(KIND_RO),
            "halo": LeafSpec(KIND_LINK, xmr=False,
                             unvoted_crossing=xv),
            "it": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        meta={
            "placement": placement,
            # Receive window of the link fault model: the halo words are
            # in flight at odd steps (packed at t, read at t+1).
            "link_window": (1, 2),
            # Which stencil shard each section's words belong to (None =
            # the shared interconnect / control surface) -- the walker's
            # cross-shard reach analysis and blast-radius attribution key.
            "shard_of": {"grid0": 0, "grid1": 1,
                         "golden0": 0, "golden1": 1,
                         "halo": None, "it": None},
            # Output-vector spans per shard (for blast-radius splits).
            "shard_slices": {"grid0": (0, H * W),
                             "grid1": (H * W, 2 * H * W)},
            "golden_full": golden_full,
        },
    )


# -- the genuinely distributed executor (shard_map + ppermute) ---------------

def distributed_step(axis: str = "x"):
    """One relaxation of a (H, W) column block under ``shard_map``: edge
    columns travel by ``ppermute`` (non-participating edges receive the
    collective's zero fill -- exactly the zero side boundary)."""

    def step(u):
        nx = jax.lax.psum(1, axis)
        fwd = [(i, i + 1) for i in range(nx - 1)]
        bwd = [(i + 1, i) for i in range(nx - 1)]
        from_left = jax.lax.ppermute(u[:, -1], axis, fwd)
        from_right = jax.lax.ppermute(u[:, 0], axis, bwd)
        up = jnp.roll(u, 1, axis=0)
        down = jnp.roll(u, -1, axis=0)
        left = jnp.concatenate([from_left[:, None], u[:, :-1]], axis=1)
        right = jnp.concatenate([u[:, 1:], from_right[:, None]], axis=1)
        return u + up + down + left + right

    return step


def run_distributed(n_iters: int = N_ITERS, n_devices: int = SHARDS
                    ) -> np.ndarray:
    """Run the stencil as an actually-sharded program: the full grid
    split over ``n_devices`` column blocks on a 1D mesh, halo exchange
    via ``ppermute`` each iteration.  Returns the final full grid; the
    differential pin asserts it equals ``golden_trajectory`` (and hence
    the region model) bit-for-bit."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < n_devices:
        raise RuntimeError(
            f"run_distributed wants {n_devices} devices, have {len(devs)}")
    cols = SHARDS * W
    if cols % n_devices:
        raise ValueError(f"{cols} columns do not shard over {n_devices}")
    mesh = Mesh(np.array(devs[:n_devices]), ("x",))
    step = distributed_step("x")

    @jax.jit
    def run(u):
        body = shard_map(step, mesh=mesh, in_specs=P(None, "x"),
                         out_specs=P(None, "x"))

        def it(carry, _):
            return body(carry), None

        out, _ = jax.lax.scan(it, u, None, length=n_iters)
        return out

    u0 = jnp.asarray(_fill(SEED, H * cols).reshape(H, cols))
    return np.asarray(run(u0))
